"""Expression -> XLA kernel compiler.

The TPU-first heart of the execution layer: an operator's whole expression
list is traced once per (expression-tree, shape-bucket, input-dtypes) into a
single jitted XLA computation operating on padded (data, validity) arrays.
XLA fuses all the elementwise work into a handful of HBM passes — the analog
of (and improvement over) the reference's per-expression cudf kernel launches
(GpuExpressions.scala columnarEval chain), and of its AST fusion subsystem
(AstUtil.scala) which only fuses within join conditions.

Also hosts the device row-compaction kernel used by filter (cumsum + scatter,
O(n), no sort) — reference analog: cudf apply_boolean_mask behind
GpuFilter (basicPhysicalOperators.scala:649).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import ColumnarBatch, DeviceColumn, HostColumn
from ..columnar.bucketing import bucket_for
from ..types import Schema, StructField
from .base import (DVal, EvalContext, Expression, collect_param_literals,
                   literal_scalars, literal_slot_map, parameterized_keys)

__all__ = ["compile_projection", "DeviceProjector", "filter_batch_device",
           "gather_batch_device", "eval_predicate_device",
           "FusedStageKernel", "compile_fused_stages", "compile_rect_chain"]

#: lock-free front memo over the executable cache for kernels resolved
#: on PER-BATCH paths (filter predicates build a DeviceProjector per
#: batch; rect chains resolve per batch): the hit path is one plain
#: dict read — no lock, no counter churn — while first resolutions
#: still flow through exec_cache.get_or_build, so the srtpu_compile_*
#: miss/compile counters stay exact (per-kernel, not per-batch)
_FRONT: Dict[Tuple, object] = {}
_FRONT_MAX = 4096


def _resolve_cached(key: Tuple, build, label: str):
    fn = _FRONT.get(key)
    if fn is None:
        from ..plan import exec_cache
        # exec_cache.clear() must release THESE strong refs too, or the
        # dropped tier would keep serving (and pinning) its executables
        exec_cache.register_clear_hook(_FRONT.clear)
        fn = exec_cache.get_or_build(key, build, label=label)
        if len(_FRONT) >= _FRONT_MAX:
            _FRONT.clear()
        _FRONT[key] = fn
    return fn


def _device_ordinals(schema: Schema) -> List[int]:
    return [i for i, f in enumerate(schema.fields) if f.dtype.device_backed]


class DeviceProjector:
    """Evaluates a fixed list of device-supported expressions against batches
    of a fixed input schema via one jitted kernel."""

    def __init__(self, exprs: Sequence[Expression], schema: Schema):
        self.exprs = list(exprs)
        self.schema = schema
        self.out_types = [e.data_type(schema) for e in self.exprs]
        with parameterized_keys():
            self._key = (tuple(e.key() for e in self.exprs),
                         tuple((f.name, f.dtype.name)
                               for f in schema.fields))
        # numeric literals ride in as traced scalars: structurally equal
        # projections/filters with different constants share ONE kernel
        self._lits = collect_param_literals(self.exprs)
        self._scalars = literal_scalars(self._lits)
        # resolved through the process-wide executable cache (not a
        # per-exec dict): a repeat query's fresh exec objects reuse the
        # SAME callable, so jax serves every shape bucket it has traced
        from ..plan import exec_cache
        self._fn = _resolve_cached(
            exec_cache.fused_key("proj", self._key), self._build,
            label="projection")

    def _build(self):
        from .base import ListVal
        exprs, schema = self.exprs, self.schema
        dtypes = [f.dtype for f in schema.fields]  # static, closed over
        slots = {id(l): i for i, l in enumerate(self._lits)}

        @functools.partial(jax.jit, static_argnums=(2,))
        def kernel(cols, num_rows, padded_len, scalars=()):
            dvals = []
            for c, dt in zip(cols, dtypes):
                if c is None:
                    dvals.append(None)
                elif len(c) == 4:       # list rectangle (nested.py)
                    dvals.append(DVal(ListVal(c[0], c[2], c[3]), c[1], dt))
                else:
                    dvals.append(DVal(c[0], c[1], dt))
            ctx = EvalContext(schema, dvals, num_rows, padded_len,
                              scalars, slots)
            outs = []
            for e in exprs:
                v = e.eval_device(ctx)
                # clamp validity so padding rows are always invalid
                outs.append((v.data, jnp.logical_and(v.validity, ctx.row_mask())))
            return outs

        return kernel

    def run(self, batch: ColumnarBatch,
            extra_scalars: tuple = ()) -> List[DeviceColumn]:
        from ..columnar.nested import ListColumn
        from ..types import ArrayType
        from .base import ListVal
        p = batch.padded_len
        cols = []
        for i, f in enumerate(batch.schema.fields):
            c = batch.columns[i]
            if isinstance(c, ListColumn):
                cols.append((c.data, c.validity, c.elem_valid, c.lengths))
            elif isinstance(c, DeviceColumn):
                cols.append((c.data, c.validity))
            else:
                cols.append(None)  # host column: device exprs must not touch it
        num_rows = jnp.int32(batch.num_rows_raw)
        outs = self._fn(cols, num_rows, p, self._scalars + extra_scalars)
        built = []
        for (d, v), dt in zip(outs, self.out_types):
            if isinstance(d, ListVal):
                built.append(ListColumn(d.values, v, dt, d.elem_valid,
                                        d.lengths))
            else:
                built.append(DeviceColumn(d, v, dt))
        return built


def compile_projection(exprs: Sequence[Expression], schema: Schema) -> DeviceProjector:
    return DeviceProjector(exprs, schema)


# ---------------------------------------------------------------------------
# filter / gather kernels
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(2,))
def _compact_kernel(arrays, keep, padded_len):
    """Move rows where keep=True to the front preserving order.

    arrays: list of (data, validity); keep: bool[P] (False on padding).
    Returns compacted (data, validity) list + new row count (int32 scalar).
    One stable variadic sort (columnar/segmented.compact_rows) — scatter
    compaction serializes on the TPU scalar core.
    """
    from ..columnar.segmented import compact_rows
    return compact_rows(arrays, keep, padded_len)


def eval_predicate_device(pred: Expression, batch: ColumnarBatch) -> jnp.ndarray:
    """bool[P] keep-mask: predicate true AND valid AND a real row."""
    proj = compile_projection([pred], batch.schema)
    col = proj.run(batch)[0]
    return jnp.logical_and(col.data, col.validity)


# ---------------------------------------------------------------------------
# dictionary-evaluated string predicates (VERDICT r1 #5)
# ---------------------------------------------------------------------------

class _DictSlot(Expression):
    """Placeholder for a string predicate inside a device filter kernel:
    the match was computed ONCE over the column's sorted dictionary on the
    host; on device it is either a code-range comparison (prefix-shaped
    predicates) or one small-table lookup. The pattern itself never
    enters the kernel — masks/bounds ride as traced operands, so every
    same-shaped predicate shares one compiled kernel."""

    def __init__(self, slot: int, ordinal: int, form: str):
        self.children = []
        self.slot = slot
        self.ordinal = ordinal
        self.form = form

    def data_type(self, schema):
        from ..types import BOOL
        return BOOL

    def device_unsupported_reason(self, schema):
        return None

    def key(self):
        return f"dictslot({self.slot},{self.ordinal},{self.form})"

    def eval_device(self, ctx):
        col = ctx.columns[self.ordinal]
        ops = ctx.scalars[self.slot]
        if self.form == "range":
            lo, hi = ops
            data = jnp.logical_and(col.data >= lo, col.data < hi)
        else:
            mask = ops
            data = jnp.take(mask, jnp.clip(col.data, 0, None),
                            mode="clip")
        return DVal(data, col.validity, self.data_type(ctx.schema))


class DictFilterFallback(Exception):
    """Raised per batch when a column expected to be dictionary-coded is
    not (high-cardinality bail-out, host batch): caller filters on host."""


class DictFilterEvaluator:
    """Keep-mask evaluation for conditions mixing device expressions with
    string predicates over dict-coded columns."""

    def __init__(self, cond: Expression, schema: Schema, rewritten,
                 preds):
        self.schema = schema
        self.rewritten = rewritten
        self.preds = preds            # [(pred, ordinal, form)]
        self._mask_cache: Dict[Tuple, object] = {}

    def keep_mask(self, batch: ColumnarBatch):
        import pyarrow as pa
        from ..columnar import DictColumn
        proj = compile_projection([self.rewritten], batch.schema)
        extra = []
        for pred, ordinal, form in self.preds:
            col = batch.columns[ordinal]
            if not isinstance(col, DictColumn):
                raise DictFilterFallback()
            ck = (pred.key(), id(col.dictionary))
            cached = self._mask_cache.get(ck)
            # the cache value pins the dictionary object so a recycled
            # id() can never serve a stale mask for different contents
            got = cached[1] if cached is not None \
                and cached[0] is col.dictionary else None
            if got is None:
                marr = pred.host_mask(
                    pa.array(col.dictionary, type=pa.string()))
                m = np.asarray(marr.fill_null(False))
                if form == "range":
                    idx = np.flatnonzero(m)
                    lo = int(idx[0]) if len(idx) else 0
                    hi = int(idx[-1]) + 1 if len(idx) else 0
                    if len(idx) != hi - lo:
                        # sorted-dictionary invariant violated: take the
                        # host path rather than a wrong range
                        raise DictFilterFallback()
                    got = (jnp.int32(lo), jnp.int32(hi))
                else:
                    card = bucket_for(max(len(m), 1), (64, 1024, 16384,
                                                       262144, 1 << 22))
                    pad = np.zeros(card, dtype=bool)
                    pad[:len(m)] = m
                    got = jnp.asarray(pad)
                self._mask_cache[ck] = (col.dictionary, got)
            extra.append(got)
        col = proj.run(batch, extra_scalars=tuple(extra))[0]
        return jnp.logical_and(col.data, col.validity)


def build_dict_filter(cond: Expression,
                      schema: Schema) -> Optional[DictFilterEvaluator]:
    """Rewrite ``cond`` replacing string predicates over plain STRING
    column refs with _DictSlot placeholders; returns an evaluator when
    the remainder is fully device-supported, else None."""
    import copy as _copy
    from ..types import STRING
    from .base import ColumnRef
    from .string_fns import _PatternPredicate
    names = schema.names()
    preds: list = []
    n_lits = len(collect_param_literals([cond]))

    def rewrite(e):
        if isinstance(e, _PatternPredicate):
            child = e.children[0]
            if isinstance(child, ColumnRef) and child.name in names \
                    and schema[child.name].dtype == STRING:
                ordinal = names.index(child.name)
                slot = n_lits + len(preds)
                preds.append((e, ordinal, e.dict_form))
                return _DictSlot(slot, ordinal, e.dict_form)
            return None
        if not getattr(e, "children", None):
            return e
        kids = [rewrite(c) for c in e.children]
        if any(k is None for k in kids):
            return None
        if all(k is o for k, o in zip(kids, e.children)):
            return e
        clone = _copy.copy(e)
        clone.children = kids
        # container exprs that mirror children in other attrs keep
        # working because predicates only appear under boolean operators
        return clone

    new = rewrite(cond)
    if new is None or not preds:
        return None
    if new.fully_device_supported(schema) is not None:
        return None
    return DictFilterEvaluator(cond, schema, new, preds)


def _lane_pairs(cols):
    """(pairs, spans): flatten device columns into 1D (data, validity)
    pairs for the variadic row kernels. Scalar columns contribute one
    pair; ListColumns decompose into W+1 lanes (nested.kernel_lanes) and
    reassemble after — the rearranging kernels stay 1D-only."""
    pairs = []
    spans = []
    for i, c in cols:
        start = len(pairs)
        if hasattr(c, "kernel_lanes"):
            pairs.extend(c.kernel_lanes())
        else:
            pairs.append((c.data, c.validity))
        spans.append((i, start, len(pairs)))
    return pairs, spans


def _lane_rebuild(batch, spans, outs, new_cols):
    for i, start, end in spans:
        c = batch.columns[i]
        if hasattr(c, "from_lanes"):
            new_cols[i] = c.from_lanes(outs[start:end])
        else:
            d, v = outs[start]
            new_cols[i] = c.with_arrays(d, v)


def filter_batch_by_mask(batch: ColumnarBatch, keep,
                         schema=None) -> ColumnarBatch:
    """Compact the batch's rows where ``keep`` (bool over padded rows) is
    True; the single home of the mask→compact→rebatch idiom. Mixed
    batches are first-class: device columns compact on device, host
    columns filter via Arrow with the same mask."""
    from ..columnar import HostColumn
    dev_pos = [i for i, c in enumerate(batch.columns)
               if isinstance(c, DeviceColumn)]
    arrays, spans = _lane_pairs([(i, batch.columns[i]) for i in dev_pos])
    outs, count = _compact_kernel(arrays, keep, batch.padded_len)
    new_cols = list(batch.columns)
    _lane_rebuild(batch, spans, outs, new_cols)
    if len(dev_pos) < len(new_cols):
        import pyarrow as pa
        mask = pa.array(np.asarray(keep)[:batch.num_rows])
        for i, c in enumerate(batch.columns):
            if isinstance(c, HostColumn):
                new_cols[i] = HostColumn(
                    c.array.slice(0, batch.num_rows).filter(mask), c.dtype)
    return ColumnarBatch(new_cols, count,
                         schema if schema is not None else batch.schema,
                         meta=batch.meta)


def filter_batch_device(pred: Expression, batch: ColumnarBatch) -> ColumnarBatch:
    """Device filter over an all-device batch (host columns unsupported here —
    the planner falls back for those)."""
    return filter_batch_by_mask(batch, eval_predicate_device(pred, batch))


def filter_mixed_batch(cond: Expression,
                       batch: ColumnarBatch) -> ColumnarBatch:
    """Filter a batch that may carry host-resident columns: device
    columns compact on device with the same mask, host columns filter
    via Arrow. When the CONDITION itself references a column that is
    host-resident in THIS batch (e.g. a width-capped list,
    columnar/nested.py), the whole batch filters on host — the single
    home of this fallback (TpuFilterExec and fused regions share it)."""
    from ..columnar import DeviceColumn as _DC
    refs = set(cond.references())
    names = batch.schema.names()
    if any(nm in refs and not isinstance(batch.column_by_name(nm), _DC)
           for nm in names):
        import pyarrow.compute as pc
        mask = pc.fill_null(cond.eval_host(batch), False)
        out = ColumnarBatch.from_arrow(batch.to_arrow().filter(mask))
        out.meta = dict(batch.meta)   # keep partition_id/input_file
        return out
    keep = eval_predicate_device(cond, batch)
    return filter_batch_by_mask(batch, keep)


# ---------------------------------------------------------------------------
# whole-stage fused lowering (ISSUE 6)
# ---------------------------------------------------------------------------

class FusedStageKernel:
    """One jitted kernel for a whole fused operator region.

    ``stages`` is the bottom-up chain between pipeline breakers, each
    ``("filter", cond)`` or ``("project", exprs, out_schema)``.
    Projections evaluate row-wise over the UNCOMPACTED bucket carrying a
    running keep-mask; masked-out rows compute garbage that the single
    final compaction discards — so N operators cost one XLA dispatch and
    ONE stable-sort compaction instead of one per filter (the
    AggregateMeta._fold_stages idea generalized to any fused region).

    Returns per batch: compacted (data, validity) pairs for the region's
    output schema, the surviving row count, and one per-stage survivor
    count (device scalars — EXPLAIN ANALYZE's per-op rows, forced only
    through the metrics view's packed fetch)."""

    def __init__(self, stages, schema: Schema):
        self.stages = list(stages)
        self.schema = schema
        self.out_schema = schema
        all_exprs: List[Expression] = []
        for st in self.stages:
            if st[0] == "filter":
                all_exprs.append(st[1])
            else:
                all_exprs.extend(st[1])
                self.out_schema = st[2]
        with parameterized_keys():
            stage_sig = ";".join(
                ("F:" + st[1].key()) if st[0] == "filter"
                else ("P:" + ",".join(e.key() for e in st[1]))
                for st in self.stages)
        self._lits = collect_param_literals(all_exprs)
        self._scalars = literal_scalars(self._lits)
        from ..plan import exec_cache
        self.digest = exec_cache.digest_of(stage_sig)
        schema_sig = tuple((f.name, f.dtype.name) for f in schema.fields)
        self._fn = exec_cache.get_or_build(
            exec_cache.fused_key(self.digest, schema_sig), self._build,
            label="wholestage")

    def _build(self):
        stages, in_schema = self.stages, self.schema
        dtypes = [f.dtype for f in in_schema.fields]
        slots = {id(l): i for i, l in enumerate(self._lits)}

        @functools.partial(jax.jit, static_argnums=(2,))
        def kernel(cols, num_rows, padded_len, scalars=()):
            from ..columnar.segmented import compact_rows
            dvals = [DVal(c[0], c[1], dt) for c, dt in zip(cols, dtypes)]
            ctx = EvalContext(in_schema, dvals, num_rows, padded_len,
                              scalars, slots)
            live = ctx.row_mask()
            counts = []
            for st in stages:
                if st[0] == "filter":
                    v = st[1].eval_device(ctx)
                    live = jnp.logical_and(
                        live, jnp.logical_and(v.data, v.validity))
                    counts.append(jnp.sum(live).astype(jnp.int32))
                else:
                    outs = [e.eval_device(ctx) for e in st[1]]
                    ctx = EvalContext(st[2], outs, num_rows, padded_len,
                                      scalars, slots)
                    counts.append(
                        counts[-1] if counts
                        else jnp.sum(live).astype(jnp.int32))
            arrays = [(c.data, jnp.logical_and(c.validity, live))
                      for c in ctx.columns]
            outs, count = compact_rows(arrays, live, padded_len)
            return outs, count, counts

        return kernel

    def run(self, batch: ColumnarBatch, extra_scalars: tuple = ()):
        cols = [(c.data, c.validity) for c in batch.columns]
        num_rows = jnp.int32(batch.num_rows_raw)
        return self._fn(cols, num_rows, batch.padded_len,
                        self._scalars + extra_scalars)


def compile_fused_stages(stages, schema: Schema) -> FusedStageKernel:
    return FusedStageKernel(stages, schema)


def compile_rect_chain(expr, width: int, padded: int, width_cap: int,
                       use_pallas: bool = False):
    """Process-wide compiled kernel for a byte-rectangle string chain
    (upper/trim/substring/... fused over [rows, width]). Previously each
    TpuProjectExec held a private kernel dict, so every query — and
    every bench iteration — re-traced the chain from scratch: the
    string_transforms_100k 17.3 s "warm" cliff. Keyed on the expression
    signature plus the (power-of-two) width/padded buckets, so the
    executable cache actually hits across queries."""
    from ..plan import exec_cache
    from .base import DVal, StrVal
    from .string_rect import eval_rect_chain
    from ..types import STRING

    def build():
        @jax.jit
        def fn(bytes_, lengths, validity, e=expr):
            outv = eval_rect_chain(
                e, DVal(StrVal(bytes_, lengths), validity, STRING),
                width_cap=width_cap, use_pallas=use_pallas)
            return outv.data, outv.validity
        return fn

    key = exec_cache.fused_key(
        exec_cache.digest_of("rect", expr.key()),
        (width, padded, width_cap, use_pallas))
    return _resolve_cached(key, build, label="rect_chain")


@functools.partial(jax.jit, static_argnums=(2,))
def _gather_kernel(arrays, indices, out_len):
    """Gather rows by index (int32[out_len]); index < 0 yields null row."""
    idx = jnp.clip(indices, 0, None)
    null_row = indices < 0
    outs = []
    for data, validity in arrays:
        od = jnp.take(data, idx, mode="clip")
        ov = jnp.logical_and(jnp.take(validity, idx, mode="clip"),
                             jnp.logical_not(null_row))
        outs.append((od, ov))
    return outs


def gather_batch_device(batch: ColumnarBatch, indices, num_rows: int,
                        out_padded: Optional[int] = None) -> ColumnarBatch:
    """Row gather (ref JoinGatherer.scala gather-map application). ``indices``
    may be longer than num_rows (padding); negative index = null output row.
    Host columns gather via Arrow take with the same index map."""
    from ..columnar import HostColumn
    out_p = out_padded if out_padded is not None else int(indices.shape[0])
    dev_pos = [i for i, c in enumerate(batch.columns)
               if isinstance(c, DeviceColumn)]
    arrays, spans = _lane_pairs([(i, batch.columns[i]) for i in dev_pos])
    outs = _gather_kernel(arrays, indices, out_p)
    # num_rows may be a device scalar (speculative sizing) — mask on device
    live = jnp.arange(out_p, dtype=jnp.int64) < jnp.asarray(num_rows)
    outs = [(d, jnp.logical_and(v, live)) for d, v in outs]
    new_cols = list(batch.columns)
    _lane_rebuild(batch, spans, outs, new_cols)
    if len(dev_pos) < len(new_cols):
        import pyarrow as pa
        idx = np.asarray(indices)[:int(num_rows)].astype(np.int64)
        null_row = idx < 0
        pa_idx = pa.array(np.where(null_row, 0, idx), mask=null_row)
        for i, c in enumerate(batch.columns):
            if isinstance(c, HostColumn):
                new_cols[i] = HostColumn(c.array.take(pa_idx), c.dtype)
    return ColumnarBatch(new_cols, num_rows, batch.schema)
