"""Conditional expressions (ref conditionalExpressions.scala: GpuIf,
GpuCaseWhen, GpuCoalesce; nullExpressions GpuNaNvl)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..types import BOOL, DataType, Schema
from .base import DVal, Expression, promote_types
from .arithmetic import arrow_to_masked_numpy, masked_numpy_to_arrow

__all__ = ["If", "CaseWhen", "Coalesce", "NaNvl"]


def _common_type(schema: Schema, exprs) -> DataType:
    dt = None
    for e in exprs:
        edt = e.data_type(schema)
        if edt.name == "void":
            continue
        dt = edt if dt is None else promote_types(dt, edt)
    return dt if dt is not None else exprs[0].data_type(schema)


def _arrow_if_else(pred_arr, true_arr, false_arr):
    """SQL if: null predicate selects the else branch."""
    import pyarrow.compute as pc
    cond = pc.fill_null(pred_arr, False)
    return pc.if_else(cond, true_arr, false_arr)


class If(Expression):
    def __init__(self, pred, if_true, if_false):
        self.children = [pred, if_true, if_false]

    def data_type(self, schema):
        return _common_type(schema, self.children[1:])

    def eval_device(self, ctx):
        dt = self.data_type(ctx.schema)
        p = self.children[0].eval_device(ctx)
        t = self.children[1].eval_device(ctx)
        f = self.children[2].eval_device(ctx)
        # null predicate selects the else branch (SQL semantics)
        cond = jnp.logical_and(p.data, p.validity)
        data = jnp.where(cond, t.data.astype(dt.np_dtype),
                         f.data.astype(dt.np_dtype))
        validity = jnp.where(cond, t.validity, f.validity)
        return DVal(data, validity, dt)

    def eval_host(self, batch):
        dt = self.data_type(batch.schema)
        if dt.np_dtype is None:  # string/nested: pure-arrow path
            return _arrow_if_else(self.children[0].eval_host(batch),
                                  self.children[1].eval_host(batch),
                                  self.children[2].eval_host(batch))
        p, pv = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        t, tv = arrow_to_masked_numpy(self.children[1].eval_host(batch))
        f, fv = arrow_to_masked_numpy(self.children[2].eval_host(batch))
        cond = p.astype(bool) & pv
        np_dt = dt.np_dtype
        data = np.where(cond, t.astype(np_dt), f.astype(np_dt))
        valid = np.where(cond, tv, fv)
        return masked_numpy_to_arrow(data, valid, dt)

    def key(self):
        return ("if(" + ",".join(c.key() for c in self.children) + ")")


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 ... ELSE e END (ref GpuCaseWhen + CaseWhen JNI)."""

    def __init__(self, branches, else_value=None):
        # branches: list of (pred_expr, value_expr)
        self.branches = list(branches)
        self.else_value = else_value
        self.children = [e for p, v in self.branches for e in (p, v)] + (
            [else_value] if else_value is not None else [])

    def data_type(self, schema):
        vals = [v for _, v in self.branches] + (
            [self.else_value] if self.else_value is not None else [])
        return _common_type(schema, vals)

    def _typed_else(self, schema):
        """else branch, with an untyped NULL literal (`otherwise(None)`)
        treated as absent — its value IS the all-null default."""
        from ..types import NULLTYPE
        ev = self.else_value
        if ev is not None and ev.data_type(schema) == NULLTYPE:
            return None
        return ev

    def eval_device(self, ctx):
        dt = self.data_type(ctx.schema)
        np_dt = dt.np_dtype
        ev = self._typed_else(ctx.schema)
        if ev is not None:
            e = ev.eval_device(ctx)
            data, validity = e.data.astype(np_dt), e.validity
        else:
            data = jnp.zeros(ctx.padded_len, dtype=np_dt)
            validity = jnp.zeros(ctx.padded_len, dtype=jnp.bool_)
        # apply branches in reverse so the first match wins
        for pred, val in reversed(self.branches):
            p = pred.eval_device(ctx)
            v = val.eval_device(ctx)
            cond = jnp.logical_and(p.data, p.validity)
            data = jnp.where(cond, v.data.astype(np_dt), data)
            validity = jnp.where(cond, v.validity, validity)
        return DVal(data, validity, dt)

    def eval_host(self, batch):
        dt = self.data_type(batch.schema)
        np_dt = dt.np_dtype
        n = batch.num_rows
        ev = self._typed_else(batch.schema)
        if np_dt is None:  # string/nested: pure-arrow path
            import pyarrow as pa
            from ..types import to_arrow
            if ev is not None:
                acc = ev.eval_host(batch)
            else:
                acc = pa.nulls(n, type=to_arrow(dt))
            for pred, val in reversed(self.branches):
                acc = _arrow_if_else(pred.eval_host(batch),
                                     val.eval_host(batch), acc)
            return acc
        if ev is not None:
            data, valid = arrow_to_masked_numpy(ev.eval_host(batch))
            data = data.astype(np_dt)
        else:
            data = np.zeros(n, dtype=np_dt)
            valid = np.zeros(n, dtype=bool)
        for pred, val in reversed(self.branches):
            p, pv = arrow_to_masked_numpy(pred.eval_host(batch))
            v, vv = arrow_to_masked_numpy(val.eval_host(batch))
            cond = p.astype(bool) & pv
            data = np.where(cond, v.astype(np_dt), data)
            valid = np.where(cond, vv, valid)
        return masked_numpy_to_arrow(data, valid, dt)

    def key(self):
        b = ";".join(f"{p.key()}->{v.key()}" for p, v in self.branches)
        e = self.else_value.key() if self.else_value is not None else ""
        return f"case({b}|{e})"


class Coalesce(Expression):
    def __init__(self, *exprs):
        self.children = list(exprs)

    def data_type(self, schema):
        return _common_type(schema, self.children)

    def eval_device(self, ctx):
        dt = self.data_type(ctx.schema)
        np_dt = dt.np_dtype
        data = jnp.zeros(ctx.padded_len, dtype=np_dt)
        validity = jnp.zeros(ctx.padded_len, dtype=jnp.bool_)
        for child in reversed(self.children):
            c = child.eval_device(ctx)
            data = jnp.where(c.validity, c.data.astype(np_dt), data)
            validity = jnp.logical_or(validity, c.validity)
        return DVal(data, validity, dt)

    def eval_host(self, batch):
        dt = self.data_type(batch.schema)
        np_dt = dt.np_dtype
        if np_dt is None:  # string/nested: pure-arrow path
            import pyarrow.compute as pc
            acc = self.children[0].eval_host(batch)
            for child in self.children[1:]:
                acc = pc.coalesce(acc, child.eval_host(batch))
            return acc
        data = np.zeros(batch.num_rows, dtype=np_dt)
        valid = np.zeros(batch.num_rows, dtype=bool)
        for child in reversed(self.children):
            v, vv = arrow_to_masked_numpy(child.eval_host(batch))
            data = np.where(vv, v.astype(np_dt), data)
            valid = valid | vv
        return masked_numpy_to_arrow(data, valid, dt)

    def key(self):
        return "coalesce(" + ",".join(c.key() for c in self.children) + ")"


class NaNvl(Expression):
    """nanvl(a, b): b where a is NaN (ref GpuNaNvl)."""

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self, schema):
        return _common_type(schema, self.children)

    def eval_device(self, ctx):
        dt = self.data_type(ctx.schema)
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        ld = l.data.astype(dt.np_dtype)
        rd = r.data.astype(dt.np_dtype)
        isnan = jnp.isnan(ld)
        return DVal(jnp.where(isnan, rd, ld),
                    jnp.where(isnan, r.validity, l.validity), dt)

    def eval_host(self, batch):
        dt = self.data_type(batch.schema)
        l, lv = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        r, rv = arrow_to_masked_numpy(self.children[1].eval_host(batch))
        ld = l.astype(dt.np_dtype)
        rd = r.astype(dt.np_dtype)
        isnan = np.isnan(ld)
        return masked_numpy_to_arrow(np.where(isnan, rd, ld),
                                     np.where(isnan, rv, lv), dt)

    def key(self):
        return f"nanvl({self.children[0].key()},{self.children[1].key()})"
