"""Conditional expressions (ref conditionalExpressions.scala: GpuIf,
GpuCaseWhen, GpuCoalesce; nullExpressions GpuNaNvl)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..types import BOOL, DataType, Schema
from .base import DVal, Expression, promote_types
from .arithmetic import arrow_to_masked_numpy, masked_numpy_to_arrow

__all__ = ["NullIf", "If", "CaseWhen", "Coalesce", "NaNvl", "Greatest",
           "Least", "AtLeastNNonNulls", "KnownNotNull",
           "KnownFloatingPointNormalized", "NormalizeNaNAndZero"]


def _common_type(schema: Schema, exprs) -> DataType:
    dt = None
    for e in exprs:
        edt = e.data_type(schema)
        if edt.name == "void":
            continue
        dt = edt if dt is None else promote_types(dt, edt)
    return dt if dt is not None else exprs[0].data_type(schema)


def _arrow_if_else(pred_arr, true_arr, false_arr):
    """SQL if: null predicate selects the else branch."""
    import pyarrow.compute as pc
    cond = pc.fill_null(pred_arr, False)
    return pc.if_else(cond, true_arr, false_arr)


class If(Expression):
    def __init__(self, pred, if_true, if_false):
        self.children = [pred, if_true, if_false]

    def data_type(self, schema):
        return _common_type(schema, self.children[1:])

    def eval_device(self, ctx):
        dt = self.data_type(ctx.schema)
        p = self.children[0].eval_device(ctx)
        t = self.children[1].eval_device(ctx)
        f = self.children[2].eval_device(ctx)
        # null predicate selects the else branch (SQL semantics)
        cond = jnp.logical_and(p.data, p.validity)
        data = jnp.where(cond, t.data.astype(dt.np_dtype),
                         f.data.astype(dt.np_dtype))
        validity = jnp.where(cond, t.validity, f.validity)
        return DVal(data, validity, dt)

    def eval_host(self, batch):
        dt = self.data_type(batch.schema)
        if dt.np_dtype is None:  # string/nested: pure-arrow path
            return _arrow_if_else(self.children[0].eval_host(batch),
                                  self.children[1].eval_host(batch),
                                  self.children[2].eval_host(batch))
        p, pv = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        t, tv = arrow_to_masked_numpy(self.children[1].eval_host(batch))
        f, fv = arrow_to_masked_numpy(self.children[2].eval_host(batch))
        cond = p.astype(bool) & pv
        np_dt = dt.np_dtype
        data = np.where(cond, t.astype(np_dt), f.astype(np_dt))
        valid = np.where(cond, tv, fv)
        return masked_numpy_to_arrow(data, valid, dt)

    def key(self):
        return ("if(" + ",".join(c.key() for c in self.children) + ")")


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 ... ELSE e END (ref GpuCaseWhen + CaseWhen JNI)."""

    def __init__(self, branches, else_value=None):
        # branches: list of (pred_expr, value_expr)
        self.branches = list(branches)
        self.else_value = else_value
        self.children = [e for p, v in self.branches for e in (p, v)] + (
            [else_value] if else_value is not None else [])

    def data_type(self, schema):
        vals = [v for _, v in self.branches] + (
            [self.else_value] if self.else_value is not None else [])
        return _common_type(schema, vals)

    def _typed_else(self, schema):
        """else branch, with an untyped NULL literal (`otherwise(None)`)
        treated as absent — its value IS the all-null default."""
        from ..types import NULLTYPE
        ev = self.else_value
        if ev is not None and ev.data_type(schema) == NULLTYPE:
            return None
        return ev

    def eval_device(self, ctx):
        dt = self.data_type(ctx.schema)
        np_dt = dt.np_dtype
        ev = self._typed_else(ctx.schema)
        if ev is not None:
            e = ev.eval_device(ctx)
            data, validity = e.data.astype(np_dt), e.validity
        else:
            data = jnp.zeros(ctx.padded_len, dtype=np_dt)
            validity = jnp.zeros(ctx.padded_len, dtype=jnp.bool_)
        # apply branches in reverse so the first match wins
        for pred, val in reversed(self.branches):
            p = pred.eval_device(ctx)
            v = val.eval_device(ctx)
            cond = jnp.logical_and(p.data, p.validity)
            data = jnp.where(cond, v.data.astype(np_dt), data)
            validity = jnp.where(cond, v.validity, validity)
        return DVal(data, validity, dt)

    def eval_host(self, batch):
        dt = self.data_type(batch.schema)
        np_dt = dt.np_dtype
        n = batch.num_rows
        ev = self._typed_else(batch.schema)
        if np_dt is None:  # string/nested: pure-arrow path
            import pyarrow as pa
            from ..types import to_arrow
            if ev is not None:
                acc = ev.eval_host(batch)
            else:
                acc = pa.nulls(n, type=to_arrow(dt))
            for pred, val in reversed(self.branches):
                acc = _arrow_if_else(pred.eval_host(batch),
                                     val.eval_host(batch), acc)
            return acc
        if ev is not None:
            data, valid = arrow_to_masked_numpy(ev.eval_host(batch))
            data = data.astype(np_dt)
        else:
            data = np.zeros(n, dtype=np_dt)
            valid = np.zeros(n, dtype=bool)
        for pred, val in reversed(self.branches):
            p, pv = arrow_to_masked_numpy(pred.eval_host(batch))
            v, vv = arrow_to_masked_numpy(val.eval_host(batch))
            cond = p.astype(bool) & pv
            data = np.where(cond, v.astype(np_dt), data)
            valid = np.where(cond, vv, valid)
        return masked_numpy_to_arrow(data, valid, dt)

    def key(self):
        b = ";".join(f"{p.key()}->{v.key()}" for p, v in self.branches)
        e = self.else_value.key() if self.else_value is not None else ""
        return f"case({b}|{e})"


class NullIf(Expression):
    """nullif(a, b): NULL when a == b (both non-null), else a (ref
    GpuNullIf / Spark's NullIf runtime replacement)."""

    def __init__(self, a, b):
        self.children = [a, b]

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def _eq(self):
        # Spark's `=` semantics verbatim — type promotion and NaN == NaN
        # (comparison.py _nan_eq); hand-rolled ==/pc.equal diverges on
        # both (r5 review findings)
        from .comparison import EqualTo
        return EqualTo(self.children[0], self.children[1])

    def eval_device(self, ctx):
        a = self.children[0].eval_device(ctx)
        e = self._eq().eval_device(ctx)
        eq = jnp.logical_and(e.data, e.validity)
        return DVal(a.data, jnp.logical_and(a.validity,
                                            jnp.logical_not(eq)),
                    self.data_type(ctx.schema))

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        a = self.children[0].eval_host(batch)
        eq = pc.fill_null(self._eq().eval_host(batch), False)
        return pc.if_else(eq, pa.nulls(len(a), type=a.type), a)

    def key(self):
        return (f"nullif({self.children[0].key()},"
                f"{self.children[1].key()})")


class Coalesce(Expression):
    def __init__(self, *exprs):
        self.children = list(exprs)

    def data_type(self, schema):
        return _common_type(schema, self.children)

    def eval_device(self, ctx):
        dt = self.data_type(ctx.schema)
        np_dt = dt.np_dtype
        data = jnp.zeros(ctx.padded_len, dtype=np_dt)
        validity = jnp.zeros(ctx.padded_len, dtype=jnp.bool_)
        for child in reversed(self.children):
            c = child.eval_device(ctx)
            data = jnp.where(c.validity, c.data.astype(np_dt), data)
            validity = jnp.logical_or(validity, c.validity)
        return DVal(data, validity, dt)

    def eval_host(self, batch):
        dt = self.data_type(batch.schema)
        np_dt = dt.np_dtype
        if np_dt is None:  # string/nested: pure-arrow path
            import pyarrow.compute as pc
            acc = self.children[0].eval_host(batch)
            for child in self.children[1:]:
                acc = pc.coalesce(acc, child.eval_host(batch))
            return acc
        data = np.zeros(batch.num_rows, dtype=np_dt)
        valid = np.zeros(batch.num_rows, dtype=bool)
        for child in reversed(self.children):
            v, vv = arrow_to_masked_numpy(child.eval_host(batch))
            data = np.where(vv, v.astype(np_dt), data)
            valid = valid | vv
        return masked_numpy_to_arrow(data, valid, dt)

    def key(self):
        return "coalesce(" + ",".join(c.key() for c in self.children) + ")"


class NaNvl(Expression):
    """nanvl(a, b): b where a is NaN (ref GpuNaNvl)."""

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self, schema):
        return _common_type(schema, self.children)

    def eval_device(self, ctx):
        dt = self.data_type(ctx.schema)
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        ld = l.data.astype(dt.np_dtype)
        rd = r.data.astype(dt.np_dtype)
        isnan = jnp.isnan(ld)
        return DVal(jnp.where(isnan, rd, ld),
                    jnp.where(isnan, r.validity, l.validity), dt)

    def eval_host(self, batch):
        dt = self.data_type(batch.schema)
        l, lv = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        r, rv = arrow_to_masked_numpy(self.children[1].eval_host(batch))
        ld = l.astype(dt.np_dtype)
        rd = r.astype(dt.np_dtype)
        isnan = np.isnan(ld)
        return masked_numpy_to_arrow(np.where(isnan, rd, ld),
                                     np.where(isnan, rv, lv), dt)

    def key(self):
        return f"nanvl({self.children[0].key()},{self.children[1].key()})"


class _NarySelect(Expression):
    """Base for greatest/least: n-ary, NULLs skipped, NULL only when every
    operand is NULL; NaN orders greatest (Spark total order — ref
    arithmetic.scala GpuGreatest/GpuLeast)."""

    _is_max = True

    def __init__(self, *children):
        assert len(children) >= 2, "greatest/least need >= 2 args"
        self.children = list(children)

    def data_type(self, schema: Schema) -> DataType:
        return _common_type(schema, self.children)

    def _sentinels(self, np_dt):
        if np.issubdtype(np_dt, np.floating):
            # NaN sorts GREATEST in Spark: max starts below NaN handling
            lo, hi = -np.inf, np.inf
        elif np_dt == np.bool_:
            lo, hi = False, True
        else:
            info = np.iinfo(np_dt)
            lo, hi = info.min, info.max
        return (lo, hi) if self._is_max else (hi, lo)

    def eval_device(self, ctx):
        dt = self.data_type(ctx.schema)
        np_dt = dt.np_dtype
        skip, _ = self._sentinels(np_dt)
        acc = None
        any_valid = None
        any_nan = None
        any_nonnan = None
        is_float = np.issubdtype(np_dt, np.floating)
        for c in self.children:
            v = c.eval_device(ctx)
            d = v.data.astype(np_dt)
            if is_float:
                nan_here = jnp.logical_and(jnp.isnan(d), v.validity)
                nonnan_here = jnp.logical_and(~jnp.isnan(d), v.validity)
                any_nan = nan_here if any_nan is None else \
                    jnp.logical_or(any_nan, nan_here)
                any_nonnan = nonnan_here if any_nonnan is None else \
                    jnp.logical_or(any_nonnan, nonnan_here)
                d = jnp.where(jnp.isnan(d), jnp.asarray(skip, np_dt), d)
            d = jnp.where(v.validity, d, jnp.asarray(skip, np_dt))
            acc = d if acc is None else (
                jnp.maximum(acc, d) if self._is_max else jnp.minimum(acc, d))
            any_valid = v.validity if any_valid is None else \
                jnp.logical_or(any_valid, v.validity)
        if is_float and self._is_max and any_nan is not None:
            acc = jnp.where(any_nan, jnp.asarray(np.nan, np_dt), acc)
        elif is_float and not self._is_max and any_nan is not None:
            # least: NaN only wins when NO valid operand is non-NaN
            # (a real +inf operand must not be mistaken for the sentinel)
            acc = jnp.where(jnp.logical_and(any_nan, ~any_nonnan),
                            jnp.asarray(np.nan, np_dt), acc)
        return DVal(acc, any_valid, dt)

    def eval_host(self, batch):
        dt = self.data_type(batch.schema)
        np_dt = dt.np_dtype
        skip, _ = self._sentinels(np_dt)
        is_float = np.issubdtype(np_dt, np.floating)
        acc = None
        any_valid = None
        any_nan = None
        any_nonnan = None
        for c in self.children:
            v, ok = arrow_to_masked_numpy(c.eval_host(batch))
            d = v.astype(np_dt)
            if is_float:
                nan_here = np.isnan(d) & ok
                any_nan = nan_here if any_nan is None else (any_nan | nan_here)
                nn = ~np.isnan(d) & ok
                any_nonnan = nn if any_nonnan is None else (any_nonnan | nn)
                d = np.where(np.isnan(d), skip, d)
            d = np.where(ok, d, skip)
            acc = d if acc is None else (
                np.maximum(acc, d) if self._is_max else np.minimum(acc, d))
            any_valid = ok if any_valid is None else (any_valid | ok)
        if is_float and self._is_max and any_nan is not None:
            acc = np.where(any_nan, np.nan, acc)
        elif is_float and not self._is_max and any_nan is not None:
            # see eval_device: NaN wins only when no valid non-NaN exists
            acc = np.where(any_nan & ~any_nonnan, np.nan, acc)
        return masked_numpy_to_arrow(acc, any_valid, dt)

    def key(self):
        kids = ",".join(c.key() for c in self.children)
        return f"{type(self).__name__}({kids})"


class Greatest(_NarySelect):
    _is_max = True


class Least(_NarySelect):
    _is_max = False


class AtLeastNNonNulls(Expression):
    """True when at least n children are non-null AND non-NaN (Spark's
    df.na.drop support expression — ref GpuAtLeastNNonNulls)."""

    def __init__(self, n: int, *children):
        self.n = int(n)
        self.children = list(children)

    def data_type(self, schema: Schema) -> DataType:
        return BOOL

    def nullable(self, schema):
        return False

    def eval_device(self, ctx):
        cnt = None
        for c in self.children:
            v = c.eval_device(ctx)
            good = v.validity
            if jnp.issubdtype(v.data.dtype, jnp.floating):
                good = jnp.logical_and(good, ~jnp.isnan(v.data))
            g = good.astype(jnp.int32)
            cnt = g if cnt is None else cnt + g
        data = cnt >= self.n if cnt is not None else \
            jnp.full(ctx.padded_len, self.n <= 0)
        return DVal(data, jnp.ones(ctx.padded_len, jnp.bool_), BOOL)

    def eval_host(self, batch):
        cnt = np.zeros(batch.num_rows, np.int32)
        for c in self.children:
            v, ok = arrow_to_masked_numpy(c.eval_host(batch))
            good = ok.copy()
            if np.issubdtype(np.asarray(v).dtype, np.floating):
                good &= ~np.isnan(v)
            cnt += good
        return masked_numpy_to_arrow(cnt >= self.n,
                                     np.ones(batch.num_rows, bool), BOOL)

    def key(self):
        kids = ",".join(c.key() for c in self.children)
        return f"AtLeastNNonNulls({self.n};{kids})"


class _IdentityHint(Expression):
    """Catalyst optimizer-hint wrappers: evaluate to the child unchanged
    (ref GpuKnownNotNull / GpuKnownFloatingPointNormalized)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema: Schema) -> DataType:
        return self.children[0].data_type(schema)

    def eval_device(self, ctx):
        return self.children[0].eval_device(ctx)

    def eval_host(self, batch):
        return self.children[0].eval_host(batch)

    def key(self):
        return f"{type(self).__name__}({self.children[0].key()})"


class KnownNotNull(_IdentityHint):
    def nullable(self, schema):
        return False


class KnownFloatingPointNormalized(_IdentityHint):
    pass


class NormalizeNaNAndZero(Expression):
    """Canonicalize -0.0 -> 0.0 and every NaN payload -> one canonical NaN
    so grouping/join keys compare consistently (ref
    NormalizeFloatingNumbers.scala / GpuNormalizeNaNAndZero)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema: Schema) -> DataType:
        return self.children[0].data_type(schema)

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        d = v.data
        if jnp.issubdtype(d.dtype, jnp.floating):
            # NOT `d + 0.0`: XLA algebraically folds that away under jit
            # and -0.0 would survive; -0.0 == 0 is True so where() works
            d = jnp.where(jnp.isnan(d), jnp.asarray(jnp.nan, d.dtype),
                          jnp.where(d == 0, jnp.asarray(0.0, d.dtype), d))
        return DVal(d, v.validity, v.dtype)

    def eval_host(self, batch):
        v, ok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        if np.issubdtype(np.asarray(v).dtype, np.floating):
            v = np.where(np.isnan(v), np.nan, np.where(v == 0, 0.0, v))
        return masked_numpy_to_arrow(v, ok,
                                     self.data_type(batch.schema))

    def key(self):
        return f"normnanzero({self.children[0].key()})"
