"""Date/time expressions (ref datetimeExpressions.scala, 1,283 LoC;
DateTimeRebase / GpuTimeZoneDB JNI for the reference — here dates are
int32 days and timestamps int64 UTC micros, and field extraction is pure
integer civil-calendar arithmetic (Hinnant's algorithm) fused into the
expression kernel — no lookup tables, VPU-friendly."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..types import DATE, INT32, INT64, TIMESTAMP, Schema, TypeSig, TypeEnum
from .base import DVal, Expression, null_and

__all__ = ["Year", "Month", "DayOfMonth", "Hour", "Minute", "Second",
           "DayOfWeek", "WeekDay", "DayOfYear", "Quarter", "DateAdd",
           "DateSub", "DateDiff", "UnixDate", "civil_from_days"]

_MICROS_PER_DAY = 86_400_000_000
_date_sig = TypeSig([TypeEnum.DATE, TypeEnum.TIMESTAMP])


def _days_of(v: DVal):
    """DVal (date or timestamp) -> int32 days since epoch."""
    if v.dtype == TIMESTAMP:
        return jnp.floor_divide(v.data, _MICROS_PER_DAY).astype(jnp.int32)
    return v.data.astype(jnp.int32)


def civil_from_days(days):
    """days since 1970-01-01 -> (year, month, day), vectorized integer ops
    (Howard Hinnant's civil_from_days, public-domain algorithm)."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36524)
        - jnp.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4)
                 - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    year = y + (m <= 2)
    return year.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


class _DateField(Expression):
    device_type_sig = _date_sig
    pa_fn = None  # pyarrow.compute function name for host eval

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self, schema: Schema):
        return INT32

    def _field(self, year, month, day, v):
        raise NotImplementedError

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        days = _days_of(v)
        y, m, d = civil_from_days(days)
        return DVal(self._field(y, m, d, v), v.validity, INT32)

    def eval_host(self, batch):
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        import pyarrow as pa
        return pc.cast(getattr(pc, self.pa_fn)(arr), pa.int32())

    def key(self):
        return f"{type(self).__name__.lower()}({self.children[0].key()})"


class Year(_DateField):
    pa_fn = "year"

    def _field(self, y, m, d, v):
        return y


class Month(_DateField):
    pa_fn = "month"

    def _field(self, y, m, d, v):
        return m


class DayOfMonth(_DateField):
    pa_fn = "day"

    def _field(self, y, m, d, v):
        return d


class Quarter(_DateField):
    pa_fn = "quarter"

    def _field(self, y, m, d, v):
        return jnp.floor_divide(m + 2, 3).astype(jnp.int32)


class DayOfWeek(_DateField):
    """Spark dayofweek: 1 = Sunday ... 7 = Saturday."""

    def _field(self, y, m, d, v):
        days = _days_of(v)
        # 1970-01-01 was a Thursday (dow 4 with Sunday=0 -> Thursday=4)
        return (jnp.fmod(jnp.fmod(days + 4, 7) + 7, 7) + 1).astype(jnp.int32)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        # arrow day_of_week: Monday=0..Sunday=6 -> Spark Sunday=1..Saturday=7
        dow = pc.day_of_week(arr, count_from_zero=True, week_start=1)
        shifted = pc.add(dow, 2)  # Monday->3 ... Sunday->8
        return pc.cast(pc.if_else(pc.greater(shifted, 7),
                                  pc.subtract(shifted, 7), shifted),
                       pa.int32())


class WeekDay(_DateField):
    """Spark weekday: 0 = Monday ... 6 = Sunday."""

    def _field(self, y, m, d, v):
        days = _days_of(v)
        return jnp.fmod(jnp.fmod(days + 3, 7) + 7, 7).astype(jnp.int32)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        return pc.cast(pc.day_of_week(arr, count_from_zero=True,
                                      week_start=1), pa.int32())


class DayOfYear(_DateField):
    pa_fn = "day_of_year"

    def _field(self, y, m, d, v):
        days = _days_of(v)
        jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        return (days.astype(jnp.int64) - jan1 + 1).astype(jnp.int32)


def _days_from_civil(y, m, d):
    """(year, month, day) -> days since epoch (inverse of civil_from_days)."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.fmod(m + 9, 12)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100) \
        + doy
    return era * 146097 + doe - 719468


class _TimeField(Expression):
    device_type_sig = TypeSig([TypeEnum.TIMESTAMP])
    divisor = 1
    modulo = 60
    pa_fn = None

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self, schema):
        return INT32

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        micros_in_day = v.data - jnp.floor_divide(
            v.data, _MICROS_PER_DAY) * _MICROS_PER_DAY
        out = jnp.fmod(jnp.floor_divide(micros_in_day, self.divisor),
                       self.modulo)
        return DVal(out.astype(jnp.int32), v.validity, INT32)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        return pc.cast(getattr(pc, self.pa_fn)(arr), pa.int32())

    def key(self):
        return f"{type(self).__name__.lower()}({self.children[0].key()})"


class Hour(_TimeField):
    divisor = 3_600_000_000
    modulo = 24
    pa_fn = "hour"


class Minute(_TimeField):
    divisor = 60_000_000
    modulo = 60
    pa_fn = "minute"


class Second(_TimeField):
    divisor = 1_000_000
    modulo = 60
    pa_fn = "second"


class DateAdd(Expression):
    """date_add(date, days) -> date (ref GpuDateAdd)."""
    device_type_sig = TypeSig([TypeEnum.DATE, TypeEnum.BYTE, TypeEnum.SHORT,
                               TypeEnum.INT])

    def __init__(self, date: Expression, days: Expression, sub: bool = False):
        self.children = [date, days]
        self.sub = sub

    def data_type(self, schema):
        return DATE

    def eval_device(self, ctx):
        d = self.children[0].eval_device(ctx)
        n = self.children[1].eval_device(ctx)
        delta = n.data.astype(jnp.int32)
        out = d.data + (-delta if self.sub else delta)
        return DVal(out, null_and(d.validity, n.validity), DATE)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        d = self.children[0].eval_host(batch)
        n = self.children[1].eval_host(batch)
        di = pc.cast(d, pa.int32())
        ni = pc.cast(n, pa.int32())
        out = pc.subtract(di, ni) if self.sub else pc.add(di, ni)
        return pc.cast(out, pa.date32())

    def key(self):
        op = "date_sub" if self.sub else "date_add"
        return f"{op}({self.children[0].key()},{self.children[1].key()})"


def DateSub(date, days):
    return DateAdd(date, days, sub=True)


class DateDiff(Expression):
    """datediff(end, start) -> int days."""
    device_type_sig = TypeSig([TypeEnum.DATE])

    def __init__(self, end: Expression, start: Expression):
        self.children = [end, start]

    def data_type(self, schema):
        return INT32

    def eval_device(self, ctx):
        e = self.children[0].eval_device(ctx)
        s = self.children[1].eval_device(ctx)
        return DVal(e.data.astype(jnp.int32) - s.data.astype(jnp.int32),
                    null_and(e.validity, s.validity), INT32)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        e = pc.cast(self.children[0].eval_host(batch), pa.int32())
        s = pc.cast(self.children[1].eval_host(batch), pa.int32())
        return pc.subtract(e, s)

    def key(self):
        return f"datediff({self.children[0].key()},{self.children[1].key()})"


class UnixDate(Expression):
    """unix_date(date) -> int32 days since epoch."""
    device_type_sig = TypeSig([TypeEnum.DATE])

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return INT32

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        return DVal(v.data.astype(jnp.int32), v.validity, INT32)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        return pc.cast(self.children[0].eval_host(batch), pa.int32())

    def key(self):
        return f"unix_date({self.children[0].key()})"


class _TzConvert(Expression):
    """from/to_utc_timestamp (ref GpuTimeZoneDB JNI + TimeZoneDB.scala).
    Named-zone DST rules come from the host's IANA database (zoneinfo) —
    timestamps are micros-since-epoch internally, so conversion is an
    offset add computed per row on the host."""

    def __init__(self, child: Expression, tz: str, to_utc: bool):
        import zoneinfo
        self.children = [child]
        self.tz = tz
        self.to_utc = to_utc
        try:
            self._zone = zoneinfo.ZoneInfo(tz)
        except (KeyError, zoneinfo.ZoneInfoNotFoundError):
            raise ValueError(f"unknown timezone: {tz}")

    def data_type(self, schema):
        return TIMESTAMP

    def device_unsupported_reason(self, schema):
        return (f"{type(self).__name__}: named-timezone DST rules are "
                "host-resident (ref GpuTimeZoneDB)")

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        naive = arr.cast(pa.timestamp("us"))
        if self.to_utc:
            # interpret the naive timestamp as wall time in tz; arrow's
            # assume_timezone applies the zone's DST rules vectorized
            aware = pc.assume_timezone(naive, self.tz,
                                       ambiguous="earliest",
                                       nonexistent="earliest")
            return aware.cast(pa.int64()).cast(pa.timestamp("us"))
        # UTC instant -> wall time in tz
        aware = naive.cast(pa.int64()).cast(pa.timestamp("us", tz=self.tz))
        return pc.local_timestamp(aware)

    def key(self):
        return (f"{type(self).__name__}({self.children[0].key()},"
                f"{self.tz})")


class FromUtcTimestamp(_TzConvert):
    def __init__(self, child, tz):
        super().__init__(child, tz, to_utc=False)

    @property
    def name_hint(self):
        return f"from_utc_timestamp({self.children[0].name_hint},{self.tz})"


class ToUtcTimestamp(_TzConvert):
    def __init__(self, child, tz):
        super().__init__(child, tz, to_utc=True)

    @property
    def name_hint(self):
        return f"to_utc_timestamp({self.children[0].name_hint},{self.tz})"
