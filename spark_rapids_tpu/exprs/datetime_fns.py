"""Date/time expressions (ref datetimeExpressions.scala, 1,283 LoC;
DateTimeRebase / GpuTimeZoneDB JNI for the reference — here dates are
int32 days and timestamps int64 UTC micros, and field extraction is pure
integer civil-calendar arithmetic (Hinnant's algorithm) fused into the
expression kernel — no lookup tables, VPU-friendly."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..types import (DATE, FLOAT64, INT32, INT64, STRING, TIMESTAMP,
                     Schema, TypeSig, TypeEnum)
from .base import DVal, Expression, Unsupported, null_and

__all__ = ["DateAddInterval", "Year", "Month", "DayOfMonth", "Hour", "Minute", "Second",
           "DayOfWeek", "WeekDay", "DayOfYear", "Quarter", "DateAdd",
           "DateSub", "DateDiff", "UnixDate", "civil_from_days",
           "LastDay", "AddMonths", "MonthsBetween", "SecondsToTimestamp",
           "MillisToTimestamp", "MicrosToTimestamp", "ToUnixTimestamp",
           "UnixTimestamp", "FromUnixTime", "DateFormatClass", "TimeAdd",
           "TruncDate"]

_MICROS_PER_DAY = 86_400_000_000
_date_sig = TypeSig([TypeEnum.DATE, TypeEnum.TIMESTAMP])


def _days_of(v: DVal):
    """DVal (date or timestamp) -> int32 days since epoch."""
    if v.dtype == TIMESTAMP:
        return jnp.floor_divide(v.data, _MICROS_PER_DAY).astype(jnp.int32)
    return v.data.astype(jnp.int32)


def civil_from_days(days):
    """days since 1970-01-01 -> (year, month, day), vectorized integer ops
    (Howard Hinnant's civil_from_days, public-domain algorithm)."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36524)
        - jnp.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4)
                 - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    year = y + (m <= 2)
    return year.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


class _DateField(Expression):
    device_type_sig = _date_sig
    pa_fn = None  # pyarrow.compute function name for host eval

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self, schema: Schema):
        return INT32

    def _field(self, year, month, day, v):
        raise NotImplementedError

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        days = _days_of(v)
        y, m, d = civil_from_days(days)
        return DVal(self._field(y, m, d, v), v.validity, INT32)

    def eval_host(self, batch):
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        import pyarrow as pa
        return pc.cast(getattr(pc, self.pa_fn)(arr), pa.int32())

    def key(self):
        return f"{type(self).__name__.lower()}({self.children[0].key()})"


class Year(_DateField):
    pa_fn = "year"

    def _field(self, y, m, d, v):
        return y


class Month(_DateField):
    pa_fn = "month"

    def _field(self, y, m, d, v):
        return m


class DayOfMonth(_DateField):
    pa_fn = "day"

    def _field(self, y, m, d, v):
        return d


class Quarter(_DateField):
    pa_fn = "quarter"

    def _field(self, y, m, d, v):
        return jnp.floor_divide(m + 2, 3).astype(jnp.int32)


class DayOfWeek(_DateField):
    """Spark dayofweek: 1 = Sunday ... 7 = Saturday."""

    def _field(self, y, m, d, v):
        days = _days_of(v)
        # 1970-01-01 was a Thursday (dow 4 with Sunday=0 -> Thursday=4)
        return (jnp.fmod(jnp.fmod(days + 4, 7) + 7, 7) + 1).astype(jnp.int32)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        # arrow day_of_week: Monday=0..Sunday=6 -> Spark Sunday=1..Saturday=7
        dow = pc.day_of_week(arr, count_from_zero=True, week_start=1)
        shifted = pc.add(dow, 2)  # Monday->3 ... Sunday->8
        return pc.cast(pc.if_else(pc.greater(shifted, 7),
                                  pc.subtract(shifted, 7), shifted),
                       pa.int32())


class WeekDay(_DateField):
    """Spark weekday: 0 = Monday ... 6 = Sunday."""

    def _field(self, y, m, d, v):
        days = _days_of(v)
        return jnp.fmod(jnp.fmod(days + 3, 7) + 7, 7).astype(jnp.int32)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        return pc.cast(pc.day_of_week(arr, count_from_zero=True,
                                      week_start=1), pa.int32())


class DayOfYear(_DateField):
    pa_fn = "day_of_year"

    def _field(self, y, m, d, v):
        days = _days_of(v)
        jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        return (days.astype(jnp.int64) - jan1 + 1).astype(jnp.int32)


def _days_from_civil(y, m, d):
    """(year, month, day) -> days since epoch (inverse of civil_from_days)."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.fmod(m + 9, 12)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100) \
        + doy
    return era * 146097 + doe - 719468


class _TimeField(Expression):
    device_type_sig = TypeSig([TypeEnum.TIMESTAMP])
    divisor = 1
    modulo = 60
    pa_fn = None

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self, schema):
        return INT32

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        micros_in_day = v.data - jnp.floor_divide(
            v.data, _MICROS_PER_DAY) * _MICROS_PER_DAY
        out = jnp.fmod(jnp.floor_divide(micros_in_day, self.divisor),
                       self.modulo)
        return DVal(out.astype(jnp.int32), v.validity, INT32)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        return pc.cast(getattr(pc, self.pa_fn)(arr), pa.int32())

    def key(self):
        return f"{type(self).__name__.lower()}({self.children[0].key()})"


class Hour(_TimeField):
    divisor = 3_600_000_000
    modulo = 24
    pa_fn = "hour"


class Minute(_TimeField):
    divisor = 60_000_000
    modulo = 60
    pa_fn = "minute"


class Second(_TimeField):
    divisor = 1_000_000
    modulo = 60
    pa_fn = "second"


class DateAdd(Expression):
    """date_add(date, days) -> date (ref GpuDateAdd)."""
    device_type_sig = TypeSig([TypeEnum.DATE, TypeEnum.BYTE, TypeEnum.SHORT,
                               TypeEnum.INT])

    def __init__(self, date: Expression, days: Expression, sub: bool = False):
        self.children = [date, days]
        self.sub = sub

    def data_type(self, schema):
        return DATE

    def eval_device(self, ctx):
        d = self.children[0].eval_device(ctx)
        n = self.children[1].eval_device(ctx)
        delta = n.data.astype(jnp.int32)
        out = d.data + (-delta if self.sub else delta)
        return DVal(out, null_and(d.validity, n.validity), DATE)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        d = self.children[0].eval_host(batch)
        n = self.children[1].eval_host(batch)
        di = pc.cast(d, pa.int32())
        ni = pc.cast(n, pa.int32())
        out = pc.subtract(di, ni) if self.sub else pc.add(di, ni)
        return pc.cast(out, pa.date32())

    def key(self):
        op = "date_sub" if self.sub else "date_add"
        return f"{op}({self.children[0].key()},{self.children[1].key()})"


def DateSub(date, days):
    return DateAdd(date, days, sub=True)


class DateDiff(Expression):
    """datediff(end, start) -> int days."""
    device_type_sig = TypeSig([TypeEnum.DATE])

    def __init__(self, end: Expression, start: Expression):
        self.children = [end, start]

    def data_type(self, schema):
        return INT32

    def eval_device(self, ctx):
        e = self.children[0].eval_device(ctx)
        s = self.children[1].eval_device(ctx)
        return DVal(e.data.astype(jnp.int32) - s.data.astype(jnp.int32),
                    null_and(e.validity, s.validity), INT32)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        e = pc.cast(self.children[0].eval_host(batch), pa.int32())
        s = pc.cast(self.children[1].eval_host(batch), pa.int32())
        return pc.subtract(e, s)

    def key(self):
        return f"datediff({self.children[0].key()},{self.children[1].key()})"


class UnixDate(Expression):
    """unix_date(date) -> int32 days since epoch."""
    device_type_sig = TypeSig([TypeEnum.DATE])

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return INT32

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        return DVal(v.data.astype(jnp.int32), v.validity, INT32)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        return pc.cast(self.children[0].eval_host(batch), pa.int32())

    def key(self):
        return f"unix_date({self.children[0].key()})"


class _TzConvert(Expression):
    """from/to_utc_timestamp (ref GpuTimeZoneDB JNI + TimeZoneDB.scala).
    Named-zone DST rules come from the host's IANA database (zoneinfo) —
    timestamps are micros-since-epoch internally, so conversion is an
    offset add computed per row on the host."""

    def __init__(self, child: Expression, tz: str, to_utc: bool):
        import zoneinfo
        self.children = [child]
        self.tz = tz
        self.to_utc = to_utc
        try:
            self._zone = zoneinfo.ZoneInfo(tz)
        except (KeyError, zoneinfo.ZoneInfoNotFoundError):
            raise ValueError(f"unknown timezone: {tz}")

    def data_type(self, schema):
        return TIMESTAMP

    def device_unsupported_reason(self, schema):
        return (f"{type(self).__name__}: named-timezone DST rules are "
                "host-resident (ref GpuTimeZoneDB)")

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        naive = arr.cast(pa.timestamp("us"))
        if self.to_utc:
            # interpret the naive timestamp as wall time in tz; arrow's
            # assume_timezone applies the zone's DST rules vectorized
            aware = pc.assume_timezone(naive, self.tz,
                                       ambiguous="earliest",
                                       nonexistent="earliest")
            return aware.cast(pa.int64()).cast(pa.timestamp("us"))
        # UTC instant -> wall time in tz
        aware = naive.cast(pa.int64()).cast(pa.timestamp("us", tz=self.tz))
        return pc.local_timestamp(aware)

    def key(self):
        return (f"{type(self).__name__}({self.children[0].key()},"
                f"{self.tz})")


class FromUtcTimestamp(_TzConvert):
    def __init__(self, child, tz):
        super().__init__(child, tz, to_utc=False)

    @property
    def name_hint(self):
        return f"from_utc_timestamp({self.children[0].name_hint},{self.tz})"


class ToUtcTimestamp(_TzConvert):
    def __init__(self, child, tz):
        super().__init__(child, tz, to_utc=True)

    @property
    def name_hint(self):
        return f"to_utc_timestamp({self.children[0].name_hint},{self.tz})"


def _days_in_month(year, month):
    import jax.numpy as jnp
    leap = jnp.logical_and(year % 4 == 0,
                           jnp.logical_or(year % 100 != 0, year % 400 == 0))
    base = jnp.asarray(
        np.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                 dtype=np.int32))
    dim = jnp.take(base, jnp.clip(month - 1, 0, 11))
    return jnp.where(jnp.logical_and(month == 2, leap), 29, dim)


class LastDay(Expression):
    """last_day(date): last day of the input's month (ref GpuLastDay)."""
    device_type_sig = TypeSig([TypeEnum.DATE])

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return DATE

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        days = _days_of(v)
        y, m, d = civil_from_days(days)
        out = days + (_days_in_month(y, m) - d).astype(jnp.int32)
        return DVal(out, v.validity, DATE)

    def eval_host(self, batch):
        import pyarrow as pa
        vals = self.children[0].eval_host(batch).to_pylist()
        import calendar
        out = []
        for d in vals:
            if d is None:
                out.append(None)
            else:
                out.append(d.replace(
                    day=calendar.monthrange(d.year, d.month)[1]))
        return pa.array(out, type=pa.date32())

    def key(self):
        return f"last_day({self.children[0].key()})"


class AddMonths(Expression):
    """add_months(date, n): calendar month arithmetic with day clamped to
    the target month's end (ref GpuAddMonths)."""
    device_type_sig = TypeSig([TypeEnum.DATE, TypeEnum.BYTE, TypeEnum.SHORT,
                               TypeEnum.INT])

    def __init__(self, date, months):
        self.children = [date, months]

    def data_type(self, schema):
        return DATE

    def eval_host(self, batch):
        import pyarrow as pa
        ds = self.children[0].eval_host(batch).to_pylist()
        ns = self.children[1].eval_host(batch).to_pylist()
        import calendar
        import datetime
        out = []
        for d, n in zip(ds, ns):
            if d is None or n is None:
                out.append(None)
                continue
            t = d.year * 12 + (d.month - 1) + int(n)
            y, m = divmod(t, 12)
            m += 1
            day = min(d.day, calendar.monthrange(y, m)[1])
            out.append(datetime.date(y, m, day))
        return pa.array(out, type=pa.date32())

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        n = self.children[1].eval_device(ctx)
        y, m, d = civil_from_days(_days_of(v))
        t = y * 12 + (m - 1) + n.data.astype(jnp.int32)
        ny = jnp.floor_divide(t, 12)
        nm = t - ny * 12 + 1
        nd = jnp.minimum(d, _days_in_month(ny, nm))
        out = _days_from_civil(ny, nm, nd)
        return DVal(out, null_and(v.validity, n.validity), DATE)

    def key(self):
        return f"add_months({self.children[0].key()},{self.children[1].key()})"


def _days_from_civil(y, m, d):
    """(year, month, day) -> days since epoch (Hinnant days_from_civil)."""
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + jnp.where(m > 2, -3, 9)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) \
        - jnp.floor_divide(yoe, 100) + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


class MonthsBetween(Expression):
    """months_between(end, start[, roundOff]): fractional months, both
    last-day-of-month => whole months (ref GpuMonthsBetween)."""
    device_type_sig = TypeSig([TypeEnum.DATE, TypeEnum.TIMESTAMP])

    def __init__(self, end, start, round_off=True):
        self.children = [end, start]
        self.round_off = bool(round_off)

    def data_type(self, schema):
        return FLOAT64

    def eval_host(self, batch):
        import pyarrow as pa
        import calendar
        import datetime

        def as_dt(x):
            if isinstance(x, datetime.datetime):
                return x
            return datetime.datetime(x.year, x.month, x.day)

        e = self.children[0].eval_host(batch).to_pylist()
        s = self.children[1].eval_host(batch).to_pylist()
        out = []
        for a, b in zip(e, s):
            if a is None or b is None:
                out.append(None)
                continue
            a, b = as_dt(a), as_dt(b)
            a_last = a.day == calendar.monthrange(a.year, a.month)[1]
            b_last = b.day == calendar.monthrange(b.year, b.month)[1]
            months = (a.year - b.year) * 12 + (a.month - b.month)
            if a.day == b.day or (a_last and b_last):
                r = float(months)
            else:
                secs_a = (a.day - 1) * 86400 + a.hour * 3600 \
                    + a.minute * 60 + a.second
                secs_b = (b.day - 1) * 86400 + b.hour * 3600 \
                    + b.minute * 60 + b.second
                r = months + (secs_a - secs_b) / (31.0 * 86400)
            out.append(round(r, 8) if self.round_off else r)
        return pa.array(out, type=pa.float64())

    def key(self):
        return (f"months_between({self.children[0].key()},"
                f"{self.children[1].key()},{self.round_off})")


class _ScaledToTimestamp(Expression):
    """timestamp_seconds/millis/micros: integral -> timestamp
    (ref GpuSecondsToTimestamp family)."""
    device_type_sig = TypeSig([TypeEnum.BYTE, TypeEnum.SHORT, TypeEnum.INT,
                               TypeEnum.LONG])
    _scale = 1

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return TIMESTAMP

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        out = v.data.astype(jnp.int64) * jnp.int64(type(self)._scale)
        return DVal(out, v.validity, TIMESTAMP)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        micros = pc.multiply(
            pc.cast(self.children[0].eval_host(batch), pa.int64()),
            pa.scalar(type(self)._scale, pa.int64()))
        return pc.cast(micros, pa.timestamp("us", "UTC"))

    def key(self):
        return f"{type(self).__name__}({self.children[0].key()})"


class SecondsToTimestamp(_ScaledToTimestamp):
    _scale = 1_000_000


class MillisToTimestamp(_ScaledToTimestamp):
    _scale = 1_000


class MicrosToTimestamp(_ScaledToTimestamp):
    _scale = 1


class ToUnixTimestamp(Expression):
    """to_unix_timestamp(ts) -> long seconds (timestamp/date input device;
    string parsing on host with the given java format; ref
    GpuToUnixTimestamp)."""
    device_type_sig = TypeSig([TypeEnum.DATE, TypeEnum.TIMESTAMP])

    def __init__(self, child, fmt: str = "yyyy-MM-dd HH:mm:ss"):
        self.children = [child]
        self.fmt = fmt

    def data_type(self, schema):
        return INT64

    def device_unsupported_reason(self, schema):
        from .base import expression_disabled_reason
        r = expression_disabled_reason(type(self))
        if r is not None:
            return r
        dt = self.children[0].data_type(schema)
        if dt == STRING:
            return "string timestamp parsing runs on host"
        return None

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        if v.dtype == TIMESTAMP:
            out = jnp.floor_divide(v.data, 1_000_000)
        else:   # DATE
            out = v.data.astype(jnp.int64) * jnp.int64(86400)
        return DVal(out.astype(jnp.int64), v.validity, INT64)

    def eval_host(self, batch):
        import pyarrow as pa
        dt = self.children[0].data_type(batch.schema)
        arr = self.children[0].eval_host(batch)
        import pyarrow.compute as pc
        if dt == TIMESTAMP:
            return pc.cast(pc.floor(pc.divide(
                pc.cast(arr, pa.int64()), pa.scalar(1_000_000.0))),
                pa.int64())
        if dt == DATE:
            return pc.multiply(pc.cast(arr, pa.int64()),
                               pa.scalar(86400, pa.int64()))
        # string: java SimpleDateFormat subset via strptime
        fmt = _java_to_strptime(self.fmt)
        out = []
        import datetime
        for s in arr.to_pylist():
            if s is None:
                out.append(None)
                continue
            try:
                d = datetime.datetime.strptime(s, fmt)
                out.append(int(d.replace(
                    tzinfo=datetime.timezone.utc).timestamp()))
            except ValueError:
                out.append(None)
        return pa.array(out, type=pa.int64())

    def key(self):
        return f"{type(self).__name__}({self.children[0].key()},{self.fmt})"


class UnixTimestamp(ToUnixTimestamp):
    """unix_timestamp(...) — same semantics (ref GpuUnixTimestamp)."""


def _java_to_strptime(fmt: str) -> str:
    """Java SimpleDateFormat subset -> strptime (shared with the cast
    machinery's date parsing; unsupported directives raise so tagging
    can reject them honestly)."""
    # no SSS: Java SSS is 3-digit millis, strftime %f is 6-digit micros
    # — mapping them would silently format wrong, so SSS stays rejected
    table = [("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"),
             ("HH", "%H"), ("mm", "%M"), ("ss", "%S")]
    out = fmt
    for j, p in table:
        out = out.replace(j, p)
    import re as _re
    residue = _re.sub(r"%[a-zA-Z]", "", out)   # strip emitted directives
    if any(ch.isalpha() for ch in residue):
        leftover = [c for c in residue if c.isalpha()]
        raise Unsupported(f"unsupported datetime format chars {leftover}")
    return out


class FromUnixTime(Expression):
    """from_unixtime(seconds, fmt) -> string (host strftime; ref
    GpuFromUnixTime)."""

    def __init__(self, child, fmt: str = "yyyy-MM-dd HH:mm:ss"):
        self.children = [child]
        self.fmt = fmt
        _java_to_strptime(fmt)   # unsupported formats reject at BUILD time

    def data_type(self, schema):
        return STRING

    def device_unsupported_reason(self, schema):
        return f"{type(self).__name__}: string formatting runs on host"

    def eval_host(self, batch):
        import datetime
        import pyarrow as pa
        fmt = _java_to_strptime(self.fmt)
        out = []
        for v in self.children[0].eval_host(batch).to_pylist():
            if v is None:
                out.append(None)
            else:
                out.append(datetime.datetime.fromtimestamp(
                    int(v), datetime.timezone.utc).strftime(fmt))
        return pa.array(out, type=pa.string())

    def key(self):
        return f"from_unixtime({self.children[0].key()},{self.fmt})"


class DateFormatClass(Expression):
    """date_format(ts, fmt) -> string (host strftime; ref
    GpuDateFormatClass)."""

    def __init__(self, child, fmt: str):
        self.children = [child]
        self.fmt = fmt
        _java_to_strptime(fmt)   # unsupported formats reject at BUILD time

    def data_type(self, schema):
        return STRING

    def device_unsupported_reason(self, schema):
        return f"{type(self).__name__}: string formatting runs on host"

    def eval_host(self, batch):
        import pyarrow as pa
        fmt = _java_to_strptime(self.fmt)
        out = []
        for v in self.children[0].eval_host(batch).to_pylist():
            out.append(None if v is None else v.strftime(fmt))
        return pa.array(out, type=pa.string())

    def key(self):
        return f"date_format({self.children[0].key()},{self.fmt})"


class TimeAdd(Expression):
    """timestamp + INTERVAL microseconds (ref GpuTimeAdd); the interval
    rides as a static literal in micros."""
    device_type_sig = TypeSig([TypeEnum.TIMESTAMP])

    def __init__(self, child, interval_micros: int):
        self.children = [child]
        self.micros = int(interval_micros)

    def data_type(self, schema):
        return TIMESTAMP

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        return DVal(v.data + jnp.int64(self.micros), v.validity, TIMESTAMP)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        out = pc.add(pc.cast(arr, pa.int64()),
                     pa.scalar(self.micros, pa.int64()))
        return pc.cast(out, pa.timestamp("us", "UTC"))

    def key(self):
        return f"time_add({self.children[0].key()},{self.micros})"


class DateAddInterval(Expression):
    """date + INTERVAL (days component only — a date plus sub-day
    intervals is a type error in ANSI Spark; ref GpuDateAddInterval)."""
    device_type_sig = TypeSig([TypeEnum.DATE])

    def __init__(self, child, interval_days: int):
        self.children = [child]
        self.days = int(interval_days)

    def data_type(self, schema):
        return DATE

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        return DVal(v.data + jnp.int32(self.days), v.validity, DATE)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        out = pc.add(pc.cast(arr, pa.int32()),
                     pa.scalar(self.days, pa.int32()))
        return pc.cast(out, pa.date32())

    def key(self):
        return f"date_add_interval({self.children[0].key()},{self.days})"


class TruncDate(Expression):
    """trunc(date, fmt): truncate to year/quarter/month/week level
    (ref GpuTruncDate)."""
    device_type_sig = TypeSig([TypeEnum.DATE])

    _LEVELS = {"year": "year", "yyyy": "year", "yy": "year",
               "quarter": "quarter", "month": "month", "mon": "month",
               "mm": "month", "week": "week"}

    def __init__(self, child, fmt: str):
        self.children = [child]
        self.fmt = str(fmt).lower()

    def data_type(self, schema):
        return DATE

    def device_unsupported_reason(self, schema):
        from .base import expression_disabled_reason
        r = expression_disabled_reason(type(self))
        if r is not None:
            return r
        if self._LEVELS.get(self.fmt) is None:
            return f"trunc level {self.fmt!r} unsupported"
        return None

    def eval_device(self, ctx):
        v = self.children[0].eval_device(ctx)
        days = _days_of(v)
        y, m, d = civil_from_days(days)
        level = self._LEVELS[self.fmt]
        if level == "week":
            # Monday-start week: 1970-01-01 was a Thursday (weekday 3)
            out = days - ((days + 3) % 7)
        else:
            if level == "year":
                nm = jnp.ones_like(m)
            elif level == "quarter":
                nm = ((m - 1) // 3) * 3 + 1
            else:
                nm = m
            out = _days_from_civil(y, nm, jnp.ones_like(d))
        return DVal(out.astype(jnp.int32), v.validity, DATE)

    def eval_host(self, batch):
        import datetime
        import pyarrow as pa
        level = self._LEVELS.get(self.fmt)
        out = []
        for v in self.children[0].eval_host(batch).to_pylist():
            if v is None or level is None:
                out.append(None)
            elif level == "year":
                out.append(v.replace(month=1, day=1))
            elif level == "quarter":
                out.append(v.replace(month=((v.month - 1) // 3) * 3 + 1,
                                     day=1))
            elif level == "month":
                out.append(v.replace(day=1))
            else:   # week, Monday start
                out.append(v - datetime.timedelta(days=v.weekday()))
        return pa.array(out, type=pa.date32())

    def key(self):
        return f"trunc({self.children[0].key()},{self.fmt})"
