"""Generator expressions: explode / posexplode / stack.

Reference analog: GpuGenerateExec (GpuGenerateExec.scala, 984 LoC) and its
GpuExplode/GpuPosExplode/GpuStack generator classes. The reference explodes on
the GPU via cudf list-explode kernels; here list/map payloads are host(Arrow)
resident by design (types.py: nested types are not device-backed), so a
generator produces (per-row repeat counts, flattened output arrays) on the
host and the *gather of the repeated pass-through columns* — the expensive,
wide part — runs on device (exec/generate.py), keying off the same gather-map
idiom the reference uses (JoinGatherer.scala).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..types import (ArrayType, DataType, INT32, MapType, Schema, StructField,
                     from_arrow, to_arrow)
from .base import Expression, Unsupported

__all__ = ["Generator", "Explode", "PosExplode", "Stack"]


class Generator(Expression):
    """An expression producing 0..n output rows per input row. Only valid
    directly under a Generate plan node (ref Spark's ExtractGenerator)."""

    #: True => emit one all-null output row for empty/null input
    #: (explode_outer / posexplode_outer)
    outer: bool = False

    def generator_output(self, schema: Schema) -> List[StructField]:
        raise NotImplementedError

    def generate(self, batch) -> "tuple[np.ndarray, list]":
        """Returns (counts, outputs): counts[i] = number of output rows for
        input row i (already accounts for ``outer``); outputs = one pyarrow
        array per generator_output field, each of length counts.sum()."""
        raise NotImplementedError

    def data_type(self, schema: Schema) -> DataType:
        # only meaningful through generator_output; keep explain working
        return self.generator_output(schema)[0].dtype

    def eval_device(self, ctx):
        raise Unsupported("generators are planned as Generate, not projected")

    def eval_host(self, batch):
        raise Unsupported("generators are planned as Generate, not projected")


class Explode(Generator):
    """explode(array) -> col / explode(map) -> key, value
    (ref GpuExplode in GpuGenerateExec.scala)."""

    def __init__(self, child: Expression, outer: bool = False):
        self.children = [child]
        self.outer = outer

    def _child_type(self, schema: Schema) -> DataType:
        return self.children[0].data_type(schema)

    def generator_output(self, schema: Schema) -> List[StructField]:
        dt = self._child_type(schema)
        if isinstance(dt, ArrayType):
            return [StructField("col", dt.element, True)]
        if isinstance(dt, MapType):
            return [StructField("key", dt.key, True),
                    StructField("value", dt.value, True)]
        raise Unsupported(f"explode requires array or map, got {dt}")

    def _rows(self, batch):
        """-> list of per-row python lists: [(elem,), ...] or
        [(k, v), ...] for maps; None for null input."""
        import pyarrow as pa
        arr = self.children[0].eval_host(batch)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        out = []
        for v in arr.to_pylist():
            if v is None:
                out.append(None)
            elif isinstance(v, dict):
                out.append(list(v.items()))
            elif v and isinstance(v[0], tuple) and len(v[0]) == 2 and \
                    isinstance(self._child_type(batch.schema), MapType):
                out.append(list(v))
            else:
                out.append([(e,) for e in v])
        return out

    def generate(self, batch, _rows=None):
        import pyarrow as pa
        fields = self.generator_output(batch.schema)
        rows = self._rows(batch) if _rows is None else _rows
        counts = np.zeros(len(rows), dtype=np.int64)
        cols: List[list] = [[] for _ in fields]
        for i, r in enumerate(rows):
            if not r:  # null or empty
                if self.outer:
                    counts[i] = 1
                    for c in cols:
                        c.append(None)
                continue
            counts[i] = len(r)
            for tup in r:
                for c, v in zip(cols, tup):
                    c.append(v)
        arrays = [pa.array(c, type=to_arrow(f.dtype))
                  for c, f in zip(cols, fields)]
        return counts, arrays

    def key(self):
        return f"Explode({self.children[0].key()},outer={self.outer})"

    @property
    def name_hint(self):
        return "col"


class PosExplode(Explode):
    """posexplode: adds a 0-based ``pos`` column
    (ref GpuPosExplode in GpuGenerateExec.scala)."""

    def generator_output(self, schema: Schema) -> List[StructField]:
        return ([StructField("pos", INT32, True)]
                + super().generator_output(schema))

    def generate(self, batch, _rows=None):
        import pyarrow as pa
        rows = self._rows(batch) if _rows is None else _rows
        counts, arrays = super().generate(batch, _rows=rows)
        pos = []
        for i, r in enumerate(rows):
            if not r:
                if self.outer:
                    pos.append(None)
                continue
            pos.extend(range(len(r)))
        return counts, [pa.array(pos, type=pa.int32())] + arrays

    def key(self):
        return f"PosExplode({self.children[0].key()},outer={self.outer})"


class Stack(Generator):
    """stack(n, e1, ..., ek): n rows of k//n columns per input row
    (ref GpuStack, added to GpuOverrides expression registry)."""

    def __init__(self, n: int, *exprs: Expression):
        if n <= 0:
            raise Unsupported("stack: n must be a positive literal")
        self.n = int(n)
        self.children = list(exprs)
        if not self.children:
            raise Unsupported("stack requires at least one value expression")

    def generator_output(self, schema: Schema) -> List[StructField]:
        width = -(-len(self.children) // self.n)
        fields = []
        for c in range(width):
            # Spark: column type from the first row's expression in that slot
            dt = self.children[c].data_type(schema)
            fields.append(StructField(f"col{c}", dt, True))
        return fields

    def generate(self, batch):
        import pyarrow as pa
        fields = self.generator_output(batch.schema)
        width = len(fields)
        n_in = batch.num_rows
        counts = np.full(n_in, self.n, dtype=np.int64)
        # evaluate every value expression on the host path once
        vals = []
        for e in self.children:
            arr = e.eval_host(batch)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            vals.append(arr.to_pylist())
        cols: List[list] = [[] for _ in fields]
        for i in range(n_in):
            for r in range(self.n):
                for c in range(width):
                    k = r * width + c
                    cols[c].append(vals[k][i] if k < len(self.children) else None)
        arrays = [pa.array(col, type=to_arrow(f.dtype))
                  for col, f in zip(cols, fields)]
        return counts, arrays

    def key(self):
        kids = ",".join(c.key() for c in self.children)
        return f"Stack({self.n},{kids})"
