"""Hash expressions: Spark-exact Murmur3, xxHash64, HiveHash, digests.

Reference analog: HashFunctions.scala + the `Hash` JNI kernels
(murmur3/xxhash64/hive hash, SURVEY.md 2.12 item 2). TPU-first design:
integral/float/date/timestamp columns hash ON DEVICE as fused jnp
uint32/uint64 bitwise kernels (XLA fuses the whole multi-column fold into
one kernel); string AND double children hash on host with the identical
bit-exact algorithm (strings are host-resident in round 1; f64 on TPU is
emulated double-double with no bitcast, so device f64 hashing cannot be
bit-exact — verified on hardware).

Bit-exactness with Spark matters because hash() feeds HashPartitioning:
matching Spark's Murmur3 means rows land in the same partition a CPU Spark
cluster would produce (differential tests of partition-dependent queries,
and the reference's "bit for bit" bar, README Compatibility).

Algorithms transcribed from the well-known public Murmur3_x86_32 / XXH64
specs with Spark's type normalizations (catalyst HashExpression):
  * bool -> 1/0 int; byte/short/int/date -> 4-byte path
  * long/timestamp -> 8-byte path
  * float -> floatToIntBits with -0.0 -> 0.0 and canonical NaN
  * double -> doubleToLongBits, same normalization
  * decimal(p<=18) -> unscaled long
  * NULL -> column skipped (seed flows through)
  * multi-column fold: seed=42, seed = hash(col_i, seed)
Spark's bytes tail handling differs from standard murmur3: each trailing
byte runs the FULL mix (Murmur3_x86_32.hashUnsafeBytes in spark/unsafe).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..types import (BINARY, DataType, DecimalType, INT32, INT64, STRING,
                     Schema, TypeSig, TypeEnum)
from .base import DVal, EvalContext, Expression, Unsupported
from .arithmetic import masked_numpy_to_arrow

__all__ = ["device_hashable", "Murmur3Hash", "XxHash64", "HiveHash", "Md5", "Sha1", "Sha2",
           "Crc32", "spark_murmur3_bytes", "spark_xxhash64_bytes"]

_M3_C1 = 0xcc9e2d51
_M3_C2 = 0x1b873593


# ---------------------------------------------------------------------------
# pure-Python scalar reference (host path for strings + test oracle)
# ---------------------------------------------------------------------------

def _rotl32(x, r):
    return ((x << r) | (x >> (32 - r))) & 0xffffffff


def _m3_mix_k1(k1):
    k1 = (k1 * _M3_C1) & 0xffffffff
    k1 = _rotl32(k1, 15)
    return (k1 * _M3_C2) & 0xffffffff


def _m3_mix_h1(h1, k1):
    h1 ^= k1
    h1 = _rotl32(h1, 13)
    return (h1 * 5 + 0xe6546b64) & 0xffffffff


def _m3_fmix(h1, length):
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85ebca6b) & 0xffffffff
    h1 ^= h1 >> 13
    h1 = (h1 * 0xc2b2ae35) & 0xffffffff
    h1 ^= h1 >> 16
    return h1


def spark_murmur3_bytes(data: bytes, seed: int) -> int:
    """Spark's Murmur3_x86_32.hashUnsafeBytes: word loop + PER-BYTE tail
    (signed bytes), returns signed int32."""
    h1 = seed & 0xffffffff
    n = len(data)
    aligned = n - (n % 4)
    for i in range(0, aligned, 4):
        k1 = int.from_bytes(data[i:i + 4], "little")
        h1 = _m3_mix_h1(h1, _m3_mix_k1(k1))
    for i in range(aligned, n):
        b = data[i]
        if b >= 128:  # signed byte
            b -= 256
        h1 = _m3_mix_h1(h1, _m3_mix_k1(b & 0xffffffff))
    out = _m3_fmix(h1, n)
    return out - (1 << 32) if out >= (1 << 31) else out


def _m3_hash_int_py(v: int, seed: int) -> int:
    h = _m3_mix_h1(seed & 0xffffffff, _m3_mix_k1(v & 0xffffffff))
    out = _m3_fmix(h, 4)
    return out - (1 << 32) if out >= (1 << 31) else out


def _m3_hash_long_py(v: int, seed: int) -> int:
    v &= 0xffffffffffffffff
    h = _m3_mix_h1(seed & 0xffffffff, _m3_mix_k1(v & 0xffffffff))
    h = _m3_mix_h1(h, _m3_mix_k1(v >> 32))
    out = _m3_fmix(h, 8)
    return out - (1 << 32) if out >= (1 << 31) else out


_XX_P1 = 0x9E3779B185EBCA87
_XX_P2 = 0xC2B2AE3D27D4EB4F
_XX_P3 = 0x165667B19E3779F9
_XX_P4 = 0x85EBCA77C2B2AE63
_XX_P5 = 0x27D4EB2F165667C5
_U64 = 0xffffffffffffffff


def _rotl64(x, r):
    return ((x << r) | (x >> (64 - r))) & _U64


def _xx_fmix(h):
    h ^= h >> 33
    h = (h * _XX_P2) & _U64
    h ^= h >> 29
    h = (h * _XX_P3) & _U64
    h ^= h >> 32
    return h


def spark_xxhash64_bytes(data: bytes, seed: int) -> int:
    """Standard XXH64 (Spark's XXH64.hashUnsafeBytes), signed int64 out."""
    seed &= _U64
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + _XX_P1 + _XX_P2) & _U64
        v2 = (seed + _XX_P2) & _U64
        v3 = seed
        v4 = (seed - _XX_P1) & _U64
        while i <= n - 32:
            for j, v in enumerate((v1, v2, v3, v4)):
                k = int.from_bytes(data[i + 8 * j:i + 8 * j + 8], "little")
                v = (v + k * _XX_P2) & _U64
                v = _rotl64(v, 31)
                v = (v * _XX_P1) & _U64
                if j == 0:
                    v1 = v
                elif j == 1:
                    v2 = v
                elif j == 2:
                    v3 = v
                else:
                    v4 = v
            i += 32
        h = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12)
             + _rotl64(v4, 18)) & _U64
        for v in (v1, v2, v3, v4):
            k = (_rotl64((v * _XX_P2) & _U64, 31) * _XX_P1) & _U64
            h = (((h ^ k) * _XX_P1) + _XX_P4) & _U64
    else:
        h = (seed + _XX_P5) & _U64
    h = (h + n) & _U64
    while i <= n - 8:
        k = int.from_bytes(data[i:i + 8], "little")
        k = (_rotl64((k * _XX_P2) & _U64, 31) * _XX_P1) & _U64
        h = ((_rotl64(h ^ k, 27) * _XX_P1) + _XX_P4) & _U64
        i += 8
    if i <= n - 4:
        k = int.from_bytes(data[i:i + 4], "little")
        h = ((_rotl64(h ^ ((k * _XX_P1) & _U64), 23) * _XX_P2) + _XX_P3) & _U64
        i += 4
    while i < n:
        k = (data[i] * _XX_P5) & _U64
        h = (_rotl64(h ^ k, 11) * _XX_P1) & _U64
        i += 1
    out = _xx_fmix(h)
    return out - (1 << 64) if out >= (1 << 63) else out


def _xx_hash_int_py(v: int, seed: int) -> int:
    h = (seed + _XX_P5 + 4) & _U64
    h ^= ((v & 0xffffffff) * _XX_P1) & _U64
    h = ((_rotl64(h, 23) * _XX_P2) + _XX_P3) & _U64
    out = _xx_fmix(h)
    return out - (1 << 64) if out >= (1 << 63) else out


def _xx_hash_long_py(v: int, seed: int) -> int:
    v &= _U64
    h = (seed + _XX_P5 + 8) & _U64
    h ^= (_rotl64((v * _XX_P2) & _U64, 31) * _XX_P1) & _U64
    h = ((_rotl64(h, 27) * _XX_P1) + _XX_P4) & _U64
    out = _xx_fmix(h)
    return out - (1 << 64) if out >= (1 << 63) else out


# ---------------------------------------------------------------------------
# device (jnp) vectorized kernels
# ---------------------------------------------------------------------------

def _rotl32_dev(x, r):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _m3_mix_k1_dev(k1):
    k1 = k1 * np.uint32(_M3_C1)
    k1 = _rotl32_dev(k1, 15)
    return k1 * np.uint32(_M3_C2)


def _m3_mix_h1_dev(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32_dev(h1, 13)
    return h1 * np.uint32(5) + np.uint32(0xe6546b64)


def _m3_fmix_dev(h1, length):
    h1 = h1 ^ np.uint32(length)
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = h1 * np.uint32(0x85ebca6b)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = h1 * np.uint32(0xc2b2ae35)
    return h1 ^ (h1 >> np.uint32(16))


def _normalize_to_words(val: DVal):
    """DVal -> ('int', u32) or ('long', u64) with Spark normalization."""
    dt, data = val.dtype, val.data
    name = dt.name
    if isinstance(dt, DecimalType):
        return "long", data.astype(jnp.uint64)
    if name in ("boolean",):
        return "int", data.astype(jnp.uint32)
    if name in ("tinyint", "smallint", "int", "date"):
        # sign-extend then reinterpret (int32 cast keeps two's complement)
        return "int", data.astype(jnp.int32).astype(jnp.uint32)
    if name in ("bigint", "timestamp"):
        return "long", data.astype(jnp.int64).astype(jnp.uint64)
    if name == "float":
        f = data.astype(jnp.float32)
        f = jnp.where(f == 0.0, jnp.float32(0.0), f)       # -0.0 -> 0.0
        f = jnp.where(jnp.isnan(f), jnp.float32(np.nan), f)  # canonical NaN
        return "int", jax.lax.bitcast_convert_type(f, jnp.uint32)
    # DOUBLE is host-only: TPU emulates f64 as double-double, so neither
    # f64 bitcast nor exact arithmetic reconstruction of the IEEE bits is
    # available — hashing doubles on device cannot be bit-exact with Spark.
    raise Unsupported(f"cannot hash {name} on device")


def murmur3_fold_device(vals: List[DVal], seed: int) -> jnp.ndarray:
    """Fold Spark murmur3 over device columns; returns int32 hashes."""
    h = jnp.full(vals[0].data.shape, np.uint32(seed), dtype=jnp.uint32)
    for v in vals:
        kind, words = _normalize_to_words(v)
        if kind == "int":
            nh = _m3_fmix_dev(_m3_mix_h1_dev(h, _m3_mix_k1_dev(words)), 4)
        else:
            lo = words.astype(jnp.uint32)
            hi = (words >> np.uint64(32)).astype(jnp.uint32)
            nh = _m3_mix_h1_dev(h, _m3_mix_k1_dev(lo))
            nh = _m3_fmix_dev(_m3_mix_h1_dev(nh, _m3_mix_k1_dev(hi)), 8)
        h = jnp.where(v.validity, nh, h)  # NULL skips the column
    return h.astype(jnp.int32)


def _rotl64_dev(x, r):
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def _xx_fmix_dev(h):
    h = h ^ (h >> np.uint64(33))
    h = h * np.uint64(_XX_P2)
    h = h ^ (h >> np.uint64(29))
    h = h * np.uint64(_XX_P3)
    return h ^ (h >> np.uint64(32))


def xxhash64_fold_device(vals: List[DVal], seed: int) -> jnp.ndarray:
    """Fold Spark xxhash64 over device columns; returns int64 hashes."""
    h = jnp.full(vals[0].data.shape, np.uint64(seed), dtype=jnp.uint64)
    for v in vals:
        kind, words = _normalize_to_words(v)
        if kind == "int":
            nh = h + np.uint64(_XX_P5) + np.uint64(4)
            nh = nh ^ (words.astype(jnp.uint64) * np.uint64(_XX_P1))
            nh = _rotl64_dev(nh, 23) * np.uint64(_XX_P2) + np.uint64(_XX_P3)
        else:
            nh = h + np.uint64(_XX_P5) + np.uint64(8)
            k = _rotl64_dev(words * np.uint64(_XX_P2), 31) * np.uint64(_XX_P1)
            nh = _rotl64_dev(nh ^ k, 27) * np.uint64(_XX_P1) + np.uint64(_XX_P4)
        nh = _xx_fmix_dev(nh)
        # xxhash64's fold re-seeds with the running hash (Spark: seed = hash)
        h = jnp.where(v.validity, nh, h)
    return h.astype(jnp.int64)


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------

# DOUBLE excluded: no bit-exact f64 bit pattern on TPU (f64 is emulated
# double-double; bitcast unsupported) — doubles hash on host instead.
device_hashable = TypeSig([TypeEnum.BOOLEAN, TypeEnum.BYTE, TypeEnum.SHORT,
                         TypeEnum.INT, TypeEnum.LONG, TypeEnum.FLOAT,
                         TypeEnum.DATE, TypeEnum.TIMESTAMP,
                         TypeEnum.DECIMAL])


def _py_norm(v, dt: DataType):
    """Python-side Spark normalization -> ('int'|'long'|'bytes', value)."""
    name = dt.name
    if isinstance(dt, DecimalType):
        return "long", int(round(v * (10 ** dt.scale))) if not isinstance(v, int) else v
    if name == "boolean":
        return "int", 1 if v else 0
    if name in ("tinyint", "smallint", "int", "date"):
        if hasattr(v, "toordinal"):  # datetime.date from arrow
            import datetime
            v = (v - datetime.date(1970, 1, 1)).days
        return "int", int(v)
    if name in ("bigint", "timestamp"):
        if hasattr(v, "timestamp"):  # datetime from arrow; exact int math
            import datetime
            if v.tzinfo is None:
                v = v.replace(tzinfo=datetime.timezone.utc)
            td = v - datetime.datetime(1970, 1, 1,
                                       tzinfo=datetime.timezone.utc)
            v = (td.days * 86_400_000_000 + td.seconds * 1_000_000
                 + td.microseconds)
        return "long", int(v)
    if name == "float":
        f = np.float32(0.0) if v == 0 else np.float32(v)
        if np.isnan(f):
            f = np.float32(np.nan)
        return "int", int(np.frombuffer(np.float32(f).tobytes(), np.int32)[0])
    if name == "double":
        d = np.float64(0.0) if v == 0 else np.float64(v)
        if np.isnan(d):
            d = np.float64(np.nan)
        return "long", int(np.frombuffer(np.float64(d).tobytes(), np.int64)[0])
    if name == "string":
        return "bytes", v.encode("utf-8")
    if name == "binary":
        return "bytes", bytes(v)
    raise Unsupported(f"cannot hash type {name}")


class _HashBase(Expression):
    """Shared: device fold when all children are device-backed, else host."""

    seed: int

    def __init__(self, children, seed):
        self.children = list(children)
        self.seed = seed

    def nullable(self, schema):
        return False

    def device_unsupported_reason(self, schema: Schema) -> Optional[str]:
        for c in self.children:
            dt = c.data_type(schema)
            r = device_hashable.reason_not_supported(dt)
            if r is not None:
                return f"{type(self).__name__}: input {r} (hashes on host)"
        return None

    def key(self):
        kids = ",".join(c.key() for c in self.children)
        return f"{type(self).__name__}({kids},seed={self.seed})"

    # host fold over mixed types
    def _host_fold(self, batch, hash_int, hash_long, hash_bytes):
        cols = []
        for c in self.children:
            arr = c.eval_host(batch)
            cols.append((arr.to_pylist(), c.data_type(batch.schema)))
        n = batch.num_rows
        out = []
        for i in range(n):
            h = self.seed
            for vals, dt in cols:
                v = vals[i]
                if v is None:
                    continue
                kind, nv = _py_norm(v, dt)
                if kind == "int":
                    h = hash_int(nv, h & self._seed_mask)
                elif kind == "long":
                    h = hash_long(nv, h & self._seed_mask)
                else:
                    h = hash_bytes(nv, h & self._seed_mask)
            out.append(h)
        return out


class Murmur3Hash(_HashBase):
    """hash(cols...) — Spark Murmur3 with seed 42 (HashPartitioning's hash)."""

    _seed_mask = 0xffffffff

    def __init__(self, children, seed: int = 42):
        super().__init__(children, seed)

    def data_type(self, schema):
        return INT32

    def eval_device(self, ctx: EvalContext) -> DVal:
        vals = [c.eval_device(ctx) for c in self.children]
        h = murmur3_fold_device(vals, self.seed)
        return DVal(h, jnp.ones_like(h, dtype=jnp.bool_), INT32)

    def eval_host(self, batch):
        out = self._host_fold(batch, _m3_hash_int_py, _m3_hash_long_py,
                              spark_murmur3_bytes)
        return masked_numpy_to_arrow(np.asarray(out, np.int32),
                                     np.ones(len(out), np.bool_), INT32)


class XxHash64(_HashBase):
    """xxhash64(cols...) — Spark XXH64 with seed 42."""

    _seed_mask = _U64

    def __init__(self, children, seed: int = 42):
        super().__init__(children, seed)

    def data_type(self, schema):
        return INT64

    def eval_device(self, ctx: EvalContext) -> DVal:
        vals = [c.eval_device(ctx) for c in self.children]
        h = xxhash64_fold_device(vals, self.seed)
        return DVal(h, jnp.ones_like(h, dtype=jnp.bool_), INT64)

    def eval_host(self, batch):
        out = self._host_fold(batch, _xx_hash_int_py, _xx_hash_long_py,
                              spark_xxhash64_bytes)
        return masked_numpy_to_arrow(np.asarray(out, np.int64),
                                     np.ones(len(out), np.bool_), INT64)


def _hive_hash_py(v, dt: DataType) -> int:
    name = dt.name
    if name == "boolean":
        return 1 if v else 0
    if name in ("tinyint", "smallint", "int", "date"):
        kind, nv = _py_norm(v, dt)
        return nv & 0xffffffff if nv < 0 else nv
    if name in ("bigint", "timestamp"):
        _, nv = _py_norm(v, dt)
        nv &= _U64
        return ((nv >> 32) ^ nv) & 0xffffffff
    if name == "float":
        _, nv = _py_norm(v, dt)
        return nv & 0xffffffff
    if name == "double":
        _, nv = _py_norm(v, dt)
        nv &= _U64
        return ((nv >> 32) ^ nv) & 0xffffffff
    if name == "string":
        # Java String.hashCode folds UTF-16 code units (surrogate pairs for
        # non-BMP chars), not code points
        h = 0
        data = v.encode("utf-16-be")
        for i in range(0, len(data), 2):
            h = (h * 31 + int.from_bytes(data[i:i + 2], "big")) & 0xffffffff
        return h
    raise Unsupported(f"hive hash of {name}")


class HiveHash(Expression):
    """hive_hash: fold h = h*31 + hash(col), h0=0 (ref HiveHash in
    HashFunctions.scala / jni Hash.hiveHash). Host implementation."""

    def __init__(self, children):
        self.children = list(children)

    def data_type(self, schema):
        return INT32

    def nullable(self, schema):
        return False

    def device_unsupported_reason(self, schema):
        return "HiveHash runs on host"

    def eval_host(self, batch):
        cols = [(c.eval_host(batch).to_pylist(), c.data_type(batch.schema))
                for c in self.children]
        out = []
        for i in range(batch.num_rows):
            h = 0
            for vals, dt in cols:
                v = vals[i]
                ch = 0 if v is None else _hive_hash_py(v, dt)
                h = (h * 31 + ch) & 0xffffffff
            out.append(h - (1 << 32) if h >= (1 << 31) else h)
        return masked_numpy_to_arrow(np.asarray(out, np.int32),
                                     np.ones(len(out), np.bool_), INT32)


class _Digest(Expression):
    """Host digests over string/binary (ref Md5/Sha1/Sha2 cudf kernels)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return STRING

    def device_unsupported_reason(self, schema):
        return f"{type(self).__name__}: digest runs on host"

    def _digest(self, data: bytes) -> str:
        raise NotImplementedError

    def eval_host(self, batch):
        import pyarrow as pa
        vals = self.children[0].eval_host(batch).to_pylist()
        out = []
        for v in vals:
            if v is None:
                out.append(None)
            else:
                b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                out.append(self._digest(b))
        return pa.array(out, type=pa.string())


class Md5(_Digest):
    def _digest(self, data):
        import hashlib
        return hashlib.md5(data).hexdigest()


class Sha1(_Digest):
    def _digest(self, data):
        import hashlib
        return hashlib.sha1(data).hexdigest()


class Sha2(_Digest):
    def __init__(self, child, num_bits: int = 256):
        super().__init__(child)
        self.num_bits = num_bits

    def _digest(self, data):
        import hashlib
        bits = 256 if self.num_bits == 0 else self.num_bits
        fn = {224: hashlib.sha224, 256: hashlib.sha256,
              384: hashlib.sha384, 512: hashlib.sha512}.get(bits)
        if fn is None:
            return None
        return fn(data).hexdigest()

    def key(self):
        return f"Sha2({self.children[0].key()},{self.num_bits})"


class Crc32(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return INT64

    def device_unsupported_reason(self, schema):
        return "Crc32 runs on host"

    def eval_host(self, batch):
        import zlib
        vals = self.children[0].eval_host(batch).to_pylist()
        out, valid = [], []
        for v in vals:
            if v is None:
                out.append(0)
                valid.append(False)
            else:
                b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                out.append(zlib.crc32(b) & 0xffffffff)
                valid.append(True)
        return masked_numpy_to_arrow(np.asarray(out, np.int64),
                                     np.asarray(valid, np.bool_), INT64)
