"""Higher-order functions: transform/filter/exists/forall/aggregate/zip_with.

Reference analog: higherOrderFunctions.scala (GpuArrayTransform,
GpuArrayFilter, GpuArrayExists, GpuArrayForAll, GpuTransformKeys,
GpuTransformValues, GpuMapFilter) registered at GpuOverrides.scala:3935.

Evaluation strategy (the vectorization trick, TPU-first even though these run
on host in round 1): instead of interpreting the lambda per element, flatten
all rows' elements into ONE synthetic batch (element column + lambda index +
outer references repeated per element via take), evaluate the lambda body
once, vectorized, over that batch, then re-wrap results with the original
offsets. The same shape is exactly what a future device list layout
(offsets + flat child in HBM) will use, so the lambda body's device kernel
carries over unchanged.

Lambda variables bind their types lazily: the HOF parent stamps each
NamedLambdaVariable's dtype from the collection's element type the first time
``data_type``/``eval_host`` sees a schema (the functions API builds the tree
before any schema is known).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..types import (ArrayType, BOOL, DataType, INT32, MapType, Schema,
                     StructField)
from .base import ColumnRef, Expression
from .collection_fns import _HostCollectionExpr, _elem_type, _pa

__all__ = ["NamedLambdaVariable", "ArrayTransform", "ArrayFilter",
           "ArrayExists", "ArrayForAll", "ArrayAggregate", "ZipWith",
           "TransformKeys", "TransformValues", "MapFilter"]


class NamedLambdaVariable(ColumnRef):
    """A lambda-bound variable; resolves by name inside the synthetic
    flattened batch (ref NamedLambdaVariable in Catalyst). dtype is stamped
    by the enclosing HOF at bind time."""

    _counter = [0]

    def __init__(self, hint: str, dtype: Optional[DataType] = None):
        NamedLambdaVariable._counter[0] += 1
        super().__init__(f"`lambda_{hint}_{NamedLambdaVariable._counter[0]}`")
        self._dtype = dtype

    def data_type(self, schema: Schema) -> DataType:
        assert self._dtype is not None, "unbound lambda variable"
        return self._dtype

    def device_unsupported_reason(self, schema):
        return None


class _SyntheticBatch:
    """Minimal batch protocol (schema/num_rows/column/column_by_name) hosting
    the flattened lambda scope; enough for every Expression.eval_host."""

    def __init__(self, names, arrays, dtypes):
        from ..columnar.column import HostColumn
        self.schema = Schema(StructField(n, d)
                             for n, d in zip(names, dtypes))
        self._cols = {n: HostColumn(a, d)
                      for n, a, d in zip(names, arrays, dtypes)}
        self._names = list(names)
        self.num_rows = len(arrays[0]) if arrays else 0

    def column_by_name(self, name):
        return self._cols[name]

    def column(self, i):
        return self._cols[self._names[i]]


class _HigherOrder(_HostCollectionExpr):
    """Shared bind -> flatten -> eval -> rewrap machinery."""

    body: Expression
    args: List[NamedLambdaVariable]

    def _bind_types(self, schema: Schema) -> None:
        """Stamp lambda-arg dtypes from the collection's type."""
        raise NotImplementedError

    def _outer_refs(self):
        # exclude only lambda variables BOUND at or below this HOF (its own
        # args plus any nested HOF's args). An enclosing lambda's variable
        # used inside this body is free here and must be replicated from the
        # enclosing (possibly synthetic) batch like any other outer column.
        bound = {a.name for a in self.args}
        stack = [self.body]
        while stack:
            e = stack.pop()
            if isinstance(e, _HigherOrder):
                bound.update(a.name for a in e.args)
            stack.extend(e.children)
        return [r for r in self.body.references() if r not in bound]

    def _flat_eval(self, batch, rows):
        """rows: per-input-row element lists (None rows contribute nothing).
        For multi-arg lambdas each element is a tuple, one slot per arg.
        Returns the flat list of lambda results, in element order."""
        parent: List[int] = []
        flats: List[list] = [[] for _ in self.args]
        for i, lst in enumerate(rows):
            if lst is None:
                continue
            for v in lst:
                parent.append(i)
                if len(self.args) == 1:
                    flats[0].append(v)
                else:
                    for k in range(len(self.args)):
                        flats[k].append(v[k])
        names = [a.name for a in self.args]
        arrays = [_pa(f, a._dtype) for f, a in zip(flats, self.args)]
        dtypes = [a._dtype for a in self.args]
        outer = self._outer_refs()
        if outer:
            import pyarrow as pa
            take_idx = pa.array(np.asarray(parent, dtype=np.int64))
            for name in dict.fromkeys(outer):
                c = batch.column_by_name(name)
                arr = c.to_arrow(batch.num_rows).take(take_idx)
                names.append(name)
                arrays.append(arr)
                dtypes.append(c.dtype)
        sb = _SyntheticBatch(names, arrays, dtypes)
        return self.body.eval_host(sb).to_pylist() if sb.num_rows else []

    def _rewrap(self, rows, res, per_row):
        """Slice flat results back per row; None rows stay None."""
        out, k = [], 0
        for a in rows:
            if a is None:
                out.append(None)
                continue
            n = per_row(a)
            out.append(res[k:k + n])
            k += n
        return out


class ArrayTransform(_HigherOrder):
    """transform(arr, x -> expr) / transform(arr, (x, i) -> expr)."""

    def __init__(self, array, args, body):
        self.children = [array, body]
        self.args = args
        self.body = body

    def _bind_types(self, schema):
        self.args[0]._dtype = _elem_type(self.children[0].data_type(schema))
        if len(self.args) > 1:
            self.args[1]._dtype = INT32

    def data_type(self, schema):
        self._bind_types(schema)
        return ArrayType(self.body.data_type(schema))

    def eval_host(self, batch):
        self._bind_types(batch.schema)
        rows = self.children[0].eval_host(batch).to_pylist()
        if len(self.args) > 1:
            rows2 = [None if a is None else [(v, i) for i, v in enumerate(a)]
                     for a in rows]
        else:
            rows2 = rows
        res = self._flat_eval(batch, rows2)
        out = self._rewrap(rows, res, len)
        return _pa(out, self.data_type(batch.schema))


class ArrayFilter(_HigherOrder):
    """filter(arr, x -> pred) / filter(arr, (x, i) -> pred)."""

    def __init__(self, array, args, body):
        self.children = [array, body]
        self.args = args
        self.body = body

    def _bind_types(self, schema):
        self.args[0]._dtype = _elem_type(self.children[0].data_type(schema))
        if len(self.args) > 1:
            self.args[1]._dtype = INT32

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_host(self, batch):
        self._bind_types(batch.schema)
        rows = self.children[0].eval_host(batch).to_pylist()
        if len(self.args) > 1:
            rows2 = [None if a is None else [(v, i) for i, v in enumerate(a)]
                     for a in rows]
        else:
            rows2 = rows
        res = self._flat_eval(batch, rows2)
        keeps = self._rewrap(rows, res, len)
        out = [None if a is None else
               [v for v, kp in zip(a, kp_row) if kp is True]
               for a, kp_row in zip(rows, (k or [] for k in keeps))]
        return _pa(out, self.data_type(batch.schema))


class _ArrayPredicate(_HigherOrder):
    """exists/forall three-valued aggregation over lambda results."""

    def __init__(self, array, args, body):
        self.children = [array, body]
        self.args = args
        self.body = body

    def _bind_types(self, schema):
        self.args[0]._dtype = _elem_type(self.children[0].data_type(schema))

    def data_type(self, schema):
        return BOOL

    def _decide(self, vals):
        raise NotImplementedError

    def eval_host(self, batch):
        self._bind_types(batch.schema)
        rows = self.children[0].eval_host(batch).to_pylist()
        res = self._flat_eval(batch, rows)
        per = self._rewrap(rows, res, len)
        out = [None if v is None else self._decide(v) for v in per]
        return _pa(out, BOOL)


class ArrayExists(_ArrayPredicate):
    """TRUE if any TRUE; NULL if none TRUE but some NULL; else FALSE."""

    def _decide(self, vals):
        if any(v is True for v in vals):
            return True
        if any(v is None for v in vals):
            return None
        return False


class ArrayForAll(_ArrayPredicate):
    """FALSE if any FALSE; NULL if none FALSE but some NULL; else TRUE."""

    def _decide(self, vals):
        if any(v is False for v in vals):
            return False
        if any(v is None for v in vals):
            return None
        return True


class ZipWith(_HigherOrder):
    """zip_with(a, b, (x, y) -> expr): padded to the longer side with NULLs."""

    def __init__(self, left, right, args, body):
        self.children = [left, right, body]
        self.args = args
        self.body = body

    def _bind_types(self, schema):
        self.args[0]._dtype = _elem_type(self.children[0].data_type(schema))
        self.args[1]._dtype = _elem_type(self.children[1].data_type(schema))

    def data_type(self, schema):
        self._bind_types(schema)
        return ArrayType(self.body.data_type(schema))

    def eval_host(self, batch):
        self._bind_types(batch.schema)
        ls = self.children[0].eval_host(batch).to_pylist()
        rs = self.children[1].eval_host(batch).to_pylist()
        rows = []
        for a, b in zip(ls, rs):
            if a is None or b is None:
                rows.append(None)
                continue
            n = max(len(a), len(b))
            rows.append([(a[i] if i < len(a) else None,
                          b[i] if i < len(b) else None) for i in range(n)])
        res = self._flat_eval(batch, rows)
        out = self._rewrap(rows, res, len)
        return _pa(out, self.data_type(batch.schema))


class ArrayAggregate(_HigherOrder):
    """aggregate(arr, zero, (acc, x) -> merge[, acc -> finish]).

    Vectorized as a scan ACROSS rows: step j evaluates the merge lambda once
    over all rows that still have an element j — O(max_len) vectorized
    evaluations instead of O(total elements) scalar ones, the same schedule
    a device segmented fold uses.
    """

    def __init__(self, array, zero, merge_args, merge_body,
                 finish_args=None, finish_body=None):
        self.children = [array, zero, merge_body] + (
            [finish_body] if finish_body is not None else [])
        self.args = merge_args
        self.body = merge_body
        self.finish_args = finish_args
        self.finish_body = finish_body

    def _bind_types(self, schema):
        self.args[0]._dtype = self.children[1].data_type(schema)  # acc
        self.args[1]._dtype = _elem_type(self.children[0].data_type(schema))
        if self.finish_args:
            self.finish_args[0]._dtype = self.args[0]._dtype

    def data_type(self, schema):
        self._bind_types(schema)
        if self.finish_body is not None:
            return self.finish_body.data_type(schema)
        return self.children[1].data_type(schema)

    def eval_host(self, batch):
        self._bind_types(batch.schema)
        rows = self.children[0].eval_host(batch).to_pylist()
        acc = list(self.children[1].eval_host(batch).to_pylist())
        max_len = max((len(a) for a in rows if a is not None), default=0)
        for j in range(max_len):
            # singleton element list per live row keeps outer-ref row
            # alignment correct in the flattened batch
            step_rows = [([(acc[i], a[j])] if a is not None and len(a) > j
                          else None) for i, a in enumerate(rows)]
            res = self._flat_eval(batch, step_rows)
            k = 0
            for i, sr in enumerate(step_rows):
                if sr is not None:
                    acc[i] = res[k]
                    k += 1
        out = [None if a is None else acc[i] for i, a in enumerate(rows)]
        if self.finish_body is not None:
            saved_args, saved_body = self.args, self.body
            self.args, self.body = self.finish_args, self.finish_body
            try:
                fin_rows = [None if a is None else [out[i]]
                            for i, a in enumerate(rows)]
                res = self._flat_eval(batch, fin_rows)
                k = 0
                for i, a in enumerate(rows):
                    if a is not None:
                        out[i] = res[k]
                        k += 1
            finally:
                self.args, self.body = saved_args, saved_body
        return _pa(out, self.data_type(batch.schema))


class _MapHigherOrder(_HigherOrder):
    """Map HOFs: lambda args are (key, value) pairs from the entry list."""

    def __init__(self, m, args, body):
        self.children = [m, body]
        self.args = args
        self.body = body

    def _bind_types(self, schema):
        dt = self.children[0].data_type(schema)
        assert isinstance(dt, MapType)
        self.args[0]._dtype = dt.key
        self.args[1]._dtype = dt.value


class TransformKeys(_MapHigherOrder):
    """transform_keys(map, (k, v) -> expr); NULL new key is an error."""

    def data_type(self, schema):
        self._bind_types(schema)
        dt = self.children[0].data_type(schema)
        return MapType(self.body.data_type(schema), dt.value)

    def eval_host(self, batch):
        self._bind_types(batch.schema)
        rows = self.children[0].eval_host(batch).to_pylist()
        res = self._flat_eval(batch, rows)
        new_keys = self._rewrap(rows, res, len)
        out = []
        for m, nk in zip(rows, (k or [] for k in new_keys)):
            if m is None:
                out.append(None)
                continue
            if any(x is None for x in nk):
                raise ValueError("Cannot use null as map key")
            out.append(list(zip(nk, (v for _, v in m))))
        return _pa(out, self.data_type(batch.schema))


class TransformValues(_MapHigherOrder):
    def data_type(self, schema):
        self._bind_types(schema)
        dt = self.children[0].data_type(schema)
        return MapType(dt.key, self.body.data_type(schema))

    def eval_host(self, batch):
        self._bind_types(batch.schema)
        rows = self.children[0].eval_host(batch).to_pylist()
        res = self._flat_eval(batch, rows)
        new_vals = self._rewrap(rows, res, len)
        out = [None if m is None else list(zip((k for k, _ in m), nv))
               for m, nv in zip(rows, (v or [] for v in new_vals))]
        return _pa(out, self.data_type(batch.schema))


class MapFilter(_MapHigherOrder):
    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_host(self, batch):
        self._bind_types(batch.schema)
        rows = self.children[0].eval_host(batch).to_pylist()
        res = self._flat_eval(batch, rows)
        keeps = self._rewrap(rows, res, len)
        out = [None if m is None else
               [kv for kv, kp in zip(m, kp_row) if kp is True]
               for m, kp_row in zip(rows, (k or [] for k in keeps))]
        return _pa(out, self.data_type(batch.schema))
