"""JSON expressions: get_json_object, from_json, to_json, json_tuple.

Reference analog: GpuGetJsonObject / GpuJsonToStructs / GpuStructsToJson
backed by the `JSONUtils` JNI kernels (SURVEY.md 2.6/2.12). Host-resident in
round 1 (strings have no dense device layout); Spark semantics:

  * get_json_object: JSONPath subset `$`, `.field`, `['field']`, `[index]`,
    `[*]`, `.*`; invalid path or missing => NULL; scalar results unquoted,
    object/array results as compact JSON.
  * from_json: PERMISSIVE mode — malformed row => all-NULL struct fields.
  * to_json: compact JSON, NULL fields omitted (Spark ignoreNullFields=true).
"""
from __future__ import annotations

import json
import math
from typing import List, Optional

from ..types import (ArrayType, DataType, MapType, STRING, Schema,
                     StructType, to_arrow)
from .base import Expression, Literal, Unsupported

__all__ = ["GetJsonObject", "JsonToStructs", "StructsToJson", "JsonTuple",
           "json_path_eval"]


class _HostJsonExpr(Expression):
    def device_unsupported_reason(self, schema: Schema) -> Optional[str]:
        return f"{type(self).__name__}: JSON expressions run on host"


# --- JSONPath subset parser/evaluator ---------------------------------------

def _parse_path(path: str):
    """'$.a[0].b' -> [('key','a'), ('idx',0), ('key','b')]; None if invalid."""
    if not path or path[0] != "$":
        return None
    steps = []
    i = 1
    n = len(path)
    while i < n:
        c = path[i]
        if c == ".":
            i += 1
            if i < n and path[i] == "*":
                steps.append(("wild", None))
                i += 1
                continue
            j = i
            while j < n and path[j] not in ".[":
                j += 1
            if j == i:
                return None
            steps.append(("key", path[i:j]))
            i = j
        elif c == "[":
            j = path.find("]", i)
            if j < 0:
                return None
            inner = path[i + 1:j].strip()
            if inner == "*":
                steps.append(("wild", None))
            elif inner.startswith("'") and inner.endswith("'") and len(inner) >= 2:
                steps.append(("key", inner[1:-1]))
            else:
                try:
                    steps.append(("idx", int(inner)))
                except ValueError:
                    return None
            i = j + 1
        else:
            return None
    return steps


def _walk(obj, steps):
    """Evaluate steps; returns (found, value). Wildcards collect lists."""
    if not steps:
        return True, obj
    kind, arg = steps[0]
    rest = steps[1:]
    if kind == "key":
        if isinstance(obj, dict) and arg in obj:
            return _walk(obj[arg], rest)
        return False, None
    if kind == "idx":
        if isinstance(obj, list) and 0 <= arg < len(obj):
            return _walk(obj[arg], rest)
        return False, None
    # wildcard: map over list elements / dict values
    if isinstance(obj, list):
        vals = []
        for el in obj:
            f, v = _walk(el, rest)
            if f:
                vals.append(v)
        if not vals:
            return False, None
        return True, vals[0] if len(vals) == 1 else vals
    if isinstance(obj, dict):
        vals = []
        for el in obj.values():
            f, v = _walk(el, rest)
            if f:
                vals.append(v)
        if not vals:
            return False, None
        return True, vals[0] if len(vals) == 1 else vals
    return False, None


def _render(v) -> str:
    """Spark renders scalars unquoted, containers as compact JSON."""
    if isinstance(v, str):
        return v
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, float) and v.is_integer():
        return json.dumps(v)
    if isinstance(v, (dict, list)):
        return json.dumps(v, separators=(",", ":"))
    return json.dumps(v)


_PATH_CACHE: dict = {}


def json_path_eval(doc: Optional[str], path: str) -> Optional[str]:
    if doc is None:
        return None
    # the path is almost always a plan-time literal: parse once per distinct
    # path, not once per row (Spark compiles the path per expression)
    if path in _PATH_CACHE:
        steps = _PATH_CACHE[path]
    else:
        steps = _parse_path(path)
        if len(_PATH_CACHE) < 1024:
            _PATH_CACHE[path] = steps
    if steps is None:
        return None
    try:
        obj = json.loads(doc)
    except (json.JSONDecodeError, ValueError):
        return None
    found, v = _walk(obj, steps)
    if not found or v is None:
        return None
    return _render(v)


class GetJsonObject(_HostJsonExpr):
    def __init__(self, child, path):
        self.children = [child, path]

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow as pa
        docs = self.children[0].eval_host(batch).to_pylist()
        paths = self.children[1].eval_host(batch).to_pylist()
        out = [None if p is None else json_path_eval(d, p)
               for d, p in zip(docs, paths)]
        return pa.array(out, type=pa.string())


def _coerce(v, dt: DataType):
    """PERMISSIVE-mode coercion of a parsed JSON value to dt; None if the
    value cannot be coerced (field nulled, row kept)."""
    if v is None:
        return None
    try:
        name = dt.name
        if isinstance(dt, StructType):
            if not isinstance(v, dict):
                return None
            return {f.name: _coerce(v.get(f.name), f.dtype) for f in dt.fields}
        if isinstance(dt, ArrayType):
            if not isinstance(v, list):
                return None
            return [_coerce(x, dt.element) for x in v]
        if isinstance(dt, MapType):
            if not isinstance(v, dict):
                return None
            return [(k, _coerce(x, dt.value)) for k, x in v.items()]
        if name == "string":
            return v if isinstance(v, str) else _render(v)
        if name == "boolean":
            return v if isinstance(v, bool) else None
        if name in ("tinyint", "smallint", "int", "bigint"):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return None
            if isinstance(v, float) and not v.is_integer():
                return None
            return int(v)
        if name in ("float", "double"):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return None
            return float(v)
    except (TypeError, ValueError):
        return None
    return None


class JsonToStructs(_HostJsonExpr):
    """from_json(col, schema) — PERMISSIVE: malformed => null row."""

    def __init__(self, child, schema: DataType):
        self.children = [child]
        self.target = schema

    def data_type(self, schema):
        return self.target

    def eval_host(self, batch):
        import pyarrow as pa
        docs = self.children[0].eval_host(batch).to_pylist()
        out = []
        for d in docs:
            if d is None:
                out.append(None)
                continue
            try:
                obj = json.loads(d)
            except (json.JSONDecodeError, ValueError):
                obj = None
            if obj is None:
                # malformed: all-null struct (PERMISSIVE), null otherwise
                if isinstance(self.target, StructType):
                    out.append({f.name: None for f in self.target.fields})
                else:
                    out.append(None)
                continue
            out.append(_coerce(obj, self.target))
        return pa.array(out, type=to_arrow(self.target))

    def key(self):
        return f"JsonToStructs({self.children[0].key()},{self.target.name})"


def _to_jsonable(v, dt: DataType):
    if v is None:
        return None
    if isinstance(dt, StructType):
        return {f.name: _to_jsonable(v[f.name], f.dtype)
                for f in dt.fields if v.get(f.name) is not None}
    if isinstance(dt, ArrayType):
        return [_to_jsonable(x, dt.element) for x in v]
    if isinstance(dt, MapType):
        return {str(k): _to_jsonable(x, dt.value) for k, x in v}
    if dt.name in ("float", "double"):
        if isinstance(v, float) and math.isnan(v):
            return "NaN"                      # Spark renders as string "NaN"
        if isinstance(v, float) and math.isinf(v):
            return "Infinity" if v > 0 else "-Infinity"
        return v
    if dt.name == "timestamp":
        return v.strftime("%Y-%m-%dT%H:%M:%S.%f%z") if hasattr(v, "strftime") else v
    if dt.name == "date":
        return v.isoformat() if hasattr(v, "isoformat") else v
    if isinstance(dt, type(STRING)) and hasattr(v, "decode"):
        return v.decode("utf-8", "replace")
    return v


class StructsToJson(_HostJsonExpr):
    """to_json(struct|map|array) — compact, NULL fields omitted (Spark
    default ignoreNullFields=true)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow as pa
        dt = self.children[0].data_type(batch.schema)
        rows = self.children[0].eval_host(batch).to_pylist()
        out = [None if v is None else
               json.dumps(_to_jsonable(v, dt), separators=(",", ":"))
               for v in rows]
        return pa.array(out, type=pa.string())


class JsonTuple(_HostJsonExpr):
    """json_tuple(col, f1, f2, ...) — struct of extracted top-level fields
    (Spark's generator form is handled by Generate; the struct output keeps
    this a scalar expression, matching GpuJsonTuple's one-kernel shape)."""

    def __init__(self, child, *fields):
        self.children = [child]
        self.fields: List[str] = [
            f.value if isinstance(f, Literal) else str(f) for f in fields]

    def data_type(self, schema):
        from ..types import StructField
        return StructType([StructField(f"c{i}", STRING)
                           for i in range(len(self.fields))])

    def eval_host(self, batch):
        import pyarrow as pa
        docs = self.children[0].eval_host(batch).to_pylist()
        out = []
        for d in docs:
            row = {}
            obj = None
            if d is not None:
                try:
                    obj = json.loads(d)
                except (json.JSONDecodeError, ValueError):
                    obj = None
            for i, f in enumerate(self.fields):
                v = obj.get(f) if isinstance(obj, dict) else None
                row[f"c{i}"] = None if v is None else _render(v)
            out.append(row)
        return pa.array(out, type=to_arrow(self.data_type(batch.schema)))

    def key(self):
        return f"JsonTuple({self.children[0].key()},{','.join(self.fields)})"
