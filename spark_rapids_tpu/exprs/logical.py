"""Logical AND/OR/NOT with SQL three-valued (Kleene) semantics.

Reference: predicates.scala GpuAnd/GpuOr (cudf and_kleene/or_kleene).
  FALSE AND NULL = FALSE;  TRUE OR NULL = TRUE; otherwise null propagates.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..types import BOOL, TypeSig, TypeEnum
from .base import DVal, Expression
from .arithmetic import arrow_to_masked_numpy, masked_numpy_to_arrow

__all__ = ["And", "Or", "Not"]

_bool_sig = TypeSig([TypeEnum.BOOLEAN])


class And(Expression):
    device_type_sig = _bool_sig
    symbol = "AND"

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self, schema):
        return BOOL

    def eval_device(self, ctx):
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        ld = jnp.logical_and(l.data, l.validity)  # null -> "unknown"
        rd = jnp.logical_and(r.data, r.validity)
        # Kleene: result valid if both valid, OR either side is definite False
        false_l = jnp.logical_and(l.validity, jnp.logical_not(l.data))
        false_r = jnp.logical_and(r.validity, jnp.logical_not(r.data))
        validity = jnp.logical_or(jnp.logical_and(l.validity, r.validity),
                                  jnp.logical_or(false_l, false_r))
        data = jnp.logical_and(ld, rd)
        return DVal(data, validity, BOOL)

    def eval_host(self, batch):
        import pyarrow.compute as pc
        return pc.and_kleene(self.children[0].eval_host(batch),
                             self.children[1].eval_host(batch))

    def key(self):
        return f"and({self.children[0].key()},{self.children[1].key()})"

    @property
    def name_hint(self):
        return f"({self.children[0].name_hint} AND {self.children[1].name_hint})"


class Or(Expression):
    device_type_sig = _bool_sig
    symbol = "OR"

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self, schema):
        return BOOL

    def eval_device(self, ctx):
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        ld = jnp.logical_and(l.data, l.validity)
        rd = jnp.logical_and(r.data, r.validity)
        true_l = jnp.logical_and(l.validity, l.data)
        true_r = jnp.logical_and(r.validity, r.data)
        validity = jnp.logical_or(jnp.logical_and(l.validity, r.validity),
                                  jnp.logical_or(true_l, true_r))
        data = jnp.logical_or(ld, rd)
        return DVal(data, validity, BOOL)

    def eval_host(self, batch):
        import pyarrow.compute as pc
        return pc.or_kleene(self.children[0].eval_host(batch),
                            self.children[1].eval_host(batch))

    def key(self):
        return f"or({self.children[0].key()},{self.children[1].key()})"

    @property
    def name_hint(self):
        return f"({self.children[0].name_hint} OR {self.children[1].name_hint})"


class Not(Expression):
    device_type_sig = _bool_sig

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return BOOL

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        return DVal(jnp.logical_not(c.data), c.validity, BOOL)

    def eval_host(self, batch):
        v, ok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        return masked_numpy_to_arrow(~v.astype(bool), ok, BOOL)

    def key(self):
        return f"not({self.children[0].key()})"
