"""Math functions (ref sql-plugin mathExpressions.scala, 820 LoC).

Unary double functions follow Spark: input cast to double, domain errors
produce NaN (not null) matching java.lang.Math.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..types import FLOAT64, INT64, Schema, numeric
from .base import DVal, Expression, null_and
from .arithmetic import arrow_to_masked_numpy, masked_numpy_to_arrow

__all__ = ["Sqrt", "Exp", "Log", "Log10", "Sin", "Cos", "Tan", "Asin",
           "Acos", "Atan", "Sinh", "Cosh", "Tanh", "Cbrt", "Floor", "Ceil",
           "Round", "Pow", "Signum", "Expm1", "Log1p", "Log2", "Atan2",
           "ToDegrees", "ToRadians", "Rint", "Asinh", "Acosh", "Atanh",
           "Cot", "Hypot", "Logarithm", "BRound"]


class _UnaryDouble(Expression):
    device_type_sig = numeric
    jnp_fn = None
    np_fn = None

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema: Schema):
        return FLOAT64

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        d = c.data.astype(jnp.float64)
        return DVal(type(self).jnp_fn(d), c.validity, FLOAT64)

    def eval_host(self, batch):
        v, ok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        with np.errstate(all="ignore"):
            out = type(self).np_fn(v.astype(np.float64))
        return masked_numpy_to_arrow(out, ok, FLOAT64)

    def key(self):
        return f"{type(self).__name__.lower()}({self.children[0].key()})"


def _mk(name, jf, nf):
    cls = type(name, (_UnaryDouble,), {"jnp_fn": staticmethod(jf),
                                       "np_fn": staticmethod(nf)})
    return cls


Sqrt = _mk("Sqrt", jnp.sqrt, np.sqrt)
Exp = _mk("Exp", jnp.exp, np.exp)
Log = _mk("Log", jnp.log, np.log)
Log10 = _mk("Log10", jnp.log10, np.log10)
Log2 = _mk("Log2", jnp.log2, np.log2)
Log1p = _mk("Log1p", jnp.log1p, np.log1p)
Expm1 = _mk("Expm1", jnp.expm1, np.expm1)
Sin = _mk("Sin", jnp.sin, np.sin)
Cos = _mk("Cos", jnp.cos, np.cos)
Tan = _mk("Tan", jnp.tan, np.tan)
Asin = _mk("Asin", jnp.arcsin, np.arcsin)
Acos = _mk("Acos", jnp.arccos, np.arccos)
Atan = _mk("Atan", jnp.arctan, np.arctan)
Sinh = _mk("Sinh", jnp.sinh, np.sinh)
Cosh = _mk("Cosh", jnp.cosh, np.cosh)
Tanh = _mk("Tanh", jnp.tanh, np.tanh)
Cbrt = _mk("Cbrt", jnp.cbrt, np.cbrt)
ToDegrees = _mk("ToDegrees", jnp.degrees, np.degrees)
ToRadians = _mk("ToRadians", jnp.radians, np.radians)
Rint = _mk("Rint", jnp.rint, np.rint)
Asinh = _mk("Asinh", jnp.arcsinh, np.arcsinh)
Acosh = _mk("Acosh", jnp.arccosh, np.arccosh)
Atanh = _mk("Atanh", jnp.arctanh, np.arctanh)
Cot = _mk("Cot", lambda x: 1.0 / jnp.tan(x), lambda x: 1.0 / np.tan(x))


class Signum(_UnaryDouble):
    jnp_fn = staticmethod(jnp.sign)
    np_fn = staticmethod(np.sign)


class Floor(Expression):
    """floor(double) -> bigint (Spark)."""
    device_type_sig = numeric

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return INT64

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        return DVal(jnp.floor(c.data.astype(jnp.float64)).astype(jnp.int64),
                    c.validity, INT64)

    def eval_host(self, batch):
        v, ok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        with np.errstate(all="ignore"):
            out = np.floor(v.astype(np.float64))
            out = np.where(np.isfinite(out), out, 0)
        return masked_numpy_to_arrow(out.astype(np.int64), ok, INT64)

    def key(self):
        return f"floor({self.children[0].key()})"


class Ceil(Expression):
    device_type_sig = numeric

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return INT64

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        return DVal(jnp.ceil(c.data.astype(jnp.float64)).astype(jnp.int64),
                    c.validity, INT64)

    def eval_host(self, batch):
        v, ok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        with np.errstate(all="ignore"):
            out = np.ceil(v.astype(np.float64))
            out = np.where(np.isfinite(out), out, 0)
        return masked_numpy_to_arrow(out.astype(np.int64), ok, INT64)

    def key(self):
        return f"ceil({self.children[0].key()})"


class Round(Expression):
    """round(x, d) HALF_UP like Spark (not banker's rounding)."""
    device_type_sig = numeric

    def __init__(self, child, decimals: int = 0):
        self.children = [child]
        self.decimals = int(decimals)

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        if jnp.issubdtype(c.data.dtype, jnp.integer) and self.decimals >= 0:
            return c
        scale = 10.0 ** self.decimals
        d = c.data.astype(jnp.float64)
        # HALF_UP: round half away from zero
        out = jnp.sign(d) * jnp.floor(jnp.abs(d) * scale + 0.5) / scale
        return DVal(out.astype(c.data.dtype) if jnp.issubdtype(
            c.data.dtype, jnp.integer) else out, c.validity,
            self.data_type(ctx.schema))

    def eval_host(self, batch):
        v, ok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        dt = self.data_type(batch.schema)
        if np.issubdtype(v.dtype, np.integer) and self.decimals >= 0:
            return masked_numpy_to_arrow(v, ok, dt)
        scale = 10.0 ** self.decimals
        d = v.astype(np.float64)
        with np.errstate(all="ignore"):
            out = np.sign(d) * np.floor(np.abs(d) * scale + 0.5) / scale
            out = np.where(np.isfinite(d), out, d)
        if np.issubdtype(v.dtype, np.integer):
            out = out.astype(v.dtype)
        return masked_numpy_to_arrow(out, ok, dt)

    def key(self):
        return f"round({self.children[0].key()},{self.decimals})"


class Pow(Expression):
    device_type_sig = numeric

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self, schema):
        return FLOAT64

    def eval_device(self, ctx):
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        return DVal(jnp.power(l.data.astype(jnp.float64),
                              r.data.astype(jnp.float64)),
                    null_and(l.validity, r.validity), FLOAT64)

    def eval_host(self, batch):
        l, lv = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        r, rv = arrow_to_masked_numpy(self.children[1].eval_host(batch))
        with np.errstate(all="ignore"):
            out = np.power(l.astype(np.float64), r.astype(np.float64))
        return masked_numpy_to_arrow(out, lv & rv, FLOAT64)

    def key(self):
        return f"pow({self.children[0].key()},{self.children[1].key()})"


class Atan2(Expression):
    device_type_sig = numeric

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self, schema):
        return FLOAT64

    def eval_device(self, ctx):
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        return DVal(jnp.arctan2(l.data.astype(jnp.float64),
                                r.data.astype(jnp.float64)),
                    null_and(l.validity, r.validity), FLOAT64)

    def eval_host(self, batch):
        l, lv = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        r, rv = arrow_to_masked_numpy(self.children[1].eval_host(batch))
        with np.errstate(all="ignore"):
            out = np.arctan2(l.astype(np.float64), r.astype(np.float64))
        return masked_numpy_to_arrow(out, lv & rv, FLOAT64)

    def key(self):
        return f"atan2({self.children[0].key()},{self.children[1].key()})"


class Hypot(Expression):
    """hypot(a, b) = sqrt(a^2 + b^2) without overflow (ref GpuHypot)."""

    device_type_sig = numeric

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self, schema: Schema):
        return FLOAT64

    def eval_device(self, ctx):
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        out = jnp.hypot(l.data.astype(jnp.float64),
                        r.data.astype(jnp.float64))
        return DVal(out, null_and(l.validity, r.validity), FLOAT64)

    def eval_host(self, batch):
        l, lv = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        r, rv = arrow_to_masked_numpy(self.children[1].eval_host(batch))
        with np.errstate(all="ignore"):
            out = np.hypot(l.astype(np.float64), r.astype(np.float64))
        return masked_numpy_to_arrow(out, lv & rv, FLOAT64)

    def key(self):
        return f"hypot({self.children[0].key()},{self.children[1].key()})"


class Logarithm(Expression):
    """log(base, x) (ref GpuLogarithm: log(x) / log(base))."""

    device_type_sig = numeric

    def __init__(self, base, x):
        self.children = [base, x]

    def data_type(self, schema: Schema):
        return FLOAT64

    def eval_device(self, ctx):
        b = self.children[0].eval_device(ctx)
        x = self.children[1].eval_device(ctx)
        out = (jnp.log(x.data.astype(jnp.float64))
               / jnp.log(b.data.astype(jnp.float64)))
        return DVal(out, null_and(b.validity, x.validity), FLOAT64)

    def eval_host(self, batch):
        b, bv = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        x, xv = arrow_to_masked_numpy(self.children[1].eval_host(batch))
        with np.errstate(all="ignore"):
            out = (np.log(x.astype(np.float64))
                   / np.log(b.astype(np.float64)))
        return masked_numpy_to_arrow(out, bv & xv, FLOAT64)

    def key(self):
        return f"log({self.children[0].key()},{self.children[1].key()})"


class BRound(Round):
    """bround: HALF_EVEN (banker's) rounding at the given scale
    (ref GpuBRound; Round is HALF_UP). numpy/jnp ``rint`` IS half-even."""

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        if jnp.issubdtype(c.data.dtype, jnp.integer) and self.decimals >= 0:
            return c
        scale = 10.0 ** self.decimals
        out = jnp.rint(c.data.astype(jnp.float64) * scale) / scale
        return DVal(out, c.validity, self.data_type(ctx.schema))

    def eval_host(self, batch):
        v, ok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        dt = self.data_type(batch.schema)
        if np.issubdtype(v.dtype, np.integer) and self.decimals >= 0:
            return masked_numpy_to_arrow(v, ok, dt)
        scale = 10.0 ** self.decimals
        with np.errstate(all="ignore"):
            d = v.astype(np.float64)
            out = np.rint(d * scale) / scale
            out = np.where(np.isfinite(d), out, d)
        return masked_numpy_to_arrow(out, ok, dt)

    def key(self):
        return f"bround({self.children[0].key()},{self.decimals})"
