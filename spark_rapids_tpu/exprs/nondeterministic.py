"""Non-deterministic / task-context expressions.

Reference analogs: GpuMonotonicallyIncreasingID, GpuSparkPartitionID
(GpuMonotonicallyIncreasingID / GpuSparkPartitionID execs noted in
SURVEY §2.7 Misc), input_file_name handling (InputFileBlockRule.scala) and
GpuRand. They read task context (partition id, current input file) from
ColumnarBatch.meta — the library-embedded analog of Spark's
TaskContext/InputFileBlockHolder — plus a per-execution running row counter
kept on the expression instance (reset via reset_task_state()).

All are host-evaluated: they are O(rows) metadata materializations with no
arithmetic to fuse, so shipping them through the XLA kernel would only add
H2D traffic for values derivable on the host for free. rand() uses a
counter-based generator (splitmix64 over (seed, partition, row)) — same
design choice as the reference, whose GpuRand draws from a device RNG and
matches CPU Spark only distributionally, not bitwise.
"""
from __future__ import annotations

import numpy as np

from ..types import FLOAT64, INT32, INT64, STRING, DataType, Schema
from .base import Expression

__all__ = ["MonotonicallyIncreasingID", "SparkPartitionID", "InputFileName",
           "Rand"]


class _TaskContextExpr(Expression):
    children = []

    def nullable(self, schema: Schema) -> bool:
        return False

    def device_unsupported_reason(self, schema):
        return f"{type(self).__name__}: host-evaluated task-context expression"

    def references(self):
        return []

    def reset_task_state(self):
        """Called by the hosting exec at the start of each plan execution so
        re-collecting the same DataFrame restarts counters (Spark resets
        per-task state on every task launch)."""


class MonotonicallyIncreasingID(_TaskContextExpr):
    """(partition_id << 33) + running row index within the partition —
    Spark's exact formula. The row index is a per-expression-instance running
    counter (the reference's GpuMonotonicallyIncreasingID likewise keeps a
    per-task count), NOT the batch's scan offset: upstream filters/generators
    change row counts, and Spark numbers the rows this operator *sees*."""

    def __init__(self):
        self._next = {}

    def reset_task_state(self):
        self._next = {}

    def data_type(self, schema: Schema) -> DataType:
        return INT64

    def eval_host(self, batch):
        import pyarrow as pa
        pid = batch.meta.get("partition_id", 0)
        off = self._next.get(pid, 0)
        self._next[pid] = off + batch.num_rows
        base = (np.int64(pid) << np.int64(33)) + np.int64(off)
        return pa.array(base + np.arange(batch.num_rows, dtype=np.int64))

    def key(self):
        return "MonotonicallyIncreasingID()"

    @property
    def name_hint(self):
        return "monotonically_increasing_id()"


class SparkPartitionID(_TaskContextExpr):
    def data_type(self, schema: Schema) -> DataType:
        return INT32

    def eval_host(self, batch):
        import pyarrow as pa
        pid = np.int32(batch.meta.get("partition_id", 0))
        return pa.array(np.full(batch.num_rows, pid, dtype=np.int32))

    def key(self):
        return "SparkPartitionID()"

    @property
    def name_hint(self):
        return "SPARK_PARTITION_ID()"


class InputFileName(_TaskContextExpr):
    """Current input file path, or "" when the source is not file-based
    (Spark semantics; ref InputFileBlockRule.scala)."""

    def data_type(self, schema: Schema) -> DataType:
        return STRING

    def eval_host(self, batch):
        import pyarrow as pa
        fname = batch.meta.get("input_file", "") or ""
        return pa.array([fname] * batch.num_rows, type=pa.string())

    def key(self):
        return "InputFileName()"

    @property
    def name_hint(self):
        return "input_file_name()"


class InputFileBlockStart(_TaskContextExpr):
    """Byte offset of the current input block; this engine reads whole
    files per task, so the block starts at 0 (-1 when the source is not
    file-based — Spark semantics; ref InputFileBlockRule.scala)."""

    def data_type(self, schema: Schema) -> DataType:
        return INT64

    def eval_host(self, batch):
        import pyarrow as pa
        v = 0 if batch.meta.get("input_file") else -1
        return pa.array([v] * batch.num_rows, type=pa.int64())

    def key(self):
        return "InputFileBlockStart()"

    @property
    def name_hint(self):
        return "input_file_block_start()"


class InputFileBlockLength(_TaskContextExpr):
    """Length of the current input block = the whole file here (-1 when
    not file-based; ref InputFileBlockRule.scala)."""

    def data_type(self, schema: Schema) -> DataType:
        return INT64

    def eval_host(self, batch):
        import os
        import pyarrow as pa
        f = batch.meta.get("input_file")
        try:
            v = os.path.getsize(f) if f else -1
        except OSError:
            v = -1
        return pa.array([v] * batch.num_rows, type=pa.int64())

    def key(self):
        return "InputFileBlockLength()"

    @property
    def name_hint(self):
        return "input_file_block_length()"


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = (x + np.uint64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


class Rand(_TaskContextExpr):
    """Uniform [0, 1) per row via a counter-based hash of
    (seed, partition, row index seen) — deterministic for a fixed plan run;
    nondeterministic under re-execution, exactly like Spark's rand()."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._next = {}

    def reset_task_state(self):
        self._next = {}

    def data_type(self, schema: Schema) -> DataType:
        return FLOAT64

    def eval_host(self, batch):
        import pyarrow as pa
        pid = batch.meta.get("partition_id", 0)
        off = self._next.get(pid, 0)
        self._next[pid] = off + batch.num_rows
        idx = np.arange(off, off + batch.num_rows, dtype=np.uint64)
        with np.errstate(over="ignore"):
            mixed = _splitmix64(
                idx ^ _splitmix64(np.uint64((self.seed & 0xFFFFFFFFFFFFFFFF))
                                  + (np.uint64(pid) << np.uint64(32))))
        u = (mixed >> np.uint64(11)).astype(np.float64) / float(1 << 53)
        return pa.array(u)

    def key(self):
        return f"Rand({self.seed})"

    @property
    def name_hint(self):
        return f"rand({self.seed})"
