"""Pallas TPU kernels for byte-rectangle string matching.

First custom-kernel tier below the XLA ops (SURVEY.md L0; the analog of
the reference's hand-written cudf string kernels, stringFunctions.scala
device paths). The sliding-pattern match family (contains / startswith /
endswith / locate) maps exactly onto the VPU: a byte rectangle
``bytes_[P, W]`` tiles as (rows, lanes); each pattern offset is a STATIC
lane slice compared against broadcast pattern constants, and the
first-match position is one lane-dim min-reduction. No gathers, no
scatters, no sorts — the kernel is pure elementwise + reduction work the
Mosaic compiler schedules tightly.

Opt-in via ``spark.rapids.tpu.sql.pallas.enabled`` (the XLA fallback in
string_rect.py stays the default until the kernel measures faster on
the target backend); on the CPU backend the kernels run in interpreter
mode so differential tests cover them everywhere.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..config import register

__all__ = ["PALLAS_ENABLED", "pallas_match", "pallas_available"]

PALLAS_ENABLED = register(
    "spark.rapids.tpu.sql.pallas.enabled", False,
    "Route byte-rectangle string predicate kernels (contains/startswith/"
    "endswith/locate and the literal LIKE forms) through hand-written "
    "Pallas TPU kernels instead of the fused XLA ops "
    "(exprs/pallas_rect.py). On the CPU backend the kernels run in "
    "interpreter mode (tests); OFF by default until measured faster "
    "than XLA on the deployment backend.")

#: rows per grid step: uint8 tiles want >= 32 sublanes; 256 rows keeps
#: each block's VMEM footprint at 256*W bytes (W <= 1024)
_BLOCK_ROWS = 256


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas  # noqa: F401
        return True
    except ImportError:  # pragma: no cover - pallas ships with jax
        return False


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.lru_cache(maxsize=None)
def _match_kernel(pat: bytes, mode: str, w: int, padded: int):
    """Build the pallas_call for one (pattern, mode, width, rows) shape.

    mode: "contains" | "startswith" | "endswith" | "equals" -> bool[P]
          "locate" -> int32[P] (1-based first occurrence, 0 if absent)
    """
    from jax.experimental import pallas as pl

    p = np.frombuffer(pat, np.uint8)
    L = len(p)
    grid = (padded // _BLOCK_ROWS,)
    out_dtype = jnp.int32 if mode == "locate" else jnp.bool_

    def kernel(b_ref, len_ref, out_ref):
        b = b_ref[...]                      # [BLOCK, W] uint8
        ln = len_ref[...]                   # [BLOCK] int32

        def match_at(s):
            # all pattern bytes match at static offset s
            m = jnp.ones((_BLOCK_ROWS,), jnp.bool_)
            for j, ch in enumerate(p):
                m = jnp.logical_and(m, b[:, s + j] == np.uint8(ch))
            return m

        if L == 0:
            # empty pattern: everything contains/starts/ends with it,
            # locate('')==1, but equals matches only empty strings
            if mode == "equals":
                out_ref[...] = ln == 0
            elif mode == "locate":
                out_ref[...] = jnp.ones((_BLOCK_ROWS,), jnp.int32)
            else:
                out_ref[...] = jnp.ones((_BLOCK_ROWS,), jnp.bool_)
            return
        if L > w:
            # pattern wider than the rectangle: no row can match
            out_ref[...] = (jnp.zeros((_BLOCK_ROWS,), jnp.int32)
                            if mode == "locate"
                            else jnp.zeros((_BLOCK_ROWS,), jnp.bool_))
            return
        if mode == "startswith":
            out_ref[...] = jnp.logical_and(ln >= L, match_at(0))
            return
        if mode == "equals":
            out_ref[...] = jnp.logical_and(ln == L, match_at(0))
            return
        if mode == "endswith":
            hit = jnp.zeros((_BLOCK_ROWS,), jnp.bool_)
            for s in range(w - L + 1):
                hit = jnp.where(ln - L == s, match_at(s), hit)
            out_ref[...] = jnp.logical_and(ln >= L, hit)
            return
        # contains / locate: first offset whose window matches
        first = jnp.full((_BLOCK_ROWS,), w + 1, jnp.int32)
        for s in range(w - L + 1):
            m = jnp.logical_and(match_at(s), ln - L >= s)
            first = jnp.minimum(first,
                                jnp.where(m, jnp.int32(s + 1), w + 1))
        if mode == "locate":
            out_ref[...] = jnp.where(first <= w, first, 0)
        else:
            out_ref[...] = first <= w

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, w), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((padded,), out_dtype),
        interpret=_interpret(),
    )


def pallas_match(bytes_, lengths, pattern: bytes, mode: str):
    """Sliding-pattern match over a byte rectangle via the Pallas kernel.
    Traced (callable inside jit); pads rows to the block multiple and
    slices back."""
    padded, w = bytes_.shape
    rows = padded
    pad_to = -padded % _BLOCK_ROWS
    if pad_to:
        bytes_ = jnp.pad(bytes_, ((0, pad_to), (0, 0)))
        lengths = jnp.pad(lengths, (0, pad_to))
        padded += pad_to
    out = _match_kernel(pattern, mode, w, padded)(
        bytes_, lengths.astype(jnp.int32))
    return out[:rows]
