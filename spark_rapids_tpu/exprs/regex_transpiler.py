"""Java-regex front-end + transpiler.

Reference analog: RegexParser.scala:44 / CudfRegexTranspiler:687 (2,186 LoC)
— Spark expressions take JAVA regex semantics, the accelerator's engine
(cudf there, Python `re` executing over Arrow here, a Pallas DFA engine
later) has different semantics, so regexes are parsed into an AST and
re-emitted for the target engine, REJECTING patterns whose semantics would
silently differ (the planner then falls back, mirroring
GpuRegExpReplaceMeta's willNotWorkOnGpu tagging).

Java -> Python divergences handled:
  * \\d \\w \\s (and negations) are ASCII in Java, Unicode in Python ->
    rewritten to explicit ASCII classes
  * \\b / \\B are ASCII in Java -> scoped (?a:...) ASCII-flag groups
  * \\Z (end before the FINAL line terminator) -> an explicit
    lookahead over Java's terminator set; \\R (any linebreak) -> its
    defined alternation
  * POSIX/Java ASCII named classes \\p{Alpha}/\\p{Digit}/... -> explicit
    ASCII classes; Unicode category classes (\\p{L}, \\p{Lu}, ...) ->
    reject (engine semantics differ)
  * nested character-class UNIONS [a[bc]] -> flattened [abc];
    class intersection && -> reject
  * \\G, \\X, \\b inside classes -> reject
  * octal escapes \\0nn -> \\nnn form
  * possessive quantifiers / atomic groups pass through (Python >= 3.11)
"""
from __future__ import annotations

import re as _re
from typing import List, Optional, Tuple

__all__ = ["RegexUnsupported", "transpile_java_regex", "RegexParser"]

_D = "[0-9]"
_ND = "[^0-9]"
_W = "[a-zA-Z0-9_]"
_NW = "[^a-zA-Z0-9_]"
_S = "[ \\t\\n\\x0b\\f\\r]"
_NS = "[^ \\t\\n\\x0b\\f\\r]"

#: Java \Z: end of input but for a final line terminator
_END_Z = "(?=(?:\\r\\n|[\\n\\r\\x85\\u2028\\u2029])?\\Z)"
#: Java \R: any unicode linebreak sequence
_ANY_BREAK = "(?:\\r\\n|[\\n\\x0b\\f\\r\\x85\\u2028\\u2029])"

#: POSIX/Java ASCII named classes (RegexParser.scala handles the same
#: set); values are class BODIES (composable inside [...])
_POSIX = {
    "Lower": "a-z", "Upper": "A-Z", "ASCII": "\\x00-\\x7f",
    "Alpha": "a-zA-Z", "Digit": "0-9", "Alnum": "a-zA-Z0-9",
    "Punct": "!-/:-@\\[-`{-~", "Graph": "!-~", "Print": " -~",
    "Blank": " \\t", "Cntrl": "\\x00-\\x1f\\x7f",
    "XDigit": "0-9a-fA-F", "Space": " \\t\\n\\x0b\\f\\r",
}


class RegexUnsupported(ValueError):
    """Pattern cannot be transpiled with identical semantics."""


class RegexParser:
    """Minimal Java-regex tokenizer/validator. Walks the pattern once,
    validating structure and rewriting escapes; nesting is tracked for
    groups and classes."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.out: List[str] = []
        self.group_depth = 0

    def error(self, msg: str):
        raise RegexUnsupported(f"{msg} near position {self.i} in "
                               f"{self.p!r}")

    def peek(self) -> str:
        return self.p[self.i] if self.i < len(self.p) else ""

    def take(self) -> str:
        c = self.peek()
        self.i += 1
        return c

    # ------------------------------------------------------------------
    def parse(self) -> str:
        while self.i < len(self.p):
            c = self.take()
            if c == "\\":
                self._escape(in_class=False)
            elif c == "[":
                self._char_class()
            elif c == "(":
                self._group_open()
            elif c == ")":
                self.group_depth -= 1
                if self.group_depth < 0:
                    self.error("unbalanced )")
                self.out.append(c)
            else:
                self.out.append(c)
        if self.group_depth != 0:
            self.error("unbalanced (")
        result = "".join(self.out)
        try:
            _re.compile(result)
        except _re.error as e:
            raise RegexUnsupported(f"transpiled pattern invalid: {e}")
        return result

    # ------------------------------------------------------------------
    def _escape(self, in_class: bool):
        c = self.take()
        if c == "":
            self.error("dangling backslash")
        if c == "d":
            self.out.append(_D if not in_class else "0-9")
        elif c == "D":
            if in_class:
                self.error("\\D inside character class")
            self.out.append(_ND)
        elif c == "w":
            self.out.append(_W if not in_class else "a-zA-Z0-9_")
        elif c == "W":
            if in_class:
                self.error("\\W inside character class")
            self.out.append(_NW)
        elif c == "s":
            self.out.append(_S if not in_class else " \\t\\n\\x0b\\f\\r")
        elif c == "S":
            if in_class:
                self.error("\\S inside character class")
            self.out.append(_NS)
        elif c == "Z":
            if in_class:
                self.error("\\Z inside character class")
            self.out.append(_END_Z)
        elif c == "R":
            if in_class:
                self.error("\\R inside character class")
            self.out.append(_ANY_BREAK)
        elif c in ("G", "X"):
            self.error(f"\\{c} is not supported")
        elif c == "p" or c == "P":
            self._named_class(negated=(c == "P"), in_class=in_class)
        elif c in ("b", "B") and not in_class:
            # Java boundaries use its ASCII \w; scope the ASCII flag
            self.out.append(f"(?a:\\{c})")
        elif c == "b" and in_class:
            self.error("\\b inside character class")
        elif c == "z":
            self.out.append("\\Z")  # Java \z == Python \Z
        elif c == "0":
            # Java octal \0nn -> Python \nnn
            digits = ""
            while self.peek().isdigit() and len(digits) < 3:
                digits += self.take()
            if not digits:
                self.error("bad octal escape")
            self.out.append("\\" + digits.zfill(3))
        else:
            self.out.append("\\" + c)

    # ------------------------------------------------------------------
    def _named_class(self, negated: bool, in_class: bool):
        if self.take() != "{":
            self.error("malformed \\p escape")
        name = ""
        while self.peek() and self.peek() != "}":
            name += self.take()
        if self.take() != "}":
            self.error("unterminated \\p{...}")
        body = _POSIX.get(name)
        if body is None:
            # Unicode category/property classes (\p{L}, \p{IsDigit},
            # scripts, blocks): Java resolves them over Unicode, which
            # the ASCII expansions cannot reproduce — honest rejection
            self.error(f"\\p{{{name}}} is not supported")
        if in_class:
            if negated:
                self.error("\\P{...} inside character class")
            self.out.append(body)
        else:
            self.out.append(("[^" if negated else "[") + body + "]")

    # ------------------------------------------------------------------
    def _char_class(self, nested: bool = False):
        if not nested:
            self.out.append("[")
            if self.peek() == "^":
                self.out.append(self.take())
        if self.peek() == "]":
            self.out.append("\\]")
            self.take()
        while True:
            c = self.take()
            if c == "":
                self.error("unterminated character class")
            if c == "]":
                if not nested:
                    self.out.append("]")
                return
            if c == "\\":
                self._escape(in_class=True)
            elif c == "[":
                # Java nested class UNION [a[bc]]: flatten the inner
                # class's members into the enclosing one. A negated
                # nested class is set arithmetic — reject.
                if self.peek() == "^":
                    self.error("negated nested character class")
                self._char_class(nested=True)
            elif c == "&" and self.peek() == "&":
                self.error("character class intersection &&")
            else:
                self.out.append(c)

    # ------------------------------------------------------------------
    def _group_open(self):
        self.group_depth += 1
        self.out.append("(")
        if self.peek() != "?":
            return
        self.out.append(self.take())  # '?'
        c = self.peek()
        if c in (":", "=", "!", ">"):
            self.out.append(self.take())
        elif c == "<":
            self.out.append(self.take())
            n = self.peek()
            if n in ("=", "!"):
                self.out.append(self.take())  # lookbehind
            else:
                # named group (?<name>...) -> Python (?P<name>...)
                self.out.pop()
                self.out.append("P<")
        elif c in ("i", "m", "s", "u", "x", "d", "-"):
            while self.peek() and self.peek() not in ":)":
                f = self.take()
                if f in ("u", "d"):
                    self.error(f"inline flag ({f}) is not supported")
                self.out.append(f)
            if self.peek():
                self.out.append(self.take())
        else:
            self.error(f"unsupported group construct (?{c}")


def transpile_java_regex(pattern: str) -> str:
    """Java regex -> semantically-equivalent Python regex, or raise
    RegexUnsupported (planner turns that into a CPU... here a
    fallback-to-row reason, mirroring the reference)."""
    return RegexParser(pattern).parse()


def sql_like_to_regex(pattern: str, escape: str = "\\") -> str:
    """SQL LIKE pattern -> anchored regex (ref GpuLike)."""
    out = ["^"]
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            out.append(_re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(_re.escape(c))
        i += 1
    out.append("$")
    return "".join(out)
