"""Java-regex front-end + transpiler.

Reference analog: RegexParser.scala:44 / CudfRegexTranspiler:687 (2,186 LoC)
— Spark expressions take JAVA regex semantics, the accelerator's engine
(cudf there, Python `re` executing over Arrow here, a Pallas DFA engine
later) has different semantics, so regexes are parsed into an AST and
re-emitted for the target engine, REJECTING patterns whose semantics would
silently differ (the planner then falls back, mirroring
GpuRegExpReplaceMeta's willNotWorkOnGpu tagging).

The transpiler is TARGET-AWARE: ``target="python"`` emits for the
stdlib `re` engine (RegExpExtract's row loop), ``target="re2"`` emits
for pyarrow's RE2 engine (RLike / RegExpReplace / StringSplit run
through pc.*_regex kernels). RE2 has no lookaround, no backreferences
and no (?a) flag, but its \\b/\\w/\\d are already ASCII like Java's —
so the two targets need different rewrites (and different rejections).

Java -> Python divergences handled:
  * \\d \\w \\s (and negations) are ASCII in Java, Unicode in Python ->
    rewritten to explicit ASCII classes (RE2: already ASCII, but the
    explicit classes are valid there too)
  * \\b / \\B are ASCII in Java -> scoped (?a:...) ASCII-flag groups for
    Python; passed through verbatim for RE2 (same ASCII semantics)
  * \\Z (end before the FINAL line terminator) -> an explicit
    lookahead over Java's terminator set for Python; REJECTED for RE2
    (no lookahead); \\R (any linebreak) -> its defined alternation
  * POSIX/Java ASCII named classes \\p{Alpha}/\\p{Digit}/... -> explicit
    ASCII classes; Unicode category classes (\\p{L}, \\p{Lu}, ...) ->
    reject (engine semantics differ)
  * nested character-class UNIONS [a[bc]] -> flattened [abc];
    class intersection && -> reject
  * \\G, \\X, \\b inside classes -> reject
  * octal escapes \\0nn -> \\nnn form
  * possessive quantifiers / atomic groups pass through (Python >= 3.11)
"""
from __future__ import annotations

import re as _re
from typing import List, Optional, Tuple

__all__ = ["RegexUnsupported", "transpile_java_regex", "RegexParser"]

_D = "[0-9]"
_ND = "[^0-9]"
_W = "[a-zA-Z0-9_]"
_NW = "[^a-zA-Z0-9_]"
_S = "[ \\t\\n\\x0b\\f\\r]"
_NS = "[^ \\t\\n\\x0b\\f\\r]"

#: Java \Z: end of input but for a final line terminator
_END_Z = "(?=(?:\\r\\n|[\\n\\r\\x85\\u2028\\u2029])?\\Z)"
#: Java \R: any unicode linebreak sequence
_ANY_BREAK = "(?:\\r\\n|[\\n\\x0b\\f\\r\\x85\\u2028\\u2029])"
#: RE2 spells non-BMP-ish escapes \x{...} rather than \uXXXX
_ANY_BREAK_RE2 = "(?:\\r\\n|[\\n\\x0b\\f\\r\\x85\\x{2028}\\x{2029}])"
#: Java line terminators (the set `.` excludes and `$`/\Z anchor before)
_TERM_RE2 = "(?:\\r\\n|[\\n\\r\\x85\\x{2028}\\x{2029}])"
#: Java `.` (no DOTALL) excludes ALL line terminators; Python/RE2 dot
#: excludes only \n -> rewrite to an explicit negated class
_DOT = "[^\\n\\r\\x85\\u2028\\u2029]"
_DOT_RE2 = "[^\\n\\r\\x85\\x{2028}\\x{2029}]"

#: POSIX/Java ASCII named classes (RegexParser.scala handles the same
#: set); values are class BODIES (composable inside [...])
_POSIX = {
    "Lower": "a-z", "Upper": "A-Z", "ASCII": "\\x00-\\x7f",
    "Alpha": "a-zA-Z", "Digit": "0-9", "Alnum": "a-zA-Z0-9",
    "Punct": "!-/:-@\\[-`{-~", "Graph": "!-~", "Print": " -~",
    "Blank": " \\t", "Cntrl": "\\x00-\\x1f\\x7f",
    "XDigit": "0-9a-fA-F", "Space": " \\t\\n\\x0b\\f\\r",
}


class RegexUnsupported(ValueError):
    """Pattern cannot be transpiled with identical semantics."""


class RegexParser:
    """Minimal Java-regex tokenizer/validator. Walks the pattern once,
    validating structure and rewriting escapes; nesting is tracked for
    groups and classes."""

    def __init__(self, pattern: str, target: str = "python",
                 mode: str = "find"):
        if target not in ("python", "re2"):
            raise ValueError(f"unknown regex target {target!r}")
        if mode not in ("find", "replace", "split"):
            raise ValueError(f"unknown regex mode {mode!r}")
        self.p = pattern
        self.i = 0
        self.out: List[str] = []
        self.group_depth = 0
        self.target = target
        self.mode = mode
        self.dotall = False
        # A global leading flag group (?s)/(?is)... is the one scoping we
        # can honor exactly: strip it, remember dotall, re-emit verbatim.
        m = _re.match(r"^\(\?([ims]+)\)", self.p)
        if m:
            if "m" in m.group(1):
                # Java multiline anchors recognize \r\n/\r/\x85/u2028/29;
                # Python's and RE2's (?m) recognize only \n
                raise RegexUnsupported(
                    "(?m) multiline anchors have Java-specific line "
                    "terminators")
            self.dotall = "s" in m.group(1)
            self.out.append(m.group(0))
            self.i = m.end()

    def error(self, msg: str):
        raise RegexUnsupported(f"{msg} near position {self.i} in "
                               f"{self.p!r}")

    def peek(self) -> str:
        return self.p[self.i] if self.i < len(self.p) else ""

    def take(self) -> str:
        c = self.peek()
        self.i += 1
        return c

    # ------------------------------------------------------------------
    def parse(self) -> str:
        while self.i < len(self.p):
            c = self.take()
            if c == "\\":
                self._escape(in_class=False)
            elif c == ".":
                if self.dotall:
                    self.out.append(".")
                else:
                    self.out.append(_DOT if self.target == "python"
                                    else _DOT_RE2)
            elif c == "$":
                self._dollar()
            elif c == "[":
                self._char_class()
            elif c == "(":
                self._group_open()
            elif c == ")":
                self.group_depth -= 1
                if self.group_depth < 0:
                    self.error("unbalanced )")
                self.out.append(c)
            else:
                self.out.append(c)
        if self.group_depth != 0:
            self.error("unbalanced (")
        result = "".join(self.out)
        if self.target == "python":
            try:
                _re.compile(result)
            except _re.error as e:
                raise RegexUnsupported(f"transpiled pattern invalid: {e}")
        else:
            # Compile-check against the actual RE2 engine: catches
            # everything RE2 rejects that the walk above passed through
            # (backreferences, possessive quantifiers, \uXXXX escapes,
            # ...), at plan time instead of mid-query. One real element —
            # pyarrow skips kernel compilation entirely on empty input.
            import pyarrow as _pa
            import pyarrow.compute as _pc
            try:
                _pc.match_substring_regex(
                    _pa.array([""], type=_pa.string()), result)
            except Exception as e:
                raise RegexUnsupported(
                    f"pattern unsupported by RE2 engine: {e}")
        return result

    # ------------------------------------------------------------------
    def _escape(self, in_class: bool):
        c = self.take()
        if c == "":
            self.error("dangling backslash")
        if c == "d":
            self.out.append(_D if not in_class else "0-9")
        elif c == "D":
            if in_class:
                self.error("\\D inside character class")
            self.out.append(_ND)
        elif c == "w":
            self.out.append(_W if not in_class else "a-zA-Z0-9_")
        elif c == "W":
            if in_class:
                self.error("\\W inside character class")
            self.out.append(_NW)
        elif c == "s":
            self.out.append(_S if not in_class else " \\t\\n\\x0b\\f\\r")
        elif c == "S":
            if in_class:
                self.error("\\S inside character class")
            self.out.append(_NS)
        elif c == "Z":
            if in_class:
                self.error("\\Z inside character class")
            # Java \Z == Java non-multiline $ -> shared rewrite
            self._dollar(spelled=r"\Z")
        elif c == "R":
            if in_class:
                self.error("\\R inside character class")
            self.out.append(_ANY_BREAK if self.target == "python"
                            else _ANY_BREAK_RE2)
        elif c in ("G", "X"):
            self.error(f"\\{c} is not supported")
        elif c == "p" or c == "P":
            self._named_class(negated=(c == "P"), in_class=in_class)
        elif c in ("b", "B") and not in_class:
            if self.target == "re2":
                # RE2's \b/\B are ASCII already — same as Java's
                self.out.append(f"\\{c}")
            else:
                # Python's use its Unicode \w; scope the ASCII flag
                self.out.append(f"(?a:\\{c})")
        elif c == "b" and in_class:
            self.error("\\b inside character class")
        elif c == "z":
            # Java \z: RE2 supports \z natively; Python spells it \Z
            self.out.append("\\z" if self.target == "re2" else "\\Z")
        elif c == "0":
            # Java octal \0nn -> Python \nnn
            digits = ""
            while self.peek().isdigit() and len(digits) < 3:
                digits += self.take()
            if not digits:
                self.error("bad octal escape")
            self.out.append("\\" + digits.zfill(3))
        else:
            self.out.append("\\" + c)

    # ------------------------------------------------------------------
    def _dollar(self, spelled: str = "$"):
        """Java non-multiline `$` (and its synonym \\Z): matches at end
        of input OR just before one FINAL line terminator — wider than
        Python's (only \\n) and RE2's (end of text only)."""
        if self.target == "python":
            self.out.append(_END_Z)
        elif self.mode == "find":
            # boolean-match contexts may CONSUME the terminator: same
            # verdict, no lookahead needed (RE2 has none)
            self.out.append(_TERM_RE2 + "?$")
        else:
            # replace/split would swallow the terminator into the match
            self.error(f"{spelled} requires lookahead in "
                       f"{self.mode} mode (RE2 target)")

    # ------------------------------------------------------------------
    def _named_class(self, negated: bool, in_class: bool):
        if self.take() != "{":
            self.error("malformed \\p escape")
        name = ""
        while self.peek() and self.peek() != "}":
            name += self.take()
        if self.take() != "}":
            self.error("unterminated \\p{...}")
        body = _POSIX.get(name)
        if body is None:
            # Unicode category/property classes (\p{L}, \p{IsDigit},
            # scripts, blocks): Java resolves them over Unicode, which
            # the ASCII expansions cannot reproduce — honest rejection
            self.error(f"\\p{{{name}}} is not supported")
        if in_class:
            if negated:
                self.error("\\P{...} inside character class")
            self.out.append(body)
        else:
            self.out.append(("[^" if negated else "[") + body + "]")

    # ------------------------------------------------------------------
    def _char_class(self, nested: bool = False):
        if not nested:
            self.out.append("[")
            if self.peek() == "^":
                self.out.append(self.take())
        if self.peek() == "]":
            self.out.append("\\]")
            self.take()
        while True:
            c = self.take()
            if c == "":
                self.error("unterminated character class")
            if c == "]":
                if not nested:
                    self.out.append("]")
                return
            if c == "\\":
                self._escape(in_class=True)
            elif c == "[":
                # Java nested class UNION [a[bc]]: flatten the inner
                # class's members into the enclosing one. A negated
                # nested class is set arithmetic — reject.
                if self.peek() == "^":
                    self.error("negated nested character class")
                self._char_class(nested=True)
            elif c == "&" and self.peek() == "&":
                self.error("character class intersection &&")
            else:
                self.out.append(c)

    # ------------------------------------------------------------------
    def _group_open(self):
        self.group_depth += 1
        self.out.append("(")
        if self.peek() != "?":
            return
        self.out.append(self.take())  # '?'
        c = self.peek()
        if c in (":", "=", "!", ">"):
            if self.target == "re2" and c in ("=", "!", ">"):
                self.error(f"(?{c} lookaround/atomic group (RE2 target)")
            self.out.append(self.take())
        elif c == "<":
            self.out.append(self.take())
            n = self.peek()
            if n in ("=", "!"):
                if self.target == "re2":
                    self.error("lookbehind (RE2 target)")
                self.out.append(self.take())  # lookbehind
            else:
                # named group (?<name>...) -> Python (?P<name>...)
                self.out.pop()
                self.out.append("P<")
        elif c in ("i", "m", "s", "u", "x", "d", "-"):
            while self.peek() and self.peek() not in ":)":
                f = self.take()
                if f in ("u", "d"):
                    self.error(f"inline flag ({f}) is not supported")
                if f == "m":
                    self.error("(?m) multiline anchors have "
                               "Java-specific line terminators")
                if f == "s":
                    # scoped/mid-pattern DOTALL would need per-region
                    # dot rewrites; only the global prefix is honored
                    self.error("non-global (?s) flag is not supported")
                self.out.append(f)
            if self.peek():
                self.out.append(self.take())
        else:
            self.error(f"unsupported group construct (?{c}")


def transpile_java_regex(pattern: str, target: str = "python",
                         mode: str = "find") -> str:
    """Java regex -> semantically-equivalent regex for ``target``
    ("python" = stdlib re, "re2" = pyarrow's RE2 kernels) in ``mode``
    ("find" boolean match / "replace" / "split" — anchors rewrite
    differently per mode, ref CudfRegexTranspiler's RegexMode), or
    raise RegexUnsupported (planner turns that into a CPU... here a
    fallback-to-row reason, mirroring the reference)."""
    return RegexParser(pattern, target=target, mode=mode).parse()


def sql_like_to_regex(pattern: str, escape: str = "\\") -> str:
    """SQL LIKE pattern -> anchored regex (ref GpuLike)."""
    out = ["^"]
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            out.append(_re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(_re.escape(c))
        i += 1
    out.append("$")
    return "".join(out)
