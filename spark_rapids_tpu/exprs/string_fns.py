"""String expressions (ref stringFunctions.scala, 2,377 LoC).

Strings are host-resident (Arrow) in round 1 — every expression here is a
vectorized Arrow kernel, honestly tagged host-only so the planner records
the fallback (the reference's TypeSig machinery makes exactly this per-type
fallback cheap, SURVEY.md section 7 hard-part #2). Numeric outputs (length,
locate, comparisons) are H2D'd by the project exec so downstream compute
stays on the TPU. Regex expressions go through the Java->Python transpiler
(regex_transpiler.py) and REJECT patterns with divergent semantics.
"""
from __future__ import annotations

from typing import Optional

from ..types import (BOOL, INT32, STRING, Schema, TypeSig, TypeEnum)
from .base import Expression, Unsupported

__all__ = ["Length", "Upper", "Lower", "Substring", "ConcatStrings",
           "Contains", "StartsWith", "EndsWith", "Like", "RLike",
           "RegExpReplace", "RegExpExtract", "StringTrim", "StringTrimLeft",
           "StringTrimRight", "StringReplace", "StringLocate", "Lpad",
           "Rpad", "Reverse", "StringRepeat", "InitCap", "StringSplit",
           "SubstringIndex", "Ascii", "Chr", "BitLength", "OctetLength",
           "RegExpExtractAll", "Conv",
           "StringInstr", "StringTranslate", "ConcatWs", "FormatNumber"]

_str_sig = TypeSig([TypeEnum.STRING])


class _HostStringExpr(Expression):
    """Base: runs on host Arrow; device tagging returns an explicit reason
    so explain output mirrors the reference's NOT_ON_GPU messages.

    ``dict_transform = True`` marks VALUE-WISE string->string transforms:
    over a dictionary-coded column the project exec evaluates them ONCE
    per distinct dictionary entry and re-encodes — row data never leaves
    the device (the O(dict) transform generalization of the r2 predicate
    trick; ref stringFunctions.scala device kernels)."""

    #: subclasses that map each string value independently set True
    dict_transform = False

    def device_unsupported_reason(self, schema: Schema) -> Optional[str]:
        return f"{type(self).__name__}: string expressions run on host"

    def key(self):
        kids = ",".join(c.key() for c in self.children)
        return f"{type(self).__name__}({kids})"


class Length(_HostStringExpr):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return INT32

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        return pc.cast(pc.utf8_length(self.children[0].eval_host(batch)),
                       pa.int32())


class Upper(_HostStringExpr):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    dict_transform = True
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow.compute as pc
        return pc.utf8_upper(self.children[0].eval_host(batch))


class Lower(_HostStringExpr):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    dict_transform = True
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow.compute as pc
        return pc.utf8_lower(self.children[0].eval_host(batch))


class Substring(_HostStringExpr):
    """Spark substring: 1-based, pos 0 treated as 1, negative from end."""
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    dict_transform = True

    def __init__(self, child, pos: int, length: Optional[int] = None):
        self.children = [child]
        self.pos = pos
        self.length = length

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        if self.length is not None and self.length <= 0:
            return pc.utf8_slice_codeunits(arr, 0, 0)  # "" (nulls preserved)
        start = self.pos - 1 if self.pos > 0 else self.pos  # 0 acts like 1
        if self.length is None:
            stop = None
        elif start >= 0:
            stop = start + self.length
        else:  # negative start: stop only if it stays negative
            stop = start + self.length if start + self.length < 0 else None
        return pc.utf8_slice_codeunits(arr, start, stop)

    def key(self):
        return (f"substr({self.children[0].key()},{self.pos},"
                f"{self.length})")


class ConcatStrings(_HostStringExpr):
    """concat(s1, s2, ...): null if any input null (Spark concat)."""

    def __init__(self, *children):
        self.children = list(children)

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arrs = [c.eval_host(batch) for c in self.children]
        # unify string width (pandas3 produces large_string)
        target = pa.large_string() if any(
            pa.types.is_large_string(a.type) for a in arrs) else pa.string()
        arrs = [pc.cast(a, target) for a in arrs]
        return pc.binary_join_element_wise(
            *arrs, pa.scalar("", type=target), null_handling="emit_null")


def _transpile_with_fallback(pattern: str, mode: str):
    """(re2_regex, py_regex): exactly one is non-None. RE2 (pyarrow's
    vectorized kernels) is the fast path; patterns it cannot run
    (lookaround, backrefs, mode-dependent anchors) transpile for the
    Python-re row loop instead — the analog of the reference's CPU
    fallback, with Java semantics restored per target."""
    from .regex_transpiler import RegexUnsupported, transpile_java_regex
    try:
        return transpile_java_regex(pattern, target="re2",
                                    mode=mode), None
    except RegexUnsupported:
        return None, transpile_java_regex(pattern, target="python")


def _py_row_map(arr, fn, out_type):
    """Per-row Python fallback over an Arrow array; nulls pass through."""
    import pyarrow as pa
    return pa.array([None if v is None else fn(v) for v in arr.to_pylist()],
                    type=out_type)


class _PatternPredicate(_HostStringExpr):
    """String->bool predicate. ``host_mask`` is the single definition of
    the match, shared by row-wise host evaluation AND the dictionary
    path: over dict-coded device columns the predicate evaluates ONCE per
    distinct value and broadcasts through the codes on device
    (exprs/compiler.py DictFilterEvaluator; ref stringFunctions.scala
    device kernels — this is the O(dict) TPU equivalent)."""

    #: "range": on the SORTED dictionary the matching codes are one
    #: contiguous span -> gather-free (codes >= lo) & (codes < hi);
    #: "mask": arbitrary match set -> one small-table lookup
    dict_form = "mask"

    def __init__(self, child, pattern: str):
        self.children = [child]
        self.pattern = pattern

    def data_type(self, schema):
        return BOOL

    def host_mask(self, arr):
        raise NotImplementedError

    def eval_host(self, batch):
        return self.host_mask(self.children[0].eval_host(batch))

    def key(self):
        return (f"{type(self).__name__}({self.children[0].key()},"
                f"{self.pattern!r})")


class Contains(_PatternPredicate):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    def host_mask(self, arr):
        import pyarrow.compute as pc
        return pc.match_substring(arr, self.pattern)


class StartsWith(_PatternPredicate):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    dict_form = "range"     # prefix match == code range on a sorted dict

    def host_mask(self, arr):
        import pyarrow.compute as pc
        return pc.starts_with(arr, self.pattern)


class EndsWith(_PatternPredicate):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    def host_mask(self, arr):
        import pyarrow.compute as pc
        return pc.ends_with(arr, self.pattern)


class Like(_PatternPredicate):
    """SQL LIKE (ref GpuLike)."""
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True

    def __init__(self, child, pattern: str, escape: str = "\\"):
        super().__init__(child, pattern)
        self.escape = escape
        from .regex_transpiler import sql_like_to_regex
        self._regex = sql_like_to_regex(pattern, escape)

    def key(self):
        return (f"Like({self.children[0].key()},{self.pattern!r},"
                f"{self.escape!r})")

    def host_mask(self, arr):
        import pyarrow.compute as pc
        return pc.match_substring_regex(arr, self._regex)


class RLike(_PatternPredicate):
    """Java-regex RLIKE through the transpiler (ref GpuRLike +
    CudfRegexTranspiler)."""
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: literal / anchored-literal patterns only — see
    #: _rlike_literal_parts)
    rect_device = True

    def __init__(self, child, pattern: str):
        super().__init__(child, pattern)
        self._regex, self._pyregex = _transpile_with_fallback(pattern,
                                                              "find")

    def host_mask(self, arr):
        import pyarrow.compute as pc
        if self._regex is not None:
            return pc.match_substring_regex(arr, self._regex)
        import re
        import pyarrow as pa
        rx = re.compile(self._pyregex)
        return _py_row_map(arr, lambda v: rx.search(v) is not None,
                           pa.bool_())


class RegExpReplace(_HostStringExpr):
    dict_transform = True
    def __init__(self, child, pattern: str, replacement: str):
        self.children = [child]
        self.pattern = pattern
        self.replacement = replacement
        self._regex, self._pyregex = _transpile_with_fallback(pattern,
                                                              "replace")

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import re
        arr = self.children[0].eval_host(batch)
        # Java $1 backrefs -> \1 (same spelling in RE2 and Python re)
        repl = re.sub(r"\$(\d)", r"\\\1", self.replacement)
        if self._regex is not None:
            import pyarrow.compute as pc
            return pc.replace_substring_regex(arr, self._regex, repl)
        import pyarrow as pa
        rx = re.compile(self._pyregex)
        return _py_row_map(arr, lambda v: rx.sub(repl, v), pa.string())

    def key(self):
        return (f"regexp_replace({self.children[0].key()},"
                f"{self.pattern!r},{self.replacement!r})")


class RegExpExtract(_HostStringExpr):
    def __init__(self, child, pattern: str, group: int = 1):
        self.children = [child]
        self.pattern = pattern
        self.group = group
        from .regex_transpiler import transpile_java_regex
        self._regex = transpile_java_regex(pattern)

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import re
        import pyarrow as pa
        arr = self.children[0].eval_host(batch)
        rx = re.compile(self._regex)
        out = []
        for v in arr.to_pylist():
            if v is None:
                out.append(None)
            else:
                m = rx.search(v)
                out.append("" if m is None else (m.group(self.group) or ""))
        return pa.array(out, type=pa.string())

    def key(self):
        return (f"regexp_extract({self.children[0].key()},"
                f"{self.pattern!r},{self.group})")


class _TrimBase(_HostStringExpr):
    """Default TRIM removes ONLY the space character 0x20 — NOT tabs or
    newlines (Spark semantics, SPARK-17299; r5 ground-truth finding:
    utf8_trim_whitespace silently stripped all whitespace)."""
    dict_transform = True
    pc_fn = "utf8_trim"

    def __init__(self, child, chars: Optional[str] = None):
        self.children = [child]
        self.chars = chars

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        return getattr(pc, self.pc_fn)(
            arr, characters=self.chars if self.chars is not None else " ")


class RegExpExtractAll(_HostStringExpr):
    """regexp_extract_all(str, regex, group) -> array<string> (ref
    GpuRegExpExtractAll via the transpiler; host-only nested output)."""

    def __init__(self, child, pattern: str, group: int = 1):
        self.children = [child]
        self.pattern = pattern
        self.group = int(group)
        # the eval is a python row loop: always transpile for python-re
        # (the re2 dialect is only valid inside pyarrow pc.* kernels)
        from .regex_transpiler import transpile_java_regex
        self._pyregex = transpile_java_regex(pattern, target="python")

    def data_type(self, schema):
        from ..types import ArrayType
        return ArrayType(STRING)

    def eval_host(self, batch):
        import re as _re
        import pyarrow as pa
        rx = _re.compile(self._pyregex)
        arr = self.children[0].eval_host(batch)
        out = []
        for v in arr.to_pylist():
            if v is None:
                out.append(None)
                continue
            vals = []
            for m in rx.finditer(v):
                g = m.group(self.group) if self.group else m.group(0)
                vals.append("" if g is None else g)
            out.append(vals)
        return pa.array(out, type=pa.list_(pa.string()))

    def key(self):
        return (f"regexp_extract_all({self.children[0].key()},"
                f"{self.pattern!r},{self.group})")


class Conv(_HostStringExpr):
    """conv(num_str, from_base, to_base): base conversion with Java
    semantics — invalid digits truncate the parse, empty parse -> NULL,
    negative to_base keeps the sign, uppercase output (ref GpuConv)."""

    def __init__(self, child, from_base: int, to_base: int):
        self.children = [child]
        self.from_base = int(from_base)
        self.to_base = int(to_base)

    def data_type(self, schema):
        return STRING

    def _convert(self, v: str):
        fb, tb = self.from_base, abs(self.to_base)
        if not (2 <= fb <= 36 and 2 <= tb <= 36):
            return None
        v = v.strip()
        neg = v.startswith("-")
        if neg:
            v = v[1:]
        digits = "0123456789abcdefghijklmnopqrstuvwxyz"[:fb]
        acc = 0
        seen = False
        for ch in v.lower():
            d = digits.find(ch)
            if d < 0:
                break
            acc = acc * fb + d
            seen = True
        if not seen:
            return None
        acc = min(acc, (1 << 64) - 1)     # Java clamps at unsigned max
        # two's-complement 64-bit value (modulo keeps '-0' at 0)
        v = ((1 << 64) - acc) % (1 << 64) if neg else acc
        if self.to_base > 0:
            neg_out, mag = False, v       # printed UNSIGNED
        else:
            # negative to_base prints the value as a SIGNED long
            sval = v - (1 << 64) if v >= (1 << 63) else v
            neg_out, mag = sval < 0, abs(sval)
        out_digits = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"
        if mag == 0:
            return "0"
        out = []
        n = mag
        while n:
            out.append(out_digits[n % tb])
            n //= tb
        body = "".join(reversed(out))
        return ("-" + body) if neg_out else body

    def eval_host(self, batch):
        import pyarrow as pa
        arr = self.children[0].eval_host(batch)
        return _py_row_map(arr, self._convert, pa.string())

    def key(self):
        return (f"conv({self.children[0].key()},{self.from_base},"
                f"{self.to_base})")


class StringTrim(_TrimBase):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    pc_fn = "utf8_trim"


class StringTrimLeft(_TrimBase):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    pc_fn = "utf8_ltrim"


class StringTrimRight(_TrimBase):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    pc_fn = "utf8_rtrim"


class StringReplace(_HostStringExpr):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    dict_transform = True
    def __init__(self, child, search: str, replace: str):
        self.children = [child]
        self.search = search
        self.replace = replace

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow.compute as pc
        return pc.replace_substring(self.children[0].eval_host(batch),
                                    self.search, self.replace)

    def key(self):
        return (f"replace({self.children[0].key()},{self.search!r},"
                f"{self.replace!r})")


class StringLocate(_HostStringExpr):
    """locate(substr, str): 1-based, 0 if absent (ref GpuStringLocate)."""
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True

    def __init__(self, substr: str, child):
        self.children = [child]
        self.substr = substr

    def data_type(self, schema):
        return INT32

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        # find_substring returns BYTE offsets; Spark wants 1-based CHARACTER
        # position -> measure the prefix before the first occurrence
        parts = pc.split_pattern(arr, self.substr, max_splits=1)
        prefix_len = pc.utf8_length(pc.list_element(parts, 0))
        found = pc.match_substring(arr, self.substr)
        pos = pc.if_else(found, pc.add(prefix_len, 1),
                         pc.cast(0, prefix_len.type))
        return pc.cast(pos, pa.int32())

    def key(self):
        return f"locate({self.substr!r},{self.children[0].key()})"


class Lpad(_HostStringExpr):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    dict_transform = True
    def __init__(self, child, length: int, pad: str = " "):
        self.children = [child]
        self.length = length
        self.pad = pad

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        if len(self.pad) == 1:
            padded = pc.utf8_lpad(arr, self.length, padding=self.pad)
        else:
            # Arrow pads single codepoints only; Spark pads cyclically
            import pyarrow as pa
            L, p = self.length, self.pad
            padded = _py_row_map(
                arr, lambda v: ((p * L)[:max(L - len(v), 0)] + v),
                pa.string())
        # Spark truncates to length
        return pc.utf8_slice_codeunits(padded, 0, self.length)

    def key(self):
        return f"lpad({self.children[0].key()},{self.length},{self.pad!r})"


class Rpad(Lpad):
    def eval_host(self, batch):
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        if len(self.pad) == 1:
            padded = pc.utf8_rpad(arr, self.length, padding=self.pad)
        else:
            import pyarrow as pa
            L, p = self.length, self.pad
            padded = _py_row_map(
                arr, lambda v: v + (p * L)[:max(L - len(v), 0)],
                pa.string())
        return pc.utf8_slice_codeunits(padded, 0, self.length)

    def key(self):
        return f"rpad({self.children[0].key()},{self.length},{self.pad!r})"


class Reverse(_HostStringExpr):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    dict_transform = True
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow.compute as pc
        return pc.utf8_reverse(self.children[0].eval_host(batch))


class StringRepeat(_HostStringExpr):
    dict_transform = True
    def __init__(self, child, times: int):
        self.children = [child]
        self.times = times

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow.compute as pc
        # Spark: repeat with n <= 0 yields '' (arrow rejects negatives)
        return pc.binary_repeat(self.children[0].eval_host(batch),
                                max(self.times, 0))

    def key(self):
        return f"repeat({self.children[0].key()},{self.times})"


class InitCap(_HostStringExpr):
    """initcap: Spark capitalizes the first letter of EVERY
    space-separated word and lowercases the rest ('hELLO wORLD' ->
    'Hello World'); arrow's utf8_capitalize only title-cases the first
    character of the whole string (r5 ground-truth finding)."""
    dict_transform = True

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return STRING

    @staticmethod
    def _initcap(v: str) -> str:
        return " ".join(w[:1].upper() + w[1:].lower()
                        for w in v.split(" "))

    def eval_host(self, batch):
        import pyarrow as pa
        return _py_row_map(self.children[0].eval_host(batch),
                           self._initcap, pa.string())


class StringSplit(_HostStringExpr):
    """split(str, java_regex) -> array<string> (host-only nested output)."""

    def __init__(self, child, pattern: str, limit: int = -1):
        self.children = [child]
        self.pattern = pattern
        self.limit = limit
        self._regex, self._pyregex = _transpile_with_fallback(pattern,
                                                              "split")

    def data_type(self, schema):
        from ..types import ArrayType
        return ArrayType(STRING)

    @staticmethod
    def _strip_trailing_empties(list_arr):
        """Spark/Java limit=0: unlimited splits, then trailing empty
        strings removed (Pattern.split)."""
        import pyarrow as pa
        out = []
        for parts in list_arr.to_pylist():
            if parts is None:
                out.append(None)
                continue
            while parts and parts[-1] == "":
                parts.pop()
            out.append(parts)
        return pa.array(out, type=pa.list_(pa.string()))

    def eval_host(self, batch):
        import pyarrow as pa
        arr = self.children[0].eval_host(batch)
        lim = self.limit
        if self._regex is not None:
            import pyarrow.compute as pc
            kwargs = {} if lim <= 0 else {"max_splits": lim - 1}
            split = pc.split_pattern_regex(arr, self._regex, **kwargs)
            return self._strip_trailing_empties(split) if lim == 0 \
                else split
        import re
        rx = re.compile(self._pyregex)

        def split_one(v):
            # Spark limit (Java Pattern.split): >0 = at most `limit`
            # elements; 0 = unlimited + trailing empties removed; <0 =
            # unlimited keeping them. Python re.split's maxsplit inverts
            # the special values (0 = unlimited, negative = no splits),
            # so neither passes through directly.
            if lim == 1:
                return [v]                      # no splits at all
            parts = rx.split(v, 0 if lim <= 0 else lim - 1)
            if lim == 0:
                while parts and parts[-1] == "":
                    parts.pop()
            return parts
        return _py_row_map(arr, split_one, pa.list_(pa.string()))

    def key(self):
        return f"split({self.children[0].key()},{self.pattern!r})"


class SubstringIndex(_HostStringExpr):
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True
    dict_transform = True
    """substring_index(str, delim, count) (ref GpuSubstringIndexUtils JNI)."""

    def __init__(self, child, delim: str, count: int):
        self.children = [child]
        self.delim = delim
        self.count = count

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow as pa
        arr = self.children[0].eval_host(batch)
        out = []
        for v in arr.to_pylist():
            if v is None:
                out.append(None)
            elif self.count > 0:
                out.append(self.delim.join(v.split(self.delim)[:self.count]))
            elif self.count < 0:
                out.append(self.delim.join(v.split(self.delim)[self.count:]))
            else:
                out.append("")
        return pa.array(out, type=pa.string())

    def key(self):
        return (f"substring_index({self.children[0].key()},"
                f"{self.delim!r},{self.count})")


class ParseUrl(_HostStringExpr):
    """parse_url(url, part[, key]) (ref ParseURI JNI: GpuParseUrl).
    Parts: PROTOCOL, HOST, PATH, QUERY, REF, AUTHORITY, FILE, USERINFO;
    QUERY with a key extracts that query parameter."""

    PARTS = ("PROTOCOL", "HOST", "PATH", "QUERY", "REF", "AUTHORITY",
             "FILE", "USERINFO")

    def __init__(self, child, part: str, query_key=None):
        self.children = [child]
        self.part = part.upper()
        self.query_key = query_key

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow as pa
        from urllib.parse import urlparse
        arr = self.children[0].eval_host(batch)
        out = []
        for v in arr.to_pylist():
            if v is None:
                out.append(None)
                continue
            try:
                u = urlparse(v)
            except ValueError:
                out.append(None)
                continue
            # Spark (java.net.URI) returns NULL for every part of an
            # unparseable URL: require a scheme with an authority or
            # opaque part
            if not u.scheme or (not u.netloc and not u.path):
                out.append(None)
                continue
            if self.part == "PROTOCOL":
                r = u.scheme or None
            elif self.part == "HOST":
                # preserve case (u.hostname lowercases, Spark does not):
                # strip userinfo and port from the raw netloc
                h = u.netloc.rsplit("@", 1)[-1]
                if h.startswith("["):            # [ipv6]:port
                    r = h.split("]")[0] + "]" if "]" in h else h
                else:
                    r = h.split(":", 1)[0] or None
            elif self.part == "PATH":
                r = u.path or None
            elif self.part == "QUERY":
                r = u.query or None
                if r is not None and self.query_key is not None:
                    # RAW parameter value (Spark does not percent-decode)
                    r = None
                    for kv in u.query.split("&"):
                        k, _, val = kv.partition("=")
                        if k == self.query_key:
                            r = val
                            break
            elif self.part == "REF":
                r = u.fragment or None
            elif self.part == "AUTHORITY":
                r = u.netloc or None
            elif self.part == "FILE":
                r = (u.path + ("?" + u.query if u.query else "")) or None
            elif self.part == "USERINFO":
                r = u.netloc.rsplit("@", 1)[0] if "@" in u.netloc else None
            else:
                r = None
            out.append(r)
        return pa.array(out, type=pa.string())

    def key(self):
        return (f"parse_url({self.children[0].key()},{self.part},"
                f"{self.query_key!r})")


class Ascii(_HostStringExpr):
    """ascii(s): code point of the first character, 0 for '' (ref
    GpuAscii in stringFunctions.scala)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return INT32

    def eval_host(self, batch):
        import pyarrow as pa
        vals = self.children[0].eval_host(batch).to_pylist()
        return pa.array([None if s is None else (ord(s[0]) if s else 0)
                         for s in vals], type=pa.int32())


class Chr(_HostStringExpr):
    """chr(n): character for code point n % 256 like Spark (0 -> '')."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow as pa
        vals = self.children[0].eval_host(batch).to_pylist()
        out = []
        for n in vals:
            if n is None:
                out.append(None)
            else:
                m = int(n) & 0xFF if int(n) >= 0 else 0
                out.append("" if m == 0 else chr(m))
        return pa.array(out, type=pa.string())


class BitLength(_HostStringExpr):
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return INT32

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        b = pc.binary_length(pc.cast(self.children[0].eval_host(batch),
                                     pa.binary()))
        return pc.cast(pc.multiply(b, pa.scalar(8)), pa.int32())


class OctetLength(_HostStringExpr):
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return INT32

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        return pc.cast(pc.binary_length(
            pc.cast(self.children[0].eval_host(batch), pa.binary())),
            pa.int32())


class StringInstr(_HostStringExpr):
    """instr(str, substr): 1-based first occurrence, 0 if absent (ref
    GpuStringInstr — locate with fixed start=1)."""
    #: device byte-rectangle kernel available (exprs/string_rect.py;
    #: ASCII-gated, see rect_supported_op for per-instance conditions)
    rect_device = True

    def __init__(self, child, substr):
        self.children = [child, substr]

    def data_type(self, schema):
        return INT32

    def eval_host(self, batch):
        import pyarrow as pa
        s = self.children[0].eval_host(batch).to_pylist()
        sub = self.children[1].eval_host(batch).to_pylist()
        out = [None if a is None or b is None else a.find(b) + 1
               for a, b in zip(s, sub)]
        return pa.array(out, type=pa.int32())


class StringTranslate(_HostStringExpr):
    """translate(s, from, to): per-character mapping; chars beyond
    len(to) are deleted (ref GpuStringTranslate)."""

    dict_transform = True

    def __init__(self, child, src, dst):
        self.children = [child, src, dst]

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow as pa
        s = self.children[0].eval_host(batch).to_pylist()
        f = self.children[1].eval_host(batch).to_pylist()
        t = self.children[2].eval_host(batch).to_pylist()
        out = []
        for a, ff, tt in zip(s, f, t):
            if a is None or ff is None or tt is None:
                out.append(None)
                continue
            table = {}
            for i, ch in enumerate(ff):
                if ord(ch) not in table:   # first occurrence wins (Spark)
                    table[ord(ch)] = tt[i] if i < len(tt) else None
            out.append(a.translate(table))
        return pa.array(out, type=pa.string())


class ConcatWs(_HostStringExpr):
    """concat_ws(sep, args...): NULL args are skipped (unlike concat);
    NULL separator -> NULL (ref GpuConcatWs)."""

    def __init__(self, sep, *children):
        self.children = [sep] + list(children)

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow as pa
        sep = self.children[0].eval_host(batch).to_pylist()
        cols = [c.eval_host(batch).to_pylist() for c in self.children[1:]]
        out = []
        for i, sp in enumerate(sep):
            if sp is None:
                out.append(None)
                continue
            parts = []
            for col in cols:
                v = col[i]
                if v is None:
                    continue
                if isinstance(v, list):
                    parts.extend(str(x) for x in v if x is not None)
                else:
                    parts.append(str(v))
            out.append(sp.join(parts))
        return pa.array(out, type=pa.string())


class FormatNumber(_HostStringExpr):
    """format_number(x, d): thousands separators + d decimal places,
    HALF_EVEN like java.text.DecimalFormat (ref GpuFormatNumber)."""

    def __init__(self, child, decimals):
        self.children = [child, decimals]

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        import pyarrow as pa
        vals = self.children[0].eval_host(batch).to_pylist()
        decs = self.children[1].eval_host(batch).to_pylist()
        out = []
        for v, d in zip(vals, decs):
            if v is None or d is None or d < 0:
                out.append(None)
                continue
            out.append(f"{v:,.{int(d)}f}")
        return pa.array(out, type=pa.string())
