"""Device string transforms over byte rectangles (VERDICT r3 #4).

High-cardinality STRING columns live in HBM as `StrVal` rectangles
(columnar/strrect.py). The transforms here are the vectorized axis-1
kernels the reference gets from cudf's string kernels
(stringFunctions.scala:1-2377): every op is elementwise/static-shift work
over `bytes_[P, W]` + `lengths[P]` — no ragged buffers, no per-row code,
everything fuses into ONE projection kernel.

ASCII gate: the device path only runs when the batch was proven
all-ASCII at ingest (ByteRectColumn.ascii_only); case mapping and char
semantics beyond ASCII fall back to the host path honestly rather than
being silently wrong.

Supported chain ops (STRING -> STRING): Upper, Lower, StringTrim(L/R)
(whitespace only), Substring (pos >= 0, fixed length); terminals:
Length (STRING -> INT), Contains/StartsWith/EndsWith (STRING -> BOOL).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..types import BOOL, INT32, STRING, Schema
from .base import ColumnRef, DVal, Expression, StrVal

__all__ = ["rect_chain_leaf", "eval_rect_expr", "rect_supported_op"]


def _live(sv: StrVal):
    w = sv.bytes_.shape[1]
    return (jnp.arange(w, dtype=jnp.int32)[None, :]
            < sv.lengths[:, None])


def _is_space(b):
    return jnp.logical_or(b == 32, jnp.logical_and(b >= 9, b <= 13))


def _realign(bytes_, start):
    """Shift each row left by its (traced, per-row) start offset: the sum
    of W static shifts masked by (start == s) — compile-friendly, no
    per-row gather."""
    w = bytes_.shape[1]
    out = jnp.zeros_like(bytes_)
    for s in range(w):
        shifted = (bytes_ if s == 0
                   else jnp.pad(bytes_[:, s:], ((0, 0), (0, s))))
        out = jnp.where((start == s)[:, None], shifted, out)
    return out


def _zero_tail(bytes_, lengths):
    w = bytes_.shape[1]
    live = jnp.arange(w, dtype=jnp.int32)[None, :] < lengths[:, None]
    return jnp.where(live, bytes_, jnp.uint8(0))


def _upper(sv: StrVal) -> StrVal:
    b = sv.bytes_
    low = jnp.logical_and(b >= 97, b <= 122)
    return StrVal(jnp.where(low, b - 32, b), sv.lengths)


def _lower(sv: StrVal) -> StrVal:
    b = sv.bytes_
    up = jnp.logical_and(b >= 65, b <= 90)
    return StrVal(jnp.where(up, b + 32, b), sv.lengths)


def _trim(sv: StrVal, left: bool, right: bool) -> StrVal:
    b, ln = sv.bytes_, sv.lengths
    live = _live(sv)
    sp = jnp.logical_and(_is_space(b), live)
    lead = jnp.zeros_like(ln)
    if left:
        # leading-space count: cumprod zeroes after the first non-space
        run = jnp.cumprod(jnp.where(live, sp.astype(jnp.int32), 0),
                          axis=1)
        lead = jnp.sum(run, axis=1).astype(jnp.int32)
    trail = jnp.zeros_like(ln)
    if right:
        # trailing run: reverse cumprod; positions past the length keep 1
        # so they don't break the run
        rev = jnp.cumprod(jnp.where(live, sp.astype(jnp.int32), 1)[:, ::-1],
                          axis=1)[:, ::-1]
        trail = jnp.sum(jnp.where(live, rev, 0), axis=1).astype(jnp.int32)
    new_len = jnp.maximum(ln - lead - trail, 0)
    # all-space strings: lead+trail may double-count; clamp start too
    start = jnp.minimum(lead, ln)
    out = _realign(b, start) if left else b
    return StrVal(_zero_tail(out, new_len), new_len)


def _substring(sv: StrVal, pos: int, length: Optional[int]) -> StrVal:
    b, ln = sv.bytes_, sv.lengths
    start = pos - 1 if pos > 0 else 0       # SQL 1-based; 0 acts like 1
    w = b.shape[1]
    if start > 0:
        b = (jnp.pad(b[:, start:], ((0, 0), (0, min(start, w))))
             if start < w else jnp.zeros_like(b))
    new_len = jnp.maximum(ln - start, 0)
    if length is not None:
        if length <= 0:
            new_len = jnp.zeros_like(new_len)
        else:
            new_len = jnp.minimum(new_len, length)
        from ..columnar.strrect import rect_width_bucket
        wb = rect_width_bucket(max(length, 1), w)
        if wb is not None and wb < b.shape[1]:
            b = b[:, :wb]
    return StrVal(_zero_tail(b, new_len), new_len)


def _match_at(b, live, pat: np.ndarray, offset):
    """all_j b[:, offset+j] == pat[j], offset static."""
    w = b.shape[1]
    L = len(pat)
    if offset + L > w:
        return jnp.zeros(b.shape[0], bool)
    m = jnp.ones(b.shape[0], bool)
    for j, ch in enumerate(pat):
        m = jnp.logical_and(m, b[:, offset + j] == np.uint8(ch))
    return m


def _startswith(sv: StrVal, pat: bytes):
    p = np.frombuffer(pat, np.uint8)
    ok_len = sv.lengths >= len(p)
    return jnp.logical_and(ok_len,
                           _match_at(sv.bytes_, None, p, 0))


def _endswith(sv: StrVal, pat: bytes):
    p = np.frombuffer(pat, np.uint8)
    L = len(p)
    b, ln = sv.bytes_, sv.lengths
    w = b.shape[1]
    if L == 0:
        return jnp.ones(b.shape[0], bool)
    out = jnp.zeros(b.shape[0], bool)
    for s in range(w - L + 1):           # match where length-L == s
        out = jnp.where(ln - L == s, _match_at(b, None, p, s), out)
    return jnp.logical_and(ln >= L, out)


def _contains(sv: StrVal, pat: bytes):
    p = np.frombuffer(pat, np.uint8)
    L = len(p)
    b, ln = sv.bytes_, sv.lengths
    w = b.shape[1]
    if L == 0:
        return jnp.ones(b.shape[0], bool)
    out = jnp.zeros(b.shape[0], bool)
    for s in range(w - L + 1):
        out = jnp.logical_or(
            out, jnp.logical_and(_match_at(b, None, p, s),
                                 ln - L >= s))
    return out


# ---------------------------------------------------------------------------
# expression bridge
# ---------------------------------------------------------------------------

def rect_supported_op(e: Expression) -> bool:
    from .string_fns import (Contains, EndsWith, Length, Lower, StartsWith,
                             StringTrim, StringTrimLeft, StringTrimRight,
                             Substring, Upper)
    if isinstance(e, (Upper, Lower)):
        return True
    if isinstance(e, (StringTrim, StringTrimLeft, StringTrimRight)):
        return e.chars is None           # whitespace-only trim
    if isinstance(e, Substring):
        return e.pos >= 0                # negative pos: from-end (host)
    if isinstance(e, Length):
        return True
    if isinstance(e, (Contains, StartsWith, EndsWith)):
        try:
            e.pattern.encode("ascii")
        except UnicodeEncodeError:
            return False
        return True
    return False


def rect_chain_leaf(e: Expression, schema: Schema) -> Optional[str]:
    """Leaf column name when ``e`` is a chain of rect-supported ops over
    one STRING ColumnRef, else None."""
    cur = e
    hops = 0
    while rect_supported_op(cur) and len(cur.children) == 1:
        cur = cur.children[0]
        hops += 1
    if hops and isinstance(cur, ColumnRef) \
            and cur.name in schema.names() \
            and schema[cur.name].dtype == STRING:
        return cur.name
    return None


def eval_rect_expr(e: Expression, child: DVal) -> DVal:
    """Evaluate one rect-supported op over a StrVal-typed DVal (traced)."""
    from .string_fns import (Contains, EndsWith, Length, Lower, StartsWith,
                             StringTrim, StringTrimLeft, StringTrimRight,
                             Substring, Upper)
    sv: StrVal = child.data
    v = child.validity
    if isinstance(e, Upper):
        return DVal(_upper(sv), v, STRING)
    if isinstance(e, Lower):
        return DVal(_lower(sv), v, STRING)
    if isinstance(e, StringTrim):
        return DVal(_trim(sv, True, True), v, STRING)
    if isinstance(e, StringTrimLeft):
        return DVal(_trim(sv, True, False), v, STRING)
    if isinstance(e, StringTrimRight):
        return DVal(_trim(sv, False, True), v, STRING)
    if isinstance(e, Substring):
        return DVal(_substring(sv, e.pos, e.length), v, STRING)
    if isinstance(e, Length):
        return DVal(jnp.where(v, sv.lengths, 0).astype(jnp.int32), v,
                    INT32)
    if isinstance(e, StartsWith):
        return DVal(_startswith(sv, e.pattern.encode()), v, BOOL)
    if isinstance(e, EndsWith):
        return DVal(_endswith(sv, e.pattern.encode()), v, BOOL)
    if isinstance(e, Contains):
        return DVal(_contains(sv, e.pattern.encode()), v, BOOL)
    raise NotImplementedError(type(e).__name__)


def eval_rect_chain(e: Expression, leaf_val: DVal) -> DVal:
    """Evaluate a rect_chain (validated by rect_chain_leaf) bottom-up."""
    if isinstance(e, ColumnRef):
        return leaf_val
    child = eval_rect_chain(e.children[0], leaf_val)
    return eval_rect_expr(e, child)
