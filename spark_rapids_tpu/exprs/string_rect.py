"""Device string transforms over byte rectangles (VERDICT r3 #4).

High-cardinality STRING columns live in HBM as `StrVal` rectangles
(columnar/strrect.py). The transforms here are the vectorized axis-1
kernels the reference gets from cudf's string kernels
(stringFunctions.scala:1-2377): every op is elementwise/static-shift work
over `bytes_[P, W]` + `lengths[P]` — no ragged buffers, no per-row code,
everything fuses into ONE projection kernel.

ASCII gate: the device path only runs when the batch was proven
all-ASCII at ingest (ByteRectColumn.ascii_only); case mapping and char
semantics beyond ASCII fall back to the host path honestly rather than
being silently wrong.

Supported chain ops (STRING -> STRING): Upper, Lower, StringTrim(L/R)
(space-only, Spark semantics), Substring (pos >= 0, fixed length), StringReplace,
Lpad/Rpad, SubstringIndex, Reverse; terminals: Length (STRING -> INT),
StringLocate/StringInstr (STRING -> INT),
Contains/StartsWith/EndsWith/Like (STRING -> BOOL).

All kernels are scatter/gather + unrolled static shifts — no lax.sort
(a sort's compile time multiplies with its module on this backend,
docs/performance.md r4) and no per-row host work.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..types import BOOL, INT32, STRING, Schema
from .base import ColumnRef, DVal, Expression, StrVal

__all__ = ["rect_chain_leaf", "eval_rect_expr", "rect_supported_op",
           "RectUnsupported"]


class RectUnsupported(Exception):
    """Raised at kernel-trace time when a rect op cannot run for THIS
    batch's concrete widths (e.g. a growing replace past the width
    cap): the caller falls back to host evaluation for the batch."""


def _live(sv: StrVal):
    w = sv.bytes_.shape[1]
    return (jnp.arange(w, dtype=jnp.int32)[None, :]
            < sv.lengths[:, None])


def _is_space(b):
    return jnp.logical_or(b == 32, jnp.logical_and(b >= 9, b <= 13))


def _realign(bytes_, start):
    """Shift each row left by its (traced, per-row) start offset: the sum
    of W static shifts masked by (start == s) — compile-friendly, no
    per-row gather."""
    w = bytes_.shape[1]
    out = jnp.zeros_like(bytes_)
    for s in range(w):
        shifted = (bytes_ if s == 0
                   else jnp.pad(bytes_[:, s:], ((0, 0), (0, s))))
        out = jnp.where((start == s)[:, None], shifted, out)
    return out


def _zero_tail(bytes_, lengths):
    w = bytes_.shape[1]
    live = jnp.arange(w, dtype=jnp.int32)[None, :] < lengths[:, None]
    return jnp.where(live, bytes_, jnp.uint8(0))


def _upper(sv: StrVal) -> StrVal:
    b = sv.bytes_
    low = jnp.logical_and(b >= 97, b <= 122)
    return StrVal(jnp.where(low, b - 32, b), sv.lengths)


def _lower(sv: StrVal) -> StrVal:
    b = sv.bytes_
    up = jnp.logical_and(b >= 65, b <= 90)
    return StrVal(jnp.where(up, b + 32, b), sv.lengths)


def _trim(sv: StrVal, left: bool, right: bool) -> StrVal:
    b, ln = sv.bytes_, sv.lengths
    live = _live(sv)
    # Spark TRIM removes ONLY the space character 0x20 (SPARK-17299)
    sp = jnp.logical_and(b == 32, live)
    lead = jnp.zeros_like(ln)
    if left:
        # leading-space count: cumprod zeroes after the first non-space
        run = jnp.cumprod(jnp.where(live, sp.astype(jnp.int32), 0),
                          axis=1)
        lead = jnp.sum(run, axis=1).astype(jnp.int32)
    trail = jnp.zeros_like(ln)
    if right:
        # trailing run: reverse cumprod; positions past the length keep 1
        # so they don't break the run
        rev = jnp.cumprod(jnp.where(live, sp.astype(jnp.int32), 1)[:, ::-1],
                          axis=1)[:, ::-1]
        trail = jnp.sum(jnp.where(live, rev, 0), axis=1).astype(jnp.int32)
    new_len = jnp.maximum(ln - lead - trail, 0)
    # all-space strings: lead+trail may double-count; clamp start too
    start = jnp.minimum(lead, ln)
    out = _realign(b, start) if left else b
    return StrVal(_zero_tail(out, new_len), new_len)


def _substring(sv: StrVal, pos: int, length: Optional[int]) -> StrVal:
    b, ln = sv.bytes_, sv.lengths
    start = pos - 1 if pos > 0 else 0       # SQL 1-based; 0 acts like 1
    w = b.shape[1]
    if start > 0:
        b = (jnp.pad(b[:, start:], ((0, 0), (0, min(start, w))))
             if start < w else jnp.zeros_like(b))
    new_len = jnp.maximum(ln - start, 0)
    if length is not None:
        if length <= 0:
            new_len = jnp.zeros_like(new_len)
        else:
            new_len = jnp.minimum(new_len, length)
        from ..columnar.strrect import rect_width_bucket
        wb = rect_width_bucket(max(length, 1), w)
        if wb is not None and wb < b.shape[1]:
            b = b[:, :wb]
    return StrVal(_zero_tail(b, new_len), new_len)


def _match_at(b, live, pat: np.ndarray, offset):
    """all_j b[:, offset+j] == pat[j], offset static."""
    w = b.shape[1]
    L = len(pat)
    if offset + L > w:
        return jnp.zeros(b.shape[0], bool)
    m = jnp.ones(b.shape[0], bool)
    for j, ch in enumerate(pat):
        m = jnp.logical_and(m, b[:, offset + j] == np.uint8(ch))
    return m


def _startswith(sv: StrVal, pat: bytes):
    p = np.frombuffer(pat, np.uint8)
    ok_len = sv.lengths >= len(p)
    return jnp.logical_and(ok_len,
                           _match_at(sv.bytes_, None, p, 0))


def _endswith(sv: StrVal, pat: bytes):
    p = np.frombuffer(pat, np.uint8)
    L = len(p)
    b, ln = sv.bytes_, sv.lengths
    w = b.shape[1]
    if L == 0:
        return jnp.ones(b.shape[0], bool)
    out = jnp.zeros(b.shape[0], bool)
    for s in range(w - L + 1):           # match where length-L == s
        out = jnp.where(ln - L == s, _match_at(b, None, p, s), out)
    return jnp.logical_and(ln >= L, out)


def _contains(sv: StrVal, pat: bytes):
    p = np.frombuffer(pat, np.uint8)
    L = len(p)
    b, ln = sv.bytes_, sv.lengths
    w = b.shape[1]
    if L == 0:
        return jnp.ones(b.shape[0], bool)
    out = jnp.zeros(b.shape[0], bool)
    for s in range(w - L + 1):
        out = jnp.logical_or(
            out, jnp.logical_and(_match_at(b, None, p, s),
                                 ln - L >= s))
    return out


def _take_shift(b, start):
    """Gather-based left shift by a per-row (traced) start offset; reads
    past the width land on a zero column."""
    w = b.shape[1]
    j = jnp.arange(w, dtype=jnp.int32)[None, :]
    src = j + start[:, None]
    bx = jnp.pad(b, ((0, 0), (0, 1)))
    return jnp.take_along_axis(bx, jnp.clip(src, 0, w), axis=1)


def _select_nonoverlap(b, ln, pat: np.ndarray):
    """Greedy left-to-right NON-OVERLAPPING occurrences of ``pat``
    (java String semantics shared by replace/split): sel[p, j] marks
    occurrence starts, cum[p, j] counts occurrences at positions <= j.
    Sequential in j but unrolled over the static width — vector ops
    only, no per-row code."""
    w = b.shape[1]
    rows = b.shape[0]
    L = len(pat)
    match = []
    for j in range(w):
        if j + L <= w:
            match.append(jnp.logical_and(_match_at(b, None, pat, j),
                                         ln >= j + L))
        else:
            match.append(jnp.zeros(rows, bool))
    next_free = jnp.zeros(rows, jnp.int32)
    sels = []
    for j in range(w):
        s = jnp.logical_and(match[j], next_free <= j)
        next_free = jnp.where(s, j + L, next_free)
        sels.append(s)
    sel = jnp.stack(sels, axis=1)
    cum = jnp.cumsum(sel.astype(jnp.int32), axis=1)
    return sel, cum


#: replacement-literal length cap: each replacement byte is one scatter
#: in the fused kernel
_REPLACE_MAX = 32
#: static pad-target cap (HBM is rows*width)
_PAD_MAX = 256


def _replace(sv: StrVal, search: bytes, replace: bytes,
             width_cap: int = 1 << 20) -> StrVal:
    """replace(str, search, replace): non-overlapping left-to-right, may
    grow the rectangle (bounded by W//len(search) occurrences) up to the
    configured width cap — past it the batch falls back to host."""
    b, ln = sv.bytes_, sv.lengths
    rows, w = b.shape
    s = np.frombuffer(search, np.uint8)
    r = np.frombuffer(replace, np.uint8)
    l1, l2 = len(s), len(r)
    if l1 == 0:
        return sv                       # Spark: empty search is identity
    sel, _ = _select_nonoverlap(b, ln, s)
    # covered: inside a selected occurrence, not at its start
    cov = jnp.zeros_like(sel)
    for k in range(1, min(l1, w)):
        cov = jnp.logical_or(cov, jnp.pad(sel[:, :-k], ((0, 0), (k, 0))))
    live = _live(sv)
    emit = jnp.where(sel, l2,
                     jnp.where(jnp.logical_or(cov, ~live), 0, 1)) \
        .astype(jnp.int32)
    outpos = jnp.cumsum(emit, axis=1) - emit        # exclusive
    new_len = outpos[:, -1] + emit[:, -1]
    w_need = w + max(0, l2 - l1) * (w // l1)
    from ..columnar.strrect import rect_width_bucket
    # growth allowance: the conf cap governs ingest width; an op may
    # grow to the cap (or the input width when already above it)
    wo = rect_width_bucket(max(w_need, 1), max(width_cap, w))
    if wo is None:      # grown width past the cap: host handles it
        raise RectUnsupported(f"replace output width {w_need}")
    rowix = jnp.arange(rows, dtype=jnp.int32)[:, None]
    out = jnp.zeros((rows, wo + 1), jnp.uint8)      # col wo = dump slot
    copy_idx = jnp.where(
        jnp.logical_or(sel, jnp.logical_or(cov, ~live)), wo, outpos)
    out = out.at[rowix, copy_idx].set(b, mode="drop")
    for k in range(l2):
        rep_idx = jnp.where(sel, outpos + k, wo)
        out = out.at[rowix, rep_idx].set(jnp.uint8(r[k]), mode="drop")
    return StrVal(_zero_tail(out[:, :wo], new_len), new_len)


def _pad(sv: StrVal, valid, length: int, pad: bytes, left: bool) -> StrVal:
    """lpad/rpad to a STATIC length with a cyclic pad pattern; longer
    inputs keep their prefix (Spark semantics). Invalid rows stay
    all-zero (the rectangle convention grouping relies on)."""
    b, ln = sv.bytes_, sv.lengths
    rows, w = b.shape
    p = np.frombuffer(pad, np.uint8)
    lp = len(p)
    from ..columnar.strrect import rect_width_bucket
    wo = rect_width_bucket(max(length, 1), 1 << 20)
    bx = b if wo <= w else jnp.pad(b, ((0, 0), (0, wo - w)))
    bx = bx[:, :wo]
    j = jnp.arange(wo, dtype=jnp.int32)[None, :]
    pad_full = jnp.asarray(np.resize(p, wo))        # pad[j % lp] table
    if left:
        shift = jnp.maximum(length - ln, 0)[:, None]
        src = j - shift
        bpad = jnp.pad(bx, ((0, 0), (0, 1)))
        orig = jnp.take_along_axis(bpad, jnp.clip(src, 0, wo), axis=1)
        out = jnp.where(src >= 0, orig, pad_full[None, :])
    else:
        out = jnp.where(j < ln[:, None], bx,
                        pad_full[jnp.clip(j - ln[:, None], 0, wo - 1)])
    new_len = jnp.where(valid, jnp.int32(length), 0)
    return StrVal(_zero_tail(out, new_len), new_len)


def _locate(sv: StrVal, sub: bytes):
    """1-based first occurrence, 0 when absent (byte == char: ASCII)."""
    b, ln = sv.bytes_, sv.lengths
    rows, w = b.shape
    p = np.frombuffer(sub, np.uint8)
    L = len(p)
    if L == 0:
        return jnp.ones(rows, jnp.int32)   # Spark: locate('', s) == 1
    pos = jnp.zeros(rows, jnp.int32)
    found = jnp.zeros(rows, bool)
    for s in range(0, max(w - L + 1, 0)):
        m = jnp.logical_and(_match_at(b, None, p, s), ln >= s + L)
        pos = jnp.where(jnp.logical_and(~found, m), s + 1, pos)
        found = jnp.logical_or(found, m)
    return pos


_REGEX_META = set(".^$*+?{}[]\\|()")


def _rlike_literal_parts(pattern: str):
    """(mode, literal) when a Java-regex RLIKE pattern is really an
    (optionally anchored) LITERAL — the common grep-style case cudf also
    fast-paths (ref RegexParser literal detection): no metacharacters
    besides the ^/$ anchors at the edges. None otherwise."""
    if not pattern:
        return None
    lead = pattern.startswith("^")
    trail = pattern.endswith("$")
    body = pattern[1 if lead else 0: len(pattern) - (1 if trail else 0)]
    if any(c in _REGEX_META for c in body):
        return None
    if _ascii(body) is None:
        return None
    if lead and trail:
        return ("equals", body)
    if lead:
        return ("startswith", body)
    if trail:
        return ("endswith", body)
    return ("contains", body)    # RLIKE is an unanchored search


def _like_parts(pattern: str):
    """(form, literal) for rectangle-supported LIKE patterns: leading/
    trailing %% around one literal (prefix/suffix/contains/exact).
    None for '_', escapes, interior %%, or non-ASCII."""
    if "_" in pattern or "\\" in pattern:
        return None
    try:
        pattern.encode("ascii")
    except UnicodeEncodeError:
        return None
    lead = pattern.startswith("%")
    trail = pattern.endswith("%")
    mid = pattern.strip("%")
    if "%" in mid:
        return None
    if lead and trail:
        return ("contains", mid)
    if lead:
        return ("endswith", mid)
    if trail:
        return ("startswith", mid)
    return ("equals", mid)


def _equals(sv: StrVal, pat: bytes):
    p = np.frombuffer(pat, np.uint8)
    return jnp.logical_and(sv.lengths == len(p),
                           _match_at(sv.bytes_, None, p, 0))


def _substring_index(sv: StrVal, delim: bytes, count: int) -> StrVal:
    """substring_index: prefix before the count-th delimiter (count>0)
    or suffix after the |count|-th-from-last (count<0); whole string
    when there are fewer delimiters."""
    b, ln = sv.bytes_, sv.lengths
    rows, w = b.shape
    d = np.frombuffer(delim, np.uint8)
    L = len(d)
    if count == 0:
        z = jnp.zeros_like(ln)
        return StrVal(jnp.zeros_like(b), z)
    sel, cum = _select_nonoverlap(b, ln, d)
    j = jnp.arange(w, dtype=jnp.int32)[None, :]
    if count > 0:
        mask = jnp.logical_and(sel, cum == count)
        cut = jnp.where(mask, j, w).min(axis=1)
        new_len = jnp.minimum(ln, cut)
        return StrVal(_zero_tail(b, new_len), new_len)
    target = cum[:, -1] + count + 1     # 1-based boundary occurrence
    mask = jnp.logical_and(sel, cum == target[:, None])
    start = jnp.where(mask, j, 0).max(axis=1) + L
    start = jnp.where(target >= 1, start, 0)
    new_len = jnp.maximum(ln - start, 0)
    return StrVal(_zero_tail(_take_shift(b, start), new_len), new_len)


def _reverse(sv: StrVal) -> StrVal:
    b, ln = sv.bytes_, sv.lengths
    w = b.shape[1]
    j = jnp.arange(w, dtype=jnp.int32)[None, :]
    src = ln[:, None] - 1 - j
    bx = jnp.pad(b, ((0, 0), (0, 1)))
    out = jnp.take_along_axis(bx, jnp.clip(src, 0, w), axis=1)
    return StrVal(_zero_tail(out, ln), ln)


# ---------------------------------------------------------------------------
# expression bridge
# ---------------------------------------------------------------------------

def _ascii(s: str) -> Optional[bytes]:
    try:
        return s.encode("ascii")
    except UnicodeEncodeError:
        return None


def rect_supported_op(e: Expression) -> bool:
    from .base import Literal
    from .string_fns import (Contains, EndsWith, Length, Like, Lower, Lpad,
                             Reverse, RLike, StartsWith, StringInstr,
                             StringLocate, StringReplace, StringTrim,
                             StringTrimLeft, StringTrimRight,
                             SubstringIndex, Substring, Upper)
    if isinstance(e, (Upper, Lower, Length, Reverse)):
        return True
    if isinstance(e, (StringTrim, StringTrimLeft, StringTrimRight)):
        return e.chars is None           # default (space-only) trim
    if isinstance(e, Substring):
        return e.pos >= 0                # negative pos: from-end (host)
    if isinstance(e, Like):
        # _like_parts rejects any '\\' in the pattern, so the default
        # escape can never fire on an accepted pattern; a CUSTOM escape
        # char would change the parse -> host
        return e.escape == "\\" and _like_parts(e.pattern) is not None
    if isinstance(e, RLike):
        return _rlike_literal_parts(e.pattern) is not None
    if isinstance(e, (Contains, StartsWith, EndsWith)):
        return _ascii(e.pattern) is not None
    if isinstance(e, StringReplace):
        return (_ascii(e.search) is not None and len(e.search) >= 1
                and _ascii(e.replace) is not None
                and len(e.replace) <= _REPLACE_MAX)
    if isinstance(e, (Lpad,)):           # covers Rpad subclass
        return (0 < e.length <= _PAD_MAX and len(e.pad) >= 1
                and _ascii(e.pad) is not None)
    if isinstance(e, StringLocate):
        return _ascii(e.substr) is not None
    if isinstance(e, StringInstr):
        sub = e.children[1]
        return (isinstance(sub, Literal) and isinstance(sub.value, str)
                and _ascii(sub.value) is not None)
    if isinstance(e, SubstringIndex):
        return len(e.delim) >= 1 and _ascii(e.delim) is not None
    return False


def rect_chain_leaf(e: Expression, schema: Schema) -> Optional[str]:
    """Leaf column name when ``e`` is a chain of rect-supported ops over
    one STRING ColumnRef, else None. StringInstr carries its substring
    as a Literal second child — the chain continues through child 0."""
    cur = e
    hops = 0
    while rect_supported_op(cur) and len(cur.children) >= 1:
        cur = cur.children[0]
        hops += 1
    if hops and isinstance(cur, ColumnRef) \
            and cur.name in schema.names() \
            and schema[cur.name].dtype == STRING:
        return cur.name
    return None


def eval_rect_expr(e: Expression, child: DVal,
                   width_cap: int = 1 << 20,
                   use_pallas: bool = False) -> DVal:
    """Evaluate one rect-supported op over a StrVal-typed DVal (traced).
    ``use_pallas`` routes the sliding-pattern match family through the
    hand-written Pallas kernels (exprs/pallas_rect.py)."""
    from .string_fns import (Contains, EndsWith, Length, Like, Lower, Lpad,
                             Reverse, RLike, Rpad, StartsWith, StringInstr,
                             StringLocate, StringReplace, StringTrim,
                             StringTrimLeft, StringTrimRight,
                             SubstringIndex, Substring, Upper)
    sv: StrVal = child.data
    v = child.validity
    if use_pallas:
        from .pallas_rect import pallas_match
        if isinstance(e, StartsWith):
            return DVal(pallas_match(sv.bytes_, sv.lengths,
                                     e.pattern.encode(), "startswith"),
                        v, BOOL)
        if isinstance(e, EndsWith):
            return DVal(pallas_match(sv.bytes_, sv.lengths,
                                     e.pattern.encode(), "endswith"),
                        v, BOOL)
        if isinstance(e, Contains):
            return DVal(pallas_match(sv.bytes_, sv.lengths,
                                     e.pattern.encode(), "contains"),
                        v, BOOL)
        if isinstance(e, (Like, RLike)):
            form, lit = (_like_parts(e.pattern) if isinstance(e, Like)
                         else _rlike_literal_parts(e.pattern))
            return DVal(pallas_match(sv.bytes_, sv.lengths,
                                     lit.encode(), form), v, BOOL)
        if isinstance(e, StringLocate):
            return DVal(pallas_match(sv.bytes_, sv.lengths,
                                     e.substr.encode(), "locate"),
                        v, INT32)
        if isinstance(e, StringInstr):
            return DVal(pallas_match(sv.bytes_, sv.lengths,
                                     e.children[1].value.encode(),
                                     "locate"), v, INT32)
    if isinstance(e, Upper):
        return DVal(_upper(sv), v, STRING)
    if isinstance(e, Lower):
        return DVal(_lower(sv), v, STRING)
    if isinstance(e, StringTrim):
        return DVal(_trim(sv, True, True), v, STRING)
    if isinstance(e, StringTrimLeft):
        return DVal(_trim(sv, True, False), v, STRING)
    if isinstance(e, StringTrimRight):
        return DVal(_trim(sv, False, True), v, STRING)
    if isinstance(e, Substring):
        return DVal(_substring(sv, e.pos, e.length), v, STRING)
    if isinstance(e, Length):
        return DVal(jnp.where(v, sv.lengths, 0).astype(jnp.int32), v,
                    INT32)
    if isinstance(e, StartsWith):
        return DVal(_startswith(sv, e.pattern.encode()), v, BOOL)
    if isinstance(e, EndsWith):
        return DVal(_endswith(sv, e.pattern.encode()), v, BOOL)
    if isinstance(e, Contains):
        return DVal(_contains(sv, e.pattern.encode()), v, BOOL)
    if isinstance(e, (Like, RLike)):
        form, lit = (_like_parts(e.pattern) if isinstance(e, Like)
                     else _rlike_literal_parts(e.pattern))
        fn = {"contains": _contains, "startswith": _startswith,
              "endswith": _endswith, "equals": _equals}[form]
        return DVal(fn(sv, lit.encode()), v, BOOL)
    if isinstance(e, StringReplace):
        return DVal(_replace(sv, e.search.encode(), e.replace.encode(),
                             width_cap), v, STRING)
    if isinstance(e, Rpad):
        return DVal(_pad(sv, v, e.length, e.pad.encode(), False), v,
                    STRING)
    if isinstance(e, Lpad):
        return DVal(_pad(sv, v, e.length, e.pad.encode(), True), v,
                    STRING)
    if isinstance(e, StringLocate):
        return DVal(_locate(sv, e.substr.encode()), v, INT32)
    if isinstance(e, StringInstr):
        return DVal(_locate(sv, e.children[1].value.encode()), v, INT32)
    if isinstance(e, SubstringIndex):
        return DVal(_substring_index(sv, e.delim.encode(), e.count), v,
                    STRING)
    if isinstance(e, Reverse):
        return DVal(_reverse(sv), v, STRING)
    raise NotImplementedError(type(e).__name__)


def eval_rect_chain(e: Expression, leaf_val: DVal,
                    width_cap: int = 1 << 20,
                    use_pallas: bool = False) -> DVal:
    """Evaluate a rect_chain (validated by rect_chain_leaf) bottom-up."""
    if isinstance(e, ColumnRef):
        return leaf_val
    child = eval_rect_chain(e.children[0], leaf_val, width_cap,
                            use_pallas)
    return eval_rect_expr(e, child, width_cap, use_pallas)
