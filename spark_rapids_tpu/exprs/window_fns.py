"""Window function expressions (ref GpuWindowExpression.scala, 2,133 LoC).

These are markers consumed by exec/window.py's sort-based kernel; they do not
evaluate standalone (same shape as the reference where window functions only
exist inside GpuWindowExec).
"""
from __future__ import annotations

from typing import Optional

from ..types import INT32, INT64, DataType, Schema
from .base import Expression

__all__ = ["WindowFunction", "RowNumber", "Rank", "DenseRank", "Lag", "Lead",
           "NTile"]


class WindowFunction:
    """Base marker. data_type(schema) like aggregates."""

    def data_type(self, schema: Schema) -> DataType:
        raise NotImplementedError

    @property
    def name_hint(self) -> str:
        return type(self).__name__.lower()

    def device_unsupported_reason(self, schema) -> Optional[str]:
        return None


class RowNumber(WindowFunction):
    def data_type(self, schema):
        return INT32


class Rank(WindowFunction):
    def data_type(self, schema):
        return INT32


class DenseRank(WindowFunction):
    def data_type(self, schema):
        return INT32


class PercentRank(WindowFunction):
    """(rank - 1) / (partition rows - 1); 0.0 for 1-row partitions
    (ref GpuPercentRank)."""

    def data_type(self, schema):
        from ..types import FLOAT64
        return FLOAT64


class NthValue(WindowFunction):
    """nth_value(e, n): the n-th row's value within the RUNNING frame
    (unbounded preceding .. current row — Spark's default frame); NULL
    while the frame holds fewer than n rows (ref GpuNthValue)."""

    def __init__(self, child: Expression, n: int):
        self.child = child
        self.n = int(n)
        if self.n < 1:
            raise ValueError("nth_value offset must be >= 1")

    def data_type(self, schema):
        return self.child.data_type(schema)

    @property
    def name_hint(self):
        return f"nth_value({self.child.name_hint},{self.n})"


class NTile(WindowFunction):
    def __init__(self, n: int):
        self.n = n

    def data_type(self, schema):
        return INT32


class Lag(WindowFunction):
    def __init__(self, child: Expression, offset: int = 1, default=None):
        self.child = child
        self.offset = offset
        self.default = default

    @property
    def signed_offset(self) -> int:
        """Shift distance with direction baked in (+N looks back).
        Lead overrides — call sites must use THIS, not an isinstance
        ternary: Lead subclasses Lag, so a Lag-first check silently
        gives lead() lag semantics (the r5 bug)."""
        return self.offset

    def data_type(self, schema):
        return self.child.data_type(schema)

    @property
    def name_hint(self):
        return f"lag({self.child.name_hint},{self.offset})"

    def device_unsupported_reason(self, schema):
        return self.child.fully_device_supported(schema)


class Lead(Lag):
    def __init__(self, child: Expression, offset: int = 1, default=None):
        super().__init__(child, offset, default)

    @property
    def signed_offset(self) -> int:
        return -self.offset

    @property
    def name_hint(self):
        return f"lead({self.child.name_hint},{self.offset})"
