"""Apache Iceberg table reads (ref com/nvidia/spark/rapids/iceberg/
IcebergProvider.scala + IcebergProviderImpl.scala and the java
iceberg/{data,parquet,spark} bridge — the reference reads Iceberg metadata
through iceberg-core on the host and decodes data files on the GPU; here the
metadata chain (version-hint -> vN.metadata.json -> manifest list avro ->
manifest avro -> data files) is parsed with the generic host Avro decoder
(io/avro.py) and the data files run through the parquet scan exec).

Supported: format v1 and v2 metadata, current or explicit snapshot,
parquet data files, live-entry filtering (status != DELETED), schema from
the current schema id. Row-level delete files (v2 positional/equality
deletes) are detected and rejected honestly.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from ..types import (BINARY, BOOL, DATE, DataType, DecimalType, FLOAT32,
                     FLOAT64, INT32, INT64, STRING, TIMESTAMP, Schema,
                     StructField)

__all__ = ["IcebergTable", "iceberg_schema_from_json"]

_PRIM = {
    "boolean": BOOL, "int": INT32, "long": INT64, "float": FLOAT32,
    "double": FLOAT64, "date": DATE, "string": STRING, "uuid": STRING,
    "binary": BINARY, "timestamp": TIMESTAMP, "timestamptz": TIMESTAMP,
}


def _field_type(t) -> DataType:
    if isinstance(t, str):
        if t.startswith("decimal("):
            p, s = t[len("decimal("):-1].split(",")
            return DecimalType(int(p), int(s))
        if t in _PRIM:
            return _PRIM[t]
    raise ValueError(f"unsupported iceberg type {t!r} "
                     "(nested types not yet supported)")


def iceberg_schema_from_json(schema: dict) -> Schema:
    return Schema([
        StructField(f["name"], _field_type(f["type"]),
                    not f.get("required", False))
        for f in schema["fields"]])


class IcebergTable:
    def __init__(self, path: str):
        self.path = path
        self.metadata = self._load_metadata()

    # ------------------------------------------------------------ metadata
    def _load_metadata(self) -> dict:
        mdir = os.path.join(self.path, "metadata")
        hint = os.path.join(mdir, "version-hint.text")
        if os.path.exists(hint):
            with open(hint) as f:
                v = f.read().strip()
            cand = os.path.join(mdir, f"v{v}.metadata.json")
        else:
            versions = sorted(
                f for f in os.listdir(mdir) if f.endswith(".metadata.json"))
            if not versions:
                raise FileNotFoundError(f"no iceberg metadata in {mdir}")
            cand = os.path.join(mdir, versions[-1])
        with open(cand) as f:
            return json.load(f)

    @property
    def schema(self) -> Schema:
        md = self.metadata
        if "schemas" in md:  # v2
            sid = md.get("current-schema-id", 0)
            js = next(s for s in md["schemas"] if s.get("schema-id") == sid)
        else:  # v1
            js = md["schema"]
        return iceberg_schema_from_json(js)

    def snapshot(self, snapshot_id: Optional[int] = None) -> Optional[dict]:
        snaps = self.metadata.get("snapshots") or []
        if snapshot_id is None:
            snapshot_id = self.metadata.get("current-snapshot-id")
        if snapshot_id is None or snapshot_id == -1:
            return None
        for s in snaps:
            if s["snapshot-id"] == snapshot_id:
                return s
        raise ValueError(f"unknown snapshot {snapshot_id}")

    def _resolve(self, p: str) -> str:
        """Manifest/data paths may be absolute or table-location-relative."""
        loc = self.metadata.get("location", self.path)
        if p.startswith(loc):
            rel = p[len(loc):].lstrip("/")
            return os.path.join(self.path, rel)
        if os.path.isabs(p):
            return p
        return os.path.join(self.path, p)

    # ----------------------------------------------------------- planning
    def data_files(self, snapshot_id: Optional[int] = None) -> List[dict]:
        """Live data-file entries of the snapshot (ref the reference's
        GpuIcebergScan planning: manifest list -> manifests -> entries)."""
        from ..io.avro import read_avro_records
        snap = self.snapshot(snapshot_id)
        if snap is None:
            return []
        mlist = self._resolve(snap["manifest-list"])
        out: List[dict] = []
        for m in read_avro_records(mlist):
            if m.get("content", 0) == 1:
                raise ValueError(
                    "iceberg delete manifests (row-level deletes) are not "
                    "yet supported")
            mpath = self._resolve(m["manifest_path"])
            for entry in read_avro_records(mpath):
                if entry.get("status") == 2:   # DELETED
                    continue
                df = entry["data_file"]
                if df.get("content", 0) != 0:
                    raise ValueError("iceberg delete files not supported")
                fmt = str(df.get("file_format", "PARQUET")).upper()
                if fmt != "PARQUET":
                    raise ValueError(f"iceberg {fmt} data files not supported")
                out.append(df)
        return out

    def file_paths(self, snapshot_id: Optional[int] = None) -> List[str]:
        return [self._resolve(d["file_path"])
                for d in self.data_files(snapshot_id)]

    def to_df(self, session, columns: Optional[List[str]] = None,
              snapshot_id: Optional[int] = None):
        from ..api.dataframe import DataFrame
        from ..plan import logical as L
        paths = self.file_paths(snapshot_id)
        schema = self.schema
        if not paths:
            import pyarrow as pa

            from ..types import to_arrow
            empty = pa.table({f.name: pa.array([], to_arrow(f.dtype))
                              for f in schema.fields})
            return DataFrame(session, L.LogicalScan([empty], schema))
        return DataFrame(session, L.ParquetScan(paths, schema, columns))
