"""Apache Iceberg table reads (ref com/nvidia/spark/rapids/iceberg/
IcebergProvider.scala + IcebergProviderImpl.scala and the java
iceberg/{data,parquet,spark} bridge — the reference reads Iceberg metadata
through iceberg-core on the host and decodes data files on the GPU; here the
metadata chain (version-hint -> vN.metadata.json -> manifest list avro ->
manifest avro -> data files) is parsed with the generic host Avro decoder
(io/avro.py) and the data files run through the parquet scan exec).

Supported: format v1 and v2 metadata, current or explicit snapshot,
parquet data files, live-entry filtering (status != DELETED), schema from
the current schema id, and v2 row-level deletes: positional delete files
(file_path, pos) and equality delete files (keyed by equality_ids) are
applied during scan planning with sequence-number scoping — a delete
applies only to data files with a strictly older data sequence number
(ref the reference's iceberg/data java bridge delete-filter chain).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from ..types import (BINARY, BOOL, DATE, DataType, DecimalType, FLOAT32,
                     FLOAT64, INT32, INT64, STRING, TIMESTAMP, Schema,
                     StructField)

__all__ = ["IcebergTable", "iceberg_schema_from_json"]

_PRIM = {
    "boolean": BOOL, "int": INT32, "long": INT64, "float": FLOAT32,
    "double": FLOAT64, "date": DATE, "string": STRING, "uuid": STRING,
    "binary": BINARY, "timestamp": TIMESTAMP, "timestamptz": TIMESTAMP,
}


def _field_type(t) -> DataType:
    if isinstance(t, str):
        if t.startswith("decimal("):
            p, s = t[len("decimal("):-1].split(",")
            return DecimalType(int(p), int(s))
        if t in _PRIM:
            return _PRIM[t]
    if isinstance(t, dict):
        # nested types (r3; ref iceberg/data java bridge readers):
        # struct/list/map scan through the host columnar layer — the
        # engine's collection expressions evaluate them there
        from ..types import ArrayType, MapType, StructField, StructType
        kind = t.get("type")
        if kind == "struct":
            return StructType([
                StructField(f["name"], _field_type(f["type"]),
                            not f.get("required", False))
                for f in t["fields"]])
        if kind == "list":
            return ArrayType(_field_type(t["element"]),
                             contains_null=not t.get("element-required",
                                                     False))
        if kind == "map":
            return MapType(_field_type(t["key"]),
                           _field_type(t["value"]))
    raise ValueError(f"unsupported iceberg type {t!r}")


def iceberg_schema_from_json(schema: dict) -> Schema:
    return Schema([
        StructField(f["name"], _field_type(f["type"]),
                    not f.get("required", False))
        for f in schema["fields"]])


class IcebergTable:
    def __init__(self, path: str):
        self.path = path
        self.metadata = self._load_metadata()

    # ------------------------------------------------------------ metadata
    def _load_metadata(self) -> dict:
        mdir = os.path.join(self.path, "metadata")
        hint = os.path.join(mdir, "version-hint.text")
        if os.path.exists(hint):
            with open(hint) as f:
                v = f.read().strip()
            cand = os.path.join(mdir, f"v{v}.metadata.json")
        else:
            versions = sorted(
                f for f in os.listdir(mdir) if f.endswith(".metadata.json"))
            if not versions:
                raise FileNotFoundError(f"no iceberg metadata in {mdir}")
            cand = os.path.join(mdir, versions[-1])
        with open(cand) as f:
            return json.load(f)

    @property
    def schema(self) -> Schema:
        md = self.metadata
        if "schemas" in md:  # v2
            sid = md.get("current-schema-id", 0)
            js = next(s for s in md["schemas"] if s.get("schema-id") == sid)
        else:  # v1
            js = md["schema"]
        return iceberg_schema_from_json(js)

    def snapshot(self, snapshot_id: Optional[int] = None) -> Optional[dict]:
        snaps = self.metadata.get("snapshots") or []
        if snapshot_id is None:
            snapshot_id = self.metadata.get("current-snapshot-id")
        if snapshot_id is None or snapshot_id == -1:
            return None
        for s in snaps:
            if s["snapshot-id"] == snapshot_id:
                return s
        raise ValueError(f"unknown snapshot {snapshot_id}")

    def _resolve(self, p: str) -> str:
        """Manifest/data paths may be absolute or table-location-relative."""
        loc = self.metadata.get("location", self.path)
        if p.startswith(loc):
            rel = p[len(loc):].lstrip("/")
            return os.path.join(self.path, rel)
        if os.path.isabs(p):
            return p
        return os.path.join(self.path, p)

    # ----------------------------------------------------------- planning
    def plan_scan(self, snapshot_id: Optional[int] = None):
        """Live data-file entries + delete-file entries of the snapshot
        (ref the reference's GpuIcebergScan planning: manifest list ->
        manifests -> entries). Returns (data, deletes): data is a list of
        (seq, data_file dict); deletes of (seq, data_file dict)."""
        from ..io.avro import read_avro_records
        snap = self.snapshot(snapshot_id)
        if snap is None:
            return [], []
        mlist = self._resolve(snap["manifest-list"])
        data: List[tuple] = []
        deletes: List[tuple] = []
        for m in read_avro_records(mlist):
            mseq = m.get("sequence_number") or 0
            mpath = self._resolve(m["manifest_path"])
            for entry in read_avro_records(mpath):
                if entry.get("status") == 2:   # DELETED
                    continue
                df = entry["data_file"]
                seq = entry.get("sequence_number")
                if seq is None:
                    seq = mseq
                content = df.get("content", 0)
                fmt = str(df.get("file_format", "PARQUET")).upper()
                if fmt != "PARQUET":
                    raise ValueError(
                        f"iceberg {fmt} data files not supported")
                if content == 0:
                    data.append((seq, df))
                else:                          # 1 positional, 2 equality
                    deletes.append((seq, df))
        return data, deletes

    def data_files(self, snapshot_id: Optional[int] = None) -> List[dict]:
        """Data-file entries WITHOUT delete awareness — raises when the
        snapshot carries row-level deletes so a caller can never read
        deleted rows silently (use plan_scan / to_df for those)."""
        data, deletes = self.plan_scan(snapshot_id)
        if deletes:
            raise ValueError(
                "snapshot has row-level delete files; use to_df() (which "
                "applies them) or plan_scan() for the raw entries")
        return [df for _, df in data]

    def file_paths(self, snapshot_id: Optional[int] = None) -> List[str]:
        return [self._resolve(d["file_path"])
                for d in self.data_files(snapshot_id)]

    def _field_names_by_id(self) -> Dict[int, str]:
        md = self.metadata
        if "schemas" in md:
            sid = md.get("current-schema-id", 0)
            js = next(s for s in md["schemas"]
                      if s.get("schema-id") == sid)
        else:
            js = md["schema"]
        return {f["id"]: f["name"] for f in js["fields"] if "id" in f}

    def _apply_deletes(self, tables, data, deletes):
        """tables: per-data-file arrow tables aligned with ``data``.
        Positional deletes drop (file_path, pos) rows; equality deletes
        drop rows matching the delete file's key tuples. A delete applies
        only to data files with an OLDER data sequence number (iceberg v2
        scoping; equal seq = same commit, not applicable)."""
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq
        by_id = self._field_names_by_id()
        # positional: target path -> [(seq, positions array)]
        pos_by_path: Dict[str, List[tuple]] = {}
        eq_sets: List[tuple] = []      # (seq, key names, key table)
        for seq, df in deletes:
            t = pq.read_table(self._resolve(df["file_path"]))
            if df.get("content") == 1:
                paths = t.column("file_path").to_pylist()
                poss = np.asarray(t.column("pos").to_pylist(),
                                  dtype=np.int64)
                for p in set(paths):
                    mask = np.asarray([x == p for x in paths])
                    pos_by_path.setdefault(p, []).append(
                        (seq, poss[mask]))
            else:
                ids = df.get("equality_ids") or []
                names = [by_id[i] for i in ids] if ids \
                    else list(t.column_names)
                eq_sets.append((seq, names, t.select(names)))
        out = []
        for (dseq, df), table in zip(data, tables):
            fpath = df["file_path"]
            keep = np.ones(table.num_rows, dtype=bool)
            for p, entries in pos_by_path.items():
                if not (p == fpath or self._resolve(p)
                        == self._resolve(fpath)):
                    continue
                for seq, poss in entries:
                    if seq >= dseq:    # delete is newer (or same commit +)
                        valid = poss[(poss >= 0)
                                     & (poss < table.num_rows)]
                        keep[valid] = False
            for seq, names, kt in eq_sets:
                if seq <= dseq:        # applies to strictly older data
                    continue
                import pandas as pd
                left = table.select(names).to_pandas()
                right = kt.to_pandas().drop_duplicates()
                merged = left.merge(right, on=names, how="left",
                                    indicator=True)
                keep &= (merged["_merge"] == "left_only").to_numpy()
            out.append(table.filter(pa.array(keep)))
        return out

    def to_df(self, session, columns: Optional[List[str]] = None,
              snapshot_id: Optional[int] = None):
        import pyarrow as pa
        from ..api.dataframe import DataFrame
        from ..plan import logical as L
        from ..types import to_arrow
        data, deletes = self.plan_scan(snapshot_id)
        schema = self.schema
        if not data:
            empty = pa.table({f.name: pa.array([], to_arrow(f.dtype))
                              for f in schema.fields})
            return DataFrame(session, L.LogicalScan([empty], schema))
        paths = [self._resolve(d["file_path"]) for _, d in data]
        if not deletes:
            return DataFrame(session, L.ParquetScan(paths, schema,
                                                    columns))
        # row-level deletes: materialize per-file tables, apply the
        # delete filter chain, scan the filtered tables
        import pyarrow.parquet as pq
        tables = [pq.read_table(p) for p in paths]
        tables = self._apply_deletes(tables, data, deletes)
        if columns:
            tables = [t.select(columns) for t in tables]
            schema = Schema([schema[c] for c in columns])
        return DataFrame(session, L.LogicalScan(tables, schema))
