from .parquet import ParquetScanExec, expand_paths, parquet_schema
from .writers import FileWriteExec
from .text import csv_to_tables, json_to_tables

__all__ = ["ParquetScanExec", "expand_paths", "parquet_schema",
           "FileWriteExec", "csv_to_tables", "json_to_tables"]
