"""Avro scan (ref GpuAvroScan.scala, 1,103 LoC + AvroDataFileReader).

The reference parses the Avro Object Container File format in Scala on the
host (AvroDataFileReader), stitches blocks, and hands the raw block bytes to
cudf for decode. Here the container parsing is the same host-side job, done
in Python: header magic + metadata map + sync markers, per-block
count/size/codec handling (null and deflate codecs), then a vectorized-ish
binary decoder for the record schema into Arrow arrays (the cudf-decode
analog). Supported field types: null, boolean, int, long, float, double,
string, bytes, and 2-branch unions with null (nullable fields), plus the
date / timestamp-micros / timestamp-millis logical types; nested
records/arrays/maps/enums/fixed are rejected at schema read so the planner
can fall back honestly (same contract as the reference's type tagging).

Avro is read-only in the reference too (no GpuAvroFileFormat writer).
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import Any, List, Optional, Tuple

from ..config import register
from ..types import (BINARY, BOOL, DATE, FLOAT32, FLOAT64, INT32, INT64,
                     STRING, TIMESTAMP, Schema, StructField)
from .file_scan import FileScanBase, expand_paths

__all__ = ["AvroScanExec", "avro_schema", "read_avro_table",
           "expand_avro_paths"]

_MAGIC = b"Obj\x01"

AVRO_READER_TYPE = register(
    "spark.rapids.tpu.sql.format.avro.reader.type", "AUTO",
    "PERFILE / COALESCING / MULTITHREADED / AUTO "
    "(ref GpuAvroScan.scala reader selection).")


# ---------------------------------------------------------------------------
# binary primitives
# ---------------------------------------------------------------------------

def _read_long(buf: bytes, pos: int) -> Tuple[int, int]:
    """zigzag varint (Avro long/int encoding)."""
    b = buf[pos]
    n = b & 0x7F
    shift = 7
    pos += 1
    while b & 0x80:
        b = buf[pos]
        n |= (b & 0x7F) << shift
        shift += 7
        pos += 1
    return (n >> 1) ^ -(n & 1), pos


def _read_bytes(buf: bytes, pos: int) -> Tuple[bytes, int]:
    ln, pos = _read_long(buf, pos)
    return buf[pos:pos + ln], pos + ln


# ---------------------------------------------------------------------------
# schema handling
# ---------------------------------------------------------------------------

class _Field:
    __slots__ = ("name", "kind", "nullable", "null_first", "logical")

    def __init__(self, name, kind, nullable, null_first, logical):
        self.name = name
        self.kind = kind            # avro primitive name
        self.nullable = nullable
        self.null_first = null_first  # union branch order ["null", T] vs [T, "null"]
        self.logical = logical      # date | timestamp-micros | timestamp-millis


_PRIMITIVES = {"boolean", "int", "long", "float", "double", "string",
               "bytes"}


def _parse_field(f: dict) -> _Field:
    t = f["type"]
    nullable = False
    null_first = True
    if isinstance(t, list):
        if len(t) != 2 or "null" not in t:
            raise ValueError(f"unsupported avro union {t}")
        nullable = True
        null_first = t[0] == "null"
        t = t[1] if t[0] == "null" else t[0]
    logical = None
    if isinstance(t, dict):
        logical = t.get("logicalType")
        t = t["type"]
    if t not in _PRIMITIVES:
        raise ValueError(f"unsupported avro type {t!r} for field {f['name']}")
    if logical not in (None, "date", "timestamp-micros", "timestamp-millis"):
        raise ValueError(f"unsupported logical type {logical}")
    return _Field(f["name"], t, nullable, null_first, logical)


def _arrow_type(fld: _Field):
    import pyarrow as pa
    if fld.logical == "date":
        return pa.date32()
    if fld.logical in ("timestamp-micros", "timestamp-millis"):
        return pa.timestamp("us")
    return {"boolean": pa.bool_(), "int": pa.int32(), "long": pa.int64(),
            "float": pa.float32(), "double": pa.float64(),
            "string": pa.string(), "bytes": pa.binary()}[fld.kind]


def _our_type(fld: _Field):
    if fld.logical == "date":
        return DATE
    if fld.logical in ("timestamp-micros", "timestamp-millis"):
        return TIMESTAMP
    return {"boolean": BOOL, "int": INT32, "long": INT64,
            "float": FLOAT32, "double": FLOAT64, "string": STRING,
            "bytes": BINARY}[fld.kind]


# ---------------------------------------------------------------------------
# container file reading
# ---------------------------------------------------------------------------

class _Container:
    def __init__(self, path: str, flat: bool = True):
        with open(path, "rb") as f:
            self.data = f.read()
        if self.data[:4] != _MAGIC:
            raise ValueError(f"{path}: not an Avro object container file")
        pos = 4
        meta = {}
        while True:
            count, pos = _read_long(self.data, pos)
            if count == 0:
                break
            if count < 0:  # block with explicit byte size
                _, pos = _read_long(self.data, pos)
                count = -count
            for _ in range(count):
                k, pos = _read_bytes(self.data, pos)
                v, pos = _read_bytes(self.data, pos)
                meta[k.decode()] = v
        self.meta = meta
        self.sync = self.data[pos:pos + 16]
        self.body_pos = pos + 16
        self.codec = meta.get("avro.codec", b"null").decode()
        if self.codec not in ("null", "deflate"):
            raise ValueError(f"unsupported avro codec {self.codec}")
        self.schema_json = json.loads(meta["avro.schema"].decode())
        if self.schema_json.get("type") != "record":
            raise ValueError("top-level avro schema must be a record")
        self.fields = ([_parse_field(f) for f in self.schema_json["fields"]]
                       if flat else None)

    def blocks(self):
        """Yield (row_count, decompressed_bytes) per data block
        (ref AvroDataFileReader block iteration + sync verification)."""
        pos = self.body_pos
        data = self.data
        while pos < len(data):
            count, pos = _read_long(data, pos)
            size, pos = _read_long(data, pos)
            payload = data[pos:pos + size]
            pos += size
            if data[pos:pos + 16] != self.sync:
                raise ValueError("avro sync marker mismatch (corrupt file)")
            pos += 16
            if self.codec == "deflate":
                payload = zlib.decompress(payload, -15)
            yield count, payload


def _decode_block(fields: List[_Field], count: int, buf: bytes,
                  columns: List[List[Any]]):
    pos = 0
    for _ in range(count):
        for fi, fld in enumerate(fields):
            if fld.nullable:
                branch, pos = _read_long(buf, pos)
                is_null = (branch == 0) == fld.null_first
                if is_null:
                    columns[fi].append(None)
                    continue
            k = fld.kind
            if k in ("int", "long"):
                v, pos = _read_long(buf, pos)
                if fld.logical == "timestamp-millis":
                    v *= 1000
            elif k == "boolean":
                v = buf[pos] != 0
                pos += 1
            elif k == "float":
                v = struct.unpack_from("<f", buf, pos)[0]
                pos += 4
            elif k == "double":
                v = struct.unpack_from("<d", buf, pos)[0]
                pos += 8
            elif k == "string":
                raw, pos = _read_bytes(buf, pos)
                v = raw.decode("utf-8")
            else:  # bytes
                v, pos = _read_bytes(buf, pos)
            columns[fi].append(v)


def read_avro_table(path: str, columns: Optional[List[str]] = None):
    """Decode a whole container file to a pyarrow Table."""
    import pyarrow as pa
    c = _Container(path)
    cols: List[List[Any]] = [[] for _ in c.fields]
    for count, payload in c.blocks():
        _decode_block(c.fields, count, payload, cols)
    arrays = {f.name: pa.array(v, type=_arrow_type(f))
              for f, v in zip(c.fields, cols)}
    t = pa.table(arrays)
    if columns:
        t = t.select(columns)
    return t


# ---------------------------------------------------------------------------
# generic (nested) record decoding — used by the Iceberg manifest reader,
# which needs record/array/map/fixed/enum support the columnar scan rejects
# (ref: the reference reads Iceberg manifests through iceberg-core on the
# host; this is the same host-side role)
# ---------------------------------------------------------------------------

class _GenericDecoder:
    def __init__(self, schema):
        self.named = {}
        self.schema = self._resolve(schema)

    def _resolve(self, s):
        if isinstance(s, str):
            return self.named.get(s, s)
        if isinstance(s, list):
            return [self._resolve(b) for b in s]
        t = s.get("type")
        if t in ("record", "fixed", "enum"):
            self.named[s.get("name")] = s
            if t == "record":
                s = dict(s)
                s["fields"] = [dict(f, type=self._resolve(f["type"]))
                               for f in s["fields"]]
                self.named[s.get("name")] = s
        elif t == "array":
            s = dict(s, items=self._resolve(s["items"]))
        elif t == "map":
            s = dict(s, values=self._resolve(s["values"]))
        return s

    def decode(self, s, buf: bytes, pos: int):
        if isinstance(s, str):
            s = self.named.get(s, s)
        if isinstance(s, list):          # union
            idx, pos = _read_long(buf, pos)
            return self.decode(s[idx], buf, pos)
        if isinstance(s, dict):
            t = s["type"]
            if t == "record":
                out = {}
                for f in s["fields"]:
                    out[f["name"]], pos = self.decode(f["type"], buf, pos)
                return out, pos
            if t == "array":
                vals = []
                while True:
                    n, pos = _read_long(buf, pos)
                    if n == 0:
                        break
                    if n < 0:
                        _, pos = _read_long(buf, pos)  # block byte size
                        n = -n
                    for _ in range(n):
                        v, pos = self.decode(s["items"], buf, pos)
                        vals.append(v)
                return vals, pos
            if t == "map":
                out = {}
                while True:
                    n, pos = _read_long(buf, pos)
                    if n == 0:
                        break
                    if n < 0:
                        _, pos = _read_long(buf, pos)
                        n = -n
                    for _ in range(n):
                        k, pos = self.decode("string", buf, pos)
                        v, pos = self.decode(s["values"], buf, pos)
                        out[k] = v
                return out, pos
            if t == "fixed":
                sz = s["size"]
                return buf[pos:pos + sz], pos + sz
            if t == "enum":
                idx, pos = _read_long(buf, pos)
                return s["symbols"][idx], pos
            return self.decode(t, buf, pos)   # {"type": "long", logical...}
        # primitive
        if s == "null":
            return None, pos
        if s == "boolean":
            return buf[pos] != 0, pos + 1
        if s in ("int", "long"):
            return _read_long(buf, pos)
        if s == "float":
            return struct.unpack_from("<f", buf, pos)[0], pos + 4
        if s == "double":
            return struct.unpack_from("<d", buf, pos)[0], pos + 8
        if s == "string":
            raw, pos = _read_bytes(buf, pos)
            return raw.decode("utf-8"), pos
        if s == "bytes":
            return _read_bytes(buf, pos)
        raise ValueError(f"unsupported avro schema {s!r}")


def read_avro_records(path: str):
    """Decode a container file of arbitrarily nested records to a list of
    Python dicts (host-side metadata reading; NOT the columnar scan path)."""
    c = _Container(path, flat=False)
    dec = _GenericDecoder(c.schema_json)
    out = []
    for count, payload in c.blocks():
        pos = 0
        for _ in range(count):
            v, pos = dec.decode(dec.schema, payload, pos)
            out.append(v)
    return out


def avro_schema(path: str) -> Schema:
    c = _Container(path)
    return Schema([StructField(f.name, _our_type(f), True)
                   for f in c.fields])


def expand_avro_paths(paths) -> List[str]:
    return expand_paths(paths)


class AvroScanExec(FileScanBase):
    FORMAT = "avro"
    READER_TYPE_KEY = AVRO_READER_TYPE

    def _read_table(self, path: str):
        return read_avro_table(self._cached_path(path), self.columns)
