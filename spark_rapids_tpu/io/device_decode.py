"""Experimental device-side parquet decode (ref GpuParquetScan device
decode: Table.readParquet feeds raw pages to cudf's GPU decoder,
GpuParquetScan.scala:1867/2063/2750).

TPU-first shape of the same idea: for UNCOMPRESSED, PLAIN-encoded,
fixed-width, null-free column chunks, the host touches only the tiny
page headers — the VALUE BYTES go to the device raw (one uint8 H2D per
column) and a jitted kernel bitcasts them into the typed column. The
host never materializes an Arrow array for these columns, so ingest
skips one full host copy per column.

Page headers are Thrift *compact protocol* structs; the ~90-line parser
below reads just the fields needed to locate each page's value bytes
(PageHeader: type, compressed size; DataPageHeader: num_values,
encoding; v2: also def/rep level byte lengths). Anything unexpected —
compression, dictionary pages, nulls, unsupported physical types —
disqualifies the chunk and the standard pyarrow path handles it.

Opt-in: ``spark.rapids.tpu.io.parquet.deviceDecode.enabled`` (an
EXPERIMENTAL tier; the eligibility window is narrow by design — being
right beats being broad for a decoder).
"""
from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

from ..config import register

__all__ = ["DEVICE_DECODE_ENABLED", "decode_chunk_values",
           "chunk_eligible"]

DEVICE_DECODE_ENABLED = register(
    "spark.rapids.tpu.io.parquet.deviceDecode.enabled", False,
    "EXPERIMENTAL: decode eligible parquet column chunks on the device "
    "(uncompressed, PLAIN, fixed-width, null-free): the host parses "
    "only page headers and ships raw value bytes; a device kernel "
    "bitcasts them into the typed column (io/device_decode.py; ref "
    "GpuParquetScan device decode). Engages only with "
    "format.parquet.reader.type=PERFILE and no pushed-down predicate; "
    "ineligible chunks/files use the standard pyarrow path.")

# thrift compact-protocol wire types
_CT_STOP = 0
_CT_TRUE = 1
_CT_FALSE = 2
_CT_BYTE = 3
_CT_I16 = 4
_CT_I32 = 5
_CT_I64 = 6
_CT_DOUBLE = 7
_CT_BINARY = 8
_CT_LIST = 9
_CT_SET = 10
_CT_MAP = 11
_CT_STRUCT = 12


class _Compact:
    """Minimal Thrift compact-protocol reader (just what PageHeader
    needs: varints, zigzag ints, binary, nested structs, lists)."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def skip(self, ctype: int) -> None:
        if ctype in (_CT_TRUE, _CT_FALSE):
            return
        if ctype == _CT_BYTE:
            self.pos += 1
        elif ctype in (_CT_I16, _CT_I32, _CT_I64):
            self.varint()
        elif ctype == _CT_DOUBLE:
            self.pos += 8
        elif ctype == _CT_BINARY:
            # NOT `self.pos += self.varint()`: the augmented assignment
            # loads the OLD pos before varint() advances it
            n = self.varint()
            self.pos += n
        elif ctype == _CT_STRUCT:
            self.read_struct(lambda fid, ct, r: r.skip(ct))
        elif ctype in (_CT_LIST, _CT_SET):
            head = self.buf[self.pos]
            self.pos += 1
            n = head >> 4
            et = head & 0x0F
            if n == 15:
                n = self.varint()
            for _ in range(n):
                self.skip(et)
        elif ctype == _CT_MAP:
            n = self.varint()
            if n:
                kv = self.buf[self.pos]
                self.pos += 1
                for _ in range(n):
                    self.skip(kv >> 4)
                    self.skip(kv & 0x0F)
        else:
            raise ValueError(f"thrift compact type {ctype}")

    def read_struct(self, on_field) -> None:
        """on_field(field_id, ctype, reader) must consume the value."""
        fid = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            if b == _CT_STOP:
                return
            delta = b >> 4
            ctype = b & 0x0F
            if delta:
                fid += delta
            else:
                fid = self.zigzag()
            on_field(fid, ctype, self)


class _PageHeader:
    __slots__ = ("type", "compressed_size", "num_values", "encoding",
                 "def_len", "rep_len")

    def __init__(self):
        self.type = None
        self.compressed_size = None
        self.num_values = 0
        self.encoding = None
        self.def_len = 0       # v2: explicit level byte lengths
        self.rep_len = 0


def _parse_page_header(buf: bytes, pos: int) -> Tuple[_PageHeader, int]:
    h = _PageHeader()

    def data_hdr(fid, ct, r):
        if fid == 1:
            h.num_values = r.zigzag()
        elif fid == 2:
            h.encoding = r.zigzag()
        elif fid == 5 and ct == _CT_STRUCT:
            r.skip(ct)         # statistics
        else:
            r.skip(ct)

    def data_hdr_v2(fid, ct, r):
        if fid == 1:
            h.num_values = r.zigzag()
        elif fid == 2:
            r.zigzag()         # num_nulls (eligibility already proven 0)
        elif fid == 3:
            r.zigzag()         # num_rows
        elif fid == 4:
            h.encoding = r.zigzag()
        elif fid == 5:
            h.def_len = r.zigzag()
        elif fid == 6:
            h.rep_len = r.zigzag()
        else:
            r.skip(ct)

    def top(fid, ct, r):
        if fid == 1:
            h.type = r.zigzag()
        elif fid == 3:
            h.compressed_size = r.zigzag()
        elif fid == 5 and ct == _CT_STRUCT:
            r.read_struct(data_hdr)
        elif fid == 8 and ct == _CT_STRUCT:
            r.read_struct(data_hdr_v2)
        else:
            r.skip(ct)

    r = _Compact(buf, pos)
    r.read_struct(top)
    return h, r.pos


#: parquet physical type id -> numpy dtype (fixed-width only)
_PHYS = {"INT32": np.dtype("<i4"), "INT64": np.dtype("<i8"),
         "FLOAT": np.dtype("<f4"), "DOUBLE": np.dtype("<f8")}
_ENC_PLAIN = 0
_PAGE_DATA, _PAGE_DATA_V2 = 0, 3


def chunk_eligible(col_meta) -> Optional[np.dtype]:
    """np dtype when this column-chunk metadata qualifies for raw-byte
    device decode, else None."""
    if col_meta.compression != "UNCOMPRESSED":
        return None
    if col_meta.dictionary_page_offset is not None:
        return None
    encs = set(col_meta.encodings)
    # BIT_PACKED def levels have no length prefix — the v1 offset math
    # below would silently land mid-page, so only RLE levels qualify
    if not encs <= {"PLAIN", "RLE"}:
        return None
    st = col_meta.statistics
    if st is None or st.null_count is None or st.null_count != 0:
        return None
    return _PHYS.get(col_meta.physical_type)


def decode_chunk_values(raw: bytes, num_values: int,
                        dtype: np.dtype,
                        max_def_level: int) -> Optional[np.ndarray]:
    """Concatenate the value bytes of every data page in a raw column
    chunk -> one contiguous little-endian array (NO host type decode —
    the caller ships these bytes to the device and bitcasts there).
    Returns None if anything in the chunk surprises the parser."""
    width = dtype.itemsize
    pos = 0
    parts: List[bytes] = []
    got = 0
    try:
        while got < num_values:
            h, data_pos = _parse_page_header(raw, pos)
            if h.compressed_size is None:
                return None
            end = data_pos + h.compressed_size
            if h.type == _PAGE_DATA:
                if h.encoding != _ENC_PLAIN:
                    return None
                off = data_pos
                if max_def_level > 0:
                    # v1 RLE def-level block: u32 length prefix
                    (lv_len,) = struct.unpack_from("<I", raw, off)
                    off += 4 + lv_len
                parts.append(raw[off:off + h.num_values * width])
            elif h.type == _PAGE_DATA_V2:
                if h.encoding != _ENC_PLAIN:
                    return None
                off = data_pos + h.def_len + h.rep_len
                parts.append(raw[off:off + h.num_values * width])
            else:
                return None          # dictionary/index page: ineligible
            got += h.num_values
            pos = end
        if got != num_values:
            return None
        out = b"".join(parts)
        if len(out) != num_values * width:
            return None
        return np.frombuffer(out, dtype=dtype)
    except (IndexError, struct.error):
        return None
