"""Shared file-scan machinery: the reference's three reader strategies
(ref GpuParquetScan.scala — ParquetPartitionReader PERFILE :2750,
MultiFileParquetPartitionReader COALESCING :1867,
MultiFileCloudParquetPartitionReader MULTITHREADED :2063; the same trio is
reused by GpuOrcScan.scala and GpuAvroScan.scala).

Each format subclass supplies ``_read_table(path) -> pyarrow.Table`` (host
decode — the CPU-side role the reference's footer/stripe/block parsing
plays) and the base turns tables into device batches:
  * PERFILE       — one host read + H2D per file;
  * COALESCING    — stitch small files' tables to target size, one H2D per
                    coalesced table;
  * MULTITHREADED — background host reads on a thread pool feeding the
                    device in file order.
"""
from __future__ import annotations

import concurrent.futures as cf
import glob as _glob
import os
from typing import Iterator, List, Optional

from ..columnar import ColumnarBatch
from ..config import MULTITHREADED_READ_THREADS, TpuConf
from ..exec.base import ESSENTIAL, ExecContext, TpuExec
from ..types import Schema


def apply_path_rules(conf, paths):
    """Rewrite paths through spark.rapids.tpu.io.pathReplacementRules
    (ref AlluxioUtils.scala's s3://->alluxio:// replacement): applied
    once, where the session first resolves the scan. Malformed rules
    (no '->') are rejected loudly — a silently mis-parsed rule strips
    prefixes instead of replacing them."""
    from ..config import IO_PATH_REPLACEMENT
    rules = []
    raw = str(conf.get(IO_PATH_REPLACEMENT))
    for r in filter(None, raw.split(";")):
        prefix, sep, repl = r.partition("->")
        if not sep or not prefix:
            raise ValueError(
                f"malformed path replacement rule {r!r} "
                "(expected 'prefix->replacement')")
        rules.append((prefix, repl))
    if not rules:
        return list(paths)
    out = []
    for p in paths:
        for prefix, repl in rules:
            if p.startswith(prefix):
                p = repl + p[len(prefix):]
                break
        out.append(p)
    return out



__all__ = ["FileScanBase", "expand_paths"]


def expand_paths(paths) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for f in sorted(os.listdir(p)):
                if not f.startswith((".", "_")):
                    out.append(os.path.join(p, f))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files for {paths}")
    return out


class FileScanBase(TpuExec):
    FORMAT = "file"
    READER_TYPE_KEY = None  # ConfEntry; None -> AUTO resolution only

    def __init__(self, paths: List[str], schema: Schema,
                 columns: Optional[List[str]], conf: TpuConf,
                 predicate=None):
        super().__init__([])
        self.paths = paths
        self._schema = schema
        self.columns = columns
        self.conf = conf
        self.predicate = predicate
        mode = "AUTO"
        if self.READER_TYPE_KEY is not None:
            mode = str(conf.get(self.READER_TYPE_KEY)).upper()
        if mode == "AUTO":
            mode = "MULTITHREADED" if len(paths) > 1 else "PERFILE"
        self.mode = mode

    def output_schema(self) -> Schema:
        return self._schema

    def set_predicate(self, pred) -> None:
        """Planner pushdown hook (skipping is conservative; the filter above
        still runs)."""
        self.predicate = pred

    def _cached_path(self, path: str) -> str:
        """FileCache indirection (ref FileCache hook surface; metrics
        filecacheHits/Misses mirror GpuExec.scala:78-87). Path-replacement
        rules were already applied when the session resolved the scan."""
        from .filecache import FileCache
        fc = FileCache.get(self.conf)
        if fc is None:
            return path
        return fc.resolve(path)

    def _read_table(self, path: str):
        raise NotImplementedError

    # ------------------------------------------------------------- modes
    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        files_m = ctx.metric(self._exec_id, "numFiles")
        files_m.add(len(self.paths))
        batch_rows = ctx.conf.batch_size_rows

        if self.mode == "COALESCING":
            yield from self._coalescing(ctx, rows_m, batch_rows)
            return
        if self.mode == "MULTITHREADED":
            yield from self._multithreaded(ctx, rows_m, batch_rows)
            return
        # PERFILE
        for pid, path in enumerate(self.paths):
            t = self._read_table(path)
            yield from self._emit(ctx, t, rows_m, batch_rows,
                                  input_file=path, pid=pid)

    def _emit(self, ctx, table, rows_m, batch_rows, input_file=None, pid=0):
        off = 0
        n = table.num_rows
        while off < n or (n == 0 and off == 0):
            chunk = table.slice(off, batch_rows)
            with ctx.semaphore.held():
                b = ColumnarBatch.from_arrow(chunk)
            b.meta = {"partition_id": pid, "input_file": input_file,
                      "row_offset": off}
            rows_m.add(b.num_rows)
            yield b
            off += batch_rows
            if n == 0:
                break

    def _coalescing(self, ctx, rows_m, batch_rows):
        import pyarrow as pa
        pending, rows = [], 0
        for path in self.paths:
            t = self._read_table(path)
            pending.append(t)
            rows += t.num_rows
            if rows >= batch_rows:
                yield from self._emit(ctx, pa.concat_tables(pending),
                                      rows_m, batch_rows)
                pending, rows = [], 0
        if pending:
            yield from self._emit(ctx, pa.concat_tables(pending),
                                  rows_m, batch_rows)

    def _multithreaded(self, ctx, rows_m, batch_rows):
        nthreads = int(self.conf.get(MULTITHREADED_READ_THREADS))
        with cf.ThreadPoolExecutor(max_workers=nthreads) as pool:
            futures = [pool.submit(self._read_table, p) for p in self.paths]
            for pid, fut in enumerate(futures):  # file order; reads overlap
                yield from self._emit(ctx, fut.result(), rows_m, batch_rows,
                                      input_file=self.paths[pid], pid=pid)

    def describe(self):
        name = type(self).__name__.replace("Exec", "")
        return (f"{name}[{len(self.paths)} files, {self.mode}"
                + (f", pushdown={self.predicate.name_hint}" if self.predicate
                   else "") + "]")
