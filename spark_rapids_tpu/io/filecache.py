"""FileCache: local cache of (remote) input files (ref the FileCache whose
implementation lives in the private rapids-4-spark-private artifact — only
its hook surface is public: FileCacheLocalityManager RPC Plugin.scala:425,
metrics GpuExec.scala:78-87, confs, and
tests/.../filecache/FileCacheIntegrationSuite.scala. This is a from-scratch
implementation of that surface).

Files are cached under ``spark.rapids.tpu.filecache.path`` keyed by
(absolute path, mtime, size) so source updates invalidate naturally; an LRU
size budget evicts cold entries. Scans consult the cache transparently via
FileScanBase when ``spark.rapids.tpu.filecache.enabled`` is on."""
from __future__ import annotations

import hashlib
import os
import shutil
import threading
from typing import Dict, Optional

from ..config import register

__all__ = ["FileCache"]

FILECACHE_ENABLED = register(
    "spark.rapids.tpu.filecache.enabled", False,
    "Cache input files on local disk before reading "
    "(ref spark.rapids.filecache.enabled).")

FILECACHE_PATH = register(
    "spark.rapids.tpu.filecache.path", "/tmp/spark_rapids_tpu_filecache",
    "Local directory for the file cache.")

FILECACHE_MAX_BYTES = register(
    "spark.rapids.tpu.filecache.maxBytes", 10 * 1024 * 1024 * 1024,
    "File-cache size budget; least-recently-used entries evict first.")


class FileCache:
    _lock = threading.Lock()
    # tpulint: guarded-by _lock
    _instances: Dict[str, "FileCache"] = {}

    def __init__(self, path: str, max_bytes: int):
        self.path = path
        self.max_bytes = max_bytes
        self._io_lock = threading.Lock()
        self.hits = 0                # tpulint: guarded-by _io_lock
        self.misses = 0              # tpulint: guarded-by _io_lock
        # thread ident -> the path resolve() last handed that thread: a
        # concurrent miss's eviction must not unlink it before the
        # reader opens it
        self._in_use: Dict[int, str] = {}  # tpulint: guarded-by _io_lock
        os.makedirs(path, exist_ok=True)

    @classmethod
    def get(cls, conf) -> Optional["FileCache"]:
        if not conf.get(FILECACHE_ENABLED):
            return None
        p = str(conf.get(FILECACHE_PATH))
        with cls._lock:
            if p not in cls._instances:
                cls._instances[p] = cls(p, int(conf.get(FILECACHE_MAX_BYTES)))
            return cls._instances[p]

    # ------------------------------------------------------------------
    def _key(self, path: str) -> str:
        st = os.stat(path)
        raw = f"{os.path.abspath(path)}|{st.st_mtime_ns}|{st.st_size}"
        return hashlib.sha256(raw.encode()).hexdigest()[:32] + \
            os.path.splitext(path)[1]

    def resolve(self, path: str) -> str:
        """Local cached path for ``path`` (copying in on miss).
        Thread-safe: resolve/evict hold the instance lock so a concurrent
        miss cannot evict an entry this call just handed out; cross-process
        sharers are safe via unique tmp names + atomic rename and the
        eviction grace window."""
        with self._io_lock:
            local = os.path.join(self.path, self._key(path))
            if os.path.exists(local):
                self.hits += 1
                os.utime(local)          # LRU touch
                self._in_use[threading.get_ident()] = local
                return local
            self.misses += 1
            self._evict_for(os.path.getsize(path))
            tmp = f"{local}.{os.getpid()}.{threading.get_ident()}.tmp"
            try:
                shutil.copyfile(path, tmp)
                os.replace(tmp, local)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            self._in_use[threading.get_ident()] = local
            return local

    def _evict_for(self, incoming: int) -> None:
        protected = set(self._in_use.values())
        entries = []
        total = 0
        for f in os.listdir(self.path):
            full = os.path.join(self.path, f)
            if os.path.isfile(full):
                st = os.stat(full)
                entries.append((st.st_atime, st.st_size, full))
                total += st.st_size
        entries.sort()
        while entries and total + incoming > self.max_bytes:
            _, sz, full = entries.pop(0)
            if full in protected:
                continue
            try:
                os.unlink(full)
            except OSError:
                continue                 # raced with another evictor; keep going
            total -= sz
