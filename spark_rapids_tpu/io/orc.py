"""ORC scan (ref GpuOrcScan.scala, 2,928 LoC — same three reader modes as
parquet, stripe stitching, schema-evolution casts).

Host decode is pyarrow's C++ ORC reader (the cudf-ORC-decode analog);
stripes play the row-group role. pyarrow exposes no per-stripe statistics,
so predicate pruning is file-level only (tagged honestly in describe());
the reference prunes stripes via the ORC SearchArgument on the CPU side
(GpuOrcScan filterStripes) — the equivalent here would need a native ORC
footer parser, tracked as future work.
"""
from __future__ import annotations

from typing import List

from ..config import register
from ..types import Schema, StructField, from_arrow
from .file_scan import FileScanBase, expand_paths

__all__ = ["OrcScanExec", "orc_schema", "expand_orc_paths"]

ORC_READER_TYPE = register(
    "spark.rapids.tpu.sql.format.orc.reader.type", "AUTO",
    "PERFILE / COALESCING / MULTITHREADED / AUTO "
    "(ref GpuOrcScan.scala multi-file reader selection).")


def expand_orc_paths(paths) -> List[str]:
    return expand_paths(paths)


def orc_schema(path: str) -> Schema:
    from pyarrow import orc
    sch = orc.ORCFile(path).schema
    return Schema([StructField(f.name, from_arrow(f.type), f.nullable)
                   for f in sch])


class OrcScanExec(FileScanBase):
    FORMAT = "orc"
    READER_TYPE_KEY = ORC_READER_TYPE

    def _read_table(self, path: str):
        from pyarrow import orc
        f = orc.ORCFile(self._cached_path(path))
        t = f.read(columns=self.columns)
        if self.columns:
            t = t.select(self.columns)  # requested order, not file order
        return t
