"""ORC scan (ref GpuOrcScan.scala, 2,928 LoC — same three reader modes as
parquet, stripe stitching, schema-evolution casts).

Host decode is pyarrow's C++ ORC reader (the cudf-ORC-decode analog);
stripes play the row-group role. pyarrow exposes no per-stripe
statistics, so stripe-level predicate pruning parses the ORC footer and
metadata sections natively (io/orc_meta.py) and skips stripes the
pushed-down predicate provably excludes — the CPU-side SearchArgument
evaluation of the reference (GpuOrcScan filterStripes), sharing
parquet's conservative interval matcher.
"""
from __future__ import annotations

from typing import List, Optional

from ..config import register
from ..types import Schema, StructField, from_arrow
from .file_scan import FileScanBase, expand_paths

__all__ = ["OrcScanExec", "orc_schema", "expand_orc_paths"]

ORC_READER_TYPE = register(
    "spark.rapids.tpu.sql.format.orc.reader.type", "AUTO",
    "PERFILE / COALESCING / MULTITHREADED / AUTO "
    "(ref GpuOrcScan.scala multi-file reader selection).")


def expand_orc_paths(paths) -> List[str]:
    return expand_paths(paths)


def orc_schema(path: str) -> Schema:
    from pyarrow import orc
    sch = orc.ORCFile(path).schema
    return Schema([StructField(f.name, from_arrow(f.type), f.nullable)
                   for f in sch])


class OrcScanExec(FileScanBase):
    FORMAT = "orc"
    READER_TYPE_KEY = ORC_READER_TYPE

    def _read_table(self, path: str):
        import pyarrow as pa
        from pyarrow import orc
        local = self._cached_path(path)
        f = orc.ORCFile(local)
        keep = self._filter_stripes(local, f.nstripes)
        if keep is None:
            t = f.read(columns=self.columns)
        elif not keep:
            t = f.schema.empty_table()
        elif len(keep) == f.nstripes:
            t = f.read(columns=self.columns)
        else:
            parts = [f.read_stripe(i, columns=self.columns)
                     for i in keep]
            t = pa.Table.from_batches(parts)
        if self.columns:
            t = t.select(self.columns)  # requested order, not file order
        return t

    def _filter_stripes(self, path: str,
                        nstripes: int) -> Optional[List[int]]:
        """Stripe pruning from the natively-parsed ORC metadata
        statistics (ref GpuOrcScan filterStripes)."""
        if self.predicate is None:
            return None
        from .orc_meta import read_orc_meta
        from .parquet import _maybe_matches
        meta = read_orc_meta(path)
        if meta is None or meta.stripe_stats is None \
                or len(meta.stripe_stats) != nstripes:
            return None
        try:
            return [i for i, stats in enumerate(meta.stripe_stats)
                    if _maybe_matches(self.predicate, stats)]
        except Exception:
            return None
