"""Native ORC footer + stripe-statistics parser.

pyarrow's ORC binding exposes no per-stripe statistics, so stripe-level
predicate pruning (ref GpuOrcScan.scala filterStripes — the ORC
SearchArgument evaluated on the CPU before any decode) needs this
minimal reader of the ORC file tail: PostScript -> Footer (stripes,
types) -> Metadata (per-stripe column statistics). Only the protobuf
fields the pruner consumes are decoded; everything else is skipped by
wire type. Handles NONE- and ZLIB-compressed footers (what pyarrow and
the Java writer emit by default); other codecs disable pruning
gracefully.

ORC spec: https://orc.apache.org/specification/ORCv1/ (public format).
"""
from __future__ import annotations

import os
import struct
import zlib
from typing import Dict, List, Optional, Tuple

__all__ = ["OrcFileMeta", "read_orc_meta"]

_VARINT = 0
_I64 = 1
_LEN = 2
_I32 = 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a protobuf message.
    LEN fields yield bytes; VARINT ints; I64/I32 raw ints."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        fno, wt = tag >> 3, tag & 7
        if wt == _VARINT:
            v, pos = _read_varint(buf, pos)
        elif wt == _LEN:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == _I64:
            v = struct.unpack_from("<q", buf, pos)[0]
            pos += 8
        elif wt == _I32:
            v = struct.unpack_from("<i", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fno, wt, v


def _snappy_block(chunk: bytes) -> bytes:
    """Raw snappy block: the leading varint is the uncompressed length
    (pyarrow's codec needs it passed explicitly)."""
    import pyarrow as pa
    size, _ = _read_varint(chunk, 0)
    out = pa.Codec("snappy").decompress(chunk, decompressed_size=size)
    return out.to_pybytes() if hasattr(out, "to_pybytes") else bytes(out)


def _zstd_block(chunk: bytes) -> bytes:
    """One zstd frame. Prefer the zstandard module (size-less streaming
    API); without it, parse the frame header's Frame_Content_Size so
    pyarrow's codec (which demands the exact size) can decode. ORC
    writers use the simple API, which always records the content size."""
    try:
        import zstandard
        return zstandard.ZstdDecompressor().decompress(
            chunk, max_output_size=1 << 26)
    except ImportError:
        pass
    import pyarrow as pa
    if chunk[:4] != b"\x28\xb5\x2f\xfd":
        raise ValueError("not a zstd frame")
    fhd = chunk[4]
    fcs_flag = fhd >> 6
    single_segment = (fhd >> 5) & 1
    pos = 5 + (0 if single_segment else 1)   # skip window descriptor
    pos += (0, 1, 2, 4)[fhd & 3]             # skip dictionary id
    if fcs_flag == 0:
        if not single_segment:
            raise ValueError("zstd frame without content size")
        size = chunk[pos]
    elif fcs_flag == 1:
        size = struct.unpack_from("<H", chunk, pos)[0] + 256
    elif fcs_flag == 2:
        size = struct.unpack_from("<I", chunk, pos)[0]
    else:
        size = struct.unpack_from("<Q", chunk, pos)[0]
    out = pa.Codec("zstd").decompress(chunk, decompressed_size=size)
    return out.to_pybytes() if hasattr(out, "to_pybytes") else bytes(out)


def _decompress(data: bytes, kind: int) -> bytes:
    """ORC compressed stream: 3-byte chunk headers
    (len << 1 | isOriginal), repeated. kind: 0=NONE 1=ZLIB 2=SNAPPY
    5=ZSTD (r3 — VERDICT r2 #10; LZO/LZ4 block codecs stay
    unsupported and disable pruning gracefully)."""
    if kind == 0:
        return data
    out = bytearray()
    pos = 0
    while pos + 3 <= len(data):
        h = data[pos] | (data[pos + 1] << 8) | (data[pos + 2] << 16)
        pos += 3
        ln = h >> 1
        chunk = data[pos:pos + ln]
        pos += ln
        if h & 1:                      # original (uncompressed) chunk
            out.extend(chunk)
        elif kind == 1:                # zlib = raw deflate
            out.extend(zlib.decompress(chunk, -15))
        elif kind == 2:                # snappy raw block
            out.extend(_snappy_block(bytes(chunk)))
        elif kind == 5:                # zstd frame
            out.extend(_zstd_block(bytes(chunk)))
        else:
            raise ValueError(f"unsupported ORC compression kind {kind}")
    return bytes(out)


class _ColStats:
    __slots__ = ("num_values", "has_null", "minimum", "maximum")

    def __init__(self):
        self.num_values: Optional[int] = None
        self.has_null: Optional[bool] = None
        self.minimum = None
        self.maximum = None


def _parse_int_stats(buf: bytes, st: _ColStats):
    for fno, wt, v in _fields(buf):
        if fno == 1:
            st.minimum = _zigzag(v)
        elif fno == 2:
            st.maximum = _zigzag(v)


def _parse_double_stats(buf: bytes, st: _ColStats):
    for fno, wt, v in _fields(buf):
        if fno == 1:
            st.minimum = struct.unpack("<d", struct.pack("<q", v))[0]
        elif fno == 2:
            st.maximum = struct.unpack("<d", struct.pack("<q", v))[0]


def _parse_string_stats(buf: bytes, st: _ColStats):
    for fno, wt, v in _fields(buf):
        if fno == 1:
            st.minimum = v.decode("utf-8", "replace")
        elif fno == 2:
            st.maximum = v.decode("utf-8", "replace")


def _parse_date_stats(buf: bytes, st: _ColStats):
    import numpy as np
    for fno, wt, v in _fields(buf):
        if fno == 1:
            st.minimum = np.datetime64(_zigzag(v), "D")
        elif fno == 2:
            st.maximum = np.datetime64(_zigzag(v), "D")


def _parse_col_stats(buf: bytes) -> _ColStats:
    st = _ColStats()
    for fno, wt, v in _fields(buf):
        if fno == 1:
            st.num_values = v
        elif fno == 2:
            _parse_int_stats(v, st)
        elif fno == 3:
            _parse_double_stats(v, st)
        elif fno == 4:
            _parse_string_stats(v, st)
        elif fno == 7:
            _parse_date_stats(v, st)
        elif fno == 10:
            st.has_null = bool(v)
    return st


class OrcFileMeta:
    """num_rows, stripe row counts, per-stripe column min/max."""

    def __init__(self, field_names: List[str], num_rows: int,
                 stripe_rows: List[int],
                 stripe_stats: Optional[List[Dict[str, Tuple]]]):
        self.field_names = field_names
        self.num_rows = num_rows
        self.stripe_rows = stripe_rows
        #: per stripe: {column name: (min, max)} — None when the file
        #: carries no usable metadata section
        self.stripe_stats = stripe_stats


def read_orc_meta(path: str) -> Optional[OrcFileMeta]:
    try:
        return _read_orc_meta(path)
    except Exception:
        return None                    # unreadable tail -> no pruning


def _read_orc_meta(path: str) -> Optional[OrcFileMeta]:
    size = os.path.getsize(path)
    tail_len = min(size, 16 * 1024)
    with open(path, "rb") as f:
        f.seek(size - tail_len)
        tail = f.read(tail_len)
    ps_len = tail[-1]
    ps = tail[-1 - ps_len:-1]
    footer_len = metadata_len = 0
    compression = 0
    magic_ok = False
    for fno, wt, v in _fields(ps):
        if fno == 1:
            footer_len = v
        elif fno == 2:
            compression = v
        elif fno == 5:
            metadata_len = v
        elif fno == 8000:              # optional string magic = 8000
            magic_ok = (v == b"ORC")
    if not magic_ok:
        return None
    need = 1 + ps_len + footer_len + metadata_len
    if need > tail_len:
        with open(path, "rb") as f:
            f.seek(size - need)
            tail = f.read(need)
        tail_len = need
    footer_raw = tail[tail_len - 1 - ps_len - footer_len:
                      tail_len - 1 - ps_len]
    meta_raw = tail[tail_len - 1 - ps_len - footer_len - metadata_len:
                    tail_len - 1 - ps_len - footer_len]
    footer = _decompress(footer_raw, compression)

    stripe_rows: List[int] = []
    num_rows = 0
    types: List[bytes] = []
    for fno, wt, v in _fields(footer):
        if fno == 3:                   # StripeInformation
            rows = 0
            for f2, _w, v2 in _fields(v):
                if f2 == 5:
                    rows = v2
            stripe_rows.append(rows)
        elif fno == 4:
            types.append(v)
        elif fno == 6:
            num_rows = v
    # flat schemas ONLY: root struct (type 0) lists child names and stats
    # column k maps to field k-1. Nested fields occupy extra column ids
    # and would shift the mapping — detected by the type count and
    # answered with "no pruning" rather than a wrong mapping.
    field_names: List[str] = []
    if types:
        for f2, _w, v2 in _fields(types[0]):
            if f2 == 3:                # fieldNames
                field_names.append(v2.decode("utf-8", "replace"))
    if len(types) != len(field_names) + 1:
        return OrcFileMeta(field_names, num_rows, stripe_rows, None)

    stripe_stats = None
    if metadata_len:
        meta = _decompress(meta_raw, compression)
        stripe_stats = []
        for fno, wt, v in _fields(meta):
            if fno != 1:               # StripeStatistics
                continue
            cols: List[_ColStats] = []
            for f2, _w, v2 in _fields(v):
                if f2 == 1:
                    cols.append(_parse_col_stats(v2))
            named: Dict[str, Tuple] = {}
            for i, name in enumerate(field_names):
                if i + 1 < len(cols):
                    st = cols[i + 1]
                    if st.minimum is not None and st.maximum is not None:
                        named[name] = (st.minimum, st.maximum)
            stripe_stats.append(named)
        if len(stripe_stats) != len(stripe_rows):
            stripe_stats = None        # inconsistent tail: no pruning
    return OrcFileMeta(field_names, num_rows, stripe_rows, stripe_stats)
