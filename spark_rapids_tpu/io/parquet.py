"""Parquet scan (ref GpuParquetScan.scala, 2,899 LoC).

Keeps the reference's three reader strategies — they are host-side
orchestration and port cleanly (SURVEY.md section 7 hard-part #6):
  * PERFILE       (ParquetPartitionReader :2750): one file -> one decode
  * COALESCING    (MultiFileParquetPartitionReader :1867): stitch row groups
    of many small files into one host table, one H2D
  * MULTITHREADED (MultiFileCloudParquetPartitionReader :2063): background
    host reads on a thread pool (ref GpuMultiFileReader.scala:343) feeding
    the device in submission order
Decode itself is pyarrow's C++ parquet reader into Arrow host memory, then
one padded H2D per shape bucket (the cudf-decode analog; a Pallas decode for
fixed-width pages is future work). Row-group pruning via parquet statistics
mirrors the reference's CPU-side filterBlocks (:670).
"""
from __future__ import annotations

import concurrent.futures as cf
import glob
import os
from typing import Iterator, List, Optional, Sequence

from ..columnar import ColumnarBatch
from ..config import (MULTITHREADED_READ_THREADS, PARQUET_READER_TYPE,
                      TpuConf)
from ..exec.base import ESSENTIAL, ExecContext, TpuExec
from ..types import Schema, StructField, from_arrow

__all__ = ["ParquetScanExec", "parquet_schema", "expand_paths"]


def expand_paths(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "**", "*.parquet"),
                                        recursive=True)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no parquet files found in {paths}")
    return out


def parquet_schema(path: str) -> Schema:
    import pyarrow.parquet as pq
    sch = pq.read_schema(path)
    return Schema([StructField(f.name, from_arrow(f.type), f.nullable)
                   for f in sch])


class ParquetScanExec(TpuExec):
    def __init__(self, paths: List[str], schema: Schema,
                 columns: Optional[List[str]], conf: TpuConf,
                 predicate=None):
        super().__init__([])
        self.paths = paths
        self._schema = schema
        self.columns = columns
        self.conf = conf
        self.predicate = predicate  # row-group pruning expression (optional)
        mode = str(conf.get(PARQUET_READER_TYPE)).upper()
        if mode == "AUTO":
            mode = "MULTITHREADED" if len(paths) > 1 else "PERFILE"
        self.mode = mode

    def output_schema(self) -> Schema:
        return self._schema

    # ---------------------------------------------------------- reading
    def _read_table(self, path: str):
        import pyarrow.parquet as pq
        f = pq.ParquetFile(path)
        groups = self._filter_row_groups(f)
        if groups is None:
            t = f.read(columns=self.columns)
        elif not groups:
            t = f.schema_arrow.empty_table()
            if self.columns:
                t = t.select(self.columns)
        else:
            t = f.read_row_groups(groups, columns=self.columns)
        return t

    def _filter_row_groups(self, f) -> Optional[List[int]]:
        """Row-group pruning from parquet min/max statistics
        (ref GpuParquetScan filterBlocks:670)."""
        if self.predicate is None:
            return None
        try:
            keep = []
            for i in range(f.metadata.num_row_groups):
                rg = f.metadata.row_group(i)
                stats = {}
                for j in range(rg.num_columns):
                    c = rg.column(j)
                    if c.statistics is not None and c.statistics.has_min_max:
                        name = c.path_in_schema
                        stats[name] = (c.statistics.min, c.statistics.max)
                if _maybe_matches(self.predicate, stats):
                    keep.append(i)
            return keep
        except Exception:
            return None  # stats unusable -> read everything

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        files_m = ctx.metric(self._exec_id, "numFiles")
        files_m.add(len(self.paths))
        batch_rows = ctx.conf.batch_size_rows

        if self.mode == "COALESCING":
            yield from self._coalescing(ctx, rows_m, batch_rows)
            return
        if self.mode == "MULTITHREADED":
            yield from self._multithreaded(ctx, rows_m, batch_rows)
            return
        # PERFILE
        for pid, path in enumerate(self.paths):
            t = self._read_table(path)
            yield from self._emit(ctx, t, rows_m, batch_rows,
                                  input_file=path, pid=pid)

    def _emit(self, ctx, table, rows_m, batch_rows, input_file=None, pid=0):
        off = 0
        n = table.num_rows
        while off < n or (n == 0 and off == 0):
            chunk = table.slice(off, batch_rows)
            with ctx.semaphore.held():
                b = ColumnarBatch.from_arrow(chunk)
            b.meta = {"partition_id": pid, "input_file": input_file}
            rows_m.add(b.num_rows)
            yield b
            off += batch_rows
            if n == 0:
                break

    def _coalescing(self, ctx, rows_m, batch_rows):
        """Stitch small files' tables into target-size host buffers, then one
        H2D per coalesced table (ref MultiFileParquetPartitionReader)."""
        import pyarrow as pa
        pending, rows = [], 0
        for path in self.paths:
            t = self._read_table(path)
            pending.append(t)
            rows += t.num_rows
            if rows >= batch_rows:
                yield from self._emit(ctx, pa.concat_tables(pending),
                                      rows_m, batch_rows)
                pending, rows = [], 0
        if pending:
            yield from self._emit(ctx, pa.concat_tables(pending),
                                  rows_m, batch_rows)

    def _multithreaded(self, ctx, rows_m, batch_rows):
        """Background host reads feeding the device in order
        (ref MultiFileCloudParquetPartitionReader + thread pool
        Plugin.scala:269-281)."""
        nthreads = int(self.conf.get(MULTITHREADED_READ_THREADS))
        with cf.ThreadPoolExecutor(max_workers=nthreads) as pool:
            futures = [pool.submit(self._read_table, p) for p in self.paths]
            for pid, fut in enumerate(futures):  # preserve file order; reads overlap
                yield from self._emit(ctx, fut.result(), rows_m, batch_rows,
                                      input_file=self.paths[pid], pid=pid)

    def describe(self):
        return (f"ParquetScan[{len(self.paths)} files, {self.mode}"
                + (f", pushdown={self.predicate.name_hint}" if self.predicate
                   else "") + "]")


def _maybe_matches(pred, stats) -> bool:
    """Conservative interval check: False only if the predicate provably
    excludes the row group. Understands And/Or and binary comparisons on
    plain column refs."""
    from ..exprs import (And, ColumnRef, EqualTo, GreaterThan,
                         GreaterThanOrEqual, LessThan, LessThanOrEqual,
                         Literal, Or)
    if isinstance(pred, And):
        return all(_maybe_matches(c, stats) for c in pred.children)
    if isinstance(pred, Or):
        return any(_maybe_matches(c, stats) for c in pred.children)
    if isinstance(pred, (EqualTo, GreaterThan, GreaterThanOrEqual, LessThan,
                         LessThanOrEqual)):
        l, r = pred.children
        if isinstance(l, Literal) and isinstance(r, ColumnRef):
            flip = {GreaterThan: LessThan, LessThan: GreaterThan,
                    GreaterThanOrEqual: LessThanOrEqual,
                    LessThanOrEqual: GreaterThanOrEqual, EqualTo: EqualTo}
            return _maybe_matches(flip[type(pred)](r, l), stats)
        if not (isinstance(l, ColumnRef) and isinstance(r, Literal)):
            return True
        if l.name not in stats or r.value is None:
            return True
        mn, mx = stats[l.name]
        v = r.value
        try:
            if isinstance(pred, EqualTo):
                return mn <= v <= mx
            if isinstance(pred, GreaterThan):
                return mx > v
            if isinstance(pred, GreaterThanOrEqual):
                return mx >= v
            if isinstance(pred, LessThan):
                return mn < v
            if isinstance(pred, LessThanOrEqual):
                return mn <= v
        except TypeError:
            return True
    return True
