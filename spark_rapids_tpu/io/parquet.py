"""Parquet scan (ref GpuParquetScan.scala, 2,899 LoC).

Keeps the reference's three reader strategies — they are host-side
orchestration and port cleanly (SURVEY.md section 7 hard-part #6):
  * PERFILE       (ParquetPartitionReader :2750): one file -> one decode
  * COALESCING    (MultiFileParquetPartitionReader :1867): stitch row groups
    of many small files into one host table, one H2D
  * MULTITHREADED (MultiFileCloudParquetPartitionReader :2063): background
    host reads on a thread pool (ref GpuMultiFileReader.scala:343) feeding
    the device in submission order
Decode itself is pyarrow's C++ parquet reader into Arrow host memory, then
one padded H2D per shape bucket (the cudf-decode analog; a Pallas decode for
fixed-width pages is future work). Row-group pruning via parquet statistics
mirrors the reference's CPU-side filterBlocks (:670).
"""
from __future__ import annotations

import glob
import os
from typing import List, Optional, Sequence

import numpy as np

from ..config import MULTITHREADED_READ_THREADS, PARQUET_READER_TYPE
from ..types import Schema, StructField, from_arrow
from .file_scan import FileScanBase

__all__ = ["ParquetScanExec", "parquet_schema", "expand_paths"]


def expand_paths(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "**", "*.parquet"),
                                        recursive=True)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no parquet files found in {paths}")
    return out


def _greedy_pack(units, n_shards: int):
    """Greedy longest-first bin packing: ``units`` are ``(rows, *key)``
    tuples; returns ``n_shards`` lists of keys balanced by row count."""
    bins = [[] for _ in range(n_shards)]
    fill = [0] * n_shards
    for rows, *key in sorted(units, reverse=True):
        i = fill.index(min(fill))
        bins[i].append(tuple(key) if len(key) > 1 else key[0])
        fill[i] += rows
    return bins


def parquet_schema(path: str) -> Schema:
    import pyarrow.parquet as pq
    sch = pq.read_schema(path)
    return Schema([StructField(f.name, from_arrow(f.type), f.nullable)
                   for f in sch])


class ParquetScanExec(FileScanBase):
    FORMAT = "parquet"
    READER_TYPE_KEY = PARQUET_READER_TYPE

    # ---------------------------------------------------------- reading
    def _read_table(self, path: str):
        import pyarrow.parquet as pq
        f = pq.ParquetFile(self._cached_path(path))
        groups = self._filter_row_groups(f)
        if groups is None:
            t = f.read(columns=self.columns)
        elif not groups:
            t = f.schema_arrow.empty_table()
            if self.columns:
                t = t.select(self.columns)
        else:
            t = f.read_row_groups(groups, columns=self.columns)
        if self.columns:
            t = t.select(self.columns)  # requested order, not file order
        return t

    def collect_row_group_shards(self, n_shards: int):
        """Row-group-partitioned read for the distributed planner (ref
        GpuMultiFileReader.scala:295 / GpuParquetScan row-group task
        assignment): (path, row-group) units greedy-pack into ``n_shards``
        bins by row count, each bin read independently — on a multi-host
        deployment each host reads only its bin. Returns a list of
        ``n_shards`` Arrow tables (possibly empty) or None when the
        format prevents per-group assignment."""
        if not self.paths:
            # zero-file scan (e.g. a fully-vacuumed snapshot): no schema
            # to build empty shard tables from — take the non-sharded
            # path, which knows how to emit a typed empty batch
            return None
        import pyarrow.parquet as pq
        try:
            units = []            # (rows, path, group_idx)
            files = {}
            for path in self.paths:
                f = pq.ParquetFile(self._cached_path(path))
                files[path] = f
                groups = self._filter_row_groups(f)
                if groups is None:
                    groups = range(f.metadata.num_row_groups)
                for g in groups:
                    units.append((f.metadata.row_group(g).num_rows,
                                  path, g))
        except Exception:
            return None
        bins = _greedy_pack(units, n_shards)
        empty = files[self.paths[0]].schema_arrow.empty_table()

        def read_bin(b):
            import pyarrow as pa
            if not b:
                return empty
            by_path: dict = {}
            for path, g in b:
                by_path.setdefault(path, []).append(g)
            # one file's groups land in several bins: each thread opens
            # its OWN ParquetFile (parquet readers are not thread-safe
            # for concurrent reads on a shared instance)
            parts = [pq.ParquetFile(self._cached_path(path))
                     .read_row_groups(sorted(gs), columns=self.columns)
                     for path, gs in by_path.items()]
            return pa.concat_tables(parts) if len(parts) > 1 else parts[0]

        # overlap bin reads the way the MULTITHREADED reader overlaps
        # per-file reads (file_scan.py _multithreaded)
        import concurrent.futures as cf
        nthreads = int(self.conf.get(MULTITHREADED_READ_THREADS))
        with cf.ThreadPoolExecutor(max_workers=max(nthreads, 1)) as pool:
            out = list(pool.map(read_bin, bins))
        if self.columns:
            out = [t.select(self.columns) if t is not None else t
                   for t in out]
        return out

    # ------------------------------------------- experimental device decode
    def do_execute(self, ctx):
        from .device_decode import DEVICE_DECODE_ENABLED
        if (bool(ctx.conf.get(DEVICE_DECODE_ENABLED))
                and self.mode == "PERFILE" and self.predicate is None):
            yield from self._device_decode_execute(ctx)
            return
        yield from super().do_execute(ctx)

    #: engine types whose device-decode bitcast is exactly the pyarrow
    #: result (timestamps/dates excluded: unit normalization diverges)
    _DD_TYPES = frozenset(["int", "bigint", "float", "double"])

    def _device_decode_execute(self, ctx):
        """EXPERIMENTAL raw-byte ingest (io/device_decode.py; ref
        GpuParquetScan device decode): eligible files skip the pyarrow
        column decode entirely — the host parses page headers, the
        value bytes land on the device raw. Ineligible files take the
        standard path unchanged."""
        from ..columnar import ColumnarBatch, DeviceColumn
        from ..columnar.bucketing import padded_len as _bucket
        from ..exec.base import ESSENTIAL
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        ctx.metric(self._exec_id, "numFiles").add(len(self.paths))
        dd_m = ctx.metric(self._exec_id, "deviceDecodedFiles")
        batch_rows = ctx.conf.batch_size_rows
        for pid, path in enumerate(self.paths):
            cols = self._try_device_decode(path)
            if cols is None:
                t = self._read_table(path)
                yield from self._emit(ctx, t, rows_m, batch_rows,
                                      input_file=path, pid=pid)
                continue
            dd_m.add(1)
            n = len(cols[0][1]) if cols else 0
            off = 0
            while off < n or (n == 0 and off == 0):
                cnt = min(batch_rows, n - off)
                pl = _bucket(cnt)
                with ctx.semaphore.held():
                    dcs = [DeviceColumn.from_numpy(
                               v[off:off + cnt], dt, padded_len=pl)
                           for _, v, dt in cols]
                b = ColumnarBatch(dcs, cnt, self._schema)
                b.meta = {"partition_id": pid, "input_file": path,
                          "row_offset": off}
                rows_m.add(cnt)
                yield b
                off += batch_rows
                if n == 0:
                    break

    def _try_device_decode(self, path):
        """[(name, raw little-endian values, engine dtype)] when EVERY
        requested column of the file qualifies, else None."""
        import pyarrow.parquet as pq
        from .device_decode import chunk_eligible, decode_chunk_values
        names = self.columns or self._schema.names()
        try:
            resolved = self._cached_path(path)
            f = pq.ParquetFile(resolved)
            md = f.metadata
            if md.num_row_groups == 0:
                return None
            rg0 = md.row_group(0)
            idx = {rg0.column(j).path_in_schema: j
                   for j in range(rg0.num_columns)}
            plan = []
            for name in names:
                dt = self._schema[name].dtype
                if dt.name not in self._DD_TYPES or name not in idx:
                    return None
                nullable = f.schema_arrow.field(name).nullable
                chunks = []
                for g in range(md.num_row_groups):
                    cm = md.row_group(g).column(idx[name])
                    np_dt = chunk_eligible(cm)
                    if np_dt is None or np_dt != dt.np_dtype.newbyteorder("<"):
                        return None
                    chunks.append((cm.data_page_offset,
                                   cm.total_compressed_size,
                                   cm.num_values))
                plan.append((name, dt, nullable, chunks))
            out = []
            with open(resolved, "rb") as fh:
                for name, dt, nullable, chunks in plan:
                    parts = []
                    for offset, size, nvals in chunks:
                        fh.seek(offset)
                        vals = decode_chunk_values(
                            fh.read(size), nvals, dt.np_dtype,
                            1 if nullable else 0)
                        if vals is None:
                            return None
                        parts.append(vals)
                    out.append((name, np.concatenate(parts)
                                if len(parts) > 1 else parts[0], dt))
            return out
        except Exception:
            return None     # anything surprising: standard path

    def _filter_row_groups(self, f) -> Optional[List[int]]:
        """Row-group pruning from parquet min/max statistics
        (ref GpuParquetScan filterBlocks:670)."""
        if self.predicate is None:
            return None
        try:
            keep = []
            for i in range(f.metadata.num_row_groups):
                rg = f.metadata.row_group(i)
                stats = {}
                for j in range(rg.num_columns):
                    c = rg.column(j)
                    if c.statistics is not None and c.statistics.has_min_max:
                        name = c.path_in_schema
                        stats[name] = (c.statistics.min, c.statistics.max)
                if _maybe_matches(self.predicate, stats):
                    keep.append(i)
            return keep
        except Exception:
            return None  # stats unusable -> read everything


def _maybe_matches(pred, stats) -> bool:
    """Conservative interval check: False only if the predicate provably
    excludes the row group. Understands And/Or and binary comparisons on
    plain column refs."""
    from ..exprs import (And, ColumnRef, EqualTo, GreaterThan,
                         GreaterThanOrEqual, LessThan, LessThanOrEqual,
                         Literal, Or)
    if isinstance(pred, And):
        return all(_maybe_matches(c, stats) for c in pred.children)
    if isinstance(pred, Or):
        return any(_maybe_matches(c, stats) for c in pred.children)
    if isinstance(pred, (EqualTo, GreaterThan, GreaterThanOrEqual, LessThan,
                         LessThanOrEqual)):
        l, r = pred.children
        if isinstance(l, Literal) and isinstance(r, ColumnRef):
            flip = {GreaterThan: LessThan, LessThan: GreaterThan,
                    GreaterThanOrEqual: LessThanOrEqual,
                    LessThanOrEqual: GreaterThanOrEqual, EqualTo: EqualTo}
            return _maybe_matches(flip[type(pred)](r, l), stats)
        if not (isinstance(l, ColumnRef) and isinstance(r, Literal)):
            return True
        if l.name not in stats or r.value is None:
            return True
        mn, mx = stats[l.name]
        v = r.value
        try:
            if isinstance(pred, EqualTo):
                return mn <= v <= mx
            if isinstance(pred, GreaterThan):
                return mx > v
            if isinstance(pred, GreaterThanOrEqual):
                return mx >= v
            if isinstance(pred, LessThan):
                return mn < v
            if isinstance(pred, LessThanOrEqual):
                return mn <= v
        except TypeError:
            return True
    return True
