"""CSV / JSON sources (ref GpuTextBasedPartitionReader: CPU line split ->
device parse; here pyarrow's multithreaded C++ CSV/JSON readers produce the
host table, then the standard padded H2D)."""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..types import Schema, StructField, from_arrow, to_arrow

__all__ = ["csv_to_tables", "json_to_tables"]


def _schema_to_arrow(schema) -> "object":
    import pyarrow as pa
    return pa.schema([pa.field(f.name, to_arrow(f.dtype), f.nullable)
                      for f in schema])


def csv_to_tables(paths: Sequence[str], schema: Optional[Schema],
                  header: bool) -> Tuple[List, Schema]:
    import pyarrow.csv as pcsv
    tables = []
    for p in paths:
        read_opts = pcsv.ReadOptions(autogenerate_column_names=not header)
        convert = pcsv.ConvertOptions(
            column_types=dict(zip(schema.names(),
                                  [to_arrow(t) for t in schema.types()]))
            if schema else None)
        tables.append(pcsv.read_csv(p, read_options=read_opts,
                                    convert_options=convert))
    sch = schema or Schema([StructField(f.name, from_arrow(f.type), True)
                            for f in tables[0].schema])
    return tables, sch


def json_to_tables(paths: Sequence[str],
                   schema: Optional[Schema]) -> Tuple[List, Schema]:
    import pyarrow.json as pjson
    tables = []
    for p in paths:
        opts = pjson.ParseOptions(
            explicit_schema=_schema_to_arrow(schema) if schema else None)
        tables.append(pjson.read_json(p, parse_options=opts))
    sch = schema or Schema([StructField(f.name, from_arrow(f.type), True)
                            for f in tables[0].schema])
    return tables, sch
