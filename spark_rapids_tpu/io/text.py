"""CSV / JSON sources (ref GpuTextBasedPartitionReader: CPU line split ->
device parse; here pyarrow's multithreaded C++ CSV/JSON readers produce the
host table, then the standard padded H2D)."""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..types import Schema, StructField, from_arrow, to_arrow

__all__ = ["csv_to_tables", "json_to_tables", "hive_text_to_tables",
           "write_hive_text"]

#: Hive LazySimpleSerDe defaults: ^A field delimiter, \N for NULL
HIVE_FIELD_DELIM = "\x01"
HIVE_NULL = "\\N"


def _schema_to_arrow(schema) -> "object":
    import pyarrow as pa
    return pa.schema([pa.field(f.name, to_arrow(f.dtype), f.nullable)
                      for f in schema])


def csv_to_tables(paths: Sequence[str], schema: Optional[Schema],
                  header: bool) -> Tuple[List, Schema]:
    import pyarrow.csv as pcsv
    tables = []
    for p in paths:
        read_opts = pcsv.ReadOptions(autogenerate_column_names=not header)
        convert = pcsv.ConvertOptions(
            column_types=dict(zip(schema.names(),
                                  [to_arrow(t) for t in schema.types()]))
            if schema else None)
        tables.append(pcsv.read_csv(p, read_options=read_opts,
                                    convert_options=convert))
    sch = schema or Schema([StructField(f.name, from_arrow(f.type), True)
                            for f in tables[0].schema])
    return tables, sch


def hive_text_to_tables(paths: Sequence[str], schema: Schema,
                        field_delim: str = HIVE_FIELD_DELIM,
                        null_value: str = HIVE_NULL) -> Tuple[List, Schema]:
    """Hive text tables (LazySimpleSerDe: ^A-delimited fields, \\N nulls,
    backslash escaping, no header — ref GpuHiveFileFormat /
    GpuHiveTextFileFormat and the hive text path of
    GpuTextBasedPartitionReader). A schema is required: hive text carries
    no self-description. The parser is escape-aware (a backslash escapes
    the delimiter, newline as ``\\n``/``\\r``, the backslash itself, and
    distinguishes a literal backslash-N from the NULL marker), which
    pyarrow's CSV reader cannot express — correctness over raw speed."""
    import pyarrow as pa
    if schema is None:
        raise ValueError("hive text requires an explicit schema")
    names = schema.names()
    atypes = [to_arrow(t) for t in schema.types()]
    tables = []
    for p in paths:
        with open(p, encoding="utf-8", newline="") as f:
            text = f.read()
        rows = _hive_parse(text, field_delim, null_value)
        cols = []
        for i, (nm, at) in enumerate(zip(names, atypes)):
            raw = [r[i] if i < len(r) else None for r in rows]
            cols.append(_hive_convert(raw, at))
        tables.append(pa.Table.from_arrays(cols, names=names))
    return tables, schema


def _hive_parse(text: str, delim: str, null_value: str):
    """Escape-aware split into rows of (str | None) cells. ``\\N`` filling
    an entire cell is the NULL marker; a literal backslash-N is written
    (and read back) as ``\\\\N``."""
    rows, row, cell = [], [], []
    is_null = escaped = False
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if ch == "\\" and i + 1 < n:
            nxt = text[i + 1]
            if (null_value == "\\N" and nxt == "N" and not cell
                    and (i + 2 >= n or text[i + 2] in (delim, "\n"))):
                is_null = True
            else:
                cell.append({"n": "\n", "r": "\r", "t": "\t"}.get(nxt, nxt))
                escaped = True
            i += 2
            continue
        if ch == delim:
            row.append(_hive_finish(cell, is_null, null_value, escaped))
            cell, is_null, escaped = [], False, False
            i += 1
            continue
        if ch == "\n":
            row.append(_hive_finish(cell, is_null, null_value, escaped))
            rows.append(row)
            row, cell, is_null, escaped = [], [], False, False
            i += 1
            continue
        cell.append(ch)
        i += 1
    if cell or row or is_null:
        row.append(_hive_finish(cell, is_null, null_value, escaped))
        rows.append(row)
    return rows


def _hive_finish(cell, is_null: bool, null_value: str, escaped: bool):
    if is_null:
        return None
    s = "".join(cell)
    # custom (non-backslash) null markers compare against the raw cell;
    # a cell containing ANY escape is a literal value, never the marker
    # (the writer escapes marker-colliding values — see write_hive_text)
    if null_value != "\\N" and not escaped and s == null_value:
        return None
    return s


def _num(v, conv):
    """Hive LazySimpleSerDe: a malformed numeric cell reads as NULL."""
    if v in (None, ""):
        return None
    try:
        return conv(v)
    except ValueError:
        return None


def _hive_convert(raw, at):
    import pyarrow as pa
    if pa.types.is_string(at):
        return pa.array(raw, type=at)
    if pa.types.is_boolean(at):
        return pa.array([None if v is None else v.lower() == "true"
                         for v in raw], type=at)
    if pa.types.is_integer(at):
        return pa.array([_num(v, int) for v in raw], type=at)
    if pa.types.is_floating(at):
        return pa.array([_num(v, float) for v in raw], type=at)
    return pa.array(raw).cast(at)


def write_hive_text(table, path: str, field_delim: str = HIVE_FIELD_DELIM,
                    null_value: str = HIVE_NULL) -> None:
    """Arrow table -> one Hive text file; backslash-escapes the delimiter,
    newlines, tabs, and backslashes inside values (LazySimpleSerDe
    escaping) so every value round-trips."""
    _check_hive_options(field_delim, null_value)
    cols = [table.column(i).to_pylist() for i in range(table.num_columns)]
    with open(path, "w", encoding="utf-8", newline="") as f:
        for row in zip(*cols) if cols else []:
            f.write(field_delim.join(
                null_value if v is None
                else _hive_cell(v, field_delim, null_value)
                for v in row) + "\n")


def _hive_cell(v, delim: str, null_value: str = HIVE_NULL) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    # single pass: with a control-char delimiter (e.g. tabs) chained
    # replaces would re-escape the backslash-delim pair into garbage
    out = []
    for ch in str(v):
        if ch == "\\":
            out.append("\\\\")
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\r":
            out.append("\\r")
        elif ch == "\t":
            out.append("\\t")
        elif ch == delim:
            out.append("\\" + ch)
        else:
            out.append(ch)
    s = "".join(out)
    if null_value != HIVE_NULL and s == null_value:
        # a literal value colliding with a custom NULL marker: escape the
        # first safely-escapable char so the reader sees a literal cell
        # (backslash before n/r/t would decode to a control char instead;
        # _check_hive_options guarantees such a char exists)
        for i, ch in enumerate(s):
            if ch not in "nrt":
                return s[:i] + "\\" + s[i:]
    return s


def _check_hive_options(field_delim: str, null_value: str) -> None:
    """Reject delimiter/marker choices the escape grammar cannot
    round-trip (silent-corruption holes otherwise)."""
    if len(field_delim) != 1:
        raise ValueError("hive text field_delim must be one character")
    if field_delim in "nrt\\":
        raise ValueError(
            f"hive text field_delim {field_delim!r} collides with the "
            "backslash escape alphabet (\\n/\\r/\\t) and cannot round-trip")
    if null_value != HIVE_NULL:
        if any(c in null_value for c in (field_delim, "\\", "\n", "\r")):
            raise ValueError(
                f"hive text null_value {null_value!r} contains the field "
                "delimiter, a backslash, or a newline and cannot round-trip")
        if not null_value:
            raise ValueError(
                "hive text null_value must be non-empty: an empty marker "
                "makes empty-string cells indistinguishable from NULL")
        if all(c in "nrt" for c in null_value):
            raise ValueError(
                f"hive text null_value {null_value!r} uses only n/r/t "
                "characters; colliding values could not be escaped")


def json_to_tables(paths: Sequence[str],
                   schema: Optional[Schema]) -> Tuple[List, Schema]:
    import pyarrow.json as pjson
    tables = []
    for p in paths:
        opts = pjson.ParseOptions(
            explicit_schema=_schema_to_arrow(schema) if schema else None)
        tables.append(pjson.read_json(p, parse_options=opts))
    sch = schema or Schema([StructField(f.name, from_arrow(f.type), True)
                            for f in tables[0].schema])
    return tables, sch
