"""File writers (ref ColumnarOutputWriter, GpuParquetFileFormat,
GpuFileFormatDataWriter.scala — single + dynamic-partition writers)."""
from __future__ import annotations

import os
import shutil
import uuid
from typing import Iterator, List, Sequence

from ..columnar import ColumnarBatch
from ..exec.base import ExecContext, TpuExec
from ..types import INT64, Schema, StructField

__all__ = ["FileWriteExec", "write_parquet_tables"]


class FileWriteExec(TpuExec):
    """D2H + chunked file write; returns a one-row stats batch
    (rows written) like the reference's BasicColumnarWriteStatsTracker."""

    def __init__(self, child: TpuExec, path: str, file_format: str,
                 mode: str = "overwrite", partition_by: Sequence[str] = (),
                 options=None):
        super().__init__([child])
        self.path = path
        self.file_format = file_format
        self.mode = mode
        self.partition_by = list(partition_by)
        self.options = dict(options or {})

    def output_schema(self) -> Schema:
        return Schema([StructField("rows_written", INT64, False),
                       StructField("files_written", INT64, False)])

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pyarrow as pa
        if self.mode == "overwrite" and os.path.exists(self.path):
            shutil.rmtree(self.path)
        os.makedirs(self.path, exist_ok=True)
        rows = 0
        files = 0
        if self.partition_by:
            rows, files = self._write_partitioned(ctx)
        else:
            for i, batch in enumerate(self.children[0].execute(ctx)):
                t = batch.to_arrow()
                self._write_one(t, os.path.join(
                    self.path, f"part-{i:05d}-{uuid.uuid4().hex[:8]}"))
                rows += t.num_rows
                files += 1
        yield ColumnarBatch.from_arrow(
            pa.table({"rows_written": pa.array([rows], pa.int64()),
                      "files_written": pa.array([files], pa.int64())}))

    def _write_partitioned(self, ctx):
        """Dynamic-partition write (ref GpuDynamicPartitionDataConcurrentWriter)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        rows = 0
        files = 0
        for i, batch in enumerate(self.children[0].execute(ctx)):
            t = batch.to_arrow()
            keys = [t.column(k) for k in self.partition_by]
            combos = pa.Table.from_arrays(keys, self.partition_by) \
                .group_by(self.partition_by).aggregate([])
            for row in combos.to_pylist():
                mask = None
                for k, v in row.items():
                    cond = pc.is_null(t.column(k)) if v is None else \
                        pc.equal(t.column(k), pa.scalar(v))
                    mask = cond if mask is None else pc.and_(mask, cond)
                sub = t.filter(mask).drop_columns(self.partition_by)
                part_dir = os.path.join(
                    self.path,
                    *[f"{k}={'__HIVE_DEFAULT_PARTITION__' if v is None else v}"
                      for k, v in row.items()])
                os.makedirs(part_dir, exist_ok=True)
                self._write_one(sub, os.path.join(
                    part_dir, f"part-{i:05d}-{uuid.uuid4().hex[:8]}"))
                rows += sub.num_rows
                files += 1
        return rows, files

    def _write_one(self, table, base: str):
        if self.file_format == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(table, base + ".parquet")
        elif self.file_format == "csv":
            import pyarrow.csv as pcsv
            pcsv.write_csv(table, base + ".csv")
        elif self.file_format == "orc":
            import pyarrow.orc as porc
            porc.write_table(table, base + ".orc")
        elif self.file_format == "hive_text":
            from .text import HIVE_FIELD_DELIM, HIVE_NULL, write_hive_text
            write_hive_text(
                table, base + ".txt",
                field_delim=self.options.get("field_delim",
                                             HIVE_FIELD_DELIM),
                null_value=self.options.get("null_value", HIVE_NULL))
        else:
            raise ValueError(f"unsupported format {self.file_format}")

    def describe(self):
        return f"WriteFile[{self.file_format} -> {self.path}]"


def write_parquet_tables(tables, path: str):
    import pyarrow.parquet as pq
    os.makedirs(path, exist_ok=True)
    for i, t in enumerate(tables):
        pq.write_table(t, os.path.join(path, f"part-{i:05d}.parquet"))
