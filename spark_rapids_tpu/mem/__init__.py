from .manager import (MemoryManager, OutOfDeviceMemory, RetryOOM,
                      SplitAndRetryOOM)
from .retry import (CheckpointRestore, RetryStats, split_batch_in_half,
                    with_retry, with_retry_no_split, wrap_spillable_sides,
                    wrap_spillables)
from .semaphore import DeviceSemaphore, QueryTimeout
from .spillable import SpillableBatch, SpillPriorities

__all__ = ["MemoryManager", "OutOfDeviceMemory", "RetryOOM",
           "SplitAndRetryOOM", "RetryStats", "split_batch_in_half",
           "with_retry", "with_retry_no_split", "wrap_spillables",
           "wrap_spillable_sides",
           "CheckpointRestore", "DeviceSemaphore", "QueryTimeout",
           "SpillableBatch", "SpillPriorities"]
