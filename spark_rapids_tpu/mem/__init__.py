from .manager import (MemoryManager, OutOfDeviceMemory, RetryOOM,
                      SplitAndRetryOOM)
from .retry import (RetryStats, split_batch_in_half, with_retry,
                    with_retry_no_split)
from .semaphore import DeviceSemaphore
from .spillable import SpillableBatch, SpillPriorities

__all__ = ["MemoryManager", "OutOfDeviceMemory", "RetryOOM",
           "SplitAndRetryOOM", "RetryStats", "split_batch_in_half",
           "with_retry", "with_retry_no_split", "DeviceSemaphore",
           "SpillableBatch", "SpillPriorities"]
