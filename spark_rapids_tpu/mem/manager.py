"""HBM budget manager + spill orchestration.

Reference analog: RMM pool + RapidsBufferCatalog + DeviceMemoryEventHandler
(RapidsBufferCatalog.scala:810-851, DeviceMemoryEventHandler.scala:36). On
TPU, XLA owns physical HBM, so the framework performs *logical* accounting:
every long-lived device buffer the runtime retains (shuffle partitions, agg
partials, cached builds, spillable batches) is registered here; ``reserve``
enforces the budget and, on pressure, synchronously spills registered buffers
(device -> host -> disk) in spill-priority order, exactly the role of the
reference's onAllocFailure callback. When spilling cannot satisfy a request,
a RetryOOM/SplitAndRetryOOM is raised for the retry framework (retry.py).

Fault injection (force_retry_oom / force_split_and_retry_oom) mirrors
RmmSpark.forceRetryOOM — the backbone of the reference's OOM test suites
(HashAggregateRetrySuite.scala:121-222).
"""
from __future__ import annotations

import logging
import sys
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

from ..config import (ALLOC_FRACTION, HBM_LIMIT_BYTES, HOST_SPILL_LIMIT,
                      SPILL_DIR, TpuConf)
from ..trace import core as trace_core

__all__ = ["MemoryManager", "RetryOOM", "SplitAndRetryOOM", "OutOfDeviceMemory"]


log = logging.getLogger(__name__)

class RetryOOM(RuntimeError):
    """Allocation failed but retrying after spill may succeed
    (ref GpuRetryOOM jni)."""


class SplitAndRetryOOM(RuntimeError):
    """Retry alone cannot succeed; caller must split its input
    (ref GpuSplitAndRetryOOM jni)."""


class OutOfDeviceMemory(RuntimeError):
    """Unrecoverable: nothing left to spill and input cannot be split."""


def _device_hbm_bytes() -> int:
    import jax
    try:
        # honour an explicitly pinned default device (tests pin 'cpu') and
        # NEVER initialize other backends just for bookkeeping — touching the
        # TPU client here would block if another process holds the chip
        dd = jax.config.jax_default_device
        if dd is not None:
            d = jax.devices(dd)[0] if isinstance(dd, str) else dd
        else:
            d = jax.local_devices()[0]
        stats = d.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return 8 * 1024 * 1024 * 1024  # assume 8 GiB if the backend won't say


class MemoryManager:
    _global_lock = threading.Lock()
    # tpulint: guarded-by _global_lock
    _instances: Dict[int, "MemoryManager"] = {}

    def __init__(self, budget_bytes: int, host_limit_bytes: int,
                 spill_dir: str, use_native: bool = False):
        self.budget = budget_bytes
        self.host_limit = host_limit_bytes
        self.spill_dir = spill_dir
        self._lock = threading.RLock()
        # native accounting + fault machine (mem/native.py -> oom_state.cpp);
        # process-global, so only opted into (the singleton path uses it)
        self._native = None
        if use_native:
            from .native import NativeOomState, load
            if load() is not None:
                self._native = NativeOomState(budget_bytes)
        self._py_device_used = 0     # tpulint: guarded-by _lock
        self.host_used = 0           # tpulint: guarded-by _lock
        self.disk_used = 0           # tpulint: guarded-by _lock
        self._py_max_device_used = 0  # tpulint: guarded-by _lock
        self.spill_to_host_bytes = 0  # tpulint: guarded-by _lock
        self.spill_to_disk_bytes = 0  # tpulint: guarded-by _lock
        # spillables: handle -> SpillableBatch, priority-ordered on demand
        self._spillables: Dict[int, "object"] = {}  # tpulint: guarded-by _lock
        self._next_handle = 0        # tpulint: guarded-by _lock
        # fault injection: thread-ident -> [(kind, remaining_skips, count)]
        self._inject: Dict[int, List] = {}  # tpulint: guarded-by _lock
        #: bytes admitted by the OOM_PRESSURE_HOST degradation rung —
        #: host-backed emergency grants OUTSIDE the device budget
        #: (mem/retry.py ladder; SpillableBatch accounts here while a
        #: pressure grant is active on its creating thread)
        self.pressure_granted = 0    # tpulint: guarded-by _lock
        #: monotonic instant the pressure pool was last seen nonzero —
        #: the /healthz memory verdict clears once the pool has been
        #: empty past a short horizon instead of flapping per grant
        #: (ISSUE 18 satellite); None = never granted
        self._grant_last_nonzero: Optional[float] = None  # tpulint: guarded-by _lock
        #: per-thread pressure-grant depth (threading.local: no lock —
        #: each thread reads/writes only its own slot)
        self._grant = threading.local()
        #: tenant the calling thread's reserves run as (threading.local:
        #: _execute_wrapped sets it per query from
        #: spark.rapids.tpu.tenant.*)
        self._tenant = threading.local()
        #: handle -> owning tenant for registered spillables: tenant
        #: usage is a CENSUS over live registrations, so a spilled or
        #: closed buffer leaves its tenant's account by construction —
        #: cross-tenant leakage is structurally impossible
        self._spillable_tenant: Dict[int, str] = {}  # tpulint: guarded-by _lock
        #: last-declared quota per tenant (bytes; telemetry only — the
        #: enforcing quota is the calling thread's own)
        self._tenant_quota: Dict[str, int] = {}  # tpulint: guarded-by _lock
        #: alloc/free logging (ref spark.rapids.memory.gpu.debug=STDOUT,
        #: RapidsConf.scala:376)
        self.debug_log = False

    # ------------------------------------------------------------------ ctor
    @classmethod
    def get(cls, conf: Optional[TpuConf] = None) -> "MemoryManager":
        conf = conf or TpuConf()
        limit = conf.get(HBM_LIMIT_BYTES)
        if not limit:
            limit = int(_device_hbm_bytes() * conf.get(ALLOC_FRACTION))
        key = limit
        with cls._global_lock:
            if key not in cls._instances:
                # first (largest-budget) singleton owns the native machine
                cls._instances[key] = cls(limit, conf.get(HOST_SPILL_LIMIT),
                                          conf.get(SPILL_DIR),
                                          use_native=not cls._instances)
            inst = cls._instances[key]
            from ..config import MEMORY_DEBUG
            inst.debug_log = bool(conf.get(MEMORY_DEBUG))
            return inst

    # ------------------------------------------------------------ accounting
    @property
    def device_used(self) -> int:
        if self._native is not None:
            return self._native.used
        # tpulint: disable=lock-discipline — lock-free by design: a
        # single int read for logging/telemetry; stats() takes the lock
        return self._py_device_used

    @property
    def max_device_used(self) -> int:
        if self._native is not None:
            return self._native.max_used
        # tpulint: disable=lock-discipline — lock-free by design: a
        # single int read for logging/telemetry; stats() takes the lock
        return self._py_max_device_used

    # ----------------------------------------------------------- registration
    def register_spillable(self, spillable) -> int:
        tenant = getattr(self._tenant, "name", None)
        with self._lock:
            h = self._next_handle
            self._next_handle += 1
            self._spillables[h] = spillable
            if tenant:
                # stamp the owner at registration: quota enforcement and
                # per-tenant telemetry census over this map
                self._spillable_tenant[h] = tenant
            return h

    def unregister_spillable(self, handle: int):
        with self._lock:
            self._spillables.pop(handle, None)
            self._spillable_tenant.pop(handle, None)

    # ------------------------------------------------------------- tenants
    def set_thread_tenant(self, tenant: Optional[str],
                          quota_bytes: int = 0) -> None:
        """Attribute the calling thread's retained buffers to ``tenant``
        (None clears). With ``quota_bytes > 0``, :meth:`reserve`
        enforces the per-tenant HBM share: a breach first spills the
        tenant's OWN spillables, then raises into the tenant's own
        rung-1/2 retry ladder — never a rung-3 cross-session spill on
        other tenants (ISSUE 18)."""
        self._tenant.name = tenant or None
        self._tenant.quota = max(0, int(quota_bytes))
        if tenant and quota_bytes > 0:
            with self._lock:
                self._tenant_quota[tenant] = int(quota_bytes)

    def thread_tenant(self) -> Optional[str]:
        return getattr(self._tenant, "name", None)

    def tenant_device_used(self, tenant: str) -> int:
        """Device-resident bytes retained by ``tenant``'s live
        spillables (the quota census)."""
        with self._lock:
            return self._tenant_used_locked(tenant)

    def _tenant_used_locked(self, tenant: str) -> int:
        return sum(s.device_bytes()
                   for h, s in self._spillables.items()
                   if s.tier == "device"
                   and self._spillable_tenant.get(h) == tenant)

    def _spill_tenant(self, tenant: str, need_bytes: int) -> int:
        """Spill ``tenant``'s OWN device spillables in priority order —
        the quota breach's self-help step, deliberately blind to every
        other tenant's buffers."""
        with self._lock:
            candidates = sorted(
                (s for h, s in self._spillables.items()
                 if s.tier == "device"
                 and self._spillable_tenant.get(h) == tenant),
                key=lambda s: s.spill_priority)
        freed = 0
        for s in candidates:
            if freed >= need_bytes:
                break
            freed += s.spill_to_host()
        return freed

    def _enforce_tenant_quota(self, nbytes: int) -> None:
        """Per-tenant HBM share gate (reserve-time, BEFORE the global
        budget): over quota, spill the tenant's own buffers; still over,
        raise RetryOOM (rung 1) or SplitAndRetryOOM when this single
        allocation alone exceeds the share (rung 2). The raise precedes
        any global-budget pressure, so a quota breach rides the
        breaching tenant's own ladder instead of forcing a cross-session
        spill on everyone else."""
        tenant = getattr(self._tenant, "name", None)
        quota = getattr(self._tenant, "quota", 0)
        if not tenant or quota <= 0:
            return
        with self._lock:
            used = self._tenant_used_locked(tenant)
        if used + nbytes <= quota:
            return
        self._spill_tenant(tenant, used + nbytes - quota)
        with self._lock:
            used = self._tenant_used_locked(tenant)
        if used + nbytes <= quota:
            return
        if nbytes > quota:
            raise SplitAndRetryOOM(
                f"tenant {tenant}: allocation of {nbytes} exceeds the "
                f"whole tenant HBM share {quota}")
        raise RetryOOM(
            f"tenant {tenant}: reserve of {nbytes} would exceed the "
            f"tenant HBM share (used={used}, quota={quota})")

    # ------------------------------------------------------------ accounting
    def reserve(self, nbytes: int, allow_spill: bool = True):
        """Account for nbytes of device memory about to be retained.

        On budget pressure: spill registered buffers; on injected or real
        exhaustion raise RetryOOM / SplitAndRetryOOM
        (ref DeviceMemoryEventHandler.onAllocFailure -> store.spill)."""
        if self.debug_log:
            log.info("alloc %d B (used %d B)", nbytes, self.device_used)
        if self.in_pressure_grant():
            # the degradation rung must never fail a granted thread's
            # reserve — checked FIRST so the native allocator (whose
            # budget enforcement and injections have no grant notion)
            # and the chaos/injection hooks are all bypassed. Bytes land
            # in the unbudgeted pressure pool, with a thread-local
            # ledger so the matching release() inside the grant drains
            # the SAME pool instead of under-counting other buffers'
            # device bytes (SpillableBatch skips reserve() entirely and
            # handles cross-grant-boundary symmetry with its _granted
            # flag).
            self._grant.ledger = getattr(self._grant, "ledger", 0) + nbytes
            self.reserve_granted(nbytes)
            return
        self._maybe_chaos()
        # per-tenant HBM share (ISSUE 18): gated BEFORE the global
        # budget so a breaching tenant self-spills / splits on its own
        # ladder instead of pressuring everyone else's buffers
        self._enforce_tenant_quota(nbytes)
        if self._native is not None:
            rc = self._native.reserve(nbytes, block_ms=0)
            if rc == 0:
                self._trace_alloc(nbytes)
                return
            if rc == 2:
                raise SplitAndRetryOOM(
                    f"native: allocation of {nbytes} cannot ever fit "
                    f"(budget {self.budget}) or split was injected")
            if allow_spill:
                self.spill_device(nbytes)
                # brief native block/wake window lets concurrent releases in
                rc = self._native.reserve(nbytes, block_ms=20)
                if rc == 0:
                    self._trace_alloc(nbytes)
                    return
            raise RetryOOM(f"native: could not reserve {nbytes} "
                           f"(used={self.device_used}, budget={self.budget})")
        self._maybe_inject()
        with self._lock:
            if self._py_device_used + nbytes <= self.budget:
                self._py_device_used += nbytes
                self._py_max_device_used = max(self._py_max_device_used,
                                               self._py_device_used)
                self._trace_alloc(nbytes)
                return
        if allow_spill:
            with self._lock:
                # read the shortfall under the lock: a stale used-count
                # here under-spills and turns a satisfiable reserve
                # into a spurious RetryOOM
                shortfall = nbytes - (self.budget - self._py_device_used)
            self.spill_device(shortfall)
            with self._lock:
                if self._py_device_used + nbytes <= self.budget:
                    self._py_device_used += nbytes
                    self._py_max_device_used = max(self._py_max_device_used,
                                                   self._py_device_used)
                    self._trace_alloc(nbytes)
                    return
        if nbytes > self.budget:
            raise SplitAndRetryOOM(
                f"allocation of {nbytes} exceeds whole budget {self.budget}")
        raise RetryOOM(f"could not reserve {nbytes} "
                       f"(used={self.device_used}, budget={self.budget})")

    def _trace_alloc(self, nbytes: int) -> None:
        tr = trace_core.TRACER       # single branch when tracing is off
        if tr is not None:
            tr.counter("mem.device_used", {"bytes": self.device_used,
                                           "alloc": nbytes}, cat="mem")

    def release(self, nbytes: int):
        if self.debug_log:
            log.info("free  %d B (used %d B)", nbytes,
                     self.device_used - nbytes)
        # symmetric with the grant branch in reserve(): bytes this
        # thread reserved UNDER the grant (ledger) drain the grant
        # pool; anything beyond the ledger is a pre-grant buffer
        # being closed under the grant and falls through to the
        # normal device accounting. The ledger is drained even when
        # the grant scope has already EXITED (ISSUE 18 satellite): a
        # reserve made under the grant whose release lands after the
        # scope closed used to strand its bytes in pressure_granted
        # forever — degrading the /healthz memory verdict with zero
        # live granted bytes — while the normal accounting was
        # under-counted by the same amount.
        led = getattr(self._grant, "ledger", 0)
        if led > 0:
            take = min(nbytes, led)
            self._grant.ledger = led - take
            self.release_granted(take)
            nbytes -= take
            if nbytes <= 0:
                return
        if self._native is not None:
            self._native.release(nbytes)
            return
        with self._lock:
            self._py_device_used = max(0, self._py_device_used - nbytes)

    def reserve_absorbing_retries(self, nbytes: int, attempts: int = 10):
        """``reserve`` that absorbs transient RetryOOMs at the allocation
        site itself: spill-and-retry a bounded number of times before
        letting the OOM escape to the caller's retry frame (ref RMM's
        alloc loop re-entering the spill callback before GpuRetryOOM
        reaches the task thread). SpillableBatch wraps reserve through
        this, so a bare ``[SpillableBatch(b, mm) for b in ...]``
        comprehension survives an injected or transient OOM without every
        call site needing its own retry closure. SplitAndRetryOOM is
        NEVER absorbed — only the caller can split its input."""
        last: Optional[BaseException] = None
        for attempt in range(max(1, attempts)):
            try:
                return self.reserve(nbytes)
            except RetryOOM as e:
                last = e
                tr = trace_core.TRACER
                if tr is not None:
                    tr.instant("oom.retry", cat="mem",
                               args={"attempt": attempt, "site": "reserve"})
                from ..metrics import registry as metrics_registry
                mr = metrics_registry.REGISTRY
                if mr is not None:
                    mr.counter("srtpu_oom_retries_total").inc()
                self.spill_device(nbytes)
                time.sleep(0)        # yield so other tasks can release
        raise last

    # --------------------------------------------------- pressure grants
    def in_pressure_grant(self) -> bool:
        """True while the calling thread runs under the OOM escalation
        ladder's host degradation rung (mem/retry.py)."""
        return getattr(self._grant, "depth", 0) > 0

    @contextmanager
    def pressure_host_grant(self):
        """Admit the calling thread's new spillables OUTSIDE the device
        budget for the duration: the final escalation rung after retries,
        splits and a cross-session pressure spill all failed. Buffers
        created under the grant account into ``pressure_granted`` (their
        own flag keeps release symmetric) and reserve-time fault
        injection is suppressed — the work is off the device path."""
        self._grant.depth = getattr(self._grant, "depth", 0) + 1
        try:
            yield self
        finally:
            self._grant.depth -= 1

    def reserve_granted(self, nbytes: int):
        with self._lock:
            self.pressure_granted += nbytes
            if self.pressure_granted > 0:
                self._grant_last_nonzero = time.monotonic()

    def release_granted(self, nbytes: int):
        with self._lock:
            if self.pressure_granted > 0:
                # stamp the drain instant: pressure_grant_idle_s (and
                # the /healthz clear horizon) measure from the moment
                # the pool was LAST nonzero, not from first grant
                self._grant_last_nonzero = time.monotonic()
            self.pressure_granted = max(0, self.pressure_granted - nbytes)

    def reserve_host(self, nbytes: int):
        with self._lock:
            self.host_used += nbytes

    def release_host(self, nbytes: int):
        with self._lock:
            self.host_used = max(0, self.host_used - nbytes)

    # --------------------------------------------------------------- spilling
    def spill_device(self, need_bytes: int) -> int:
        """Synchronously spill device-tier spillables in priority order until
        need_bytes freed (ref RapidsBufferStore.synchronousSpill)."""
        tr = trace_core.TRACER
        t0 = tr.now() if tr is not None else 0
        with self._lock:
            candidates = sorted(
                (s for s in self._spillables.values()
                 if s.tier == "device"),
                key=lambda s: s.spill_priority)
        freed = 0
        for s in candidates:
            if freed >= need_bytes:
                break
            freed += s.spill_to_host()
        if tr is not None and (need_bytes > 0 or freed > 0):
            # the retry loop's spill_device(0) nudge is a no-op here
            # (freed >= 0 breaks immediately) — a span for it would
            # count phantom spills in the profiler
            tr.complete("spill.device", t0, cat="mem",
                        args={"need_bytes": need_bytes,
                              "freed_bytes": freed})
        # host pressure cascades to disk
        with self._lock:
            over = self.host_used - self.host_limit
        if over > 0:
            self.spill_host(over)
        return freed

    def spill_everything(self) -> int:
        """Spill EVERY device-tier spillable this manager tracks (and
        cascade host pressure to disk): the cross-session pressure rung
        of the OOM escalation ladder — other sessions' builds, broadcast
        relations and parked partials all move off-device so one starving
        operator gets the whole budget (ref synchronousSpill(store, 0))."""
        with self._lock:
            need = sum(s.device_bytes() for s in self._spillables.values()
                       if s.tier == "device")
        return self.spill_device(need) if need > 0 else 0

    @classmethod
    def spill_all_sessions(cls) -> int:
        """``spill_everything`` across every live budget singleton — the
        process-wide pressure valve the retry ladder pulls before the
        host degradation rung. Returns total bytes freed."""
        with cls._global_lock:
            insts = list(cls._instances.values())
        freed = 0
        for mm in insts:
            freed += mm.spill_everything()
        from ..metrics import registry as metrics_registry
        mr = metrics_registry.REGISTRY
        if mr is not None:
            mr.counter("srtpu_oom_pressure_spills_total").inc()
        return freed

    def spill_host(self, need_bytes: int) -> int:
        tr = trace_core.TRACER
        t0 = tr.now() if tr is not None else 0
        with self._lock:
            candidates = sorted(
                (s for s in self._spillables.values() if s.tier == "host"),
                key=lambda s: s.spill_priority)
        freed = 0
        for s in candidates:
            if freed >= need_bytes:
                break
            freed += s.spill_to_disk()
        if tr is not None and (need_bytes > 0 or freed > 0):
            tr.complete("spill.host", t0, cat="mem",
                        args={"need_bytes": need_bytes,
                              "freed_bytes": freed})
        return freed

    # -------------------------------------------------------- fault injection
    def force_retry_oom(self, num_ooms: int = 1, skip: int = 0,
                        thread_id: Optional[int] = None):
        """Next `num_ooms` reserves on the thread raise RetryOOM after
        skipping `skip` (ref RmmSpark.forceRetryOOM)."""
        if self._native is not None:
            self._native.force_retry_oom(num_ooms, skip, thread_id)
            return
        tid = thread_id if thread_id is not None else threading.get_ident()
        with self._lock:
            self._inject.setdefault(tid, []).append(["retry", skip, num_ooms])

    def force_split_and_retry_oom(self, num_ooms: int = 1, skip: int = 0,
                                  thread_id: Optional[int] = None):
        if self._native is not None:
            self._native.force_split_and_retry_oom(num_ooms, skip, thread_id)
            return
        tid = thread_id if thread_id is not None else threading.get_ident()
        with self._lock:
            self._inject.setdefault(tid, []).append(["split", skip, num_ooms])

    def clear_injections(self):
        if self._native is not None:
            self._native.clear_injections()
        with self._lock:
            self._inject.clear()

    def _maybe_chaos(self):
        """Config-armed chaos sites at the reserve entry point (the
        process-global ChaosController, aux/fault.py): ``mem.oom`` raises
        an injected RetryOOM, ``mem.reserve.delay`` stalls the reserve.
        One list-read when chaos is disarmed; suppressed entirely under a
        pressure grant (the thread is already off the device path)."""
        from ..aux.fault import active_chaos
        ctl = active_chaos()
        if ctl is None or self.in_pressure_grant():
            return
        if ctl.wants("mem.reserve.delay"):
            ctl.maybe_delay("mem.reserve.delay")
        if ctl.wants("mem.oom") and ctl.fires("mem.oom"):
            # record the OPERATOR-level reserve site (first frame outside
            # mem/) so the chaos battery can assert injection breadth
            f = sys._getframe(1)
            while f is not None and ("/mem/" in
                                     f.f_code.co_filename.replace("\\", "/")):
                f = f.f_back
            if f is not None:
                import os as _os
                ctl.note_context(
                    "mem.oom",
                    f"{_os.path.basename(f.f_code.co_filename)}:"
                    f"{f.f_code.co_name}")
            raise RetryOOM("chaos: injected mem.oom at reserve()")

    def _maybe_inject(self):
        if self.in_pressure_grant():
            return
        tid = threading.get_ident()
        with self._lock:
            queue = self._inject.get(tid)
            if not queue:
                return
            entry = queue[0]
            kind, skip, count = entry
            if skip > 0:
                entry[1] -= 1
                return
            entry[2] -= 1
            if entry[2] <= 0:
                queue.pop(0)
                if not queue:
                    self._inject.pop(tid, None)
        if kind == "retry":
            raise RetryOOM("injected RetryOOM")
        raise SplitAndRetryOOM("injected SplitAndRetryOOM")

    # ----------------------------------------------------------- leak audit
    def audit_leaks(self) -> List[dict]:
        """Live (unclosed) spillable registrations — the MemoryCleaner
        leak tracker analog (ref Plugin.scala:573-588: cudf MemoryCleaner
        asserts no leaked device buffers at shutdown). Every
        SpillableBatch a query creates must be close()d by the time its
        sink finishes; anything still registered here afterwards is a
        leak. Entries carry the creation site when leak-detection debug
        is on (SpillableBatch records it)."""
        with self._lock:
            return [{"handle": h, "tier": s.tier,
                     "bytes": s.device_bytes(),
                     "created_at": getattr(s, "created_at", None)}
                    for h, s in self._spillables.items()]

    @classmethod
    def audit_all_leaks(cls) -> List[dict]:
        with cls._global_lock:
            insts = list(cls._instances.values())
        out = []
        for mm in insts:
            out.extend(mm.audit_leaks())
        return out

    @classmethod
    def stats_all(cls) -> Dict[str, int]:
        """Aggregate accounting across every live budget singleton — the
        metrics sampler's view (one process may hold several budgets in
        tests; fleet gauges sum them). Each instance is read through
        its own lock'd stats() so a manager mid-spill contributes a
        consistent row, not a torn one."""
        with cls._global_lock:
            insts = list(cls._instances.values())
        out = {"device_used": 0, "host_used": 0, "disk_used": 0,
               "max_device_used": 0, "budget": 0,
               "spill_to_host_bytes": 0, "spill_to_disk_bytes": 0,
               "pressure_granted": 0}
        tenant_used: Dict[str, int] = {}
        tenant_quota: Dict[str, int] = {}
        idle = None
        for mm in insts:
            st = mm.stats()
            for k in out:
                out[k] += st[k]
            for t, v in (st.get("tenant_used") or {}).items():
                tenant_used[t] = tenant_used.get(t, 0) + v
            for t, v in (st.get("tenant_quota") or {}).items():
                tenant_quota[t] = tenant_quota.get(t, 0) + v
            i = st.get("pressure_grant_idle_s")
            if i is not None:
                # MIN across instances: the most recent grant activity
                # anywhere governs the process-wide clear horizon
                idle = i if idle is None else min(idle, i)
        out["tenant_used"] = tenant_used
        out["tenant_quota"] = tenant_quota
        out["pressure_grant_idle_s"] = idle
        return out

    # ------------------------------------------------------------------ stats
    def stats(self) -> Dict[str, int]:
        with self._lock:
            tenants = sorted(set(self._spillable_tenant.values())
                             | set(self._tenant_quota))
            return {"device_used": self.device_used,
                    "host_used": self.host_used,
                    "disk_used": self.disk_used,
                    "max_device_used": self.max_device_used,
                    "budget": self.budget,
                    "spill_to_host_bytes": self.spill_to_host_bytes,
                    "spill_to_disk_bytes": self.spill_to_disk_bytes,
                    "pressure_granted": self.pressure_granted,
                    # seconds since the pressure pool was last nonzero
                    # (0.0 while nonzero; None = never granted): the
                    # /healthz memory verdict's clear horizon and the
                    # admission shed check both read this
                    "pressure_grant_idle_s": (
                        0.0 if self.pressure_granted > 0
                        else (round(time.monotonic()
                                    - self._grant_last_nonzero, 3)
                              if self._grant_last_nonzero is not None
                              else None)),
                    # per-tenant device residency census (ISSUE 18):
                    # live registered spillables per owning tenant
                    "tenant_used": {t: self._tenant_used_locked(t)
                                    for t in tenants},
                    "tenant_quota": dict(self._tenant_quota),
                    "num_spillables": len(self._spillables)}
