"""ctypes binding to the native OOM state machine (native/oom_state.cpp).

Builds the shared library on demand with g++ (cached beside the source);
`load()` returns None when no compiler is available so the Python twin in
manager.py keeps working — same pattern as the reference where RmmSpark is
mandatory native but our runtime degrades gracefully.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

__all__ = ["load", "NativeOomState"]

_SRC = os.path.join(os.path.dirname(__file__), "..", "native",
                    "oom_state.cpp")
_SO = os.path.join(os.path.dirname(__file__), "..", "native",
                   "liboom_state.so")
_LOCK = threading.Lock()
_lib = None          # tpulint: guarded-by _LOCK
_tried = False       # tpulint: guarded-by _LOCK


def _build() -> Optional[str]:
    src = os.path.abspath(_SRC)
    so = os.path.abspath(_SO)
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    try:
        subprocess.run(["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
                        "-pthread", src, "-o", so], check=True,
                       capture_output=True, timeout=120)
        return so
    except Exception:
        return None


def load():
    global _lib, _tried
    with _LOCK:
        if _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        i64, lng = ctypes.c_int64, ctypes.c_long
        lib.oom_init.argtypes = [i64]
        lib.oom_set_budget.argtypes = [i64]
        lib.oom_register_thread.argtypes = [i64, lng]
        lib.oom_unregister_thread.argtypes = [i64]
        lib.oom_reserve.argtypes = [i64, i64, lng]
        lib.oom_reserve.restype = ctypes.c_int
        lib.oom_release.argtypes = [i64]
        lib.oom_host_reserve.argtypes = [i64]
        lib.oom_host_release.argtypes = [i64]
        lib.oom_force_retry_oom.argtypes = [i64, lng, lng]
        lib.oom_force_split_and_retry_oom.argtypes = [i64, lng, lng]
        for f in ("oom_get_used", "oom_get_max_used", "oom_get_host_used",
                  "oom_get_budget"):
            getattr(lib, f).restype = i64
        lib.oom_get_blocked_threads.restype = lng
        lib.oom_get_retry_count.argtypes = [i64]
        lib.oom_get_retry_count.restype = lng
        lib.oom_get_split_count.argtypes = [i64]
        lib.oom_get_split_count.restype = lng
        lib.oom_get_blocked_ns.argtypes = [i64]
        lib.oom_get_blocked_ns.restype = i64
        _lib = lib
        return _lib


class NativeOomState:
    """Thin OO wrapper used by MemoryManager when the native lib loads."""

    def __init__(self, budget: int):
        self.lib = load()
        assert self.lib is not None
        self.lib.oom_init(budget)

    def reserve(self, nbytes: int, block_ms: int = 0) -> int:
        return self.lib.oom_reserve(threading.get_ident(), nbytes, block_ms)

    def release(self, nbytes: int):
        self.lib.oom_release(nbytes)

    def force_retry_oom(self, num: int = 1, skip: int = 0, tid=None):
        self.lib.oom_force_retry_oom(
            tid if tid is not None else threading.get_ident(), num, skip)

    def force_split_and_retry_oom(self, num: int = 1, skip: int = 0,
                                  tid=None):
        self.lib.oom_force_split_and_retry_oom(
            tid if tid is not None else threading.get_ident(), num, skip)

    def clear_injections(self):
        self.lib.oom_clear_injections()

    @property
    def used(self) -> int:
        return self.lib.oom_get_used()

    @property
    def max_used(self) -> int:
        return self.lib.oom_get_max_used()

    @property
    def blocked_threads(self) -> int:
        return self.lib.oom_get_blocked_threads()

    def retry_count(self, tid=None) -> int:
        return self.lib.oom_get_retry_count(
            tid if tid is not None else threading.get_ident())

    def blocked_ns(self, tid=None) -> int:
        return self.lib.oom_get_blocked_ns(
            tid if tid is not None else threading.get_ident())
