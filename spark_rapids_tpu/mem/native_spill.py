"""ctypes binding to the native disk spill store (native/spill_store.cpp
— the RapidsDiskStore/RapidsDiskBlockManager analog).

Spilled batches append into large slab files through a C++ block store
with CRC32 verification on read-back; one store per spill directory,
shared by every MemoryManager pointing at it. Falls back to None when no
compiler is available — SpillableBatch then uses per-batch Arrow IPC
files (the pure-Python tier).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

__all__ = ["NativeSpillStore", "get_store"]

_SRC = os.path.join(os.path.dirname(__file__), "..", "native",
                    "spill_store.cpp")
_SO = os.path.join(os.path.dirname(__file__), "..", "native",
                   "libspill_store.so")
_LOCK = threading.Lock()
_lib = None          # tpulint: guarded-by _LOCK
_tried = False       # tpulint: guarded-by _LOCK
_stores: Dict[str, "NativeSpillStore"] = {}  # tpulint: guarded-by _LOCK


def _load_lib():
    global _lib, _tried
    with _LOCK:
        if _tried:
            return _lib
        _tried = True
        src, so = os.path.abspath(_SRC), os.path.abspath(_SO)
        try:
            if not (os.path.exists(so)
                    and os.path.getmtime(so) >= os.path.getmtime(src)):
                subprocess.run(
                    ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", src,
                     "-o", so], check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(so)
        except Exception:
            return None
        lib.sp_open.restype = ctypes.c_void_p
        lib.sp_open.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.sp_write.restype = ctypes.c_int64
        lib.sp_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_int64]
        lib.sp_block_size.restype = ctypes.c_int64
        lib.sp_block_size.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sp_read.restype = ctypes.c_int64
        lib.sp_read.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                ctypes.c_char_p, ctypes.c_int64]
        lib.sp_free.restype = ctypes.c_int
        lib.sp_free.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sp_stats.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_int64 * 4)]
        lib.sp_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class NativeSpillStore:
    """One slab-file block store rooted at a spill directory."""

    def __init__(self, lib, handle):
        self._lib = lib
        self._h = handle
        self._lock = threading.Lock()

    def write(self, data: bytes) -> int:
        with self._lock:
            bid = self._lib.sp_write(self._h, data, len(data))
        if bid < 0:
            raise IOError("native spill write failed")
        return int(bid)

    def read(self, block_id: int) -> bytes:
        n = self._lib.sp_block_size(self._h, block_id)
        if n < 0:
            raise KeyError(f"unknown spill block {block_id}")
        buf = ctypes.create_string_buffer(int(n))
        with self._lock:
            got = self._lib.sp_read(self._h, block_id, buf, n)
        if got == -2:
            raise IOError(
                f"spill block {block_id} failed CRC verification "
                "(disk corruption)")
        if got != n:
            raise IOError(f"short read of spill block {block_id}")
        return buf.raw

    def free(self, block_id: int) -> None:
        with self._lock:
            self._lib.sp_free(self._h, block_id)

    def stats(self) -> dict:
        out = (ctypes.c_int64 * 4)()
        self._lib.sp_stats(self._h, ctypes.byref(out))
        return {"live_blocks": out[0], "live_bytes": out[1],
                "slab_files": out[2], "file_bytes": out[3]}


def _close_all():
    with _LOCK:
        for st in _stores.values():
            try:
                st._lib.sp_close(st._h)
            except Exception:
                pass
        _stores.clear()


def get_store(spill_dir: str) -> Optional[NativeSpillStore]:
    """Shared store per spill directory, or None without a toolchain.
    Slab files are pid-unique (safe for shared directories) and removed
    by sp_close at interpreter exit; files left by a CRASHED process are
    dead weight the operator reclaims by clearing the spill dir (same
    contract as the reference's disk block manager)."""
    lib = _load_lib()
    if lib is None:
        return None
    with _LOCK:
        first = not _stores
        st = _stores.get(spill_dir)
        if st is None:
            os.makedirs(spill_dir, exist_ok=True)
            h = lib.sp_open(spill_dir.encode(), 0)
            if not h:
                return None
            st = NativeSpillStore(lib, h)
            _stores[spill_dir] = st
            if first:
                import atexit
                atexit.register(_close_all)
        return st
