"""The OOM retry / split-and-retry framework.

Reference analog: RmmRapidsRetryIterator.scala:33-200 (withRetry /
withRetryNoSplit / splitAndRetry), driven by GpuRetryOOM /
GpuSplitAndRetryOOM thrown from the allocator. Semantics preserved:

  * the attempted function must be idempotent over its (spillable) input
  * RetryOOM     -> spill happened (or will), just run again
  * SplitAndRetryOOM -> halve the input and process the pieces recursively
  * bounded attempts, then OutOfDeviceMemory

Used by every memory-hungry operator (aggregate merge, sort, join build,
coalesce) exactly like the reference wraps theirs.
"""
from __future__ import annotations

import time
from typing import Callable, Iterator, List, Optional, TypeVar

from ..metrics import registry as metrics_registry
from ..trace import core as trace_core
from .manager import (MemoryManager, OutOfDeviceMemory, RetryOOM,
                      SplitAndRetryOOM)
from .spillable import SpillableBatch

__all__ = ["with_retry_no_split", "with_retry", "split_batch_in_half",
           "RetryStats"]

T = TypeVar("T")
MAX_RETRIES = 100


class RetryStats:
    def __init__(self):
        self.retries = 0
        self.splits = 0


def _trace_oom(kind: str, attempt: int) -> None:
    tr = trace_core.TRACER           # single branch when tracing is off
    if tr is not None:
        tr.instant(kind, cat="mem", args={"attempt": attempt})
    mr = metrics_registry.REGISTRY   # same contract for the registry
    if mr is not None:
        mr.counter("srtpu_oom_retries_total" if kind == "oom.retry"
                   else "srtpu_oom_splits_total").inc()


def with_retry_no_split(fn: Callable[[], T], mm: Optional[MemoryManager] = None,
                        stats: Optional[RetryStats] = None) -> T:
    """Run fn; on RetryOOM spill+retry; SplitAndRetryOOM is fatal here
    (ref withRetryNoSplit)."""
    mm = mm or MemoryManager.get()
    last = None
    for attempt in range(MAX_RETRIES):
        try:
            return fn()
        except RetryOOM as e:
            last = e
            stats and setattr(stats, "retries", stats.retries + 1)
            _trace_oom("oom.retry", attempt)
            mm.spill_device(0)
            time.sleep(0)  # yield so other tasks can release
        except SplitAndRetryOOM as e:
            raise OutOfDeviceMemory(
                f"operation cannot split its input: {e}") from e
    raise OutOfDeviceMemory(f"exceeded {MAX_RETRIES} OOM retries: {last}")


def split_batch_in_half(sb: SpillableBatch) -> List[SpillableBatch]:
    """Default splitter (ref RmmRapidsRetryIterator splitSpillableInHalfByRows).

    Exception-safe: the input is closed whether or not the split
    succeeds, and a piece already wrapped when the second slice or
    wrap raises is closed too — a half-built split must not pin pool
    budget (the caller's retry loop closes only what it was handed)."""
    pieces: List[SpillableBatch] = []
    try:
        batch = sb.get()
        n = batch.num_rows
        if n < 2:
            raise OutOfDeviceMemory("cannot split a batch with < 2 rows")
        mid = n // 2
        mm = sb.memory_manager
        pieces.append(SpillableBatch(batch.slice(0, mid), mm))
        pieces.append(SpillableBatch(batch.slice(mid, n - mid), mm))
        return pieces
    except BaseException:
        for p in pieces:
            p.close()
        raise
    finally:
        sb.close()


def with_retry(inputs: List[SpillableBatch],
               fn: Callable[[SpillableBatch], T],
               mm: Optional[MemoryManager] = None,
               splitter: Callable = split_batch_in_half,
               stats: Optional[RetryStats] = None) -> Iterator[T]:
    """Process each spillable input through fn with retry+split semantics
    (ref withRetry + RetryIterator). Yields one result per (possibly split)
    input piece, in order."""
    mm = mm or MemoryManager.get()
    queue: List[SpillableBatch] = list(inputs)
    item: Optional[SpillableBatch] = None
    try:
        while queue:
            item = queue.pop(0)
            attempts = 0
            while True:
                try:
                    yield fn(item)
                    break
                except RetryOOM:
                    attempts += 1
                    stats and setattr(stats, "retries", stats.retries + 1)
                    _trace_oom("oom.retry", attempts)
                    if attempts > MAX_RETRIES:
                        raise OutOfDeviceMemory("retry limit exceeded")
                    mm.spill_device(0)
                except SplitAndRetryOOM:
                    stats and setattr(stats, "splits", stats.splits + 1)
                    _trace_oom("oom.split", attempts)
                    pieces = splitter(item)
                    # process pieces in order before the rest of the queue
                    queue = pieces + queue
                    item = None
                    break
            if item is None:
                continue
    except BaseException:
        # fatal error or abandoned consumer: the iterator owns every input
        # still queued — release them or they pin pool budget forever
        # (close() is idempotent, so an input fn already consumed is a
        # no-op; ref RmmRapidsRetryIterator closes its attempt on throw)
        if item is not None:
            item.close()
        for sb in queue:
            sb.close()
        raise
