"""The OOM retry / split-and-retry escalation ladder.

Reference analog: RmmRapidsRetryIterator.scala:33-200 (withRetry /
withRetryNoSplit / splitAndRetry), driven by GpuRetryOOM /
GpuSplitAndRetryOOM thrown from the allocator, plus the Retryable.scala
CheckpointRestore contract that keeps retried operator state
side-effect-free. The r14 rebuild turns the original split-in-half
helper into a full state machine with four rungs:

1. **retry**   — ``RetryOOM``: restore checkpoints, spill this
   manager's device tier, run the attempt again (bounded).
2. **split**   — ``SplitAndRetryOOM``: halve the input and process the
   pieces recursively, bounded by ``spark.rapids.tpu.oom.maxSplitDepth``
   (ref splitSpillableInHalfByRows).
3. **pressure** — cross-session spill: every live MemoryManager's
   spillables (other sessions' builds, broadcasts, parked partials)
   move off-device so the one starving operator gets the whole budget.
4. **host degradation** — ``spark.rapids.tpu.oom.hostFallback.enabled``:
   the attempt runs ONCE more on the host backend under an unbudgeted
   pressure grant instead of failing the query. Recorded as an
   ``OOM_PRESSURE_HOST`` placement tag (plan/tags.py) and counted by
   ``srtpu_oom_host_fallback_total``.

Invariants the ladder preserves:

  * the attempted function must be idempotent over its (spillable)
    input; mutable operator state passes a :class:`CheckpointRestore`
    via ``retryable=`` and is restored before every re-attempt
  * ``close()`` idempotence lets every rung release exactly what it was
    handed — no path leaks a registered spillable

Used by every memory-hungry operator (aggregate merge, sort, join
build, coalesce) exactly like the reference wraps theirs.
"""
from __future__ import annotations

import time
from typing import Callable, Iterable, Iterator, List, Optional, TypeVar

from ..metrics import registry as metrics_registry
from ..trace import core as trace_core
from .manager import (MemoryManager, OutOfDeviceMemory, RetryOOM,
                      SplitAndRetryOOM)
from .spillable import SpillableBatch

__all__ = ["with_retry_no_split", "with_retry", "split_batch_in_half",
           "RetryStats", "CheckpointRestore", "wrap_spillables"]

T = TypeVar("T")
MAX_RETRIES = 100
#: extra attempts granted after the cross-session pressure rung fires
PRESSURE_ATTEMPTS = 2


class RetryStats:
    def __init__(self):
        self.retries = 0
        self.splits = 0
        self.pressure_spills = 0
        self.host_fallbacks = 0


class CheckpointRestore:
    """Mutable operator state that must survive OOM retries (ref
    Retryable.scala CheckpointRestore): ``checkpoint()`` is called once
    before the first attempt, ``restore()`` before every re-attempt, so
    an attempt that mutated its state then OOM'd re-runs from the same
    starting point — retries stay side-effect-free by construction."""

    def checkpoint(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError


def _trace_oom(kind: str, attempt: int) -> None:
    tr = trace_core.TRACER           # single branch when tracing is off
    if tr is not None:
        tr.instant(kind, cat="mem", args={"attempt": attempt})
    mr = metrics_registry.REGISTRY   # same contract for the registry
    if mr is not None:
        if kind == "oom.retry":
            mr.counter("srtpu_oom_retries_total").inc()
        elif kind == "oom.split":
            mr.counter("srtpu_oom_splits_total").inc()


def wrap_spillable_sides(mm: MemoryManager, *batch_iters: Iterable
                         ) -> List[List[SpillableBatch]]:
    """``wrap_spillables`` over several input streams (a join's build
    and stream sides) with CROSS-stream cleanup: if wrapping a later
    stream fails, every batch already wrapped from the earlier streams
    closes too before the exception re-raises."""
    sides: List[List[SpillableBatch]] = []
    try:
        for it in batch_iters:
            sides.append(wrap_spillables(it, mm))
        return sides
    except BaseException:
        for side in sides:
            for sb in side:
                sb.close()
        raise


def wrap_spillables(batches: Iterable, mm: MemoryManager
                    ) -> List[SpillableBatch]:
    """Exception-safe bulk wrap: ``[SpillableBatch(b, mm) for b in it]``
    leaks every already-wrapped batch when a later wrap (or the
    producing iterator — e.g. a cooperative QueryTimeout) raises. This
    closes the partial list before re-raising, so cancellation and OOM
    paths hold the zero-leak audit."""
    out: List[SpillableBatch] = []
    try:
        for b in batches:
            out.append(SpillableBatch(b, mm))
        return out
    except BaseException:
        for sb in out:
            sb.close()
        raise


class _Ladder:
    """Shared escalation state for one with_retry / with_retry_no_split
    call: checkpointed retryables, the one-shot pressure rung, and the
    host degradation rung."""

    def __init__(self, mm: MemoryManager, stats: Optional[RetryStats],
                 retryable, ctx, op: Optional[str], host_fallback):
        self.mm = mm
        self.stats = stats
        self.retryables = ([] if retryable is None else
                           list(retryable) if isinstance(retryable,
                                                         (list, tuple))
                           else [retryable])
        self.ctx = ctx
        self.op = op
        self.host_fallback = host_fallback
        self.pressured = False
        for r in self.retryables:
            r.checkpoint()

    # ------------------------------------------------------------ helpers
    def check_cancelled(self) -> None:
        if self.ctx is not None:
            self.ctx.check_cancelled()

    def restore(self) -> None:
        for r in self.retryables:
            r.restore()

    def note_retry(self, attempt: int) -> None:
        if self.stats is not None:
            self.stats.retries += 1
        _trace_oom("oom.retry", attempt)
        if self.ctx is not None:
            self.ctx.note_ladder_rung(1)
        self.restore()

    def note_split(self, attempt: int) -> None:
        if self.stats is not None:
            self.stats.splits += 1
        _trace_oom("oom.split", attempt)
        if self.ctx is not None:
            self.ctx.note_ladder_rung(2)
        self.restore()

    def _conf(self):
        if self.ctx is not None:
            return self.ctx.conf
        from ..config import DEFAULT
        return DEFAULT

    def max_split_depth(self, override: Optional[int]) -> int:
        if override is not None:
            return int(override)
        from ..config import OOM_MAX_SPLIT_DEPTH
        return int(self._conf().get(OOM_MAX_SPLIT_DEPTH))

    # -------------------------------------------------------- rung 3 / 4
    def pressure_spill(self) -> None:
        """Rung 3, fired at most once per ladder: spill EVERY live
        session's spillables (this manager first — a directly-
        constructed manager may not be in the singleton table)."""
        self.pressured = True
        if self.stats is not None:
            self.stats.pressure_spills += 1
        tr = trace_core.TRACER
        freed = self.mm.spill_everything()
        freed += MemoryManager.spill_all_sessions()
        if tr is not None:
            tr.instant("oom.pressure_spill", cat="mem",
                       args={"freed_bytes": freed, "op": self.op})
        detail = (f"rung-3 cross-session pressure spill for "
                  f"op={self.op or '?'} freed {freed} bytes")
        if self.ctx is not None:
            self.ctx.note_ladder_rung(3, detail)
        else:
            # no ExecContext (a bare with_retry outside any query): the
            # anomaly still pages — trigger the flight recorder directly
            from ..ops import flight as flight_mod
            fr = flight_mod.RECORDER
            if fr is not None:
                fr.trigger("oom_ladder", detail=detail)

    def degrade(self, thunk: Callable[[], T], detail: str,
                prefer_fallback: bool = True) -> T:
        """Rung 4: run the attempt on the host backend under an
        unbudgeted pressure grant instead of failing the query. The
        operator-provided ``host_fallback`` wins when given (it knows a
        cheaper host path); otherwise the SAME attempt runs with new
        buffers admitted outside the budget and jax pointed at the host
        platform — identical kernels, host-resident working set."""
        from ..config import OOM_HOST_FALLBACK_ENABLED
        if not bool(self._conf().get(OOM_HOST_FALLBACK_ENABLED)):
            raise OutOfDeviceMemory(detail)
        self.restore()
        if self.stats is not None:
            self.stats.host_fallbacks += 1
        op_kind = (self.op or "op").split("@")[0]
        tr = trace_core.TRACER
        if tr is not None:
            tr.instant("oom.host_fallback", cat="mem",
                       args={"op": op_kind, "detail": detail})
        if self.ctx is not None:
            self.ctx.record_oom_degradation(op_kind, detail)
        else:
            mr = metrics_registry.REGISTRY
            if mr is not None:
                mr.counter("srtpu_oom_host_fallback_total",
                           op=op_kind).inc()
            from ..ops import flight as flight_mod
            fr = flight_mod.RECORDER
            if fr is not None:
                fr.trigger("oom_ladder",
                           detail=f"rung-4 host degradation for "
                                  f"op={op_kind}: {detail}")
        if prefer_fallback and self.host_fallback is not None:
            return self.host_fallback()
        cpu = None
        try:
            import jax
            cpu = jax.devices("cpu")[0]
        except Exception:
            pass
        with self.mm.pressure_host_grant():
            if cpu is not None:
                import jax
                with jax.default_device(cpu):
                    return thunk()
            return thunk()


def with_retry_no_split(fn: Callable[[], T], mm: Optional[MemoryManager]
                        = None, stats: Optional[RetryStats] = None, *,
                        retryable=None, ctx=None, op: Optional[str] = None,
                        host_fallback: Optional[Callable[[], T]] = None
                        ) -> T:
    """Run fn through the escalation ladder without splitting (ref
    withRetryNoSplit): RetryOOM -> spill+retry; SplitAndRetryOOM cannot
    be honored here, so it escalates straight to the pressure spill and
    then the host degradation rung (pre-r14 this was fatal)."""
    mm = mm or (ctx.memory if ctx is not None else MemoryManager.get())
    lad = _Ladder(mm, stats, retryable, ctx, op, host_fallback)
    attempts = 0
    budget = MAX_RETRIES
    while True:
        lad.check_cancelled()
        try:
            return fn()
        except RetryOOM as e:
            attempts += 1
            lad.note_retry(attempts)
            if attempts > budget:
                if not lad.pressured:
                    lad.pressure_spill()
                    budget = attempts + PRESSURE_ATTEMPTS
                    continue
                return lad.degrade(
                    fn, f"exceeded {attempts} OOM retries even after a "
                        f"cross-session pressure spill: {e}")
            mm.spill_device(0)
            time.sleep(0)  # yield so other tasks can release
        except SplitAndRetryOOM as e:
            lad.restore()
            if not lad.pressured:
                # a pressure spill can turn an unsatisfiable reserve into
                # a satisfiable one when other sessions held the budget
                lad.pressure_spill()
                budget = attempts + PRESSURE_ATTEMPTS
                continue
            return lad.degrade(
                fn, f"operation cannot split its input and the pressure "
                    f"spill did not free enough: {e}")


def split_batch_in_half(sb: SpillableBatch) -> List[SpillableBatch]:
    """Default splitter (ref RmmRapidsRetryIterator
    splitSpillableInHalfByRows).

    On success the input is consumed (closed) — the pieces replace it.
    On failure the pieces are closed but the INPUT STAYS OPEN: the
    retry ladder still owns it and may escalate (pressure spill, host
    degradation) with the data intact; pre-r14 a failed split closed
    the input too, so nothing above it could ever retry. A batch of
    < 2 rows raises OutOfDeviceMemory (unsplittable)."""
    pieces: List[SpillableBatch] = []
    try:
        batch = sb.get()
        n = batch.num_rows
        if n < 2:
            raise OutOfDeviceMemory("cannot split a batch with < 2 rows")
        mid = n // 2
        mm = sb.memory_manager
        pieces.append(SpillableBatch(batch.slice(0, mid), mm))
        pieces.append(SpillableBatch(batch.slice(mid, n - mid), mm))
    except BaseException:
        for p in pieces:
            p.close()
        raise
    sb.close()
    return pieces


def with_retry(inputs: List[SpillableBatch],
               fn: Callable[[SpillableBatch], T],
               mm: Optional[MemoryManager] = None,
               splitter: Callable = split_batch_in_half,
               stats: Optional[RetryStats] = None, *,
               retryable=None, ctx=None, op: Optional[str] = None,
               host_fallback: Optional[Callable] = None,
               max_split_depth: Optional[int] = None) -> Iterator[T]:
    """Process each spillable input through fn with the full escalation
    ladder (ref withRetry + RetryIterator). Yields one result per
    (possibly split) input piece, in order. Splitting is bounded by
    ``spark.rapids.tpu.oom.maxSplitDepth`` (or the ``max_split_depth``
    override); a piece that still cannot fit at max depth — or cannot
    split at all — escalates to the pressure spill and then runs on the
    host degradation rung (``host_fallback(item)`` when provided)."""
    mm = mm or (ctx.memory if ctx is not None else MemoryManager.get())
    lad = _Ladder(mm, stats, retryable, ctx, op, host_fallback)
    depth_cap = lad.max_split_depth(max_split_depth)
    queue: List[tuple] = [(sb, 0) for sb in inputs]
    item: Optional[SpillableBatch] = None
    try:
        while queue:
            item, depth = queue.pop(0)
            attempts = 0
            budget = MAX_RETRIES
            while True:
                lad.check_cancelled()
                try:
                    out = fn(item)
                    item = None
                    yield out
                    break
                except RetryOOM as e:
                    attempts += 1
                    lad.note_retry(attempts)
                    if attempts > budget:
                        if not lad.pressured:
                            lad.pressure_spill()
                            budget = attempts + PRESSURE_ATTEMPTS
                            continue
                        out = _degrade_item(lad, fn, item,
                                            f"retry limit exceeded after "
                                            f"pressure spill: {e}")
                        item = None
                        yield out
                        break
                    mm.spill_device(0)
                except SplitAndRetryOOM as e:
                    lad.note_split(attempts)
                    if depth >= depth_cap:
                        if not lad.pressured:
                            lad.pressure_spill()
                            continue
                        out = _degrade_item(
                            lad, fn, item,
                            f"split depth {depth} reached "
                            f"oom.maxSplitDepth={depth_cap}: {e}")
                        item = None
                        yield out
                        break
                    try:
                        pieces = splitter(item)
                    except (OutOfDeviceMemory, RetryOOM) as se:
                        # unsplittable (< 2 rows), or the split itself
                        # could not reserve its pieces even after the
                        # allocation-site absorb loop: either way the
                        # input is still open — escalate with the data
                        # intact instead of aborting the ladder
                        if not lad.pressured:
                            lad.pressure_spill()
                            continue
                        out = _degrade_item(lad, fn, item,
                                            f"split failed: {se}")
                        item = None
                        yield out
                        break
                    # process pieces in order before the rest of the queue
                    queue = [(p, depth + 1) for p in pieces] + queue
                    item = None
                    break
    except BaseException:
        # fatal error or abandoned consumer: the iterator owns every input
        # still queued — release them or they pin pool budget forever
        # (close() is idempotent, so an input fn already consumed is a
        # no-op; ref RmmRapidsRetryIterator closes its attempt on throw)
        if item is not None:
            item.close()
        for sb, _ in queue:
            sb.close()
        raise


def _degrade_item(lad: _Ladder, fn, item, detail: str):
    """Host-degradation rung for one queue item: the operator-provided
    fallback receives the item (it consumes it exactly like fn)."""
    if lad.host_fallback is not None:
        thunk = lambda: lad.host_fallback(item)   # noqa: E731
    else:
        thunk = lambda: fn(item)                  # noqa: E731
    return lad.degrade(thunk, detail, prefer_fallback=False)
