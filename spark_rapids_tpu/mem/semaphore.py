"""Device admission semaphore (ref GpuSemaphore.scala:51).

Gates how many tasks may have live device work at once
(spark.rapids.tpu.sql.concurrentTpuTasks); tracks wait time the way
GpuTaskMetrics records gpuSemaphoreWait (GpuTaskMetrics.scala:146).
"""
from __future__ import annotations

import threading
import time
import weakref
from contextlib import contextmanager

from ..trace import core as trace_core

__all__ = ["DeviceSemaphore"]

#: live semaphores, observed by the metrics sampler (queue depth / wait
#: totals across every in-flight query context); weak so a finished
#: query's semaphore just drops out of the sums
_SEMAPHORES: "weakref.WeakSet" = weakref.WeakSet()


class DeviceSemaphore:
    def __init__(self, permits: int, timeout_s: float = 600.0):
        self._permits = max(1, int(permits))
        self._sem = threading.BoundedSemaphore(self._permits)
        self._timeout = timeout_s
        self._lock = threading.Lock()
        self.total_wait_s = 0.0      # tpulint: guarded-by _lock
        self.acquires = 0            # tpulint: guarded-by _lock
        #: tasks currently blocked in acquire() (metrics queue depth)
        self.waiting = 0             # tpulint: guarded-by _lock
        self._held = threading.local()
        _SEMAPHORES.add(self)

    @property
    def permits(self) -> int:
        return self._permits

    def acquire(self):
        if getattr(self._held, "count", 0) > 0:
            self._held.count += 1  # reentrant per task thread
            return
        tr = trace_core.TRACER
        t0n = tr.now() if tr is not None else 0
        t0 = time.perf_counter()
        with self._lock:
            self.waiting += 1
        try:
            acquired = self._sem.acquire(timeout=self._timeout)
        finally:
            with self._lock:
                self.waiting -= 1
        if not acquired:
            if tr is not None:
                # the timed-out wait is the WORST contention case — the
                # profiler must see it, not just successful acquires
                tr.complete("semaphore.wait", t0n, cat="sem",
                            args={"permits": self._permits,
                                  "timeout": True})
            raise TimeoutError(
                f"device semaphore not acquired within {self._timeout}s")
        wait = time.perf_counter() - t0
        with self._lock:
            self.total_wait_s += wait
            self.acquires += 1
        if tr is not None:
            tr.complete("semaphore.wait", t0n, cat="sem",
                        args={"permits": self._permits})
        self._held.count = 1

    def release(self):
        c = getattr(self._held, "count", 0)
        if c <= 0:
            return
        if c == 1:
            self._sem.release()
        self._held.count = c - 1

    @contextmanager
    def held(self):
        self.acquire()
        try:
            yield self
        finally:
            self.release()
