"""Device admission semaphore (ref GpuSemaphore.scala:51).

Gates how many tasks may have live device work at once
(spark.rapids.tpu.sql.concurrentTpuTasks); tracks wait time the way
GpuTaskMetrics records gpuSemaphoreWait (GpuTaskMetrics.scala:146).

r14 adds the **wedge watchdog**: a waiter blocked past
``spark.rapids.tpu.semaphore.wedgeTimeoutMs`` wakes up, dumps a
holder/waiter/held-bytes diagnostic, and force-releases permits whose
holder THREAD is dead — a worker killed while holding the semaphore can
no longer wedge every later query (counted by
``srtpu_semaphore_wedge_total``). Waits also poll the query-lifecycle
``deadline`` (api/dataframe.py cooperative cancellation), so a timed-out
query never sits out the full task timeout inside acquire().
"""
from __future__ import annotations

import logging
import threading
import time
import weakref
from contextlib import contextmanager
from typing import Dict, List, Optional

from ..trace import core as trace_core

__all__ = ["DeviceSemaphore", "QueryTimeout", "wedged_census"]

log = logging.getLogger(__name__)

#: live semaphores, observed by the metrics sampler (queue depth / wait
#: totals across every in-flight query context); weak so a finished
#: query's semaphore just drops out of the sums
_SEMAPHORES: "weakref.WeakSet" = weakref.WeakSet()


def wedged_census() -> Dict[str, int]:
    """Dead/overdue holder counts across every live semaphore — the
    cheap process-wide wedge probe shared by the ops ``/healthz``
    semaphore verdict (ops/server.py) and the admission controller's
    shed check (sched/admission.py): a holder whose thread died, or
    one past the wedge horizon, means new low-priority work should be
    refused rather than queued behind a wedge."""
    dead = overdue = 0
    for s in list(_SEMAPHORES):
        d = s.diagnostics()
        horizon_s = (s.wedge_timeout_ms / 1000.0
                     if s.wedge_timeout_ms > 0 else None)
        for h in d["holders"]:
            if h.get("alive") is False:
                dead += 1
            elif horizon_s is not None and h["held_s"] >= horizon_s:
                overdue += 1
    return {"dead": dead, "overdue": overdue}


class QueryTimeout(RuntimeError):
    """The query's cooperative deadline (spark.rapids.tpu.query.timeout)
    expired: raised at batch boundaries and from semaphore waits so the
    query unwinds through the normal exception path — semaphore permits
    release via their ``with`` scopes and spillables close via the
    operators' cleanup handlers (the zero-leak audit holds)."""


class DeviceSemaphore:
    def __init__(self, permits: int, timeout_s: float = 600.0,
                 wedge_timeout_ms: int = 10000, memory=None):
        self._permits = max(1, int(permits))
        self._sem = threading.BoundedSemaphore(self._permits)
        self._timeout = timeout_s
        self.wedge_timeout_ms = int(wedge_timeout_ms)
        #: MemoryManager for held-bytes diagnostics (optional)
        self._memory = memory
        self._lock = threading.Lock()
        self.total_wait_s = 0.0      # tpulint: guarded-by _lock
        self.acquires = 0            # tpulint: guarded-by _lock
        #: tasks currently blocked in acquire() (metrics queue depth)
        self.waiting = 0             # tpulint: guarded-by _lock
        #: dead holders force-released by the wedge watchdog
        self.wedges = 0              # tpulint: guarded-by _lock
        #: thread ident -> {name, thread, since, count} for every live
        #: top-level holder (the watchdog's force-release census)
        self._holders: Dict[int, dict] = {}  # tpulint: guarded-by _lock
        self._held = threading.local()
        #: query-lifecycle deadline (time.monotonic() instant) polled by
        #: this THREAD's waits — thread-local, because sessions may share
        #: one semaphore (multi-tenant ExecContexts): a global attribute
        #: would let query A's timeout cancel query B's wait, and B's
        #: no-timeout reset would strip A's deadline mid-wait
        self._deadline = threading.local()
        _SEMAPHORES.add(self)

    def set_thread_deadline(self, deadline: Optional[float]) -> None:
        """Install (None clears) the calling thread's query deadline;
        acquire() waits on this thread poll it and raise QueryTimeout."""
        self._deadline.value = deadline

    @property
    def deadline(self) -> Optional[float]:
        return getattr(self._deadline, "value", None)

    @property
    def permits(self) -> int:
        return self._permits

    # ------------------------------------------------------------ acquire
    def acquire(self):
        if getattr(self._held, "count", 0) > 0:
            self._held.count += 1  # reentrant per task thread
            with self._lock:
                h = self._holders.get(threading.get_ident())
                if h is not None:
                    h["count"] += 1
            return
        self._maybe_watchdog()
        tr = trace_core.TRACER
        t0n = tr.now() if tr is not None else 0
        t0 = time.perf_counter()
        with self._lock:
            self.waiting += 1
        try:
            acquired = self._wait_acquire()
        finally:
            with self._lock:
                self.waiting -= 1
        if not acquired:
            if tr is not None:
                # the timed-out wait is the WORST contention case — the
                # profiler must see it, not just successful acquires
                tr.complete("semaphore.wait", t0n, cat="sem",
                            args={"permits": self._permits,
                                  "timeout": True})
            raise TimeoutError(
                f"device semaphore not acquired within {self._timeout}s; "
                f"diagnostics: {self.diagnostics()}")
        wait = time.perf_counter() - t0
        me = threading.current_thread()
        stale = None
        with self._lock:
            self.total_wait_s += wait
            self.acquires += 1
            old = self._holders.get(threading.get_ident())
            if old is not None and old.get("thread") is not None \
                    and old["thread"] is not me \
                    and not old["thread"].is_alive():
                # the OS recycled a dead holder's thread ident before
                # the watchdog saw it; overwriting the record would
                # orphan the dead thread's permit forever — reclaim it
                stale = old
                self.wedges += 1
            self._holders[threading.get_ident()] = {
                "name": me.name, "thread": me,
                "since": time.monotonic(), "count": 1}
        if stale is not None:
            try:
                self._sem.release()
            except ValueError:  # pragma: no cover - over-release race
                pass
            log.error("semaphore wedge: reclaimed permit of dead thread "
                      "%r whose ident was recycled", stale["name"])
            from ..metrics import registry as metrics_registry
            mr = metrics_registry.REGISTRY
            if mr is not None:
                mr.counter("srtpu_semaphore_wedge_total").inc()
            from ..ops import flight as flight_mod
            fr = flight_mod.RECORDER
            if fr is not None:
                fr.trigger("semaphore_wedge",
                           detail=f"reclaimed permit of dead thread "
                                  f"{stale['name']!r} (recycled ident); "
                                  f"diagnostics: {self.diagnostics()}")
        if tr is not None:
            tr.complete("semaphore.wait", t0n, cat="sem",
                        args={"permits": self._permits})
        self._held.count = 1
        # chaos site: a holder that stalls WITH the permit (the stuck-
        # holder scenario the wedge watchdog diagnoses; aux/fault.py)
        from ..aux.fault import active_chaos
        ctl = active_chaos()
        if ctl is not None and ctl.wants("sem.stall"):
            ctl.maybe_delay("sem.stall")

    def _wait_acquire(self) -> bool:
        """Bounded-step wait loop: wake at the wedge horizon to run the
        watchdog, and at the query deadline to cancel cooperatively.
        With the watchdog off and no deadline this is one plain
        acquire(timeout=task timeout), the pre-r14 behavior."""
        start = time.monotonic()
        wedge_s = (self.wedge_timeout_ms / 1000.0
                   if self.wedge_timeout_ms > 0 else None)
        while True:
            now = time.monotonic()
            remaining = self._timeout - (now - start)
            if remaining <= 0:
                return False
            step = remaining
            if wedge_s is not None:
                step = min(step, wedge_s)
            dl = self.deadline
            if dl is not None:
                dl_rem = dl - now
                if dl_rem <= 0:
                    raise QueryTimeout(
                        "query deadline expired while waiting on the "
                        f"device semaphore; diagnostics: "
                        f"{self.diagnostics()}")
                step = min(step, dl_rem)
            if self._sem.acquire(timeout=max(step, 0.001)):
                return True
            if wedge_s is not None \
                    and (time.monotonic() - start) >= wedge_s:
                self.check_wedged()

    # ----------------------------------------------------------- watchdog
    def _maybe_watchdog(self) -> None:
        """Cheap overdue-holder sweep on every top-level acquire: a dead
        holder of one of N permits silently halves capacity even when
        no single waiter ever starves past the wedge horizon — the
        starving-waiter path alone would never notice. One short
        lock'd scan (<= permits entries) per acquire."""
        if self.wedge_timeout_ms <= 0:
            return
        wedge_s = self.wedge_timeout_ms / 1000.0
        now = time.monotonic()
        with self._lock:
            overdue = any(now - h["since"] >= wedge_s
                          for h in self._holders.values())
        if overdue:
            self.check_wedged()

    def check_wedged(self) -> List[dict]:
        """Wedge watchdog pass: force-release permits whose holder
        thread is DEAD (it can never release; a killed worker must not
        wedge the semaphore forever) and dump holder/waiter diagnostics
        when anything looks stuck. Returns the force-released holder
        records. Safe to call from any thread (the sampler or a waiter);
        live holders are never touched — cooperative cancellation is the
        tool for those."""
        now = time.monotonic()
        released: List[dict] = []
        stuck = False
        wedge_s = self.wedge_timeout_ms / 1000.0 \
            if self.wedge_timeout_ms > 0 else None
        with self._lock:
            for tid, h in list(self._holders.items()):
                th = h.get("thread")
                if th is not None and not th.is_alive():
                    self._holders.pop(tid)
                    released.append(h)
                    self.wedges += 1
                elif wedge_s is not None and now - h["since"] >= wedge_s:
                    stuck = True
        for h in released:
            try:
                self._sem.release()
            except ValueError:  # pragma: no cover - over-release race
                log.error("semaphore force-release raced a real release "
                          "for holder %r", h["name"])
            log.error(
                "semaphore wedge: force-released permit held by DEAD "
                "thread %r (held %.1fs)", h["name"], now - h["since"])
            from ..metrics import registry as metrics_registry
            mr = metrics_registry.REGISTRY
            if mr is not None:
                mr.counter("srtpu_semaphore_wedge_total").inc()
        if released:
            # anomaly hook (ISSUE 15): a force-release previously left
            # its census only in the log — dump a flight bundle while
            # the holder table still shows the wedge
            from ..ops import flight as flight_mod
            fr = flight_mod.RECORDER
            if fr is not None:
                fr.trigger(
                    "semaphore_wedge",
                    detail=f"force-released {len(released)} permit(s) "
                           f"of dead holder(s) "
                           f"{[h['name'] for h in released]}; "
                           f"diagnostics: {self.diagnostics()}")
        if released or stuck:
            log.warning("semaphore diagnostics: %s", self.diagnostics())
        return released

    def diagnostics(self) -> dict:
        """Holder/waiter/held-bytes census for wedge dumps and timeout
        errors (the GpuSemaphore dump analog)."""
        now = time.monotonic()
        with self._lock:
            holders = [{"thread": h["name"], "ident": tid,
                        "alive": (h["thread"].is_alive()
                                  if h.get("thread") is not None else None),
                        "held_s": round(now - h["since"], 3),
                        "reentry": h["count"]}
                       for tid, h in self._holders.items()]
            waiting = self.waiting
            wedges = self.wedges
        out = {"permits": self._permits, "waiting": waiting,
               "holders": holders, "wedges": wedges}
        if self._memory is not None:
            out["memory"] = self._memory.stats()
        return out

    # ------------------------------------------------------------ release
    def release(self):
        c = getattr(self._held, "count", 0)
        if c <= 0:
            return
        if c == 1:
            with self._lock:
                self._holders.pop(threading.get_ident(), None)
            self._sem.release()
        else:
            with self._lock:
                h = self._holders.get(threading.get_ident())
                if h is not None:
                    h["count"] = c - 1
        self._held.count = c - 1

    @contextmanager
    def held(self):
        self.acquire()
        try:
            yield self
        finally:
            self.release()
