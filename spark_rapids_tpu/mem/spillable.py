"""SpillableBatch: a columnar batch that can migrate device -> host -> disk
and come back on demand.

Reference analog: SpillableColumnarBatch (SpillableColumnarBatch.scala:29) +
the tiered stores (RapidsDeviceMemoryStore / RapidsHostMemoryStore /
RapidsDiskStore). Device tier holds jax arrays (HBM); host tier holds an
Arrow table; disk tier holds an Arrow IPC file in the spill directory.
"""
from __future__ import annotations

import os
import threading
import uuid
from typing import Optional

from ..columnar import ColumnarBatch
from .manager import MemoryManager

__all__ = ["SpillableBatch", "SpillPriorities"]


class SpillPriorities:
    """Lower spills first (ref SpillPriorities.scala)."""
    OUTPUT_FOR_SHUFFLE = 0
    ACTIVE_BATCHING = 50
    ACTIVE_ON_DECK = 100


class SpillableBatch:
    """Wraps a ColumnarBatch; while registered it may be spilled by the
    MemoryManager at any time, `get()` migrates it back to device."""

    def __init__(self, batch: ColumnarBatch, mm: Optional[MemoryManager] = None,
                 spill_priority: int = SpillPriorities.ACTIVE_BATCHING):
        self._mm = mm or MemoryManager.get()
        self._lock = threading.RLock()
        self._batch: Optional[ColumnarBatch] = batch
        self._host_table = None           # pyarrow.Table when tier=host
        self._disk_path: Optional[str] = None
        self._disk_block: Optional[int] = None   # native store block id
        self._disk_bytes = 0
        self.tier = "device"
        self.spill_priority = spill_priority
        # keep a lazy count: forcing a device-scalar row count here
        # would cost a tunnel sync on every spillable wrap
        self._num_rows = batch.num_rows_raw
        self._cap = next((c.padded_len for c in batch.columns
                          if hasattr(c, "padded_len")), None)
        self.schema = batch.schema
        self._device_bytes = batch.device_size_bytes()
        #: True while the resident device bytes were admitted by an
        #: OOM_PRESSURE_HOST emergency grant instead of the budget —
        #: the matching release must come from the same pool
        self._granted = False        # tpulint: guarded-by _lock
        self._closed = False
        self._reserve_device(self._device_bytes)
        # register LAST: the moment the handle exists, another thread's
        # spill_device() may pick this batch up — every field the spill
        # paths read must already be published (the r14 concurrency
        # battery caught a half-constructed batch being spilled)
        self._handle = self._mm.register_spillable(self)
        #: creation site for the leak auditor (MemoryCleaner analog) —
        #: only captured in debug mode, a traceback walk per wrap is not
        #: free on the hot path
        self.created_at = None
        import os
        if os.environ.get("SRTPU_LEAK_DEBUG"):
            import traceback
            self.created_at = "".join(traceback.format_stack(limit=6)[:-1])

    def _reserve_device(self, nbytes: int) -> None:
        """Admit ``nbytes`` of device residency: through the budget with
        allocation-site RetryOOM absorption (spill-and-retry a bounded
        number of times before the OOM escapes — bare
        ``[SpillableBatch(b, mm) for b in ...]`` comprehensions survive
        transient pressure), or through the unbudgeted pressure pool when
        the creating thread runs under the escalation ladder's host
        degradation rung (mem/retry.py)."""
        self._device_bytes = nbytes
        if self._mm.in_pressure_grant():
            self._granted = True
            self._mm.reserve_granted(nbytes)
        else:
            self._granted = False
            self._mm.reserve_absorbing_retries(nbytes)

    def _release_device(self, nbytes: int) -> None:
        if self._granted:
            self._granted = False
            self._mm.release_granted(nbytes)
        else:
            self._mm.release(nbytes)

    @property
    def memory_manager(self) -> MemoryManager:
        """The manager accounting for this batch (public accessor —
        splitters re-wrap pieces under the SAME manager)."""
        return self._mm

    @property
    def num_rows(self) -> int:
        if not isinstance(self._num_rows, int):
            n = int(self._num_rows)
            if self._cap is not None and n > self._cap:
                from ..columnar.batch import SpeculativeOverflow
                raise SpeculativeOverflow(n, self._cap)
            self._num_rows = n
        return self._num_rows

    def device_bytes(self) -> int:
        """Device footprint when resident (size estimate for spill/split
        decisions, ref SpillableColumnarBatch.sizeInBytes)."""
        # tpulint: disable=lock-discipline — lock-free by design: a
        # single immutable-int read used as a sizing estimate
        return self._device_bytes

    @property
    def padded_len(self) -> int:
        """Shape-bucket length of the wrapped batch (static — known
        without materializing any tier)."""
        return self._cap if self._cap is not None else self.num_rows

    # ------------------------------------------------------------- migration
    def spill_to_host(self) -> int:
        with self._lock:
            if self.tier != "device" or self._closed:
                return 0
            self._host_table = self._batch.to_arrow()
            nbytes = self._device_bytes
            self._batch = None
            self.tier = "host"
            self._release_device(nbytes)
            self._mm.reserve_host(self._host_table.nbytes)
            self._mm.spill_to_host_bytes += nbytes
            return nbytes

    def spill_to_disk(self) -> int:
        import pyarrow as pa
        with self._lock:
            if self.tier != "host" or self._closed:
                return 0
            nbytes = self._host_table.nbytes
            store = self._native_store()
            if store is not None:
                # native slab block store (spill_store.cpp): append into
                # big shared files with CRC-verified read-back
                sink = pa.BufferOutputStream()
                with pa.ipc.new_file(sink, self._host_table.schema) as w:
                    w.write_table(self._host_table)
                data = sink.getvalue().to_pybytes()
                self._disk_block = store.write(data)
                self._mm.disk_used += len(data)
                self._disk_bytes = len(data)
            else:
                os.makedirs(self._mm.spill_dir, exist_ok=True)
                path = os.path.join(self._mm.spill_dir,
                                    f"spill-{uuid.uuid4().hex}.arrow")
                with pa.OSFile(path, "wb") as f:
                    with pa.ipc.new_file(f, self._host_table.schema) as w:
                        w.write_table(self._host_table)
                self._mm.disk_used += os.path.getsize(path)
                self._disk_path = path
            self._mm.release_host(nbytes)
            self._mm.spill_to_disk_bytes += nbytes
            self._host_table = None
            self.tier = "disk"
            return nbytes

    def _native_store(self):
        from .native_spill import get_store
        return get_store(self._mm.spill_dir)

    def _unspill(self) -> ColumnarBatch:
        """Migrate back to device. The device reservation happens BEFORE
        the source tier is dismantled: a failed reserve (real or injected
        RetryOOM) must leave this batch intact in its current tier — the
        pre-r14 order released the host table / freed the disk block
        first, so an OOM mid-unspill lost the only copy of the data."""
        import pyarrow as pa
        if self.tier == "host":
            table = self._host_table
            batch = ColumnarBatch.from_arrow(table)
            self._reserve_device(batch.device_size_bytes())  # may raise
            self._mm.release_host(table.nbytes)
            self._host_table = None
        elif self._disk_block is not None:
            data = self._native_store().read(self._disk_block)
            table = pa.ipc.open_file(pa.BufferReader(data)).read_all()
            batch = ColumnarBatch.from_arrow(table)
            self._reserve_device(batch.device_size_bytes())  # may raise
            self._native_store().free(self._disk_block)
            self._mm.disk_used -= self._disk_bytes
            self._disk_block, self._disk_bytes = None, 0
        else:  # per-file fallback tier
            with pa.memory_map(self._disk_path, "rb") as f:
                table = pa.ipc.open_file(f).read_all()
            batch = ColumnarBatch.from_arrow(table)
            self._reserve_device(batch.device_size_bytes())  # may raise
            try:
                self._mm.disk_used -= os.path.getsize(self._disk_path)
                os.unlink(self._disk_path)
            except OSError:
                pass
            self._disk_path = None
        self.tier = "device"
        return batch

    # ------------------------------------------------------------------- api
    def get(self) -> ColumnarBatch:
        """Materialize on device (migrating back if spilled)."""
        with self._lock:
            if self._closed:
                raise ValueError("closed SpillableBatch")
            if self.tier != "device":
                self._batch = self._unspill()
            return self._batch

    def size_bytes(self) -> int:
        # tpulint: disable=lock-discipline — lock-free by design: a
        # single immutable-int read used as a sizing estimate
        return self._device_bytes

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._mm.unregister_spillable(self._handle)
            if self.tier == "device":
                self._release_device(self._device_bytes)
            elif self.tier == "host" and self._host_table is not None:
                self._mm.release_host(self._host_table.nbytes)
                self._host_table = None
            elif self.tier == "disk" and self._disk_block is not None:
                self._native_store().free(self._disk_block)
                self._mm.disk_used -= self._disk_bytes
                self._disk_block = None
            elif self.tier == "disk" and self._disk_path:
                try:
                    self._mm.disk_used -= os.path.getsize(self._disk_path)
                    os.unlink(self._disk_path)
                except OSError:
                    pass
            self._batch = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
