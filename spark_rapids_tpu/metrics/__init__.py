"""Continuous-telemetry subsystem (ISSUE 5): the always-on metric
registry + background sampler (registry.py / sampler.py), Prometheus and
JSON exporters (export.py), the rotating query event log (events.py)
and the EXPLAIN ANALYZE renderer (analyze.py).

The process-global registry follows the tracer's one-branch-when-off
contract: instrumented sites read ``registry.REGISTRY`` and skip when it
is ``None``; ``ensure_metrics_from_conf`` installs it (and starts the
sampler) iff ``spark.rapids.tpu.metrics.enabled``. See
docs/monitoring.md for the metric catalog and event-log schema.
"""
from .registry import (Counter, Gauge, Histogram, MetricRegistry,
                       METRICS_ENABLED, METRICS_SAMPLE_INTERVAL_MS,
                       Summary, active_registry, declare_metric,
                       ensure_metrics_from_conf, install_metrics,
                       metric_inventory, shutdown_metrics)
from .sampler import (SAMPLER_THREAD_NAME, sample_now, sampler_thread,
                      start_sampler, stop_sampler)
from .sketch import QuantileSketch, fold_sketches
from .export import (SUMMARY_QUANTILES, json_text, merge_snapshots,
                     prometheus_text, registry_snapshot)
from .events import (ACTIVE_NAME, EVENT_LOG_DIR, EVENT_LOG_ENABLED,
                     EVENT_LOG_MAX_BYTES, EventLogWriter, plan_digest)
from .analyze import render_analyzed_plan

__all__ = ["Counter", "Gauge", "Histogram", "MetricRegistry", "Summary",
           "METRICS_ENABLED", "METRICS_SAMPLE_INTERVAL_MS",
           "active_registry", "declare_metric", "ensure_metrics_from_conf",
           "install_metrics", "metric_inventory", "shutdown_metrics",
           "SAMPLER_THREAD_NAME", "sample_now", "sampler_thread",
           "start_sampler", "stop_sampler", "json_text",
           "merge_snapshots", "prometheus_text", "registry_snapshot",
           "QuantileSketch", "fold_sketches", "SUMMARY_QUANTILES",
           "ACTIVE_NAME", "EVENT_LOG_DIR", "EVENT_LOG_ENABLED",
           "EVENT_LOG_MAX_BYTES", "EventLogWriter", "plan_digest",
           "render_analyzed_plan"]
