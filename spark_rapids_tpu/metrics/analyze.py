"""EXPLAIN ANALYZE rendering — the SQL-UI per-operator view.

``df.explain("analyze")`` executes the query, then renders the physical
plan annotated with per-operator output rows, batches, cumulative and
SELF time pulled from ``ExecContext.metrics`` (the GpuMetric registry
analog, GpuExec.scala:54-165). Cumulative time for a pipelined operator
includes the time spent pulling from its children (the iterator chain),
so self time is cumulative minus the children's cumulative, clamped at
zero — the same interval math the trace profiler uses on spans.

Lazy device row counts are forced through the metrics summary view's
single packed fetch, so rendering costs one tunnel round trip total,
not one per operator.
"""
from __future__ import annotations

from typing import Dict

__all__ = ["render_analyzed_plan", "record_learned_op_costs"]


def _fmt_count(v) -> str:
    if v is None:
        return "-"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    if f == int(f):
        return str(int(f))
    return f"{f:.2f}"


def _fmt_ms(seconds: float) -> str:
    return f"{seconds * 1000.0:.1f}ms"


#: physical exec class name -> learned-cost kind (plan/cost.node_kind's
#: key space): device execs and their CPU twins land on the SAME kind so
#: the cost model holds a device AND a host per-row price per operator
#: family. WholeStageExec keeps its own kind (it prices fused regions).
_EXEC_KIND = {
    "TpuFilterExec": "Filter", "CpuFilterExec": "Filter",
    "TpuProjectExec": "Project", "CpuProjectExec": "Project",
    "TpuHashAggregateExec": "Aggregate", "CpuAggregateExec": "Aggregate",
    "TpuHashJoinExec": "Join", "TpuBroadcastHashJoinExec": "Join",
    "TpuNestedLoopJoinExec": "Join", "CpuJoinExec": "Join",
    "TpuSortExec": "Sort", "CpuSortExec": "Sort",
    "TpuWindowExec": "Window", "CpuWindowExec": "Window",
    "TpuExpandExec": "Expand",
    "WholeStageExec": "WholeStageExec",
}


def record_learned_op_costs(physical, ctx, compile_free: bool) -> None:
    """Feed the per-operator SELF times this query measured into the
    cost model's learned per-operator row cost table (plan/cost.py
    _OP_COSTS) — the live feedback loop that replaces the static
    host/device per-row guesses with what this machine measured.

    Self time = cumulative opTime minus the children's cumulative (the
    EXPLAIN ANALYZE interval math); rows = the operator's INPUT rows
    (children's numOutputRows — the rows it processed, matching how the
    cost model charges nodes). Lazy device row counts (jax scalars) are
    SKIPPED rather than forced: this runs on every query and must never
    add a tunnel sync. record_op_wall's per-query sample gate
    (_OP_COST_SAMPLE_MIN_ROWS) drops dispatch-floor-dominated small
    runs; compile-laden runs are dropped wholesale (the exec-cache-hit
    keying).

    What a DEVICE self-time measures — deliberately: device kernels
    dispatch asynchronously (the host-sync-flow lint rule bans mid-pipeline
    forces), so a device operator's metered wall is its dispatch + any
    host-side prep, while the device wait drains in the sink's single
    packed fetch, which the per-query floor already prices. That makes
    the learned device s/row the operator's MARGINAL contribution to
    the query wall — the quantity the per-subtree host-vs-device
    comparison needs on a tunneled backend — not device occupancy.
    Device-BOUND shapes (where occupancy is the wall) are caught by the
    whole-query engine-wall arbitration and its symmetric exploration
    (plan/cost.py), never by per-node pricing. The distortion left:
    an operator that does sync per batch (the aggregate's speculation
    windows) absorbs its upstream chain's lazy work into its own self
    time — an overestimate, i.e. conservative for device placement."""
    from ..plan.cost import _OP_COST_SAMPLE_MIN_ROWS, record_op_wall

    def raw(node, name):
        m = (ctx.metrics.get(node._exec_id) or {}).get(name)
        v = m.value if m is not None else None
        return v if isinstance(v, (int, float)) else None

    # iterative traversal, deliberately: a recursive closure here would
    # be a function->cell reference cycle pinning ctx (and through it
    # every cached broadcast relation) until the next gc pass — the
    # suite's zero-leak audit relies on refcount-driven cleanup
    try:
        stack = [physical]
        while stack:
            node = stack.pop()
            stack.extend(node.children)
            kind = _EXEC_KIND.get(type(node).__name__)
            # WholeStageExec feeds its own measured dispatch wall from
            # inside execution (exec/wholestage.py) — never double-count
            if kind is None or kind == "WholeStageExec":
                continue
            if kind == "Aggregate" and getattr(node, "pre_stages", None):
                # folded filter/project stages run INSIDE this exec's
                # update kernel, so its self time covers THEIR work too
                # — but the planner still charges the logical Filter /
                # Project nodes their own learned costs on the same
                # rows. Learning "Aggregate" from a folded sample would
                # double-count the folded work in every device estimate
                # for exactly the q9 shapes this feed exists to flip.
                continue
            cum = raw(node, "opTime") or 0.0
            child_cum = sum(raw(c, "opTime") or 0.0
                            for c in node.children)
            self_s = max(0.0, float(cum) - float(child_cum))
            if node.children:
                rows = [raw(c, "numOutputRows") for c in node.children]
                rows_in = (sum(int(r) for r in rows)
                           if all(r is not None for r in rows) else None)
            else:
                r = raw(node, "numOutputRows")
                rows_in = int(r) if r is not None else None
            if rows_in and self_s > 0.0:
                record_op_wall(kind,
                               "device" if node.is_tpu else "host",
                               rows_in, self_s,
                               compile_free=compile_free,
                               min_rows=_OP_COST_SAMPLE_MIN_ROWS)
    except Exception:  # noqa: BLE001 - telemetry must never fail a query
        pass


def render_analyzed_plan(physical, ctx) -> str:
    """Physical tree string with per-operator metric annotations."""
    from ..aux.metrics import metrics_summary
    summary: Dict[str, dict] = dict(metrics_summary(ctx))

    def node_time(node) -> float:
        ms = summary.get(node._exec_id) or {}
        try:
            return float(ms.get("opTime", 0.0) or 0.0)
        except (TypeError, ValueError):
            return 0.0

    def fused_lines(node, indent: int) -> str:
        """Per-operator breakdown INSIDE a fused region
        (exec/wholestage.py): the fused ops are not children in the
        iterator chain, but the region records each one's output rows
        (exact, from the kernel's per-stage survivor counts) and its
        apportioned share of the fused dispatch wall — so EXPLAIN
        ANALYZE keeps per-op rows and self time through fusion."""
        out = ""
        for op in getattr(node, "fused_ops", ()):
            ms = summary.get(op._exec_id) or {}
            t = node_time(op)
            ann = (f"rows={_fmt_count(ms.get('numOutputRows'))} "
                   f"batches={_fmt_count(ms.get('numOutputBatches'))} "
                   f"self={_fmt_ms(t)}")
            out += "  " * (indent + 1) + f"+ {op.describe()} [{ann}]\n"
        return out

    def walk(node, indent: int) -> str:
        ms = summary.get(node._exec_id) or {}
        cum = node_time(node)
        child_cum = sum(node_time(c) for c in node.children)
        self_s = max(0.0, cum - child_cum)
        ann = (f"rows={_fmt_count(ms.get('numOutputRows'))} "
               f"batches={_fmt_count(ms.get('numOutputBatches'))} "
               f"time={_fmt_ms(cum)} self={_fmt_ms(self_s)}")
        marker = "*" if node.is_tpu else "!"
        line = "  " * indent + f"{marker} {node.describe()} [{ann}]\n"
        line += fused_lines(node, indent)
        return line + "".join(walk(c, indent + 1)
                              for c in node.children)

    return walk(physical, 0)
