"""EXPLAIN ANALYZE rendering — the SQL-UI per-operator view.

``df.explain("analyze")`` executes the query, then renders the physical
plan annotated with per-operator output rows, batches, cumulative and
SELF time pulled from ``ExecContext.metrics`` (the GpuMetric registry
analog, GpuExec.scala:54-165). Cumulative time for a pipelined operator
includes the time spent pulling from its children (the iterator chain),
so self time is cumulative minus the children's cumulative, clamped at
zero — the same interval math the trace profiler uses on spans.

Lazy device row counts are forced through the metrics summary view's
single packed fetch, so rendering costs one tunnel round trip total,
not one per operator.
"""
from __future__ import annotations

from typing import Dict

__all__ = ["render_analyzed_plan"]


def _fmt_count(v) -> str:
    if v is None:
        return "-"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    if f == int(f):
        return str(int(f))
    return f"{f:.2f}"


def _fmt_ms(seconds: float) -> str:
    return f"{seconds * 1000.0:.1f}ms"


def render_analyzed_plan(physical, ctx) -> str:
    """Physical tree string with per-operator metric annotations."""
    from ..aux.metrics import metrics_summary
    summary: Dict[str, dict] = dict(metrics_summary(ctx))

    def node_time(node) -> float:
        ms = summary.get(node._exec_id) or {}
        try:
            return float(ms.get("opTime", 0.0) or 0.0)
        except (TypeError, ValueError):
            return 0.0

    def fused_lines(node, indent: int) -> str:
        """Per-operator breakdown INSIDE a fused region
        (exec/wholestage.py): the fused ops are not children in the
        iterator chain, but the region records each one's output rows
        (exact, from the kernel's per-stage survivor counts) and its
        apportioned share of the fused dispatch wall — so EXPLAIN
        ANALYZE keeps per-op rows and self time through fusion."""
        out = ""
        for op in getattr(node, "fused_ops", ()):
            ms = summary.get(op._exec_id) or {}
            t = node_time(op)
            ann = (f"rows={_fmt_count(ms.get('numOutputRows'))} "
                   f"batches={_fmt_count(ms.get('numOutputBatches'))} "
                   f"self={_fmt_ms(t)}")
            out += "  " * (indent + 1) + f"+ {op.describe()} [{ann}]\n"
        return out

    def walk(node, indent: int) -> str:
        ms = summary.get(node._exec_id) or {}
        cum = node_time(node)
        child_cum = sum(node_time(c) for c in node.children)
        self_s = max(0.0, cum - child_cum)
        ann = (f"rows={_fmt_count(ms.get('numOutputRows'))} "
               f"batches={_fmt_count(ms.get('numOutputBatches'))} "
               f"time={_fmt_ms(cum)} self={_fmt_ms(self_s)}")
        marker = "*" if node.is_tpu else "!"
        line = "  " * indent + f"{marker} {node.describe()} [{ann}]\n"
        line += fused_lines(node, indent)
        return line + "".join(walk(c, indent + 1)
                              for c in node.children)

    return walk(physical, 0)
