"""Session-scoped structured event log (JSONL, rotating).

Reference analog: Spark's event log (spark.eventLog.enabled/dir) — the
durable query-history record the History Server and the spark-rapids
qualification/profiling tools replay. Each materializing query appends
a ``queryStart`` record (plan digest + config snapshot) and a
``queryEnd`` record (ok/failed, duration, TaskMetrics, fault stats,
trace-artifact path); ``tools/history`` renders and diffs the logs.

Format: one JSON object per line. The active file is
``events.jsonl``; when it exceeds ``rotate.maxBytes`` after a write it
is renamed to ``events-<seq>.jsonl`` (ascending seq = older). A
crash-truncated trailing line is tolerated by every reader
(tools/history skips undecodable lines and counts them).

Event-log writes must never fail a query: I/O errors are logged and
swallowed, exactly like trace-artifact writes.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
import weakref
from typing import List, Optional

from ..config import register

__all__ = ["EventLogWriter", "plan_digest", "writer_health",
           "EVENT_LOG_ENABLED", "EVENT_LOG_DIR", "EVENT_LOG_MAX_BYTES",
           "ACTIVE_NAME"]

log = logging.getLogger(__name__)

#: live writers, observed by the ops /healthz event-log-lag section;
#: weak so a closed session's writer just drops out of the census
_WRITERS: "weakref.WeakSet" = weakref.WeakSet()


def writer_health() -> List[dict]:
    """Per-writer write/error recency for the ops /healthz verdicts
    (ops/server.py): a writer whose newest attempt FAILED — or that has
    not landed a record in far too long — degrades the section."""
    out = []
    for w in list(_WRITERS):
        with w._lock:
            out.append({"dir": w.dir,
                        "lastWriteTs": w.last_write_ts,
                        "lastErrorTs": w.last_error_ts})
    return sorted(out, key=lambda d: d["dir"])

EVENT_LOG_ENABLED = register(
    "spark.rapids.tpu.eventLog.enabled", False,
    "Append a structured JSONL record per materialized query "
    "(queryStart: plan digest + config snapshot; queryEnd: status, "
    "duration, TaskMetrics, fault stats, trace-artifact path) to "
    "spark.rapids.tpu.eventLog.dir — the Spark event-log analog. "
    "Render/diff with python -m spark_rapids_tpu.tools.history "
    "(docs/monitoring.md).", commonly_used=True)

EVENT_LOG_DIR = register(
    "spark.rapids.tpu.eventLog.dir", "/tmp/srtpu_events",
    "Directory for the rotating query event log (created on first "
    "write).")

EVENT_LOG_MAX_BYTES = register(
    "spark.rapids.tpu.eventLog.rotate.maxBytes", 16 * 1024 * 1024,
    "The active events.jsonl rotates to events-<seq>.jsonl once it "
    "exceeds this many bytes (ascending seq = older records); <= 0 "
    "disables rotation.")

ACTIVE_NAME = "events.jsonl"


def plan_digest(plan) -> str:
    """Stable digest of a logical plan's structure — the join key for
    run-over-run regression diffs (tools/history --diff). Uses the
    plan's tree string, which renders structure + expressions but not
    data, so re-running the same query text matches across sessions."""
    return hashlib.sha256(
        plan.tree_string().encode("utf-8")).hexdigest()[:16]


class EventLogWriter:
    """Appends JSONL records with size-based rotation. Thread-safe;
    one writer per session (the session serializes queries anyway, but
    background samplers may interleave)."""

    def __init__(self, directory: str, max_bytes: int = 0):
        self.dir = directory
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._seq = self._next_seq()  # tpulint: guarded-by _lock
        #: wall-clock of the last successful append / failed attempt
        #: (the ops /healthz event-log-lag inputs)
        self.last_write_ts: Optional[float] = None  # tpulint: guarded-by _lock
        self.last_error_ts: Optional[float] = None  # tpulint: guarded-by _lock
        _WRITERS.add(self)

    @classmethod
    def from_conf(cls, conf) -> Optional["EventLogWriter"]:
        if not conf.get(EVENT_LOG_ENABLED):
            return None
        return cls(str(conf.get(EVENT_LOG_DIR)),
                   int(conf.get(EVENT_LOG_MAX_BYTES)))

    @property
    def active_path(self) -> str:
        return os.path.join(self.dir, ACTIVE_NAME)

    def _next_seq(self) -> int:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return 0
        seqs = []
        for n in names:
            if n.startswith("events-") and n.endswith(".jsonl"):
                try:
                    seqs.append(int(n[len("events-"):-len(".jsonl")]))
                except ValueError:
                    continue
        return max(seqs) + 1 if seqs else 0

    # tpulint: never-raise
    def write(self, record: dict) -> bool:
        """Append one record (stamped with a wall-clock ``ts``).
        Returns False — never raises — on I/O failure."""
        rec = dict(record)
        rec.setdefault("ts", round(time.time(), 6))
        try:
            line = json.dumps(rec, sort_keys=True, default=str) + "\n"
            with self._lock:
                os.makedirs(self.dir, exist_ok=True)
                with open(self.active_path, "a", encoding="utf-8") as f:
                    f.write(line)
                    f.flush()
                    size = f.tell()
                self.last_write_ts = time.time()
                if 0 < self.max_bytes < size:
                    self._rotate()
        except Exception as e:  # noqa: BLE001 - never fail a query
            log.warning("event log write to %s failed: %s",
                        self.dir, e)
            with self._lock:
                self.last_error_ts = time.time()
            return False
        from .registry import REGISTRY
        if REGISTRY is not None:
            REGISTRY.counter("srtpu_event_log_records_total").inc()
        return True

    def _rotate(self) -> None:
        # re-scan at rotation time: another writer sharing the
        # directory (two sessions, two processes) may have rotated
        # since construction — never os.replace() onto its records
        self._seq = max(self._seq, self._next_seq())
        dst = os.path.join(self.dir, f"events-{self._seq}.jsonl")
        os.replace(self.active_path, dst)
        self._seq += 1
