"""Exporters over registry snapshots: Prometheus text format + JSON.

Both exporters consume the plain-dict snapshot interchange format
(:meth:`MetricRegistry.snapshot`) rather than live metric objects, so
the driver can render snapshots shipped from worker processes without
reconstructing registries — the merged cluster view is just the same
snapshots with a ``worker`` label stamped on (:func:`merge_snapshots`).

Prometheus exposition follows the text format spec: ``# HELP`` /
``# TYPE`` headers, label values escaped (backslash, double quote,
newline), histograms as cumulative ``_bucket{le=...}`` series plus
``_sum`` / ``_count`` with the implicit ``+Inf`` bucket equal to
``_count``.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

from .registry import MetricRegistry, metric_inventory

__all__ = ["prometheus_text", "json_text", "merge_snapshots",
           "registry_snapshot", "SUMMARY_QUANTILES"]


def registry_snapshot(reg: MetricRegistry,
                      sample: bool = True) -> dict:
    """Snapshot with an optional synchronous sample pass first — gauges
    are current at read time even when the sampler thread is off."""
    if sample:
        from .sampler import sample_now
        sample_now(reg)
    return reg.snapshot()


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _fmt_exemplar(ex: dict) -> str:
    """OpenMetrics exemplar suffix: `` # {labels} value [ts]``. An
    exemplar links a tail observation back to its on-disk artifact
    (trace path, flight bundle) — the p99-outlier-to-evidence hop the
    SLO layer exists for (ops/slo.py, docs/monitoring.md)."""
    labels = _fmt_labels(dict(ex.get("labels") or {})) or "{}"
    out = f" # {labels} {_fmt_value(ex.get('value', 0))}"
    if ex.get("ts") is not None:
        out += f" {_fmt_value(ex['ts'])}"
    return out


#: the quantile ladder summaries expose (Summary.QUANTILES mirror —
#: exposition renders from snapshots, which don't carry class attrs)
SUMMARY_QUANTILES = (0.5, 0.95, 0.99)


def prometheus_text(snapshot: dict,
                    extra_labels: Optional[Dict[str, str]] = None) -> str:
    """Snapshot -> Prometheus exposition text. ``extra_labels`` are
    stamped on every series (the merged cluster view adds
    ``worker="worker-N"``)."""
    inv = metric_inventory()
    extra = dict(extra_labels or {})
    out: List[str] = []
    for name in sorted(k for k in snapshot if not k.startswith("__")):
        ent = snapshot[name]
        help_text = inv.get(name, {}).get("help", "")
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} {ent['kind']}")
        for s in ent["series"]:
            labels = dict(s.get("labels") or {})
            labels.update(extra)
            ex = s.get("exemplar")
            if ent["kind"] == "histogram":
                for le, c in s["buckets"]:
                    bl = dict(labels)
                    bl["le"] = (f"{le:g}" if isinstance(le, float)
                                else str(le))
                    out.append(f"{name}_bucket{_fmt_labels(bl)} {c}")
                bl = dict(labels)
                bl["le"] = "+Inf"
                out.append(
                    f"{name}_bucket{_fmt_labels(bl)} {s['count']}")
                out.append(f"{name}_sum{_fmt_labels(labels)} "
                           f"{_fmt_value(s['sum'])}")
                out.append(f"{name}_count{_fmt_labels(labels)} "
                           f"{s['count']}"
                           + (_fmt_exemplar(ex) if ex else ""))
            elif ent["kind"] == "summary":
                from .sketch import QuantileSketch
                sk = QuantileSketch.from_json(s.get("sketch") or {})
                for q in SUMMARY_QUANTILES:
                    ql = dict(labels)
                    ql["quantile"] = f"{q:g}"
                    out.append(f"{name}{_fmt_labels(ql)} "
                               f"{_fmt_value(sk.quantile(q))}")
                out.append(f"{name}_sum{_fmt_labels(labels)} "
                           f"{_fmt_value(s['sum'])}")
                out.append(f"{name}_count{_fmt_labels(labels)} "
                           f"{s['count']}"
                           + (_fmt_exemplar(ex) if ex else ""))
            else:
                out.append(f"{name}{_fmt_labels(labels)} "
                           f"{_fmt_value(s['value'])}"
                           + (_fmt_exemplar(ex) if ex else ""))
    return "\n".join(out) + ("\n" if out else "")


def json_text(snapshot: dict, indent: Optional[int] = 2) -> str:
    return json.dumps(snapshot, indent=indent, sort_keys=True,
                      default=float)


def merge_snapshots(snapshots: Dict[str, dict]) -> dict:
    """{lane_name: snapshot} -> one snapshot whose every series carries
    a ``worker`` label naming its source lane. Series are sorted, so
    the merged view is deterministic regardless of arrival order.

    Every lane is also stamped with its snapshot's capture instant: a
    ``srtpu_worker_last_seen_ms`` gauge series per worker plus a
    ``__lanes__`` metadata map. A dead (or wedged) worker's final
    counters keep being merged — re-emitting them as if fresh was the
    bug: now the exposition itself carries each lane's staleness, and
    the ops ``/healthz`` heartbeat-age verdicts read it."""
    out: Dict[str, dict] = {}
    lanes_meta: Dict[str, dict] = {}
    for lane in sorted(snapshots):
        snap = snapshots[lane] or {}
        ts = snap.get("__ts__")
        if ts is not None:
            lanes_meta[lane] = {
                "last_seen_ms": round(float(ts) * 1000.0, 1)}
        for name, ent in snap.items():
            if name.startswith("__"):
                continue
            dst = out.setdefault(name, {"kind": ent["kind"],
                                        "series": []})
            for s in ent["series"]:
                s2 = {k: v for k, v in s.items() if k != "labels"}
                labels = dict(s.get("labels") or {})
                labels["worker"] = lane
                s2["labels"] = labels
                dst["series"].append(s2)
    if lanes_meta:
        out["srtpu_worker_last_seen_ms"] = {"kind": "gauge", "series": [
            {"labels": {"worker": lane},
             "value": lanes_meta[lane]["last_seen_ms"]}
            for lane in sorted(lanes_meta)]}
    for ent in out.values():
        ent["series"].sort(key=lambda s: sorted(s["labels"].items()))
    if lanes_meta:
        out["__lanes__"] = lanes_meta
    return out
