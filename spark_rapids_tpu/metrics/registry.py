"""Process-global metric registry: counters, gauges, histograms.

Reference analog: the Spark metrics system (ExecutorMetrics + the
DropwizardReporter sinks) the reference plugin feeds its GPU memory /
spill / semaphore telemetry into — the long-lived, *between*-queries
view the SQL UI and qualification tools consume. Here a single
process-global :class:`MetricRegistry` plays that role, with
Prometheus-text and JSON exporters (export.py) and a background sampler
(sampler.py) snapshotting the runtime singletons.

Design contract (ISSUE 5, same shape as trace/core.py):

* **one branch when off** — instrumentation sites read the module
  global ``REGISTRY`` and skip entirely when it is ``None``; no conf
  lookup, no allocation, no lock on the disabled path;
* **declared inventory** — every shipped metric name is declared at
  import time in ``_INVENTORY`` with its kind and help text; creating
  an undeclared metric raises, so docs/monitoring.md and the
  ``metric-name-drift`` lint rule always check against a closed,
  honest catalog (the RapidsConf-registry pattern applied to metrics);
* **cheap when unread** — counters and gauges are a slot store plus a
  lock'd add; histograms bisect a short bucket ladder. Nothing is
  formatted, aggregated, or exported until somebody asks.
"""
from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..config import register

__all__ = ["MetricRegistry", "Counter", "Gauge", "Histogram", "Summary",
           "declare_metric", "metric_inventory", "active_registry",
           "install_metrics", "shutdown_metrics",
           "ensure_metrics_from_conf", "METRICS_ENABLED",
           "METRICS_SAMPLE_INTERVAL_MS"]

METRICS_ENABLED = register(
    "spark.rapids.tpu.metrics.enabled", False,
    "Maintain the process-global MetricRegistry (metrics/registry.py): "
    "always-on counters/gauges/histograms for HBM pressure, spill "
    "totals, semaphore contention, shuffle health and query outcomes, "
    "sampled by a background thread and exported as Prometheus text or "
    "JSON (docs/monitoring.md). Off by default: every instrumentation "
    "site is a single branch when disabled.", commonly_used=True)

METRICS_SAMPLE_INTERVAL_MS = register(
    "spark.rapids.tpu.metrics.sample.intervalMs", 1000,
    "Background sampler period for gauge snapshots (HBM used/budget, "
    "spill-store bytes, semaphore queue depth, shuffle block-store "
    "size). <= 0 disables the sampler thread; instrumented counters "
    "still record, and exporters run one synchronous sample pass so "
    "snapshots are never stale.")

#: Prometheus-style default latency buckets (seconds)
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

#: the process-global registry; ``None`` means metrics are OFF and every
#: instrumentation site costs exactly one attribute load + branch
REGISTRY: Optional["MetricRegistry"] = None

#: name -> {"kind", "help"[, "buckets"]}; the closed catalog every
#: registry enforces
_INVENTORY: Dict[str, Dict[str, object]] = {}


def declare_metric(name: str, kind: str, help_text: str,
                   buckets: Optional[Tuple[float, ...]] = None) -> str:
    """Declare a metric name in the process-wide inventory (import
    time). Idempotent for identical declarations; a kind conflict is a
    programming error and raises. ``buckets`` declares a histogram's
    per-metric bucket ladder — the fix for DEFAULT_BUCKETS saturating
    at 60 s while queries run to the 600 s timeout."""
    prev = _INVENTORY.get(name)
    if prev is not None and prev["kind"] != kind:
        raise ValueError(f"metric {name} redeclared as {kind}, "
                         f"was {prev['kind']}")
    ent: Dict[str, object] = {"kind": kind, "help": help_text}
    if buckets is not None:
        ent["buckets"] = tuple(sorted(buckets))
    _INVENTORY[name] = ent
    return name


def metric_inventory() -> Dict[str, Dict[str, str]]:
    """The declared catalog (docs/monitoring.md + metric-name-drift)."""
    return dict(_INVENTORY)


class Counter:
    """Monotone counter. ``set_total`` exists for mirror counters whose
    source of truth is an external cumulative total (e.g. the memory
    manager's spill_to_host_bytes) — the sampler overwrites rather than
    re-adding."""

    __slots__ = ("name", "labels", "value", "_lock")
    kind = "counter"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.labels = labels
        self.value = 0               # tpulint: guarded-by _lock
        self._lock = threading.Lock()

    def inc(self, n=1) -> None:
        with self._lock:
            self.value += n

    def set_total(self, v) -> None:
        with self._lock:
            self.value = v

    def set_max(self, v) -> None:
        """Monotone mirror for totals summed over WEAKLY-held sources
        (semaphores, block servers): a GC'd source drops out of the
        sum, and a decreasing counter would read as a reset to
        Prometheus rate()/increase() — hold the high-water mark
        instead."""
        with self._lock:
            if v > self.value:
                self.value = v


class Gauge:
    __slots__ = ("name", "labels", "value", "_lock")
    kind = "gauge"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.labels = labels
        self.value = 0               # tpulint: guarded-by _lock
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self.value = v

    def inc(self, n=1) -> None:
        with self._lock:
            self.value += n

    def dec(self, n=1) -> None:
        with self._lock:
            self.value -= n


class Histogram:
    """Cumulative-bucket histogram, Prometheus exposition semantics:
    ``bucket_counts[i]`` counts observations <= ``buckets[i]``; the
    implicit +Inf bucket is ``count``."""

    __slots__ = ("name", "labels", "buckets", "bucket_counts", "sum",
                 "count", "_lock")
    kind = "histogram"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 buckets=DEFAULT_BUCKETS):
        self.name = name
        self.labels = labels
        self.buckets = tuple(sorted(buckets))
        self.bucket_counts = [0] * len(self.buckets)  # tpulint: guarded-by _lock
        self.sum = 0.0               # tpulint: guarded-by _lock
        self.count = 0               # tpulint: guarded-by _lock
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            for j in range(i, len(self.bucket_counts)):
                self.bucket_counts[j] += 1
            self.sum += v
            self.count += 1


class Summary:
    """Quantile summary over a mergeable relative-error sketch
    (metrics/sketch.py). Exposed as Prometheus
    ``name{quantile="0.5|0.95|0.99"}`` lines plus ``_sum``/``_count``;
    snapshots carry the serialized sketch so ``merge_snapshots`` ships
    it worker-labeled like any other series and the driver can fold a
    cluster-wide tail without raw samples."""

    __slots__ = ("name", "labels", "sketch", "_lock")
    kind = "summary"

    #: the exported quantile ladder
    QUANTILES = (0.5, 0.95, 0.99)

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...]):
        from .sketch import QuantileSketch
        self.name = name
        self.labels = labels
        self.sketch = QuantileSketch()  # tpulint: guarded-by _lock
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.sketch.observe(v)


class MetricRegistry:
    """Thread-safe store of live metric instances, keyed on
    (name, sorted labels). Snapshots are plain dicts — the interchange
    format task-completion RPCs ship and the exporters consume."""

    def __init__(self):
        # tpulint: guarded-by _lock
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                            object] = {}
        # bounded-cardinality label admission per (metric, label) pair
        self._label_seen: Dict[Tuple[str, str],
                               set] = {}  # tpulint: guarded-by _lock
        self._lock = threading.Lock()

    def _get(self, cls, name: str, labels: dict, **kw):
        if name not in _INVENTORY:
            raise KeyError(
                f"metric {name!r} is not declared in the inventory — "
                "declare_metric() it (and document it in "
                "docs/monitoring.md) before use")
        if _INVENTORY[name]["kind"] != cls.kind:
            raise TypeError(f"metric {name} is declared as "
                            f"{_INVENTORY[name]['kind']}, not {cls.kind}")
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, key[1], **kw)
                self._metrics[key] = m
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, buckets=None,
                  **labels) -> Histogram:
        if buckets is None:
            buckets = (_INVENTORY.get(name, {}).get("buckets")
                       or DEFAULT_BUCKETS)
        return self._get(Histogram, name, labels, buckets=buckets)

    def summary(self, name: str, **labels) -> Summary:
        return self._get(Summary, name, labels)

    def bounded_label(self, name: str, label: str, value: str,
                      cap: int = 32) -> str:
        """Admit a label value under a per-(metric, label) cardinality
        cap: the first ``cap`` distinct values keep their identity,
        later ones collapse to ``"other"`` — an unbounded plan-digest
        stream must not mint unbounded series. Deterministic for a
        given observation order; reset with the registry (per-test
        ``shutdown_metrics``)."""
        value = str(value)
        key = (name, label)
        with self._lock:
            seen = self._label_seen.setdefault(key, set())
            if value in seen:
                return value
            if len(seen) < cap:
                seen.add(value)
                return value
        return "other"

    # ------------------------------------------------------------- read
    def snapshot(self) -> dict:
        """JSON-able {name: {kind, series: [...]}} snapshot plus a
        wall-clock stamp (the driver keeps the freshest of
        task-completion vs heartbeat snapshots per worker)."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, dict] = {"__ts__": time.time()}
        for m in metrics:
            ent = out.setdefault(m.name, {"kind": m.kind, "series": []})
            s = {"labels": dict(m.labels)}
            if m.kind == "histogram":
                with m._lock:
                    s["buckets"] = [[b, c] for b, c in
                                    zip(m.buckets, m.bucket_counts)]
                    s["sum"] = m.sum
                    s["count"] = m.count
            elif m.kind == "summary":
                with m._lock:
                    s["sketch"] = m.sketch.to_json()
                    # tpulint: disable=lock-discipline — lock-free by
                    # design: the summary's _lock (held here) is the
                    # sketch's guard; the sketch itself is unsynchronized
                    s["sum"] = m.sketch.sum
                    # tpulint: disable=lock-discipline — same guard
                    s["count"] = m.sketch.count
            else:
                with m._lock:
                    # a torn scalar read is survivable, but exporting a
                    # value mid-update while histograms are snapshotted
                    # consistently made the two families disagree
                    s["value"] = m.value
            ent["series"].append(s)
        for ent in out.values():
            if isinstance(ent, dict) and "series" in ent:
                ent["series"].sort(
                    key=lambda s: sorted(s["labels"].items()))
        return out


# ---------------------------------------------------------------------------
# installation (the trace/core.py pattern)
# ---------------------------------------------------------------------------

_INSTALL_LOCK = threading.Lock()


def active_registry() -> Optional[MetricRegistry]:
    # tpulint: disable=lock-discipline — lock-free by design: the
    # disabled-path contract is one unlocked reference read per site
    return REGISTRY


def install_metrics(reg: Optional[MetricRegistry]) -> \
        Optional[MetricRegistry]:
    """Install (or with ``None`` remove) the process-global registry."""
    global REGISTRY
    with _INSTALL_LOCK:
        REGISTRY = reg
    return reg


def shutdown_metrics() -> None:
    """Stop the sampler thread (if any) and uninstall the registry —
    the per-test reset (conftest) and the bench artifact teardown."""
    from .sampler import stop_sampler
    stop_sampler()
    install_metrics(None)


def ensure_metrics_from_conf(conf) -> Optional[MetricRegistry]:
    """Install a registry (and start the sampler) iff
    ``spark.rapids.tpu.metrics.enabled`` — the one conf lookup, paid per
    ExecContext construction, never per metric event."""
    global REGISTRY
    if not conf.get(METRICS_ENABLED):
        # tpulint: disable=lock-discipline — lock-free by design:
        # metrics-off fast path; installation itself locks below
        return REGISTRY
    with _INSTALL_LOCK:
        if REGISTRY is None:
            REGISTRY = MetricRegistry()
        reg = REGISTRY
    interval_ms = int(conf.get(METRICS_SAMPLE_INTERVAL_MS))
    if interval_ms > 0:
        from .sampler import start_sampler
        start_sampler(reg, interval_ms)
    return reg


# ---------------------------------------------------------------------------
# the shipped metric catalog (docs/monitoring.md mirrors this; the
# metric-name-drift lint rule enforces the mirror)
# ---------------------------------------------------------------------------

declare_metric("srtpu_hbm_used_bytes", "gauge",
               "Logical HBM bytes currently accounted by the memory "
               "manager(s), summed across budgets.")
declare_metric("srtpu_hbm_budget_bytes", "gauge",
               "Total HBM budget across memory manager instances.")
declare_metric("srtpu_hbm_max_used_bytes", "gauge",
               "High-water mark of accounted HBM bytes.")
declare_metric("srtpu_spill_store_host_bytes", "gauge",
               "Bytes currently held in the host spill tier.")
declare_metric("srtpu_spill_store_disk_bytes", "gauge",
               "Bytes currently held in the disk spill tier.")
declare_metric("srtpu_spill_to_host_bytes_total", "counter",
               "Cumulative bytes spilled device -> host.")
declare_metric("srtpu_spill_to_disk_bytes_total", "counter",
               "Cumulative bytes spilled host -> disk.")
declare_metric("srtpu_semaphore_queue_depth", "gauge",
               "Tasks currently blocked waiting on the device "
               "semaphore, summed across live semaphores.")
declare_metric("srtpu_semaphore_wait_seconds_total", "counter",
               "Cumulative seconds tasks spent waiting on the device "
               "semaphore.")
declare_metric("srtpu_semaphore_acquires_total", "counter",
               "Cumulative successful device-semaphore acquisitions.")
declare_metric("srtpu_shuffle_block_store_bytes", "gauge",
               "Serialized shuffle block bytes currently resident in "
               "this process's block store(s).")
declare_metric("srtpu_shuffle_block_store_blocks", "gauge",
               "Shuffle blocks currently resident in this process's "
               "block store(s).")
declare_metric("srtpu_shuffle_put_bytes_total", "counter",
               "Cumulative serialized bytes accepted by block-store "
               "puts.")
declare_metric("srtpu_shuffle_fetch_bytes_total", "counter",
               "Cumulative serialized bytes served by block-store "
               "fetches.")
declare_metric("srtpu_shuffle_crc_rejects_total", "counter",
               "Corrupt shuffle blocks rejected by CRC32C verification "
               "(never stored/served).")
declare_metric("srtpu_oom_retries_total", "counter",
               "RetryOOM events absorbed by the retry framework.")
declare_metric("srtpu_oom_splits_total", "counter",
               "SplitAndRetryOOM events (input halved and retried).")
declare_metric("srtpu_oom_pressure_spills_total", "counter",
               "Cross-session pressure spills: the escalation rung that "
               "spills EVERY live session's spillables before the host "
               "degradation rung (mem/retry.py ladder).")
declare_metric("srtpu_oom_host_fallback_total", "counter",
               "Operators (or whole queries, op=Query) degraded to the "
               "host backend by the final OOM escalation rung instead of "
               "failing — labeled op=<operator kind>; each is also "
               "recorded as an OOM_PRESSURE_HOST placement tag.")
declare_metric("srtpu_semaphore_wedge_total", "counter",
               "Dead device-semaphore holders force-released by the "
               "wedge watchdog (spark.rapids.tpu.semaphore."
               "wedgeTimeoutMs): a holder thread died without releasing "
               "and its permit was reclaimed.")
declare_metric("srtpu_query_timeout_total", "counter",
               "Queries cancelled by the spark.rapids.tpu.query.timeout "
               "cooperative deadline.")
declare_metric("srtpu_queries_total", "counter",
               "Materialized queries, labeled status=ok|failed.")
declare_metric("srtpu_query_seconds", "histogram",
               "Whole-query wall time distribution (seconds), labeled "
               "tenant=<id or 'default'>. Per-metric buckets extend to "
               "600 s so queries near spark.rapids.tpu.query.timeout "
               "are not collapsed into +Inf.",
               buckets=DEFAULT_BUCKETS + (120.0, 300.0, 600.0))
declare_metric("srtpu_sampler_ticks_total", "counter",
               "Background sampler passes completed.")
declare_metric("srtpu_compile_cache_hits_total", "counter",
               "In-process executable-cache hits: a kernel request "
               "served by an already-built jitted callable "
               "(plan/exec_cache.py) — zero retrace, zero compile.")
declare_metric("srtpu_compile_cache_misses_total", "counter",
               "In-process executable-cache misses (a new kernel was "
               "built; XLA compile may still be served by the "
               "persistent tier).")
declare_metric("srtpu_compile_persistent_hits_total", "counter",
               "Compiles served by the persistent on-disk executable "
               "tier (JAX compilation-cache deserialization) instead "
               "of a fresh XLA compile.")
declare_metric("srtpu_compile_seconds_total", "counter",
               "Cumulative XLA backend-compile seconds this process "
               "actually paid (persistent-tier hits pay none).")
declare_metric("srtpu_event_log_records_total", "counter",
               "Records appended to the session event log.")
declare_metric("srtpu_hbm_pressure_grant_bytes", "gauge",
               "Bytes currently admitted OUTSIDE the device budget under "
               "the rung-4 pressure host grant (mem/manager.py); any "
               "nonzero value means an emergency host degradation is in "
               "flight and degrades the ops /healthz memory verdict.")
declare_metric("srtpu_worker_last_seen_ms", "gauge",
               "Wall-clock milliseconds of each merged metric lane's "
               "newest snapshot (merge_snapshots stamps one series per "
               "worker label): the exposition itself says how stale a "
               "lane's counters are, and the ops /healthz worker "
               "verdicts read heartbeat age from it.")
declare_metric("srtpu_ops_requests_total", "counter",
               "HTTP requests served by the live ops endpoint, labeled "
               "endpoint=/metrics|/healthz|/queries|/slo "
               "(ops/server.py).")
declare_metric("srtpu_flight_dumps_total", "counter",
               "Flight-recorder bundles written, labeled "
               "trigger=<kind from the ops/flight.py closed taxonomy> "
               "(semaphore_wedge, oom_ladder, query_timeout, "
               "worker_evicted, warm_recompile, placement_revert, "
               "sentinel_regression, admission_shed, slo_burn — "
               "docs/ops.md); rate-limited suppressions are not "
               "counted.")
declare_metric("srtpu_query_regressions_total", "counter",
               "Regressions flagged by the per-digest sentinel, labeled "
               "kind=warm_slowdown|verdict_flip|rung_escalation|"
               "tail_regression (ops/sentinel.py, docs/ops.md).")
declare_metric("srtpu_placement_fallback_total", "counter",
               "Operators/expressions kept off the device at plan time, "
               "labeled code=<reason code from the plan/tags.py closed "
               "registry> and op=<logical operator>; incremented once "
               "per executed query with that query's PlacementReport "
               "tag counts (docs/placement.md).")
declare_metric("srtpu_admission_admitted_total", "counter",
               "Queries admitted through the multi-tenant admission "
               "controller (sched/admission.py), labeled tenant=<id or "
               "'default'>; only counted when spark.rapids.tpu."
               "admission.enabled is on (docs/serving.md).")
declare_metric("srtpu_admission_rejected_total", "counter",
               "Admissions refused with AdmissionRejected, labeled "
               "reason=queue_full|deadline|shed|chaos "
               "(sched/admission.py, docs/serving.md).")
declare_metric("srtpu_admission_wait_seconds", "histogram",
               "Time admitted queries spent queued in the admission "
               "controller before their permit (seconds), labeled "
               "tenant=<id or 'default'>.")
declare_metric("srtpu_admission_queue_depth", "gauge",
               "Queries currently queued in the admission controller "
               "waiting for an in-flight slot (sampler snapshot).")
declare_metric("srtpu_tenant_hbm_used_bytes", "gauge",
               "Device-tier spillable bytes attributed to each tenant "
               "by the memory manager's ownership census, labeled "
               "tenant=<id> (mem/manager.py, docs/serving.md).")
declare_metric("srtpu_tenant_hbm_quota_bytes", "gauge",
               "Per-tenant HBM quota in bytes (spark.rapids.tpu."
               "tenant.hbmShare x the device budget), labeled "
               "tenant=<id>; 0 rows are not exported.")
declare_metric("srtpu_aqe_replans_total", "counter",
               "Adaptive-execution decisions recorded by the AQE log, "
               "labeled kind=<decision kind from the aqe/ closed "
               "taxonomy: coalesce_partitions|skew_split|"
               "broadcast_demote|broadcast_promote|cost_replan|"
               "feedback_replan> (aqe/__init__.py, docs/aqe.md).")
declare_metric("srtpu_aqe_coalesced_partitions_total", "counter",
               "Shuffle partitions merged into larger reduce units by "
               "AQE coalescing (cluster boundary re-planning plus the "
               "single-process adaptive reader).")
declare_metric("srtpu_aqe_skew_splits_total", "counter",
               "Sub-partitions created by AQE skew splits (salted "
               "re-partition of oversized shuffle partitions; for "
               "shuffled joins both sides split co-partitioned).")
declare_metric("srtpu_aqe_broadcast_demotions_total", "counter",
               "Broadcast build sides observed LARGER than the "
               "auto-broadcast threshold at materialization; the "
               "measured size re-plans the next run of the shape to a "
               "shuffled join (exec/joins.py, docs/aqe.md).")
declare_metric("srtpu_query_latency_seconds", "summary",
               "Whole-query wall time quantile summary (relative-error "
               "sketch, metrics/sketch.py), labeled tenant=<id or "
               "'default'>; exported as quantile=0.5|0.95|0.99 lines "
               "and mergeable across workers (docs/monitoring.md).")
declare_metric("srtpu_digest_latency_seconds", "summary",
               "Per-plan-digest wall time quantile summary, labeled "
               "digest=<plan digest, bounded cardinality — past the "
               "cap new digests collapse into digest=\"other\">; the "
               "tail-contribution ranking /slo serves reads it.")
declare_metric("srtpu_admission_wait_latency_seconds", "summary",
               "Admission-queue wait quantile summary, labeled "
               "tenant=<id or 'default'> (sched/admission.py, "
               "docs/serving.md).")
declare_metric("srtpu_worker_task_seconds", "summary",
               "Worker-side task wall time quantile summary, labeled "
               "task=<worker task name> (shuffle/cluster.py); per-lane "
               "sketches merge into the cluster-wide task tail.")
declare_metric("srtpu_slo_events_total", "counter",
               "Queries folded into the SLO tracker (ops/slo.py), "
               "labeled tenant=<id or 'default'> and status=good|bad "
               "(bad = over the tenant's latency target or failed).")
declare_metric("srtpu_slo_burn_rate", "gauge",
               "Error-budget burn rate per tenant and window, labeled "
               "tenant=<id> window=short|long; 1.0 burns the budget "
               "exactly at the objective's allowance, >1 burns faster "
               "(ops/slo.py, docs/serving.md).")
declare_metric("srtpu_slo_error_budget_remaining", "gauge",
               "Fraction of the long-window error budget left per "
               "tenant, labeled tenant=<id>; 1.0 = untouched, 0.0 = "
               "exhausted (ops/slo.py).")
declare_metric("srtpu_slo_burn_alerts_total", "counter",
               "Multi-window SLO burn alerts fired, labeled "
               "tenant=<id>; each also fires the flight recorder's "
               "slo_burn trigger (ops/slo.py, docs/ops.md).")
