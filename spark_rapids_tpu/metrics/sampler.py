"""Background gauge sampler for the metric registry.

One daemon thread (``srtpu-metrics-sampler``) snapshots the runtime
singletons — memory managers, device semaphores, shuffle block stores —
into registry gauges every ``spark.rapids.tpu.metrics.sample.intervalMs``.
Exporters also call :func:`sample_now` synchronously, so a snapshot is
never staler than the moment it was asked for even with the thread
disabled (interval <= 0).

The sources are observed non-invasively: ``MemoryManager._instances``
is the existing singleton table, semaphores and block servers register
into weak sets at construction — a dead query's semaphore or a closed
server just drops out of the sums.
"""
from __future__ import annotations

import threading
from typing import Optional

from .registry import MetricRegistry

__all__ = ["start_sampler", "stop_sampler", "sample_now",
           "sampler_thread", "SAMPLER_THREAD_NAME"]

SAMPLER_THREAD_NAME = "srtpu-metrics-sampler"

_LOCK = threading.Lock()
_THREAD: Optional[threading.Thread] = None  # tpulint: guarded-by _LOCK
_STOP = threading.Event()


def sample_now(reg: MetricRegistry) -> None:
    """One synchronous sample pass: set every sampled gauge (and mirror
    the cumulative spill totals) from the live runtime singletons.
    Gauges are always set — a worker that never spilled still exports a
    zero series, so fleet dashboards have a lane per process."""
    from ..mem.manager import MemoryManager
    from ..mem import semaphore as sem_mod
    from ..shuffle import transport as transport_mod

    mm = MemoryManager.stats_all()
    reg.gauge("srtpu_hbm_used_bytes").set(mm["device_used"])
    reg.gauge("srtpu_hbm_budget_bytes").set(mm["budget"])
    reg.gauge("srtpu_hbm_max_used_bytes").set(mm["max_device_used"])
    reg.gauge("srtpu_spill_store_host_bytes").set(mm["host_used"])
    reg.gauge("srtpu_spill_store_disk_bytes").set(mm["disk_used"])
    # rung-4 emergency pool: nonzero means a host degradation is live
    # (the ops /healthz memory verdict reads the same accounting)
    reg.gauge("srtpu_hbm_pressure_grant_bytes").set(
        mm["pressure_granted"])
    reg.counter("srtpu_spill_to_host_bytes_total").set_total(
        mm["spill_to_host_bytes"])
    reg.counter("srtpu_spill_to_disk_bytes_total").set_total(
        mm["spill_to_disk_bytes"])
    # per-tenant HBM ownership census + quotas (ISSUE 18): one labeled
    # series per tenant that owns device-tier spillables or has a quota
    for t, used in (mm.get("tenant_used") or {}).items():
        reg.gauge("srtpu_tenant_hbm_used_bytes", tenant=t).set(used)
    for t, quota in (mm.get("tenant_quota") or {}).items():
        reg.gauge("srtpu_tenant_hbm_quota_bytes", tenant=t).set(quota)

    # per-tenant SLO burn/budget gauges (ISSUE 20): re-evaluated from
    # the current clock so burn rates decay on /metrics as bad events
    # age out of their windows, not only when a new query lands
    from ..ops import slo as slo_mod
    slo = slo_mod.TRACKER
    if slo is not None:
        slo.export_gauges(reg)

    from ..sched import admission as adm_mod
    adm = adm_mod.CONTROLLER
    if adm is not None:
        # the racy accessor, NOT stats(): a flight bundle's metrics
        # section runs this pass from inside the controller's reject
        # path — taking the admission lock here could deadlock
        reg.gauge("srtpu_admission_queue_depth").set(adm.queue_depth())

    sems = list(sem_mod._SEMAPHORES)
    reg.gauge("srtpu_semaphore_queue_depth").set(
        sum(s.waiting for s in sems))
    # set_max, not set_total: semaphores/servers are weakly held, so a
    # GC'd one falling out of the sum must not make the counter drop
    reg.counter("srtpu_semaphore_wait_seconds_total").set_max(
        round(sum(s.total_wait_s for s in sems), 6))
    reg.counter("srtpu_semaphore_acquires_total").set_max(
        sum(s.acquires for s in sems))

    servers = list(transport_mod._SERVERS)
    blocks = 0
    nbytes = 0
    rejects = 0
    for srv in servers:
        b, n = srv.store_stats()
        blocks += b
        nbytes += n
        rejects += srv.crc_rejects
    reg.gauge("srtpu_shuffle_block_store_blocks").set(blocks)
    reg.gauge("srtpu_shuffle_block_store_bytes").set(nbytes)
    reg.counter("srtpu_shuffle_crc_rejects_total").set_max(rejects)


def _run(reg: MetricRegistry, interval_s: float) -> None:
    ticks = reg.counter("srtpu_sampler_ticks_total")
    # tpulint: disable=lock-discipline — lock-free by design:
    # threading.Event is self-synchronizing; wait() must not hold _LOCK
    while not _STOP.wait(interval_s):
        try:
            sample_now(reg)
            ticks.inc()
        except Exception:  # pragma: no cover - must never kill the thread
            pass


def start_sampler(reg: MetricRegistry, interval_ms: int) -> None:
    """Start the daemon sampler thread (idempotent)."""
    global _THREAD
    with _LOCK:
        if _THREAD is not None and _THREAD.is_alive():
            return
        _STOP.clear()
        _THREAD = threading.Thread(
            target=_run, args=(reg, max(0.01, interval_ms / 1000.0)),
            name=SAMPLER_THREAD_NAME, daemon=True)
        _THREAD.start()


def stop_sampler() -> None:
    """Stop and join the sampler thread (per-test reset)."""
    global _THREAD
    with _LOCK:
        t, _THREAD = _THREAD, None
        _STOP.set()
    if t is not None and t.is_alive():
        t.join(timeout=5.0)


def sampler_thread() -> Optional[threading.Thread]:
    """The live sampler thread, or None (test assertions that the
    disabled path never starts one)."""
    # tpulint: disable=lock-discipline — lock-free by design: a racy
    # snapshot of the reference is fine for an observability probe
    t = _THREAD
    return t if (t is not None and t.is_alive()) else None
