"""Deterministic relative-error quantile sketch (DDSketch family).

The tail-latency substrate (ISSUE 20): a ``Summary`` metric's storage.
Log-boundary buckets give a *relative* accuracy guarantee — the
estimate of any quantile q is within ``alpha`` (default 1%) of the true
value, whether that value is 5 ms or 500 s — which is exactly the
property fixed-boundary histograms lose when a latency distribution
outgrows its ladder (the ``DEFAULT_BUCKETS``-saturation bug this PR
fixes for ``srtpu_query_seconds``).

Three contracts everything downstream leans on:

* **deterministic** — bucket keys are a pure function of the value and
  ``alpha``; quantile estimates are a pure function of the bucket
  contents. Same observations (any order, any grouping) -> identical
  JSON, identical quantiles. The 3-worker merge test and the SLO
  replay (``tools/history --slo``) both pin this.
* **mergeable** — :meth:`merge` sums bucket counts; merging per-worker
  sketches equals one sketch that saw every observation. This is what
  lets ``merge_snapshots`` ship sketches as plain series dicts and the
  driver fold a cluster-wide p99 without raw samples.
* **JSON-serializable** — :meth:`to_json` / :meth:`from_json` round-trip
  through the snapshot interchange format (plain dicts, string bucket
  keys) so sketches ride task-completion RPCs, ``SERVE_r*.json``
  artifacts and sentinel baselines unchanged.

Memory is bounded: at most ``max_bins`` live buckets; on overflow the
*lowest* buckets collapse into one (tail accuracy is the product; the
cheap end degrades first).
"""
from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["QuantileSketch", "DEFAULT_ALPHA", "fold_sketches"]

#: default relative accuracy (1%): p99 of a 10 s tail is known +-100 ms
DEFAULT_ALPHA = 0.01

#: values at or below this collapse into the zero bucket (sub-nanosecond
#: latencies carry no signal and their log keys would be huge negatives)
MIN_VALUE = 1e-9

#: live-bucket cap; ~2048 buckets span MIN_VALUE..1e9 s at alpha=0.01
DEFAULT_MAX_BINS = 2048


class QuantileSketch:
    """Mergeable log-boundary quantile sketch.

    Bucket key of a value v is ``ceil(log(v) / log(gamma))`` with
    ``gamma = (1 + alpha) / (1 - alpha)``; every value in bucket k lies
    in ``(gamma^(k-1), gamma^k]`` and is estimated by the bucket
    midpoint ``2 * gamma^k / (gamma + 1)`` — within ``alpha`` of the
    true value, relatively.

    NOT thread-safe by itself; the registry's ``Summary`` wraps it in a
    lock. Pure-Python, stdlib-only, deterministic.
    """

    __slots__ = ("alpha", "gamma", "_log_gamma", "max_bins", "bins",
                 "zero_count", "count", "sum", "min", "max")

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 max_bins: int = DEFAULT_MAX_BINS):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.alpha = float(alpha)
        self.gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._log_gamma = math.log(self.gamma)
        self.max_bins = int(max_bins)
        self.bins: Dict[int, int] = {}
        self.zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # ------------------------------------------------------------ write
    def key_of(self, v: float) -> int:
        """The bucket key of a positive value (pure, deterministic)."""
        return int(math.ceil(math.log(v) / self._log_gamma))

    def observe(self, v: float) -> None:
        v = float(v)
        if math.isnan(v):
            return
        if v < 0.0:
            v = 0.0
        if v <= MIN_VALUE:
            self.zero_count += 1
        else:
            k = self.key_of(v)
            self.bins[k] = self.bins.get(k, 0) + 1
            if len(self.bins) > self.max_bins:
                self._collapse()
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def _collapse(self) -> None:
        """Fold the lowest buckets together until within ``max_bins``.
        Collapsing low keys preserves tail (high-quantile) accuracy."""
        keys = sorted(self.bins)
        while len(keys) > self.max_bins:
            lo, nxt = keys[0], keys[1]
            self.bins[nxt] += self.bins.pop(lo)
            keys.pop(0)

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into self (commutative + associative on the
        bucket contents: any merge order yields identical state)."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with different alpha "
                f"({self.alpha} vs {other.alpha})")
        for k, c in other.bins.items():
            self.bins[k] = self.bins.get(k, 0) + c
        if len(self.bins) > self.max_bins:
            self._collapse()
        self.zero_count += other.zero_count
        self.count += other.count
        self.sum += other.sum
        if other.min < self.min:
            self.min = other.min
        if other.max > self.max:
            self.max = other.max
        return self

    # ------------------------------------------------------------- read
    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0 <= q <= 1); 0.0 when empty."""
        if self.count <= 0:
            return 0.0
        q = min(1.0, max(0.0, float(q)))
        rank = q * (self.count - 1)
        cum = self.zero_count
        if rank < cum:
            return 0.0
        for k in sorted(self.bins):
            cum += self.bins[k]
            if rank < cum:
                return 2.0 * (self.gamma ** k) / (self.gamma + 1.0)
        # numerically-unreachable fallback: the recorded maximum
        return self.max if self.max > -math.inf else 0.0

    def quantiles(self, qs: Iterable[float]) -> List[float]:
        return [self.quantile(q) for q in qs]

    # ------------------------------------------------ JSON interchange
    def to_json(self) -> dict:
        """Plain-dict form (string bucket keys — JSON object keys)."""
        return {"alpha": self.alpha,
                "bins": {str(k): c for k, c in sorted(self.bins.items())},
                "zero": self.zero_count,
                "count": self.count,
                "sum": self.sum,
                "min": self.min if self.count else None,
                "max": self.max if self.count else None}

    @classmethod
    def from_json(cls, doc: dict, max_bins: int = DEFAULT_MAX_BINS) \
            -> "QuantileSketch":
        sk = cls(alpha=float(doc.get("alpha", DEFAULT_ALPHA)),
                 max_bins=max_bins)
        for k, c in (doc.get("bins") or {}).items():
            sk.bins[int(k)] = int(c)
        sk.zero_count = int(doc.get("zero", 0))
        sk.count = int(doc.get("count", 0))
        sk.sum = float(doc.get("sum", 0.0))
        mn, mx = doc.get("min"), doc.get("max")
        sk.min = float(mn) if mn is not None else math.inf
        sk.max = float(mx) if mx is not None else -math.inf
        if len(sk.bins) > sk.max_bins:
            sk._collapse()
        return sk


def fold_sketches(docs: Iterable[Optional[dict]]) -> QuantileSketch:
    """Merge serialized sketch dicts (e.g. per-worker summary series
    from ``merge_snapshots``) into one sketch. ``None`` entries are
    skipped; an empty input folds to an empty sketch."""
    out: Optional[QuantileSketch] = None
    for doc in docs:
        if not doc:
            continue
        sk = QuantileSketch.from_json(doc)
        if out is None:
            out = sk
        else:
            out.merge(sk)
    return out if out is not None else QuantileSketch()
