// Native memory-accounting + per-thread OOM state machine.
//
// Reference analog: the RmmSpark JNI layer (com.nvidia.spark.rapids.jni.RmmSpark,
// consumed by RmmRapidsRetryIterator.scala:27): a concurrent native state
// machine that (a) tracks a logical HBM budget, (b) lets one task's failed
// reservation BLOCK its thread until another task frees memory or a spill
// completes, (c) injects RetryOOM / SplitAndRetryOOM faults at exact
// reservation counts for the retry test suites, and (d) records per-thread
// retry metrics. The Python MemoryManager binds this via ctypes
// (mem/native.py) and keeps a pure-Python twin for environments without a
// compiler; semantics are identical by test.
//
// Thread model: any number of Python task threads; all state guarded by one
// mutex + condvar (reservation paths are not hot: they run once per batch,
// not per element).
//
// Return codes for oom_reserve:
//   0 = reserved
//   1 = RetryOOM   (caller should spill and retry)
//   2 = SplitAndRetryOOM (caller must split its input)
//   3 = timed out waiting for memory (treated as RetryOOM by the binding)

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <chrono>
#include <map>
#include <mutex>
#include <vector>

namespace {

struct Injection {
  int kind;      // 1 = retry, 2 = split
  long skip;     // reservations to let through first
  long count;    // how many faults to raise after the skips
};

struct ThreadState {
  long task_id = -1;
  long retry_count = 0;
  long split_count = 0;
  long blocked_ns = 0;
  bool blocked = false;
  std::vector<Injection> injections;
};

struct Globals {
  std::mutex mu;
  std::condition_variable cv;
  int64_t budget = 0;
  int64_t used = 0;
  int64_t max_used = 0;
  int64_t host_used = 0;
  long blocked_threads = 0;
  std::map<int64_t, ThreadState> threads;
};

Globals g;

ThreadState& state_for(int64_t tid) {
  return g.threads[tid];  // default-constructs on first touch
}

// returns 0 = no injection, 1 = retry, 2 = split
int consume_injection(ThreadState& ts) {
  if (ts.injections.empty()) return 0;
  Injection& inj = ts.injections.front();
  if (inj.skip > 0) {
    inj.skip--;
    return 0;
  }
  int kind = inj.kind;
  if (--inj.count <= 0) {
    ts.injections.erase(ts.injections.begin());
  }
  if (kind == 1) ts.retry_count++;
  else ts.split_count++;
  return kind;
}

}  // namespace

extern "C" {

void oom_init(int64_t budget_bytes) {
  std::lock_guard<std::mutex> lk(g.mu);
  g.budget = budget_bytes;
  g.used = 0;
  g.max_used = 0;
  g.host_used = 0;
  g.threads.clear();
}

void oom_set_budget(int64_t budget_bytes) {
  std::lock_guard<std::mutex> lk(g.mu);
  g.budget = budget_bytes;
  g.cv.notify_all();
}

void oom_register_thread(int64_t tid, long task_id) {
  std::lock_guard<std::mutex> lk(g.mu);
  state_for(tid).task_id = task_id;
}

void oom_unregister_thread(int64_t tid) {
  std::lock_guard<std::mutex> lk(g.mu);
  g.threads.erase(tid);
}

// Reserve nbytes. If it does not fit: wait up to block_ms for another thread
// to release memory (the RmmSpark block/wake behaviour); if still failing,
// report RetryOOM so the caller runs a spill-and-retry cycle.
int oom_reserve(int64_t tid, int64_t nbytes, long block_ms) {
  std::unique_lock<std::mutex> lk(g.mu);
  ThreadState& ts = state_for(tid);
  int inj = consume_injection(ts);
  if (inj != 0) return inj;
  if (nbytes > g.budget) return 2;  // can never fit: split required
  auto fits = [&] { return g.used + nbytes <= g.budget; };
  if (!fits() && block_ms > 0) {
    auto t0 = std::chrono::steady_clock::now();
    ts.blocked = true;
    g.blocked_threads++;
    bool ok = g.cv.wait_for(lk, std::chrono::milliseconds(block_ms), fits);
    g.blocked_threads--;
    ts.blocked = false;
    ts.blocked_ns += std::chrono::duration_cast<std::chrono::nanoseconds>(
        std::chrono::steady_clock::now() - t0).count();
    if (!ok) return 3;
  }
  if (!fits()) return 1;
  g.used += nbytes;
  if (g.used > g.max_used) g.max_used = g.used;
  return 0;
}

void oom_release(int64_t nbytes) {
  std::lock_guard<std::mutex> lk(g.mu);
  g.used -= nbytes;
  if (g.used < 0) g.used = 0;
  g.cv.notify_all();
}

void oom_host_reserve(int64_t nbytes) {
  std::lock_guard<std::mutex> lk(g.mu);
  g.host_used += nbytes;
}

void oom_host_release(int64_t nbytes) {
  std::lock_guard<std::mutex> lk(g.mu);
  g.host_used -= nbytes;
  if (g.host_used < 0) g.host_used = 0;
}

void oom_force_retry_oom(int64_t tid, long num_ooms, long skip) {
  std::lock_guard<std::mutex> lk(g.mu);
  state_for(tid).injections.push_back({1, skip, num_ooms});
}

void oom_force_split_and_retry_oom(int64_t tid, long num_ooms, long skip) {
  std::lock_guard<std::mutex> lk(g.mu);
  state_for(tid).injections.push_back({2, skip, num_ooms});
}

void oom_clear_injections() {
  std::lock_guard<std::mutex> lk(g.mu);
  for (auto& kv : g.threads) kv.second.injections.clear();
}

int64_t oom_get_used() {
  std::lock_guard<std::mutex> lk(g.mu);
  return g.used;
}

int64_t oom_get_max_used() {
  std::lock_guard<std::mutex> lk(g.mu);
  return g.max_used;
}

int64_t oom_get_host_used() {
  std::lock_guard<std::mutex> lk(g.mu);
  return g.host_used;
}

int64_t oom_get_budget() {
  std::lock_guard<std::mutex> lk(g.mu);
  return g.budget;
}

long oom_get_blocked_threads() {
  std::lock_guard<std::mutex> lk(g.mu);
  return g.blocked_threads;
}

long oom_get_retry_count(int64_t tid) {
  std::lock_guard<std::mutex> lk(g.mu);
  auto it = g.threads.find(tid);
  return it == g.threads.end() ? 0 : it->second.retry_count;
}

long oom_get_split_count(int64_t tid) {
  std::lock_guard<std::mutex> lk(g.mu);
  auto it = g.threads.find(tid);
  return it == g.threads.end() ? 0 : it->second.split_count;
}

int64_t oom_get_blocked_ns(int64_t tid) {
  std::lock_guard<std::mutex> lk(g.mu);
  auto it = g.threads.find(tid);
  return it == g.threads.end() ? 0 : it->second.blocked_ns;
}

}  // extern "C"
