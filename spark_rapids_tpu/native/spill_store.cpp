// Native disk spill block store (ref RapidsDiskStore.scala:38 +
// RapidsDiskBlockManager: the reference's disk tier writes through a
// JVM-managed block manager; here a C++ slab store owns the files).
//
// Design: spill data is appended into large SLAB files (default 128 MiB)
// instead of one file per batch — far fewer inode operations and no
// per-batch open/close on the hot spill path. Freed blocks return to a
// per-slab free accounting; a slab whose bytes are fully freed is
// truncated and recycled. Every block carries a CRC32 computed at write
// and verified at read (failure detection for silent disk corruption —
// SURVEY.md aux subsystems).
//
// C API (ctypes-consumed; no pybind11 in this environment):
//   sp_open(dir, slab_bytes)            -> store*
//   sp_write(store, buf, len)           -> block id (>=0) or -1
//   sp_block_size(store, id)            -> stored payload length
//   sp_read(store, id, buf, cap)        -> bytes read, -1 bad id, -2 CRC
//   sp_free(store, id)                  -> 0/-1
//   sp_stats(store, out[4])             -> {live_blocks, live_bytes,
//                                           slab_files, file_bytes}
//   sp_close(store)
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include <sys/stat.h>
#include <unistd.h>

namespace {

uint32_t crc32(const uint8_t* data, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : (c >> 1);
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Block {
  int slab;
  int64_t offset;
  int64_t length;
  uint32_t crc;
};

struct Slab {
  std::string path;
  FILE* f = nullptr;
  int64_t write_pos = 0;   // append cursor
  int64_t live_bytes = 0;  // not-yet-freed payload bytes
};

struct Store {
  std::mutex mu;
  std::string dir;
  int64_t slab_bytes;
  std::vector<Slab> slabs;
  std::map<int64_t, Block> blocks;
  int64_t next_id = 0;
};

Slab* slab_for_append(Store* s, int64_t need) {
  for (auto& sl : s->slabs) {
    if (sl.f && sl.write_pos + need <= s->slab_bytes) return &sl;
    // recycle fully-freed slabs
    if (sl.f && sl.live_bytes == 0 && sl.write_pos > 0) {
      if (ftruncate(fileno(sl.f), 0) == 0) {
        sl.write_pos = 0;
        if (need <= s->slab_bytes) return &sl;
      }
    }
  }
  Slab sl;
  char name[96];
  // pid + store address in the name: stores sharing a directory (other
  // processes, or several managers in one process) never collide — the
  // old fixed names truncated each other's live data via "w+b"
  snprintf(name, sizeof(name), "/spill-slab-%d-%p-%zu.bin",
           (int)getpid(), (void*)s, s->slabs.size());
  sl.path = s->dir + name;
  sl.f = fopen(sl.path.c_str(), "w+b");
  if (!sl.f) return nullptr;
  s->slabs.push_back(sl);
  return &s->slabs.back();
}

}  // namespace

extern "C" {

void* sp_open(const char* dir, int64_t slab_bytes) {
  auto* s = new Store();
  s->dir = dir;
  s->slab_bytes = slab_bytes > 0 ? slab_bytes : (128LL << 20);
  ::mkdir(dir, 0777);  // best effort; caller pre-creates parents
  return s;
}

int64_t sp_write(void* store, const uint8_t* buf, int64_t len) {
  auto* s = static_cast<Store*>(store);
  std::lock_guard<std::mutex> g(s->mu);
  Slab* sl = slab_for_append(s, len);
  if (!sl) return -1;
  if (fseeko(sl->f, sl->write_pos, SEEK_SET) != 0) return -1;
  if ((int64_t)fwrite(buf, 1, (size_t)len, sl->f) != len) return -1;
  fflush(sl->f);
  Block b;
  b.slab = (int)(sl - s->slabs.data());
  b.offset = sl->write_pos;
  b.length = len;
  b.crc = crc32(buf, (size_t)len);
  sl->write_pos += len;
  sl->live_bytes += len;
  int64_t id = s->next_id++;
  s->blocks[id] = b;
  return id;
}

int64_t sp_block_size(void* store, int64_t id) {
  auto* s = static_cast<Store*>(store);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->blocks.find(id);
  return it == s->blocks.end() ? -1 : it->second.length;
}

int64_t sp_read(void* store, int64_t id, uint8_t* buf, int64_t cap) {
  auto* s = static_cast<Store*>(store);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->blocks.find(id);
  if (it == s->blocks.end()) return -1;
  const Block& b = it->second;
  if (cap < b.length) return -1;
  Slab& sl = s->slabs[b.slab];
  if (fseeko(sl.f, b.offset, SEEK_SET) != 0) return -1;
  if ((int64_t)fread(buf, 1, (size_t)b.length, sl.f) != b.length) return -1;
  if (crc32(buf, (size_t)b.length) != b.crc) return -2;
  return b.length;
}

int sp_free(void* store, int64_t id) {
  auto* s = static_cast<Store*>(store);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->blocks.find(id);
  if (it == s->blocks.end()) return -1;
  s->slabs[it->second.slab].live_bytes -= it->second.length;
  s->blocks.erase(it);
  return 0;
}

void sp_stats(void* store, int64_t out[4]) {
  auto* s = static_cast<Store*>(store);
  std::lock_guard<std::mutex> g(s->mu);
  int64_t live = 0;
  for (auto& kv : s->blocks) live += kv.second.length;
  int64_t fbytes = 0;
  for (auto& sl : s->slabs) fbytes += sl.write_pos;
  out[0] = (int64_t)s->blocks.size();
  out[1] = live;
  out[2] = (int64_t)s->slabs.size();
  out[3] = fbytes;
}

void sp_close(void* store) {
  auto* s = static_cast<Store*>(store);
  for (auto& sl : s->slabs) {
    if (sl.f) fclose(sl.f);
    ::unlink(sl.path.c_str());
  }
  delete s;
}

}  // extern "C"
