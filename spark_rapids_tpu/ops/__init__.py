"""Live operations plane (ISSUE 15).

The runtime's *serving-side* observability surface — the role the Spark
live UI / history server plus the RAPIDS profiling tool play for the
reference stack, collapsed into three cooperating modules:

* :mod:`.server` — a stdlib ``http.server`` daemon thread (gated by
  ``spark.rapids.tpu.ops.port``) serving ``/metrics`` (Prometheus
  exposition, cluster-merged when a LocalCluster is live), ``/healthz``
  (JSON verdicts over the semaphore, memory tiers, exec cache, worker
  heartbeats and event-log lag) and ``/queries`` (in-flight + recent
  queries with digest, placement verdict, elapsed and ladder rung);
* :mod:`.flight` — an anomaly-triggered flight recorder: a bounded
  always-on diagnostic ring plus trigger hooks at the PR-14 anomaly
  sites (semaphore wedge, OOM ladder rung >= 3, query timeout,
  chaos-free worker eviction) and two detectors (warm-digest recompile,
  placement revert) that atomically dump ONE redacted bundle directory
  per trigger, rate-limited per trigger kind;
* :mod:`.sentinel` — a per-digest regression sentinel folding every
  ``queryEnd`` into rolling baselines (median wall, compile seconds,
  placement verdict, ladder rung) and flagging warm-digest slowdowns,
  verdict flips and new rung-3+ escalations.

Contract (the trace/metrics pattern): when nothing is configured the
plane installs NO threads and every instrumented site costs one
module-global load + branch.
"""
from __future__ import annotations

__all__ = ["ensure_ops_plane_from_conf", "shutdown_ops_plane"]


def ensure_ops_plane_from_conf(conf):
    """Install the configured pieces of the ops plane (server, flight
    recorder, sentinel, SLO tracker) — one conf lookup each, paid per
    ExecContext construction, never per event. Returns (server,
    recorder, sentinel), any of which may be None; the SLO tracker is
    installed as the ``ops.slo.TRACKER`` module global."""
    from .flight import ensure_flight_from_conf
    from .sentinel import ensure_sentinel_from_conf
    from .server import ensure_ops_from_conf
    from .slo import ensure_slo_from_conf
    srv = ensure_ops_from_conf(conf)
    rec = ensure_flight_from_conf(conf)
    sen = ensure_sentinel_from_conf(conf)
    ensure_slo_from_conf(conf)
    return srv, rec, sen


def shutdown_ops_plane() -> None:
    """Stop the ops server thread (if any) and uninstall the flight
    recorder, sentinel and SLO tracker — the per-test reset
    (conftest)."""
    from .flight import install_flight
    from .sentinel import install_sentinel
    from .server import shutdown_ops
    from .slo import install_slo
    shutdown_ops()
    install_flight(None)
    install_sentinel(None)
    install_slo(None)
