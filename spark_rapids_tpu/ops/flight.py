"""Anomaly-triggered flight recorder (ISSUE 15).

Reference analog: the diagnostic artifacts the RAPIDS Profiling tool
mines after the fact — except cut *at the moment of the anomaly*, while
the wedged holder table, the pressure-grant pool and the trace ring
still show the failure. The PR-14 watchdogs detect wedges, OOM ladders
and timeouts but dump their diagnostics only into exception strings;
this module turns each of those sites into a trigger hook that writes
ONE self-contained bundle directory.

Trigger taxonomy (closed — :data:`TRIGGERS`; docs/ops.md):

* ``semaphore_wedge``    — the wedge watchdog force-released a dead
  holder's permit (mem/semaphore.py);
* ``oom_ladder``         — an OOM escalation reached rung >= 3 (the
  cross-session pressure spill or the host degradation rung,
  mem/retry.py / the query-level ladder);
* ``query_timeout``      — a query was cancelled by the cooperative
  ``spark.rapids.tpu.query.timeout`` deadline;
* ``worker_evicted``     — the driver evicted a worker that chaos did
  NOT deliberately kill (shuffle/cluster.py);
* ``warm_recompile``     — backend-compile seconds were observed on a
  plan digest in the compiled-plan set (a warm digest paid a compile it
  was vouched never to pay again);
* ``placement_revert``   — a digest whose history says device planned
  host (fired by the regression sentinel's verdict-flip check);
* ``sentinel_regression``— any other sentinel flag (warm-digest
  slowdown, new rung-3+ escalation);
* ``admission_shed``     — a burst of admission rejections past the
  controller's rate threshold (``spark.rapids.tpu.admission.shed.*``):
  the bundle names the pressured section the shed verdict blamed
  (sched/admission.py, docs/serving.md).

Bundle layout — five sections, written atomically (a temp directory
renamed into place, so a reader never sees a partial bundle):

* ``trace.json``     — the tracer ring tail plus the recorder's own
  breadcrumb ring;
* ``metrics.json``   — a metric-registry snapshot (after one
  synchronous sample pass), or null when metrics are off;
* ``state.json``     — semaphore holder/waiter diagnostics, memory-tier
  accounting (pressure-grant pool included) and executable-cache
  counters;
* ``placement.json`` — the trigger, detail, and the current query's
  digest + coded PlacementReport summary when one is in flight;
* ``config.json``    — the conf delta from registered defaults,
  redacted (secret-shaped keys keep their names, lose their values).

Dumps are rate-limited per trigger kind
(``spark.rapids.tpu.flight.rateLimitMs``) and counted by
``srtpu_flight_dumps_total{trigger=...}``. Disabled
(``spark.rapids.tpu.flight.enabled`` off) the recorder is ``None`` and
every trigger site costs one module-global load + branch.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..config import register

__all__ = ["FlightRecorder", "TRIGGERS", "install_flight",
           "ensure_flight_from_conf", "active_flight", "FLIGHT_ENABLED",
           "FLIGHT_DIR", "FLIGHT_RATE_LIMIT_MS", "FLIGHT_RING_EVENTS"]

log = logging.getLogger(__name__)

FLIGHT_ENABLED = register(
    "spark.rapids.tpu.flight.enabled", False,
    "Arm the anomaly-triggered flight recorder (ops/flight.py): "
    "semaphore wedges, OOM ladder rungs >= 3, query timeouts, "
    "chaos-free worker evictions, warm-digest recompiles and placement "
    "reverts each atomically dump one redacted diagnostic bundle "
    "directory (trace ring tail, metrics snapshot, semaphore/memory/"
    "exec-cache state, placement report, config delta) under "
    "spark.rapids.tpu.flight.dir, rate-limited per trigger kind "
    "(docs/ops.md). Off by default: every trigger site is a single "
    "branch when disabled.", commonly_used=True)

FLIGHT_DIR = register(
    "spark.rapids.tpu.flight.dir", "/tmp/srtpu_flight",
    "Directory flight-recorder bundles are written under (one "
    "subdirectory per dump, created on first trigger).")

FLIGHT_RATE_LIMIT_MS = register(
    "spark.rapids.tpu.flight.rateLimitMs", 60000,
    "Minimum milliseconds between two bundles of the SAME trigger kind; "
    "suppressed triggers are counted (FlightRecorder.stats) but write "
    "nothing. <= 0 disables rate limiting.")

FLIGHT_RING_EVENTS = register(
    "spark.rapids.tpu.flight.ring.events", 256,
    "Capacity of the recorder's always-on breadcrumb ring (anomaly "
    "notes kept in memory between dumps; the newest tail ships inside "
    "every bundle's trace.json).")

#: closed trigger taxonomy — an unknown kind is a programming error and
#: raises (the plan/tags.py idiom: structurally impossible to ship an
#: undocumented trigger)
TRIGGERS = ("semaphore_wedge", "oom_ladder", "query_timeout",
            "worker_evicted", "warm_recompile", "placement_revert",
            "sentinel_regression", "admission_shed", "slo_burn")

#: the process-global recorder; ``None`` means the flight recorder is
#: OFF and every trigger site costs exactly one attribute load + branch
RECORDER: Optional["FlightRecorder"] = None

#: substrings marking a conf key as secret-bearing: the bundle keeps the
#: key (operators need to know it was set) but redacts the value
_SECRET_TOKENS = ("secret", "password", "passwd", "token", "credential",
                  "apikey", "api.key", "auth")


def redact_conf(raw: dict) -> dict:
    """Copy of a raw conf dict with secret-shaped values replaced."""
    out = {}
    for k in sorted(raw):
        kl = str(k).lower()
        if any(t in kl for t in _SECRET_TOKENS):
            out[str(k)] = "<redacted>"
        else:
            out[str(k)] = str(raw[k])
    return out


class FlightRecorder:
    """Bounded diagnostic ring + atomic bundle writer. Thread-safe;
    triggers never raise into their (already-failing) call sites —
    bundle-write errors are logged and swallowed."""

    def __init__(self, directory: str, rate_limit_ms: int = 60000,
                 ring_events: int = 256, conf=None):
        self.dir = str(directory)
        self.rate_limit_ms = int(rate_limit_ms)
        #: conf the recorder was installed from (the config.json delta)
        self._conf = conf
        self._lock = threading.Lock()
        #: always-on breadcrumb ring, oldest dropped
        self._ring: deque = deque(
            maxlen=max(16, int(ring_events)))  # tpulint: guarded-by _lock
        self._last: Dict[str, float] = {}    # tpulint: guarded-by _lock
        self._seq = 0                        # tpulint: guarded-by _lock
        self.dumps: Dict[str, int] = {}      # tpulint: guarded-by _lock
        self.suppressed: Dict[str, int] = {}  # tpulint: guarded-by _lock
        #: paths of every bundle written, oldest first
        self.bundles: List[str] = []         # tpulint: guarded-by _lock
        #: the in-flight query on THIS thread (set by _execute_wrapped):
        #: {"queryId", "planDigest", "placement", "startedMonotonic"}
        self._query = threading.local()

    # ------------------------------------------------------------- notes
    # tpulint: never-raise
    def note(self, kind: str, **info) -> None:
        """Append one breadcrumb to the always-on ring (never dumps)."""
        ev = {"ts": round(time.time(), 6), "kind": str(kind)}
        if info:
            ev["info"] = info
        with self._lock:
            self._ring.append(ev)

    def ring_tail(self, n: int = 256) -> List[dict]:
        with self._lock:
            buf = list(self._ring)
        return buf[-n:]

    # ----------------------------------------------------- query context
    def set_query(self, info: Optional[dict]) -> None:
        """Install (None clears) the calling thread's in-flight query
        summary so anomaly dumps fired from this thread carry the
        query's digest and placement report."""
        self._query.info = info

    def query_context(self) -> Optional[dict]:
        return getattr(self._query, "info", None)

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        with self._lock:
            return {"dumps": dict(self.dumps),
                    "suppressed": dict(self.suppressed),
                    "bundles": list(self.bundles)}

    # ----------------------------------------------------------- trigger
    # tpulint: never-raise
    def trigger(self, kind: str, detail: str = "",
                query: Optional[dict] = None) -> Optional[str]:
        """Fire one trigger: rate-limit per kind, then atomically write
        a bundle directory. Returns the bundle path, or None when
        rate-limited or the write failed (never raises)."""
        if kind not in TRIGGERS:
            # tpulint: disable=never-raise — an unregistered kind is a
            # PROGRAMMING error caught by the taxonomy tests, not a
            # runtime failure of a failing call site; it must be loud
            raise ValueError(
                f"unknown flight trigger {kind!r}; registered kinds: "
                f"{TRIGGERS} (ops/flight.py — add it to the taxonomy "
                "and docs/ops.md first)")
        now = time.monotonic()
        with self._lock:
            last = self._last.get(kind)
            if (self.rate_limit_ms > 0 and last is not None
                    and (now - last) * 1000.0 < self.rate_limit_ms):
                self.suppressed[kind] = self.suppressed.get(kind, 0) + 1
                return None
            self._last[kind] = now
            self._seq += 1
            seq = self._seq
        self.note("flight.trigger", trigger=kind, detail=detail[:200])
        if query is None:
            query = self.query_context()
        try:
            path = self._write_bundle(kind, detail, seq, query)
        except Exception as e:  # noqa: BLE001 - never fail the caller
            log.warning("flight recorder could not write a %s bundle "
                        "under %s: %s", kind, self.dir, e)
            with self._lock:
                # a FAILED write must not consume the rate-limit
                # window: the next real anomaly of this kind (possibly
                # after the disk recovers) still deserves its bundle
                if self._last.get(kind) == now:
                    if last is not None:
                        self._last[kind] = last
                    else:
                        self._last.pop(kind, None)
            return None
        with self._lock:
            self.dumps[kind] = self.dumps.get(kind, 0) + 1
            self.bundles.append(path)
        from ..metrics import registry as metrics_registry
        mr = metrics_registry.REGISTRY
        if mr is not None:
            mr.counter("srtpu_flight_dumps_total", trigger=kind).inc()
        log.warning("flight recorder: %s bundle written to %s (%s)",
                    kind, path, detail[:200])
        return path

    # ----------------------------------------------------- bundle writer
    def _write_bundle(self, kind: str, detail: str, seq: int,
                      query: Optional[dict]) -> str:
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        name = f"flight-{stamp}-{kind}-{seq:04d}"
        final = os.path.join(self.dir, name)
        tmp = os.path.join(self.dir, f".tmp-{name}-{os.getpid()}")
        os.makedirs(tmp)
        try:
            for fname, payload in (
                    ("trace.json", self._trace_section()),
                    ("metrics.json", self._metrics_section()),
                    ("state.json", self._state_section()),
                    ("placement.json", self._placement_section(
                        kind, detail, query)),
                    ("config.json", self._config_section())):
                with open(os.path.join(tmp, fname), "w",
                          encoding="utf-8") as f:
                    json.dump(payload, f, indent=2, sort_keys=True,
                              default=str)
            # the rename is the commit point: a reader listing self.dir
            # either sees the whole bundle or none of it
            os.rename(tmp, final)
        except BaseException:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return final

    def _trace_section(self) -> dict:
        from ..trace import core as trace_core
        tr = trace_core.TRACER
        events = tr.tail(512) if tr is not None else []
        return {"traceRingTail": events,
                "breadcrumbs": self.ring_tail()}

    def _metrics_section(self) -> Optional[dict]:
        from ..metrics import registry as metrics_registry
        reg = metrics_registry.REGISTRY
        if reg is None:
            return None
        try:
            from ..metrics.export import registry_snapshot
            return registry_snapshot(reg)
        except Exception:  # noqa: BLE001 - a wedged sampler source must
            return reg.snapshot()  # not lose the bundle

    def _state_section(self) -> dict:
        out: dict = {}
        try:
            from ..mem import semaphore as sem_mod
            out["semaphores"] = [s.diagnostics()
                                 for s in list(sem_mod._SEMAPHORES)]
        except Exception as e:  # noqa: BLE001
            out["semaphores"] = f"<unavailable: {e}>"
        try:
            from ..mem.manager import MemoryManager
            out["memory"] = MemoryManager.stats_all()
        except Exception as e:  # noqa: BLE001
            out["memory"] = f"<unavailable: {e}>"
        try:
            from ..plan import exec_cache
            out["execCache"] = exec_cache.stats()
        except Exception as e:  # noqa: BLE001
            out["execCache"] = f"<unavailable: {e}>"
        return out

    def _placement_section(self, kind: str, detail: str,
                           query: Optional[dict]) -> dict:
        return {"trigger": kind, "detail": detail,
                "tsMs": round(time.time() * 1000.0, 1),
                "query": query}

    def _config_section(self) -> dict:
        raw = dict(getattr(self._conf, "raw", None) or {})
        return {"overridesFromDefaults": redact_conf(raw)}


# ---------------------------------------------------------------------------
# installation (the trace/metrics pattern)
# ---------------------------------------------------------------------------

_INSTALL_LOCK = threading.Lock()


def active_flight() -> Optional[FlightRecorder]:
    # tpulint: disable=lock-discipline — lock-free by design: the
    # disabled-path contract is one unlocked reference read per site
    return RECORDER


def install_flight(rec: Optional[FlightRecorder]) -> \
        Optional[FlightRecorder]:
    """Install (or with ``None`` remove) the process-global recorder."""
    global RECORDER
    with _INSTALL_LOCK:
        RECORDER = rec
    return rec


def ensure_flight_from_conf(conf) -> Optional[FlightRecorder]:
    """Install a recorder iff ``spark.rapids.tpu.flight.enabled`` — one
    conf lookup per ExecContext construction, never per trigger."""
    global RECORDER
    if not conf.get(FLIGHT_ENABLED):
        # tpulint: disable=lock-discipline — lock-free by design:
        # flight-off fast path; installation itself locks below
        return RECORDER
    with _INSTALL_LOCK:
        if RECORDER is None:
            RECORDER = FlightRecorder(
                str(conf.get(FLIGHT_DIR)),
                rate_limit_ms=int(conf.get(FLIGHT_RATE_LIMIT_MS)),
                ring_events=int(conf.get(FLIGHT_RING_EVENTS)),
                conf=conf)
        return RECORDER
