"""Per-digest regression sentinel (ISSUE 15).

Reference analog: the qualification/profiling tools' run-over-run diffs
— promoted from an offline CLI to a live check. Every ``queryEnd``
folds into a rolling per-plan-digest baseline (median warm wall,
cumulative compile seconds, placement verdict, max OOM-ladder rung) and
is compared against it FIRST, so a regression pages on the query that
regressed, not at the next manual diff:

* ``warm_slowdown``   — a compile-free run of a digest with >=
  ``sentinel.minSamples`` baselined walls took more than
  ``sentinel.wallFactor`` x the baseline median;
* ``verdict_flip``    — a digest whose baseline verdict is ``device``
  planned ``host`` (the "nothing silently reverts" check, ROADMAP
  item 1) — fires the flight recorder's ``placement_revert`` trigger;
* ``rung_escalation`` — a digest that never escalated past rung 2
  reached the cross-session pressure spill (rung 3) or the host
  degradation rung (rung 4);
* ``tail_regression``  — a compile-free ok run exceeded
  ``sentinel.tailFactor`` x the digest's baselined p99 (a rolling
  relative-error sketch, metrics/sketch.py). The median-based
  ``warm_slowdown`` is blind to a digest whose typical wall is fine
  but whose tail stretched; this is the per-digest half of the SLO
  layer (ISSUE 20, ops/slo.py).

Each flag increments ``srtpu_query_regressions_total{kind=...}`` and
fires the flight recorder. Baselines persist beside the adaptive stats
store (plan/stats_store.py) so a fresh serving process inherits its
predecessor's notion of normal; ``tools/regress`` replays an event log
through the SAME fold (``fold_record``) into a deterministic report.

Baselines are *rolling*: the flagged run still enters the window, so a
genuine persistent change re-baselines after ~``sentinel.window`` runs
(one page, not a permanent alarm) — the flight rate limiter bounds the
bundle volume in between.
"""
from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, List, Optional

from ..config import register

__all__ = ["RegressionSentinel", "fold_record", "REGRESSION_KINDS",
           "install_sentinel", "ensure_sentinel_from_conf",
           "active_sentinel", "default_baselines_path",
           "SENTINEL_ENABLED", "SENTINEL_WALL_FACTOR",
           "SENTINEL_MIN_SAMPLES", "SENTINEL_WINDOW", "SENTINEL_PATH",
           "SENTINEL_TAIL_FACTOR"]

log = logging.getLogger(__name__)

SENTINEL_ENABLED = register(
    "spark.rapids.tpu.sentinel.enabled", False,
    "Fold every queryEnd into per-plan-digest rolling baselines (median "
    "warm wall, compile seconds, placement verdict, OOM-ladder rung; "
    "persisted beside the adaptive stats store) and flag regressions — "
    "warm-digest slowdowns past sentinel.wallFactor, device->host "
    "verdict flips, new rung-3+ escalations — via "
    "srtpu_query_regressions_total and the flight recorder "
    "(ops/sentinel.py, docs/ops.md).", commonly_used=True)

SENTINEL_WALL_FACTOR = register(
    "spark.rapids.tpu.sentinel.wallFactor", 3.0,
    "A compile-free run slower than this multiple of the digest's "
    "baseline median wall is flagged as a warm_slowdown regression.")

SENTINEL_MIN_SAMPLES = register(
    "spark.rapids.tpu.sentinel.minSamples", 3,
    "Baselined walls required before the warm_slowdown check engages "
    "for a digest (fewer and the median is noise).")

SENTINEL_WINDOW = register(
    "spark.rapids.tpu.sentinel.window", 32,
    "Rolling window of per-digest walls the baseline median is computed "
    "over; a genuine persistent change re-baselines after this many "
    "runs.")

SENTINEL_PATH = register(
    "spark.rapids.tpu.sentinel.path", "",
    "Baseline persistence file; empty uses sentinel_baselines.json "
    "beside the adaptive stats store (SRTPU_STATS_PATH directory).")

SENTINEL_TAIL_FACTOR = register(
    "spark.rapids.tpu.sentinel.tailFactor", 2.0,
    "A compile-free run slower than this multiple of the digest's "
    "baselined p99 (rolling quantile sketch) is flagged as a "
    "tail_regression — the tail-latency analog of sentinel.wallFactor "
    "(docs/ops.md).")

#: closed regression taxonomy (docs/ops.md)
REGRESSION_KINDS = ("warm_slowdown", "verdict_flip", "rung_escalation",
                    "tail_regression")

#: persist baselines at most every N clean folds (every regression
#: persists immediately) — durability without a whole-table JSON
#: serialization on every query's completion path
_SAVE_EVERY_FOLDS = 16

#: the process-global sentinel; ``None`` means the sentinel is OFF and
#: the queryEnd site costs exactly one attribute load + branch
SENTINEL: Optional["RegressionSentinel"] = None


def default_baselines_path() -> str:
    from ..plan import stats_store
    return os.path.join(os.path.dirname(stats_store.store_path()),
                        "sentinel_baselines.json")


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def fold_record(baselines: Dict[str, dict], rec: dict, *,
                wall_factor: float = 3.0, min_samples: int = 3,
                window: int = 32, tail_factor: float = 2.0) -> List[dict]:
    """Fold ONE query record into ``baselines`` (mutated in place) and
    return the regressions it triggered. Pure and deterministic — the
    single code path shared by the live sentinel and the
    ``tools/regress`` event-log replay.

    ``rec`` keys: ``digest`` (required), ``wallMs``, ``verdict``
    (``device``/``host``), ``rung`` (max OOM-ladder rung reached),
    ``ok``, ``compileS`` (backend-compile seconds paid — a run that
    compiled is cold, so it neither trips nor feeds the warm-wall
    window)."""
    digest = rec.get("digest")
    if not digest:
        return []
    digest = str(digest)
    wall = rec.get("wallMs")
    verdict = rec.get("verdict")
    rung = int(rec.get("rung") or 0)
    ok = bool(rec.get("ok", True))
    compile_free = float(rec.get("compileS") or 0.0) == 0.0
    b = baselines.get(digest)
    regs: List[dict] = []
    if b is not None:
        med = _median(b.get("walls") or [])
        if (ok and compile_free and wall is not None
                and len(b.get("walls") or []) >= min_samples
                and med > 0 and float(wall) > wall_factor * med):
            regs.append({"kind": "warm_slowdown", "digest": digest,
                         "wallMs": round(float(wall), 3),
                         "medianMs": round(med, 3),
                         "factor": round(float(wall) / med, 2)})
        if verdict == "host" and b.get("verdict") == "device":
            regs.append({"kind": "verdict_flip", "digest": digest,
                         "from": "device", "to": "host"})
        if rung >= 3 and int(b.get("maxRung") or 0) < 3:
            regs.append({"kind": "rung_escalation", "digest": digest,
                         "rung": rung,
                         "baselineRung": int(b.get("maxRung") or 0)})
        # per-digest p99 check: the median is blind to a stretched tail
        # (ISSUE 20). The flagged wall still folds into the sketch
        # below, so a persistent shift re-baselines like the median.
        if ok and compile_free and wall is not None and b.get("tail"):
            from ..metrics.sketch import QuantileSketch
            sk = QuantileSketch.from_json(b["tail"])
            p99 = sk.quantile(0.99)
            if (sk.count >= min_samples and p99 > 0
                    and float(wall) > tail_factor * p99):
                regs.append({"kind": "tail_regression", "digest": digest,
                             "wallMs": round(float(wall), 3),
                             "p99Ms": round(p99, 3),
                             "factor": round(float(wall) / p99, 2)})
    if b is None:
        b = baselines[digest] = {"walls": [], "verdict": None,
                                 "maxRung": 0, "compileS": 0.0, "n": 0,
                                 "highRungs": 0, "warmSlowdowns": 0}
    if ok and compile_free and wall is not None:
        b["walls"] = (b.get("walls") or []) + [round(float(wall), 3)]
        b["walls"] = b["walls"][-max(1, int(window)):]
        # rolling tail sketch (JSON-able — rides baseline persistence);
        # .get-defaulted so pre-ISSUE-20 baselines keep folding. A
        # sketch has no eviction, so decay by halving bin counts once
        # it holds 4x the wall window: old observations lose weight
        # deterministically and a persistent tail shift re-baselines
        # within ~2 windows instead of never.
        from ..metrics.sketch import QuantileSketch
        sk = QuantileSketch.from_json(b.get("tail") or {})
        sk.observe(float(wall))
        if sk.count >= 4 * max(1, int(window)):
            sk.bins = {k: c // 2 for k, c in sk.bins.items() if c // 2}
            sk.zero_count //= 2
            sk.count = sk.zero_count + sum(sk.bins.values())
            sk.sum /= 2.0
        b["tail"] = sk.to_json()
    if verdict in ("device", "host"):
        b["verdict"] = verdict
    b["maxRung"] = max(int(b.get("maxRung") or 0), rung)
    # AQE feedback counters (ISSUE 19, aqe/feedback.py): how OFTEN this
    # digest hit the pressure-spill rung or a warm slowdown — maxRung
    # says "ever", the feedback loop needs "repeatedly". .get-defaulted
    # so baselines persisted before these keys existed keep folding.
    if rung >= 3:
        b["highRungs"] = int(b.get("highRungs") or 0) + 1
    if any(r["kind"] == "warm_slowdown" for r in regs):
        b["warmSlowdowns"] = int(b.get("warmSlowdowns") or 0) + 1
    b["compileS"] = round(float(b.get("compileS") or 0.0)
                          + float(rec.get("compileS") or 0.0), 4)
    b["n"] = int(b.get("n") or 0) + 1
    return regs


class RegressionSentinel:
    """Thread-safe live fold over the shared baseline table, with
    best-effort atomic persistence and metric/flight fan-out."""

    def __init__(self, path: str, wall_factor: float = 3.0,
                 min_samples: int = 3, window: int = 32,
                 tail_factor: float = 2.0):
        self.path = str(path)
        self.wall_factor = float(wall_factor)
        self.min_samples = int(min_samples)
        self.window = int(window)
        self.tail_factor = float(tail_factor)
        self._lock = threading.Lock()
        #: serializes whole-file persists: two concurrent save()s share
        #: one pid-derived tmp name, so an unserialized pair could
        #: os.replace a half-written file over the baselines (the
        #: stats_store._save_lock idiom). Taken BEFORE _lock, never
        #: while holding it.
        self._save_lock = threading.Lock()
        self._baselines: Dict[str, dict] = {}  # tpulint: guarded-by _lock
        #: regressions flagged this process, oldest first (ops /healthz)
        self.flagged: List[dict] = []          # tpulint: guarded-by _lock
        self._folds_since_save = 0             # tpulint: guarded-by _lock
        self._load()

    # ------------------------------------------------------- persistence
    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
            if isinstance(doc, dict) and isinstance(
                    doc.get("digests"), dict):
                with self._lock:
                    self._baselines = {str(k): dict(v) for k, v
                                       in doc["digests"].items()
                                       if isinstance(v, dict)}
        except (OSError, ValueError):
            # absent or corrupt baselines: start fresh — the sentinel
            # must never fail a query over its own persistence
            pass

    # tpulint: never-raise
    def save(self) -> bool:
        """Atomic best-effort persist (tmp + replace, serialized by
        ``_save_lock``); returns False on failure, never raises.

        The catch is deliberately ``Exception``, not just ``OSError``: a
        baseline record that picked up a non-JSON value (a numpy scalar
        riding in through a folded query record) makes ``json.dump``
        raise ``TypeError``, and that must degrade to an unsaved
        baseline, not fail the query-completion path that called
        ``fold``."""
        with self._save_lock:
            with self._lock:
                doc = {"digests": {k: dict(v) for k, v
                                   in self._baselines.items()}}
                self._folds_since_save = 0
            tmp = f"{self.path}.tmp.{os.getpid()}"
            try:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(doc, f, sort_keys=True)
                os.replace(tmp, self.path)
                return True
            except Exception as e:  # noqa: BLE001 - never-raise surface
                log.warning("sentinel baselines not persisted to %s: "
                            "%s", self.path, e)
                try:
                    os.unlink(tmp)
                except Exception:  # noqa: BLE001 - best-effort cleanup
                    pass
                return False

    # -------------------------------------------------------------- fold
    # tpulint: never-raise
    def fold(self, rec: dict) -> List[dict]:
        """Fold one live query record; flags fan out to the metric
        registry and the flight recorder. Never raises."""
        try:
            with self._lock:
                regs = fold_record(self._baselines, rec,
                                   wall_factor=self.wall_factor,
                                   min_samples=self.min_samples,
                                   window=self.window,
                                   tail_factor=self.tail_factor)
                self.flagged.extend(regs)
                # /healthz shows recent flags, not unbounded history
                del self.flagged[:-64]
                self._folds_since_save += 1
                save_due = bool(regs) or \
                    self._folds_since_save >= _SAVE_EVERY_FOLDS
        except Exception as e:  # noqa: BLE001 - observability only
            log.warning("sentinel fold failed: %s", e)
            return []
        if regs:
            # the fan-out is fallible too — json.dumps raises TypeError
            # when a flag record carries a non-JSON value (numpy scalars
            # from a folded metric), and nothing here may escape into
            # the query-completion path that called fold
            try:
                from ..metrics import registry as metrics_registry
                mr = metrics_registry.REGISTRY
                from .flight import RECORDER as _frec
                for r in regs:
                    if mr is not None:
                        mr.counter("srtpu_query_regressions_total",
                                   kind=r["kind"]).inc()
                    if _frec is not None:
                        trig = ("placement_revert"
                                if r["kind"] == "verdict_flip"
                                else "sentinel_regression")
                        _frec.trigger(trig, detail=json.dumps(
                            r, sort_keys=True))
                    log.warning("regression sentinel: %s", r)
            except Exception as e:  # noqa: BLE001 - observability only
                log.warning("sentinel flag fan-out failed: %s", e)
        if save_due:
            # debounced persist: re-serializing the whole baseline
            # table per queryEnd would tax the completion path of a
            # short-query serving workload for no added durability
            self.save()
        return regs

    # ------------------------------------------------------------- reads
    def baselines(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._baselines.items()}

    def recent_flags(self) -> List[dict]:
        with self._lock:
            return list(self.flagged)


# ---------------------------------------------------------------------------
# installation (the trace/metrics pattern)
# ---------------------------------------------------------------------------

_INSTALL_LOCK = threading.Lock()


def active_sentinel() -> Optional[RegressionSentinel]:
    # tpulint: disable=lock-discipline — lock-free by design: the
    # disabled-path contract is one unlocked reference read per site
    return SENTINEL


def install_sentinel(sen: Optional[RegressionSentinel]) -> \
        Optional[RegressionSentinel]:
    """Install (or with ``None`` remove) the process-global sentinel."""
    global SENTINEL
    with _INSTALL_LOCK:
        SENTINEL = sen
    return sen


def ensure_sentinel_from_conf(conf) -> Optional[RegressionSentinel]:
    """Install a sentinel iff ``spark.rapids.tpu.sentinel.enabled`` —
    one conf lookup per ExecContext construction, never per query."""
    global SENTINEL
    if not conf.get(SENTINEL_ENABLED):
        # tpulint: disable=lock-discipline — lock-free by design:
        # sentinel-off fast path; installation itself locks below
        return SENTINEL
    with _INSTALL_LOCK:
        if SENTINEL is None:
            path = str(conf.get(SENTINEL_PATH) or "").strip() \
                or default_baselines_path()
            SENTINEL = RegressionSentinel(
                path,
                wall_factor=float(conf.get(SENTINEL_WALL_FACTOR)),
                min_samples=int(conf.get(SENTINEL_MIN_SAMPLES)),
                window=int(conf.get(SENTINEL_WINDOW)),
                tail_factor=float(conf.get(SENTINEL_TAIL_FACTOR)))
        return SENTINEL
