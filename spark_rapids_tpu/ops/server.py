"""Live ops HTTP endpoint (ISSUE 15).

Reference analog: the Spark live UI + Prometheus servlet sink the
reference stack is operated through. One stdlib ``http.server`` daemon
thread, bound to ``127.0.0.1`` only, gated by
``spark.rapids.tpu.ops.port`` (0 = disabled: no thread, no socket):

* ``GET /metrics``  — Prometheus text exposition of the process metric
  registry (after one synchronous sample pass); when a LocalCluster has
  registered itself the merged cluster view is served instead, every
  series carrying a ``worker`` label;
* ``GET /healthz``  — JSON health sections, each with an
  ``ok``/``degraded`` verdict: semaphore holders/waiters (a dead or
  overdue holder degrades), memory tiers + the rung-4 pressure-grant
  pool, executable-cache hit rate, worker heartbeat ages, event-log
  write lag, flight-recorder dumps and sentinel flags. HTTP 200 when
  every section is ok, 503 otherwise (load-balancer-pluggable);
* ``GET /queries``  — in-flight and recent queries: id, plan digest,
  placement verdict, elapsed/wall ms, max OOM-ladder rung, status and
  failure reason (the live analog of ``tools/history``).

The server holds NO references that keep a query alive: clusters
register via weakref, runtime singletons are observed through the same
weak registries the metrics sampler uses.
"""
from __future__ import annotations

import json
import logging
import threading
import time
import weakref
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..config import register

__all__ = ["OpsServer", "QueryTracker", "install_ops",
           "ensure_ops_from_conf", "shutdown_ops", "active_ops",
           "OPS_PORT", "OPS_RECENT_QUERIES"]

log = logging.getLogger(__name__)

OPS_PORT = register(
    "spark.rapids.tpu.ops.port", 0,
    "Serve the live ops endpoint on 127.0.0.1:<port> — GET /metrics "
    "(Prometheus exposition, cluster-merged when a LocalCluster is "
    "live), /healthz (JSON ok/degraded verdicts over semaphore, "
    "memory, exec cache, worker heartbeats, event-log lag) and "
    "/queries (in-flight + recent queries with digest, placement, "
    "elapsed, OOM-ladder rung). 0 disables: no thread, no socket "
    "(docs/ops.md).", commonly_used=True)

OPS_RECENT_QUERIES = register(
    "spark.rapids.tpu.ops.queries.recent", 64,
    "Finished queries the /queries endpoint keeps in its recency ring.")

#: the process-global server; ``None`` means the ops plane is OFF and
#: every instrumented site costs exactly one attribute load + branch
SERVER: Optional["OpsServer"] = None

#: /healthz exec-cache verdict: below this hit rate (with enough
#: lookups to mean something) the section reads degraded
_CACHE_HIT_RATE_FLOOR = 0.5
_CACHE_MIN_LOOKUPS = 64
#: /healthz memory verdict: device tier fuller than this is degraded
_HBM_DEGRADED_FRACTION = 0.95
#: /healthz memory verdict: seconds the pressure-grant pool must stay
#: EMPTY before the degraded verdict clears (hysteresis keyed off the
#: pool's last-nonzero instant, mem/manager.py): a pool that flickers
#: empty between rung-4 grants must not flap the verdict, and a drained
#: one must clear instead of degrading forever (ISSUE 18 satellite).
#: The admission shed check (sched/admission.py) reads the same horizon.
_GRANT_CLEAR_HORIZON_S = 2.0
#: /healthz worker verdict: a peer older than this fraction of the
#: eviction horizon reads degraded — strictly BELOW 1.0, because
#: _evict (run by every heartbeat/live_peers call) removes the peer at
#: the full horizon: an equal threshold would let a silent worker
#: vanish from the census at the same instant it first read degraded
_WORKER_DEGRADED_FRACTION = 0.5


class QueryTracker:
    """In-flight + recent query table behind /queries. Thread-safe;
    bounded (the recency ring drops oldest)."""

    def __init__(self, recent: int = 64):
        self._lock = threading.Lock()
        self._seq = 0                     # tpulint: guarded-by _lock
        self._inflight: Dict[int, dict] = {}  # tpulint: guarded-by _lock
        self._recent: deque = deque(
            maxlen=max(1, int(recent)))   # tpulint: guarded-by _lock

    def begin(self, query_id, digest: Optional[str],
              verdict: Optional[str], root: Optional[str] = None,
              tenant: Optional[str] = None) -> int:
        rec = {"queryId": query_id, "planDigest": digest,
               "placement": verdict, "root": root,
               "tenant": tenant,
               "startedMs": round(time.time() * 1000.0, 1),
               "_t0": time.monotonic()}
        with self._lock:
            self._seq += 1
            tok = self._seq
            self._inflight[tok] = rec
        return tok

    def admission(self, token: int, status: str,
                  queued_ms: Optional[float] = None) -> None:
        """Record the query's admission-controller outcome (ISSUE 18):
        ``queued`` while it waits at the front door, then ``admitted``
        (with the wait it paid) or ``shed``. /queries renders it live,
        and end() carries it into the recency ring."""
        with self._lock:
            rec = self._inflight.get(token)
            if rec is None:
                return
            rec["admission"] = status
            if queued_ms is not None:
                rec["queuedMs"] = round(float(queued_ms), 3)

    def end(self, token: int, ok: bool, wall_ms: Optional[float] = None,
            rung: int = 0, reason: Optional[str] = None,
            degraded: bool = False,
            aqe: Optional[dict] = None) -> None:
        with self._lock:
            rec = self._inflight.pop(token, None)
            if rec is None:
                return
            rec = dict(rec)
            rec.pop("_t0", None)
            rec["status"] = "ok" if ok else "failed"
            rec["degraded"] = bool(degraded)
            rec["wallMs"] = (round(float(wall_ms), 3)
                             if wall_ms is not None else None)
            rec["ladderRung"] = int(rung or 0)
            if reason:
                rec["reason"] = str(reason)
            if aqe:
                # AQE decision summary (ISSUE 19): kind -> count, the
                # same compact map the queryEnd record carries
                rec["aqe"] = dict(aqe)
            self._recent.append(rec)

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            inflight = []
            for rec in self._inflight.values():
                r = dict(rec)
                r["elapsedMs"] = round((now - r.pop("_t0")) * 1000.0, 1)
                r["status"] = "running"
                inflight.append(r)
            recent = [dict(r) for r in self._recent]
        inflight.sort(key=lambda r: r["startedMs"])
        return {"inflight": inflight, "recent": recent}


class _Handler(BaseHTTPRequestHandler):
    # the ops endpoint must never spam the serving process's stderr
    def log_message(self, fmt, *args):  # noqa: A003 - stdlib signature
        log.debug("ops: " + fmt, *args)

    # tpulint: never-raise
    def do_GET(self):  # noqa: N802 - stdlib naming
        ops: "OpsServer" = self.server.ops  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            # the request counter is part of the guarded body: a registry
            # error in the fan-out must degrade to a 500, not escape into
            # socketserver's handle_error (stderr traceback + a dropped
            # connection — exactly what this handler promises never to do)
            if path in ("/metrics", "/healthz", "/queries", "/slo"):
                from ..metrics import registry as metrics_registry
                mr = metrics_registry.REGISTRY
                if mr is not None:
                    mr.counter("srtpu_ops_requests_total",
                               endpoint=path).inc()
            if path == "/metrics":
                body = ops.metrics_text().encode("utf-8")
                self._reply(200, body,
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                doc = ops.healthz()
                code = 200 if doc.get("status") == "ok" else 503
                self._reply(code, json.dumps(
                    doc, indent=2, sort_keys=True,
                    default=str).encode("utf-8"), "application/json")
            elif path == "/queries":
                self._reply(200, json.dumps(
                    ops.queries(), indent=2, sort_keys=True,
                    default=str).encode("utf-8"), "application/json")
            elif path == "/slo":
                self._reply(200, json.dumps(
                    ops.slo(), indent=2, sort_keys=True,
                    default=str).encode("utf-8"), "application/json")
            elif path == "/":
                self._reply(200, json.dumps(
                    {"endpoints": ["/metrics", "/healthz", "/queries",
                                   "/slo"]}
                ).encode("utf-8"), "application/json")
            else:
                self._reply(404, b'{"error": "not found"}',
                            "application/json")
        except Exception as e:  # noqa: BLE001 - a probe must never kill
            log.warning("ops endpoint %s failed: %s", path, e)
            try:
                self._reply(500, json.dumps(
                    {"error": str(e)}).encode("utf-8"),
                    "application/json")
            except Exception:  # noqa: BLE001 - client went away
                pass           # mid-reply (or the error body itself
                #                failed to build): nothing left to do

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class OpsServer:
    """The live ops plane: one daemon HTTP thread + the query tracker.

    ``port=0`` binds an OS-assigned ephemeral port (tests); the conf
    gate in :func:`ensure_ops_from_conf` only starts a server for
    explicit ports > 0."""

    def __init__(self, port: int = 0, recent_queries: int = 64):
        self.tracker = QueryTracker(recent_queries)
        self._cluster: Optional[weakref.ref] = None
        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._httpd.ops = self  # type: ignore[attr-defined]
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="srtpu-ops-server", daemon=True)

    def start(self) -> "OpsServer":
        self._thread.start()
        log.info("ops server listening on 127.0.0.1:%d "
                 "(/metrics /healthz /queries)", self.port)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # ------------------------------------------------------------ wiring
    def register_cluster(self, cluster) -> None:
        """Weakly remember a LocalCluster so /metrics serves the merged
        cluster view and /healthz sees worker heartbeat ages. The last
        registered live cluster wins; a GC'd one silently drops."""
        self._cluster = weakref.ref(cluster)

    def _live_cluster(self):
        ref = self._cluster
        return ref() if ref is not None else None

    # --------------------------------------------------------- /metrics
    def metrics_text(self) -> str:
        cl = self._live_cluster()
        if cl is not None:
            try:
                txt = cl.prometheus_snapshot()
                if txt:
                    return txt
            except Exception as e:  # noqa: BLE001 - fall back to local
                log.warning("ops: cluster metrics merge failed: %s", e)
        from ..metrics import registry as metrics_registry
        reg = metrics_registry.REGISTRY
        if reg is None:
            return ("# spark.rapids.tpu.metrics.enabled is off: "
                    "no metric registry installed\n")
        from ..metrics.export import prometheus_text, registry_snapshot
        snap = registry_snapshot(reg)
        from .slo import TRACKER as _slo
        if _slo is not None:
            # OpenMetrics exemplars: each tenant's newest over-target
            # query rides its summary series, linking the quantile line
            # to the on-disk trace/flight artifact (ops/slo.py)
            snap = _slo.decorate_snapshot(snap)
        return prometheus_text(snap)

    # --------------------------------------------------------- /healthz
    def healthz(self) -> dict:
        sections = {"semaphore": self._health_semaphore(),
                    "memory": self._health_memory(),
                    "admission": self._health_admission(),
                    "execCache": self._health_exec_cache(),
                    "workers": self._health_workers(),
                    "eventLog": self._health_event_log(),
                    "flight": self._health_flight(),
                    "sentinel": self._health_sentinel(),
                    "slo": self._health_slo()}
        status = ("ok" if all(s.get("verdict") == "ok"
                              for s in sections.values())
                  else "degraded")
        return {"status": status, "tsMs": round(time.time() * 1000.0, 1),
                **sections}

    def _health_semaphore(self) -> dict:
        from ..mem import semaphore as sem_mod
        sems = list(sem_mod._SEMAPHORES)
        holders: List[dict] = []
        dead = overdue = 0
        permits = waiting = wedges = 0
        for s in sems:
            d = s.diagnostics()
            permits += d["permits"]
            waiting += d["waiting"]
            wedges += d["wedges"]
            horizon_s = (s.wedge_timeout_ms / 1000.0
                         if s.wedge_timeout_ms > 0 else None)
            for h in d["holders"]:
                holders.append(h)
                if h.get("alive") is False:
                    dead += 1
                elif horizon_s is not None and h["held_s"] >= horizon_s:
                    overdue += 1
        verdict = "degraded" if (dead or overdue) else "ok"
        return {"semaphores": len(sems), "permits": permits,
                "waiting": waiting, "holders": holders,
                "deadHolders": dead, "overdueHolders": overdue,
                "wedges": wedges, "verdict": verdict}

    def _health_memory(self) -> dict:
        from ..mem.manager import MemoryManager
        st = MemoryManager.stats_all()
        budget = st.get("budget") or 0
        used = st.get("device_used") or 0
        grant = st.get("pressure_granted") or 0
        # the grant pool degrades while nonzero AND for a short horizon
        # after it drains (last-nonzero hysteresis) — then CLEARS: a
        # pool back to zero live bytes must not read degraded forever
        # (ISSUE 18 satellite; mem/manager.py pressure_grant_idle_s)
        idle = st.get("pressure_grant_idle_s")
        grant_hot = bool(grant) or (
            idle is not None and idle < _GRANT_CLEAR_HORIZON_S)
        degraded = grant_hot or (
            budget > 0 and used > _HBM_DEGRADED_FRACTION * budget)
        out = dict(st)
        out["verdict"] = "degraded" if degraded else "ok"
        return out

    def _health_admission(self) -> dict:
        from ..sched import admission as adm_mod
        ctl = adm_mod.CONTROLLER
        if ctl is None:
            return {"enabled": False, "verdict": "ok"}
        st = ctl.stats()
        shed = adm_mod.shed_reason()
        out = {"enabled": True, "shedActive": shed is not None,
               **st}
        if shed is not None:
            out["shedReason"] = shed
        # shedding mirrors the memory/semaphore pressure verdicts —
        # report it here too so a load balancer reading only this
        # section still sees the front door is refusing work
        out["verdict"] = "degraded" if shed is not None else "ok"
        return out

    def _health_exec_cache(self) -> dict:
        from ..plan import exec_cache
        st = exec_cache.stats()
        lookups = st["hits"] + st["misses"]
        rate = exec_cache.hit_rate()
        degraded = (lookups >= _CACHE_MIN_LOOKUPS and rate is not None
                    and rate < _CACHE_HIT_RATE_FLOOR)
        out = dict(st)
        out["hitRate"] = round(rate, 4) if rate is not None else None
        out["verdict"] = "degraded" if degraded else "ok"
        return out

    def _health_workers(self) -> dict:
        cl = self._live_cluster()
        if cl is None:
            return {"workers": {}, "verdict": "ok",
                    "note": "no LocalCluster registered"}
        try:
            ages = cl.manager.peer_ages()
            stale_after = float(cl.manager.stale_after_s)
        except Exception as e:  # noqa: BLE001 - a mid-shutdown cluster
            return {"workers": {}, "verdict": "ok",
                    "note": f"cluster unreadable: {e}"}
        degraded_at = stale_after * _WORKER_DEGRADED_FRACTION
        workers = {wid: {"heartbeatAgeS": age,
                         "verdict": ("degraded" if age > degraded_at
                                     else "ok")}
                   for wid, age in sorted(ages.items())}
        verdict = ("degraded" if any(w["verdict"] == "degraded"
                                     for w in workers.values())
                   else "ok")
        return {"workers": workers, "staleAfterS": stale_after,
                "verdict": verdict}

    def _health_event_log(self) -> dict:
        from ..metrics.events import writer_health
        writers = writer_health()
        if not writers:
            return {"writers": [], "verdict": "ok",
                    "note": "no event-log writer active"}
        now = time.time()
        degraded = False
        for w in writers:
            wts, ets = w.get("lastWriteTs"), w.get("lastErrorTs")
            if ets is not None and (wts is None or ets >= wts):
                degraded = True      # the newest attempt failed
            if wts is not None:
                # informational only: a long lag just means no queries
                # ran — an idle process is healthy, not degraded
                w["lagS"] = round(now - wts, 3)
        return {"writers": writers,
                "verdict": "degraded" if degraded else "ok"}

    def _health_flight(self) -> dict:
        from .flight import RECORDER
        if RECORDER is None:
            return {"enabled": False, "verdict": "ok"}
        st = RECORDER.stats()
        return {"enabled": True, "dumps": st["dumps"],
                "suppressed": st["suppressed"],
                "lastBundle": (st["bundles"][-1] if st["bundles"]
                               else None), "verdict": "ok"}

    def _health_sentinel(self) -> dict:
        from .sentinel import SENTINEL
        if SENTINEL is None:
            return {"enabled": False, "verdict": "ok"}
        flags = SENTINEL.recent_flags()
        return {"enabled": True, "recentFlags": flags[-8:],
                "flaggedTotal": len(flags), "verdict": "ok"}

    def _health_slo(self) -> dict:
        from .slo import TRACKER
        if TRACKER is None:
            return {"enabled": False, "verdict": "ok"}
        h = TRACKER.healthz()
        return {"enabled": True,
                "burningTenants": h["burningTenants"],
                "alertsFired": h["alertsFired"],
                "shedActive": h["shedActive"],
                "exemplars": h["exemplars"],
                "verdict": ("degraded" if h["status"] == "degraded"
                            else "ok")}

    # --------------------------------------------------------- /queries
    def queries(self) -> dict:
        return self.tracker.snapshot()

    # ------------------------------------------------------------- /slo
    def slo(self) -> dict:
        """The GET /slo report: burn rates, error-budget remaining,
        worst digests by tail contribution, exemplars — or an
        ``enabled: false`` stub when the tracker is off."""
        from .slo import TRACKER
        if TRACKER is None:
            return {"enabled": False}
        return {"enabled": True, **TRACKER.report()}


# ---------------------------------------------------------------------------
# installation (the trace/metrics pattern)
# ---------------------------------------------------------------------------

_INSTALL_LOCK = threading.Lock()


def active_ops() -> Optional[OpsServer]:
    # tpulint: disable=lock-discipline — lock-free by design: the
    # disabled-path contract is one unlocked reference read per site
    return SERVER


def install_ops(srv: Optional[OpsServer]) -> Optional[OpsServer]:
    """Install (or with ``None`` remove) the process-global server; the
    caller owns start/stop."""
    global SERVER
    with _INSTALL_LOCK:
        SERVER = srv
    return srv


def shutdown_ops() -> None:
    """Stop and uninstall the server (per-test reset)."""
    global SERVER
    with _INSTALL_LOCK:
        srv, SERVER = SERVER, None
    if srv is not None:
        try:
            srv.stop()
        except Exception:  # pragma: no cover - teardown best effort
            pass


def ensure_ops_from_conf(conf) -> Optional[OpsServer]:
    """Start the ops server iff ``spark.rapids.tpu.ops.port`` > 0 — one
    conf lookup per ExecContext construction. The first port wins for
    the process lifetime (the install-once registry pattern); a bind
    failure logs and leaves the plane off rather than failing a query."""
    global SERVER
    port = int(conf.get(OPS_PORT))
    if port <= 0:
        # tpulint: disable=lock-discipline — lock-free by design:
        # ops-off fast path; installation itself locks below
        return SERVER
    with _INSTALL_LOCK:
        if SERVER is None:
            try:
                SERVER = OpsServer(
                    port,
                    recent_queries=int(conf.get(OPS_RECENT_QUERIES))
                ).start()
            except OSError as e:
                log.error("ops server could not bind 127.0.0.1:%d: %s "
                          "— ops plane disabled for this process",
                          port, e)
                return None
        return SERVER
