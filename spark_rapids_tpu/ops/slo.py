"""Per-tenant tail-latency SLO tracker (ISSUE 20).

The closed-loop half of the tail-latency layer: every finished query is
folded as a *good* or *bad* event against its tenant's latency target
(bad = over ``spark.rapids.tpu.slo.targetMs`` or failed), and the
good/bad stream drives multi-window **burn rates** — the standard SRE
alerting shape. A burn rate of 1.0 spends the error budget exactly at
the objective's allowance; ``slo.burn.threshold`` x that over BOTH the
short and the long window means the budget is burning fast enough,
persistently enough, to act on:

* the flight recorder's ``slo_burn`` trigger fires (one diagnostic
  bundle, rate-limited),
* the admission controller starts shedding below its priority floor
  (``shed_reason`` consults :meth:`SloTracker.shed_hint`) while the
  alert is live — the same graceful-degradation path memory pressure
  uses (docs/serving.md),
* AQE feedback sees per-digest breach counts and re-plans repeat
  offenders to smaller batches (aqe/feedback.py).

Every over-target observation also records an **exemplar** — a bounded
ring entry linking the outlier to its on-disk evidence (trace path,
flight bundle, queryId, plan digest) — surfaced through OpenMetrics
exemplar syntax on ``/metrics`` and the ``GET /slo`` report, so a p99
spike on a dashboard is one hop from the artifact that explains it.

The fold is **pure** (:func:`fold_slo_event` / :func:`burn_rate` /
:func:`budget_remaining` operate on plain dicts) and shared verbatim
with the offline replay (``tools/history --slo``), the sentinel's
``fold_record`` idiom. Install follows the tracer/flight pattern:
``TRACKER`` is ``None`` when off and every instrumented site costs one
module-global load + branch.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..config import register

__all__ = ["SloTracker", "TRACKER", "install_slo", "active_slo",
           "ensure_slo_from_conf", "fold_slo_event", "burn_rate",
           "budget_remaining", "parse_tenant_overrides", "new_slo_state",
           "SLO_ENABLED", "SLO_TARGET_MS", "SLO_OBJECTIVE",
           "SLO_TENANT_OVERRIDES", "SLO_SHORT_WINDOW_S",
           "SLO_LONG_WINDOW_S", "SLO_BURN_THRESHOLD", "SLO_EXEMPLARS",
           "SLO_SHED_ENABLED", "SLO_DIGESTS"]

log = logging.getLogger(__name__)

SLO_ENABLED = register(
    "spark.rapids.tpu.slo.enabled", False,
    "Fold every finished query into the per-tenant tail-latency SLO "
    "tracker (ops/slo.py): good/bad events against slo.targetMs drive "
    "multi-window error-budget burn rates, exemplars linking p99 "
    "outliers to trace/flight artifacts, the GET /slo report, the "
    "flight recorder's slo_burn trigger and (with slo.shed.enabled) "
    "admission shedding while the budget burns (docs/serving.md).",
    commonly_used=True)

SLO_TARGET_MS = register(
    "spark.rapids.tpu.slo.targetMs", 1000.0,
    "Default per-query latency target in milliseconds: a query slower "
    "than this (or failed) is a bad SLO event for its tenant. "
    "Per-tenant overrides via slo.tenant.overrides.")

SLO_OBJECTIVE = register(
    "spark.rapids.tpu.slo.objective", 0.99,
    "Default SLO objective — the fraction of queries that must meet "
    "the latency target; 1 - objective is the error budget the burn "
    "rates are measured against.")

SLO_TENANT_OVERRIDES = register(
    "spark.rapids.tpu.slo.tenant.overrides", "",
    "Per-tenant target/objective overrides, "
    "'tenant=targetMs[:objective]' comma-separated — e.g. "
    "'alpha=500:0.999,batch=30000:0.9'. Tenants not listed use "
    "slo.targetMs / slo.objective.")

SLO_SHORT_WINDOW_S = register(
    "spark.rapids.tpu.slo.burn.shortWindowS", 60.0,
    "Short burn-rate window in seconds (the fast signal of the "
    "multi-window alert; both windows must exceed slo.burn.threshold "
    "to fire).")

SLO_LONG_WINDOW_S = register(
    "spark.rapids.tpu.slo.burn.longWindowS", 600.0,
    "Long burn-rate window in seconds (the sustained signal; also the "
    "horizon events are retained for and the error-budget-remaining "
    "denominator).")

SLO_BURN_THRESHOLD = register(
    "spark.rapids.tpu.slo.burn.threshold", 2.0,
    "Burn-rate multiple that fires the slo_burn alert when BOTH "
    "windows exceed it: 1.0 spends the budget exactly at the "
    "objective's allowance, 2.0 twice as fast.")

SLO_EXEMPLARS = register(
    "spark.rapids.tpu.slo.exemplars", 64,
    "Bounded ring of over-target exemplars retained (queryId, plan "
    "digest, tenant, trace path, flight-bundle path) — served by "
    "GET /slo and attached to /metrics in OpenMetrics exemplar "
    "syntax.")

SLO_SHED_ENABLED = register(
    "spark.rapids.tpu.slo.shed.enabled", True,
    "Let a live slo_burn alert drive admission shedding (below the "
    "admission priority floor) for the duration of the short window — "
    "the burn->shed half of the closed loop (docs/serving.md).")

SLO_DIGESTS = register(
    "spark.rapids.tpu.slo.digests", 128,
    "Distinct plan digests tracked for tail contribution (worst-digest "
    "ranking, AQE feedback); past the cap new digests collapse into "
    "'other'.")

#: the process-global tracker; ``None`` means SLO tracking is OFF and
#: the query-completion site costs exactly one attribute load + branch
TRACKER: Optional["SloTracker"] = None


# ---------------------------------------------------------------------------
# the pure fold (shared with tools/history --slo replay)
# ---------------------------------------------------------------------------

def parse_tenant_overrides(spec: str) -> Dict[str, Tuple[float, float]]:
    """``'alpha=500:0.999,beta=2000'`` -> {tenant: (target_ms,
    objective-or-None)}. Malformed entries are skipped (a bad conf
    string must not take down the tracker install)."""
    out: Dict[str, Tuple[float, float]] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        tenant, _, val = part.partition("=")
        target, _, obj = val.partition(":")
        try:
            out[tenant.strip()] = (float(target),
                                   float(obj) if obj else None)
        except ValueError:
            log.warning("slo: ignoring malformed tenant override %r",
                        part)
    return out


def new_slo_state() -> dict:
    """Empty fold state: {tenant: {"events": [(ts, bad)], "good": n,
    "bad": n}} — events pruned to the long window, good/bad cumulative
    over the process lifetime."""
    return {}


def fold_slo_event(state: dict, *, tenant: str, ts: float, bad: bool,
                   long_window_s: float) -> dict:
    """Fold one good/bad event into ``state`` (mutated in place) and
    return the tenant's sub-state. Pure and deterministic — the single
    code path shared by the live tracker and the ``tools/history
    --slo`` replay."""
    t = state.setdefault(tenant, {"events": [], "good": 0, "bad": 0})
    t["events"].append((round(float(ts), 3), 1 if bad else 0))
    cutoff = float(ts) - float(long_window_s)
    ev = t["events"]
    i = 0
    while i < len(ev) and ev[i][0] < cutoff:
        i += 1
    if i:
        del ev[:i]
    t["bad" if bad else "good"] += 1
    return t


def burn_rate(tenant_state: dict, *, now: float, window_s: float,
              objective: float) -> float:
    """Error-budget burn rate over the trailing window: the observed
    bad fraction divided by the budget fraction (1 - objective). 0.0
    with no events; an objective of 1.0 makes any bad event an
    infinite burn, clamped to a large finite value (JSON-safe)."""
    cutoff = float(now) - float(window_s)
    n = bad = 0
    for ts, isbad in tenant_state.get("events") or []:
        if ts >= cutoff:
            n += 1
            bad += isbad
    if n == 0 or bad == 0:
        return 0.0
    budget = 1.0 - float(objective)
    if budget <= 0.0:
        return 1e9
    return min(1e9, (bad / n) / budget)


def budget_remaining(tenant_state: dict, *, objective: float) -> float:
    """Fraction of the error budget left over the retained horizon:
    1.0 untouched, 0.0 exhausted (clamped)."""
    ev = tenant_state.get("events") or []
    n = len(ev)
    if n == 0:
        return 1.0
    bad = sum(isbad for _, isbad in ev)
    budget = n * (1.0 - float(objective))
    if budget <= 0.0:
        return 0.0 if bad else 1.0
    return min(1.0, max(0.0, 1.0 - bad / budget))


# ---------------------------------------------------------------------------
# the live tracker
# ---------------------------------------------------------------------------

class SloTracker:
    """Thread-safe live fold over the pure SLO state, with exemplar
    ring, per-digest tail attribution, burn alerting and the shed
    hint the admission controller consults."""

    def __init__(self, *, target_ms: float = 1000.0,
                 objective: float = 0.99,
                 tenant_overrides: Optional[
                     Dict[str, Tuple[float, float]]] = None,
                 short_window_s: float = 60.0,
                 long_window_s: float = 600.0,
                 burn_threshold: float = 2.0,
                 exemplar_cap: int = 64,
                 shed_enabled: bool = True,
                 digest_cap: int = 128):
        self.target_ms = float(target_ms)
        self.objective = float(objective)
        self.overrides = dict(tenant_overrides or {})
        self.short_window_s = max(1.0, float(short_window_s))
        self.long_window_s = max(self.short_window_s,
                                 float(long_window_s))
        self.burn_threshold = float(burn_threshold)
        self.exemplar_cap = max(1, int(exemplar_cap))
        self.shed_enabled = bool(shed_enabled)
        self.digest_cap = max(1, int(digest_cap))
        self._lock = threading.Lock()
        self._state = new_slo_state()       # tpulint: guarded-by _lock
        #: newest-last over-target exemplar ring
        self._exemplars: List[dict] = []    # tpulint: guarded-by _lock
        #: digest -> {"n", "over", "excessMs"} tail attribution
        self._digests: Dict[str, dict] = {}  # tpulint: guarded-by _lock
        #: tenant -> last alert wall-clock (alert cooldown = short win)
        self._alerted_at: Dict[str, float] = {}  # tpulint: guarded-by _lock
        #: (tenant, expiry) of the live shed hint
        self._shed_until: Tuple[str, float] = ("", 0.0)  # tpulint: guarded-by _lock
        self.alerts_fired = 0               # tpulint: guarded-by _lock

    # ----------------------------------------------------------- targets
    def target_for(self, tenant: str) -> Tuple[float, float]:
        """(target_ms, objective) for a tenant, overrides applied."""
        ov = self.overrides.get(tenant)
        if ov is None:
            return self.target_ms, self.objective
        target, obj = ov
        return target, (obj if obj is not None else self.objective)

    # -------------------------------------------------------------- fold
    # tpulint: never-raise
    def observe(self, *, tenant: Optional[str], wall_ms: float,
                ok: bool, query_id=None, digest: Optional[str] = None,
                trace_path: Optional[str] = None,
                flight_path: Optional[str] = None,
                ts: Optional[float] = None) -> None:
        """Fold one finished query. Runs on the query-completion path —
        never raises, and fans out (metrics, flight trigger) only
        behind the same guards every other completion hook uses."""
        try:
            alert_tenant = self._fold(
                tenant=tenant or "default", wall_ms=float(wall_ms),
                ok=bool(ok), query_id=query_id,
                digest=str(digest) if digest else None,
                trace_path=trace_path, flight_path=flight_path,
                ts=float(ts) if ts is not None else time.time())
        except Exception as e:  # noqa: BLE001 - observability only
            log.warning("slo fold failed: %s", e)
            return
        if alert_tenant is not None:
            self._fire_alert(alert_tenant)

    def _fold(self, *, tenant: str, wall_ms: float, ok: bool, query_id,
              digest: Optional[str], trace_path: Optional[str],
              flight_path: Optional[str], ts: float) -> Optional[str]:
        """The locked fold; returns the tenant to alert on, if any."""
        target_ms, objective = self.target_for(tenant)
        over = wall_ms > target_ms
        bad = over or not ok
        with self._lock:
            tstate = fold_slo_event(self._state, tenant=tenant, ts=ts,
                                    bad=bad,
                                    long_window_s=self.long_window_s)
            if digest:
                if digest not in self._digests and \
                        len(self._digests) >= self.digest_cap:
                    digest = "other"
                d = self._digests.setdefault(
                    digest, {"n": 0, "over": 0, "excessMs": 0.0})
                d["n"] += 1
                if over:
                    d["over"] += 1
                    d["excessMs"] = round(
                        d["excessMs"] + (wall_ms - target_ms), 3)
            if over:
                self._exemplars.append({
                    "queryId": query_id,
                    "planDigest": digest,
                    "tenant": tenant,
                    "wallMs": round(wall_ms, 3),
                    "targetMs": target_ms,
                    "trace": trace_path,
                    "flight": flight_path,
                    "tsMs": round(ts * 1000.0, 1)})
                del self._exemplars[:-self.exemplar_cap]
            short = burn_rate(tstate, now=ts,
                              window_s=self.short_window_s,
                              objective=objective)
            long_ = burn_rate(tstate, now=ts,
                              window_s=self.long_window_s,
                              objective=objective)
            alerting = (short >= self.burn_threshold
                        and long_ >= self.burn_threshold)
            alert = None
            if alerting:
                if self.shed_enabled:
                    self._shed_until = (tenant,
                                        ts + self.short_window_s)
                last = self._alerted_at.get(tenant, 0.0)
                if ts - last >= self.short_window_s:
                    self._alerted_at[tenant] = ts
                    self.alerts_fired += 1
                    alert = tenant
        # metric fan-out outside the tracker lock (registry locks its
        # own metrics; holding ours across it invites ordering bugs)
        from ..metrics import registry as metrics_registry
        mr = metrics_registry.REGISTRY
        if mr is not None:
            mr.counter("srtpu_slo_events_total", tenant=tenant,
                       status="bad" if bad else "good").inc()
            mr.gauge("srtpu_slo_burn_rate", tenant=tenant,
                     window="short").set(round(short, 4))
            mr.gauge("srtpu_slo_burn_rate", tenant=tenant,
                     window="long").set(round(long_, 4))
            mr.gauge("srtpu_slo_error_budget_remaining",
                     tenant=tenant).set(round(
                         budget_remaining(tstate,
                                          objective=objective), 4))
        return alert

    # tpulint: never-raise
    def _fire_alert(self, tenant: str) -> None:
        """Alert fan-out: counter + flight trigger. Never raises —
        the caller is the query-completion path."""
        try:
            from ..metrics import registry as metrics_registry
            mr = metrics_registry.REGISTRY
            if mr is not None:
                mr.counter("srtpu_slo_burn_alerts_total",
                           tenant=tenant).inc()
            from .flight import RECORDER as _frec
            if _frec is not None:
                with self._lock:
                    detail = {"tenant": tenant,
                              "burnThreshold": self.burn_threshold,
                              "exemplars": list(self._exemplars[-8:])}
                _frec.trigger("slo_burn",
                              detail=json.dumps(detail, sort_keys=True,
                                                default=str))
            log.warning("slo burn alert: tenant=%s burning > %gx over "
                        "both windows", tenant, self.burn_threshold)
        except Exception as e:  # noqa: BLE001 - observability only
            log.warning("slo alert fan-out failed: %s", e)

    # ------------------------------------------------------------- reads
    def shed_hint(self, now: Optional[float] = None) -> Optional[str]:
        """The live burn-driven shed reason, or None. Consulted by
        ``sched.admission.shed_reason`` on every admission attempt —
        cheap (one lock, two compares) and self-expiring."""
        if not self.shed_enabled:
            return None
        t = time.time() if now is None else float(now)
        with self._lock:
            tenant, until = self._shed_until
        if until > t:
            return f"slo_burn:{tenant}"
        return None

    def digest_breaches(self, digest: str) -> int:
        """Over-target observation count for a digest (AQE feedback)."""
        with self._lock:
            d = self._digests.get(str(digest))
            return int(d["over"]) if d else 0

    def exemplars(self) -> List[dict]:
        """Newest-first exemplar ring copy."""
        with self._lock:
            return [dict(e) for e in reversed(self._exemplars)]

    def latest_exemplar(self, tenant: str) -> Optional[dict]:
        with self._lock:
            for e in reversed(self._exemplars):
                if e.get("tenant") == tenant:
                    return dict(e)
        return None

    def report(self, now: Optional[float] = None) -> dict:
        """The GET /slo document: per-tenant burn rates and budget,
        worst digests by tail contribution, exemplars."""
        t = time.time() if now is None else float(now)
        with self._lock:
            tenants = {}
            for tenant in sorted(self._state):
                tstate = self._state[tenant]
                target_ms, objective = self.target_for(tenant)
                tenants[tenant] = {
                    "targetMs": target_ms,
                    "objective": objective,
                    "good": tstate["good"],
                    "bad": tstate["bad"],
                    "burn": {
                        "short": round(burn_rate(
                            tstate, now=t,
                            window_s=self.short_window_s,
                            objective=objective), 4),
                        "long": round(burn_rate(
                            tstate, now=t,
                            window_s=self.long_window_s,
                            objective=objective), 4)},
                    "errorBudgetRemaining": round(budget_remaining(
                        tstate, objective=objective), 4)}
            worst = sorted(
                ((dg, dict(d)) for dg, d in self._digests.items()
                 if d["over"] > 0),
                key=lambda kv: (-kv[1]["excessMs"], kv[0]))[:8]
            shed_tenant, shed_until = self._shed_until
            return {
                "windows": {"shortS": self.short_window_s,
                            "longS": self.long_window_s},
                "burnThreshold": self.burn_threshold,
                "alertsFired": self.alerts_fired,
                "shedActive": shed_until > t,
                "shedTenant": shed_tenant if shed_until > t else None,
                "tenants": tenants,
                "worstDigests": [
                    {"digest": dg, **d} for dg, d in worst],
                "exemplars": [dict(e) for e
                              in reversed(self._exemplars)]}

    def healthz(self, now: Optional[float] = None) -> dict:
        """The /healthz slo section: degraded while a burn alert's
        shed hint is live."""
        t = time.time() if now is None else float(now)
        rep = self.report(t)
        burning = sorted(
            tenant for tenant, d in rep["tenants"].items()
            if d["burn"]["short"] >= self.burn_threshold
            and d["burn"]["long"] >= self.burn_threshold)
        return {"status": "degraded" if burning else "ok",
                "burningTenants": burning,
                "alertsFired": rep["alertsFired"],
                "shedActive": rep["shedActive"],
                "exemplars": len(rep["exemplars"])}

    def export_gauges(self, reg) -> None:
        """Refresh the per-tenant burn/budget gauges from the current
        clock (sampler pass) — burn rates DECAY as bad events age out
        of their windows, and a gauge last set at observe time would
        freeze a stale alarm on /metrics."""
        rep = self.report()
        for tenant, d in rep["tenants"].items():
            reg.gauge("srtpu_slo_burn_rate", tenant=tenant,
                      window="short").set(d["burn"]["short"])
            reg.gauge("srtpu_slo_burn_rate", tenant=tenant,
                      window="long").set(d["burn"]["long"])
            reg.gauge("srtpu_slo_error_budget_remaining",
                      tenant=tenant).set(d["errorBudgetRemaining"])

    def decorate_snapshot(self, snap: dict) -> dict:
        """Attach each tenant's newest exemplar to its
        ``srtpu_query_latency_seconds`` summary series (mutates and
        returns ``snap``) — the OpenMetrics exemplar hop from a
        /metrics quantile line to the on-disk artifact."""
        ent = snap.get("srtpu_query_latency_seconds")
        for s in (ent or {}).get("series") or []:
            tenant = (s.get("labels") or {}).get("tenant")
            ex = self.latest_exemplar(tenant) if tenant else None
            if ex is None:
                continue
            labels = {"query_id": str(ex.get("queryId")),
                      "tenant": tenant}
            if ex.get("trace"):
                labels["trace_path"] = str(ex["trace"])
            if ex.get("flight"):
                labels["flight_path"] = str(ex["flight"])
            if ex.get("planDigest"):
                labels["plan_digest"] = str(ex["planDigest"])
            s["exemplar"] = {"labels": labels,
                             "value": ex["wallMs"] / 1000.0,
                             "ts": ex["tsMs"] / 1000.0}
        return snap


# ---------------------------------------------------------------------------
# installation (the trace/metrics pattern)
# ---------------------------------------------------------------------------

_INSTALL_LOCK = threading.Lock()


def active_slo() -> Optional[SloTracker]:
    # tpulint: disable=lock-discipline — lock-free by design: the
    # disabled-path contract is one unlocked reference read per site
    return TRACKER


def install_slo(tracker: Optional[SloTracker]) -> Optional[SloTracker]:
    """Install (or with ``None`` remove) the process-global tracker."""
    global TRACKER
    with _INSTALL_LOCK:
        TRACKER = tracker
    return tracker


def ensure_slo_from_conf(conf) -> Optional[SloTracker]:
    """Install a tracker iff ``spark.rapids.tpu.slo.enabled`` — one
    conf lookup per ExecContext construction, never per query."""
    global TRACKER
    if not conf.get(SLO_ENABLED):
        # tpulint: disable=lock-discipline — lock-free by design:
        # slo-off fast path; installation itself locks below
        return TRACKER
    with _INSTALL_LOCK:
        if TRACKER is None:
            TRACKER = SloTracker(
                target_ms=float(conf.get(SLO_TARGET_MS)),
                objective=float(conf.get(SLO_OBJECTIVE)),
                tenant_overrides=parse_tenant_overrides(
                    str(conf.get(SLO_TENANT_OVERRIDES) or "")),
                short_window_s=float(conf.get(SLO_SHORT_WINDOW_S)),
                long_window_s=float(conf.get(SLO_LONG_WINDOW_S)),
                burn_threshold=float(conf.get(SLO_BURN_THRESHOLD)),
                exemplar_cap=int(conf.get(SLO_EXEMPLARS)),
                shed_enabled=bool(conf.get(SLO_SHED_ENABLED)),
                digest_cap=int(conf.get(SLO_DIGESTS)))
        return TRACKER
