from .mesh import Mesh, NamedSharding, P, make_mesh, replicated, row_sharding
from .collective import (build_distributed_agg_step,
                         build_distributed_join_step, distributed_groupby,
                         distributed_join)

__all__ = ["Mesh", "NamedSharding", "P", "make_mesh", "replicated",
           "row_sharding", "build_distributed_agg_step",
           "distributed_groupby", "distributed_join"]
