from .mesh import Mesh, NamedSharding, P, make_mesh, replicated, row_sharding
from .collective import build_distributed_agg_step, distributed_groupby

__all__ = ["Mesh", "NamedSharding", "P", "make_mesh", "replicated",
           "row_sharding", "build_distributed_agg_step",
           "distributed_groupby"]
