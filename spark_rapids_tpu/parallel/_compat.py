"""jax version compatibility for the SPMD layer.

``shard_map`` moved from ``jax.experimental.shard_map`` to a top-level
export (jax >= 0.4.31 keeps both, newer releases only the latter), and
its replication-check kwarg was renamed ``check_rep`` -> ``check_vma``.
This shim presents the NEW surface (top-level name, ``check_vma``) on
either jax, so call sites never branch on version.
"""
from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:                     # older jax: experimental only
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    global _PARAMS
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if check_vma is not None:
        if _PARAMS is None:
            try:
                _PARAMS = frozenset(
                    inspect.signature(_shard_map).parameters)
            except (TypeError, ValueError):
                _PARAMS = frozenset()
        if "check_vma" in _PARAMS:
            kwargs["check_vma"] = check_vma
        elif "check_rep" in _PARAMS:    # pre-rename spelling
            kwargs["check_rep"] = check_vma
    return _shard_map(f, **kwargs)
