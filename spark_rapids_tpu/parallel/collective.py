"""Multi-chip SPMD query execution over a device mesh.

This is the ICI/DCN replacement for the reference's shuffle transport
(SURVEY.md section 2.10 "TPU equivalent"): instead of UCX point-to-point RDMA
between executor processes (RapidsShuffleClient.doFetch), the whole exchange
is ONE XLA `all_to_all` collective inside a shard_map'd program — batches
stay in HBM, XLA schedules the ICI transfers, and DCN handles cross-slice
legs automatically for meshes spanning slices.

Distributed aggregation pipeline (per device, lockstep SPMD):
  1. local filter/project + first-pass segmented groupby  (compute, no comm)
  2. route each local group to owner = key_hash % n_devices
  3. all_to_all the routed group partials                 (ICI)
  4. merge-pass groupby over received partials            (compute)
  5. finalize -> each device owns a disjoint set of final groups
This is the same update/merge maths as the single-chip path (shared
exec/groupby_core.py), so distributing cannot change results.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import shard_map

from ..exprs.base import DVal, EvalContext, Expression
from ..exec.groupby_core import segmented_groupby
from ..types import Schema

__all__ = ["build_distributed_agg_step", "distributed_groupby",
           "build_distributed_join_step", "distributed_join"]

# Engine-INTERNAL routing hash for group->owner placement (placement here
# never needs Spark parity — unlike shuffle partitioning, which uses the
# Spark-exact Murmur3 in exprs/hash_fns.py). 32-bit mixing only, so it
# works for every device dtype including f64 (hashed via its f32 image;
# equal keys still hash equal, the only requirement) — TPU has no f64
# bitcast (hash_fns.py device notes).

_M1 = jnp.uint32(0x85EBCA6B)
_M2 = jnp.uint32(0xC2B2AE35)


def _mix32(h):
    h = h ^ (h >> jnp.uint32(16))
    h = h * _M1
    h = h ^ (h >> jnp.uint32(13))
    h = h * _M2
    h = h ^ (h >> jnp.uint32(16))
    return h


def _col_hash_u32(v: DVal):
    d = v.data
    if jnp.issubdtype(d.dtype, jnp.floating):
        f = d.astype(jnp.float32)
        f = jnp.where(f == 0.0, jnp.zeros_like(f), f)
        f = jnp.where(jnp.isnan(f), jnp.full_like(f, jnp.nan), f)
        h = jax.lax.bitcast_convert_type(f, jnp.uint32)
    elif d.dtype == jnp.bool_:
        h = d.astype(jnp.uint32)
    else:
        x = d.astype(jnp.int64)
        lo = (x & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (x >> jnp.int64(32)).astype(jnp.uint32)
        h = lo ^ _mix32(hi)
    # null contributes a fixed tag so null keys land together
    return jnp.where(v.validity, _mix32(h), jnp.uint32(42))


def _route_to_buffers(arrays, pid, padded_len: int, n_dev: int):
    """Pack rows into (n_dev, padded_len) send buffers by destination.

    Worst case (every row to one destination) still fits because the chunk
    size equals the local padded length; slot = pid*P + rank-within-pid,
    computed via one stable sort by pid (the contiguous-split trick)."""
    order = jnp.argsort(pid, stable=True)
    s_pid = jnp.take(pid, order)
    idx = jnp.arange(padded_len, dtype=jnp.int32)
    first_of_pid = jnp.logical_or(idx == 0, s_pid != jnp.roll(s_pid, 1))
    seg_start = jnp.where(first_of_pid, idx, 0)
    seg_start = jax.lax.associative_scan(jnp.maximum, seg_start)
    intra = idx - seg_start
    slot = jnp.where(s_pid < n_dev, s_pid * padded_len + intra,
                     n_dev * padded_len)
    outs = []
    for d, v in arrays:
        sd = jnp.take(d, order)
        sv = jnp.take(v, order)
        od = jnp.zeros((n_dev * padded_len,), dtype=d.dtype) \
            .at[slot].set(sd, mode="drop")
        ov = jnp.zeros((n_dev * padded_len,), dtype=jnp.bool_) \
            .at[slot].set(jnp.logical_and(sv, s_pid < n_dev), mode="drop")
        outs.append((od.reshape(n_dev, padded_len),
                     ov.reshape(n_dev, padded_len)))
    return outs


def _compact_rows(arrays, keep, length):
    """Move keep-rows to the front; arrays are (data, validity) pairs;
    returns compacted pairs + count. Sort-based (segmented.compact_rows):
    scatter compaction serializes on the TPU scalar core."""
    from ..columnar.segmented import compact_rows
    masked = [(d, jnp.logical_and(v, keep)) for d, v in arrays]
    return compact_rows(masked, keep, length)


def build_distributed_agg_step(mesh: Mesh, schema: Schema,
                               key_exprs: Sequence[Expression],
                               aggs: Sequence,
                               local_padded: int,
                               pre_filter: Optional[Expression] = None,
                               axis: str = "data"):
    """Compile the full distributed query step: returns fn(cols, num_rows)
    where cols are GLOBAL (n_dev*local_padded,) arrays sharded on `axis` and
    num_rows is a (n_dev,) int32 vector of per-shard row counts. Output:
    per-device final group columns (global (n_dev*local_padded,)) and a
    (n_dev,) group-count vector."""
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    dtypes = [f.dtype for f in schema.fields]
    partial_counts = [len(a.partial_types(schema)) for a in aggs]

    _compact = _compact_rows

    def local_step(nrows, *cols):
        P_ = local_padded
        nloc = nrows[0]
        dvals = [DVal(d, v, dt)
                 for d, v, dt in zip(cols[0::2], cols[1::2], dtypes)]
        ctx = EvalContext(schema, dvals, nloc, P_)
        # 1. local filter: evaluate predicate, compact surviving rows
        keys = [e.eval_device(ctx) for e in key_exprs]
        vals = [[e.eval_device(ctx) for e in a.input_exprs()] for a in aggs]
        flat = [(k.data, k.validity) for k in keys]
        for vs in vals:
            flat.extend((v.data, v.validity) for v in vs)
        if pre_filter is not None:
            keep = pre_filter.eval_device(ctx)
            keepb = jnp.logical_and(jnp.logical_and(keep.data, keep.validity),
                                    ctx.row_mask())
            flat, nloc = _compact(flat, keepb, P_)
        # rebuild DVals (post-compaction or as-is)
        ai = 0
        keys2, vals2 = [], []
        for k in keys:
            keys2.append(DVal(flat[ai][0], flat[ai][1], k.dtype))
            ai += 1
        for vs in vals:
            cur = []
            for v in vs:
                cur.append(DVal(flat[ai][0], flat[ai][1], v.dtype))
                ai += 1
            vals2.append(cur)
        # 2. first-pass local aggregation
        key_outs, partial_outs, n_groups = segmented_groupby(
            keys2, vals2, aggs, "update", nloc, P_)
        # 3. route groups to owners by key hash
        glive = jnp.arange(P_, dtype=jnp.int32) < n_groups
        if key_exprs:
            h = jnp.full(P_, jnp.uint32(42))
            for (kd, kv), k in zip(key_outs, keys2):
                h = _mix32(h * jnp.uint32(31)
                           + _col_hash_u32(DVal(kd, kv, k.dtype)))
            pid = jnp.where(glive, (h % jnp.uint32(n_dev)).astype(jnp.int32),
                            jnp.int32(n_dev))
        else:
            pid = jnp.where(glive, 0, n_dev)  # global agg -> device 0
        bufs = _route_to_buffers(key_outs + partial_outs, pid, P_, n_dev)
        # 4. ICI all_to_all: every device receives the groups it owns
        recv = []
        for d, v in bufs:
            rd = jax.lax.all_to_all(d, axis, 0, 0, tiled=False)
            rv = jax.lax.all_to_all(v, axis, 0, 0, tiled=False)
            recv.append((rd.reshape(n_dev * P_), rv.reshape(n_dev * P_)))
        # compact received group rows (validity marks real rows; count is
        # never null so every live group row has >=1 valid column)
        live = jnp.zeros(n_dev * P_, dtype=jnp.bool_)
        for _, v in recv:
            live = jnp.logical_or(live, v)
        comp, cnt = _compact(recv, live, n_dev * P_)
        # 5. merge pass over received partials
        rkeys = [DVal(comp[i][0], comp[i][1], k.dtype)
                 for i, k in enumerate(keys2)]
        rvals = []
        ai = len(keys2)
        for a, npart in zip(aggs, partial_counts):
            pts = a.partial_types(schema)
            rvals.append([DVal(comp[ai + j][0], comp[ai + j][1], pts[j])
                          for j in range(npart)])
            ai += npart
        mkey_outs, mpartial_outs, m_groups = segmented_groupby(
            rkeys, rvals, aggs, "merge", cnt, n_dev * P_)
        if not key_exprs:
            # the single global group lives on device 0 only
            m_groups = jnp.where(jax.lax.axis_index(axis) == 0,
                                 m_groups, 0)
        # 6. finalize
        glive2 = jnp.arange(n_dev * P_, dtype=jnp.int32) < m_groups
        outs = []
        for d, v in mkey_outs:
            outs.extend([d, jnp.logical_and(v, glive2)])
        ai = 0
        for a, npart in zip(aggs, partial_counts):
            pts = a.partial_types(schema)
            parts = [DVal(mpartial_outs[ai + j][0], mpartial_outs[ai + j][1],
                          pts[j]) for j in range(npart)]
            ai += npart
            f = a.finalize(parts)
            outs.extend([f.data, jnp.logical_and(f.validity, glive2)])
        return (m_groups.reshape(1),) + tuple(outs)

    in_specs = (P(axis),) + tuple(P(axis) for _ in range(2 * len(dtypes)))
    n_out = 1 + 2 * (len(key_exprs) + len(aggs))
    out_specs = (P(axis),) + tuple(P(axis) for _ in range(n_out - 1))

    fn = shard_map(local_step, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_vma=False)
    return jax.jit(fn), n_dev


def distributed_groupby(mesh: Mesh, table, key_names: List[str], aggs,
                        pre_filter=None, axis: str = "data"):
    """Host-friendly wrapper: Arrow table -> sharded arrays -> distributed
    step -> Arrow result table. Used by tests and the dryrun."""
    import pyarrow as pa
    from ..columnar import ColumnarBatch
    from ..columnar.bucketing import bucket_for
    from ..exprs.base import ColumnRef
    from ..types import to_arrow

    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    n = table.num_rows
    per = -(-n // n_dev)
    local_p = bucket_for(max(per, 1))
    schema = ColumnarBatch.from_arrow_host(table).schema
    key_exprs = [ColumnRef(k) for k in key_names]
    step, _ = build_distributed_agg_step(mesh, schema, key_exprs, aggs,
                                         local_p, pre_filter, axis)
    nrows_dev, cols_dev = _shard_table_arrays(mesh, table, schema,
                                              local_p, axis)
    out = step(nrows_dev, *cols_dev)
    m_groups = np.asarray(jax.device_get(out[0]))
    data = [np.asarray(jax.device_get(x)) for x in out[1:]]
    # stitch per-device group slices
    names = key_names + [a.name_hint for a in aggs]
    dtypes = [schema[k].dtype for k in key_names] + \
        [a.data_type(schema) for a in aggs]
    chunk = n_dev * local_p
    arrays = []
    for ci in range(len(names)):
        d_all, v_all = data[2 * ci], data[2 * ci + 1]
        parts_d, parts_v = [], []
        for dev in range(n_dev):
            g = int(m_groups[dev])
            parts_d.append(d_all[dev * chunk: dev * chunk + g])
            parts_v.append(v_all[dev * chunk: dev * chunk + g])
        dv = np.concatenate(parts_d)
        vv = np.concatenate(parts_v)
        from ..columnar.column import DeviceColumn
        col = DeviceColumn(jnp.asarray(dv), jnp.asarray(vv), dtypes[ci])
        arrays.append(col.to_arrow(len(dv)))
    return pa.Table.from_arrays(arrays, names=names)


# ---------------------------------------------------------------------------
# distributed equi-join (the ICI analog of the reference's UCX shuffle join:
# both sides hash-route rows to key owners with ONE all_to_all each, then
# every device runs the local sort-based join kernel on its co-partitioned
# slice — the same kernel as single-chip exec/joins.py, so distribution
# cannot change results)
# ---------------------------------------------------------------------------

def build_distributed_join_step(mesh: Mesh, lschema: Schema,
                                rschema: Schema,
                                lkey_exprs: Sequence[Expression],
                                rkey_exprs: Sequence[Expression],
                                local_padded: int, out_factor: int = 4,
                                axis: str = "data"):
    """Returns fn(nl, nr, *lcols, *rcols) under shard_map. Per device the
    local join output is bounded by ``out_factor * local_padded`` rows
    (static shapes: XLA requirement); the returned per-device `total` lets
    the caller detect overflow and re-run with a larger factor."""
    from ..exec.joins import _build_count_kernel, _gather_index_kernel
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    ldtypes = [f.dtype for f in lschema.fields]
    rdtypes = [f.dtype for f in rschema.fields]
    P_ = local_padded
    RP = n_dev * P_                 # received rows bound per device
    OUT = out_factor * P_           # local join output bound
    count_k = _build_count_kernel(lkey_exprs, rkey_exprs, lschema, rschema,
                                  "inner")

    # both sides must hash each key through a COMMON dtype, or equal keys
    # of different widths route to different owners and matches silently
    # vanish (the local count kernel promotes before comparing; routing
    # must promote identically)
    l0, r0 = lschema, rschema
    key_np = [np.promote_types(lk.data_type(l0).np_dtype,
                               rk.data_type(r0).np_dtype)
              for lk, rk in zip(lkey_exprs, rkey_exprs)]

    def route_side(nloc, pairs, dtypes, schema, key_exprs):
        dvals = [DVal(d, v, dt) for (d, v), dt in zip(pairs, dtypes)]
        ctx = EvalContext(schema, dvals, nloc, P_)
        live = ctx.row_mask()
        keys = [e.eval_device(ctx) for e in key_exprs]
        h = jnp.full(P_, jnp.uint32(42))
        for k, npdt in zip(keys, key_np):
            kk = DVal(k.data.astype(npdt), k.validity, k.dtype)
            h = _mix32(h * jnp.uint32(31) + _col_hash_u32(kk))
        pid = jnp.where(live, (h % jnp.uint32(n_dev)).astype(jnp.int32),
                        jnp.int32(n_dev))
        # explicit liveness lane: a routed row may be all-null, so column
        # validities cannot double as the row-live flag
        flat = list(pairs) + [(jnp.ones(P_, jnp.int8), live)]
        bufs = _route_to_buffers(flat, pid, P_, n_dev)
        recv = []
        for d, v in bufs:
            rd = jax.lax.all_to_all(d, axis, 0, 0, tiled=False)
            rv = jax.lax.all_to_all(v, axis, 0, 0, tiled=False)
            recv.append((rd.reshape(RP), rv.reshape(RP)))
        live_recv = recv[-1][1]
        comp, cnt = _compact_rows(recv[:-1], live_recv, RP)
        return comp, cnt

    def local(nl, nr, *cols):
        nL, nR = len(ldtypes), len(rdtypes)
        lpairs = [(cols[2 * i], cols[2 * i + 1]) for i in range(nL)]
        rpairs = [(cols[2 * nL + 2 * i], cols[2 * nL + 2 * i + 1])
                  for i in range(nR)]
        lcomp, nl2 = route_side(nl[0], lpairs, ldtypes, lschema, lkey_exprs)
        rcomp, nr2 = route_side(nr[0], rpairs, rdtypes, rschema, rkey_exprs)
        (s_orig, cnt_l, cnt_r, start_l, start_r, _pairs, offsets, total,
         _ng) = count_k(lcomp, rcomp, nl2, nr2, RP, RP)
        cfg = jnp.zeros(3, dtype=jnp.int32)       # inner join
        l_row, r_row = _gather_index_kernel(
            s_orig, cnt_l, cnt_r, start_l, start_r, offsets, cfg, OUT)
        out_live = jnp.arange(OUT, dtype=jnp.int64) < total
        outs = []
        for d, v in lcomp:
            idx = jnp.clip(l_row, 0, None)
            outs.append(jnp.take(d, idx, mode="clip"))
            outs.append(jnp.logical_and(
                jnp.take(v, idx, mode="clip"),
                jnp.logical_and(out_live, l_row >= 0)))
        for d, v in rcomp:
            idx = jnp.clip(r_row, 0, None)
            outs.append(jnp.take(d, idx, mode="clip"))
            outs.append(jnp.logical_and(
                jnp.take(v, idx, mode="clip"),
                jnp.logical_and(out_live, r_row >= 0)))
        return (total.astype(jnp.int64).reshape(1),
                out_live.reshape(1, OUT)) + tuple(
                    o.reshape(1, OUT) for o in outs)

    n_in = 2 * (len(ldtypes) + len(rdtypes))
    in_specs = (P(axis), P(axis)) + tuple(P(axis) for _ in range(n_in))
    n_out = 2 + n_in
    out_specs = tuple(P(axis) for _ in range(n_out))
    fn = shard_map(local, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_vma=False)
    return jax.jit(fn), n_dev, OUT


def _shard_table_arrays(mesh, table, schema, local_p, axis):
    """Split an Arrow table row-wise across the mesh into padded, sharded
    global (data, validity) device arrays + per-shard row counts."""
    from ..columnar import ColumnarBatch
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    per = -(-table.num_rows // n_dev) if table.num_rows else 1
    shards = [table.slice(i * per, per) for i in range(n_dev)]
    nrows = np.array([s.num_rows for s in shards], dtype=np.int32)
    sharding = NamedSharding(mesh, P(axis))
    cols_dev = []
    for f in schema.fields:
        ds, vs = [], []
        for s in shards:
            b = ColumnarBatch.from_arrow(s.select([f.name]))
            c = b.columns[0]
            d = np.asarray(jax.device_get(c.data))
            v = np.asarray(jax.device_get(c.validity))
            if d.shape[0] < local_p:
                d = np.pad(d, (0, local_p - d.shape[0]))
                v = np.pad(v, (0, local_p - v.shape[0]))
            ds.append(d[:local_p])
            vs.append(v[:local_p])
        cols_dev.append(jax.device_put(jnp.asarray(np.concatenate(ds)),
                                       sharding))
        cols_dev.append(jax.device_put(jnp.asarray(np.concatenate(vs)),
                                       sharding))
    nrows_dev = jax.device_put(jnp.asarray(nrows), sharding)
    return nrows_dev, cols_dev


def distributed_join(mesh: Mesh, ltable, rtable, on, out_factor: int = 4,
                     axis: str = "data"):
    """Host-friendly wrapper: inner equi-join of two Arrow tables over the
    mesh; returns the joined Arrow table (l columns then r columns).
    ``on`` is a list of (left_col, right_col) name pairs."""
    import pyarrow as pa
    from ..columnar import ColumnarBatch
    from ..columnar.bucketing import bucket_for
    from ..columnar.column import DeviceColumn
    from ..exprs.base import ColumnRef

    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    per = max(-(-max(ltable.num_rows, rtable.num_rows) // n_dev), 1)
    local_p = bucket_for(per)
    lschema = ColumnarBatch.from_arrow_host(ltable).schema
    rschema = ColumnarBatch.from_arrow_host(rtable).schema
    lkeys = [ColumnRef(a) for a, _ in on]
    rkeys = [ColumnRef(b) for _, b in on]
    step, _, OUT = build_distributed_join_step(
        mesh, lschema, rschema, lkeys, rkeys, local_p, out_factor, axis)
    nl, lcols = _shard_table_arrays(mesh, ltable, lschema, local_p, axis)
    nr, rcols = _shard_table_arrays(mesh, rtable, rschema, local_p, axis)
    out = step(nl, nr, *(lcols + rcols))
    totals = np.asarray(jax.device_get(out[0]))
    if (totals > OUT).any():
        raise RuntimeError(
            f"distributed join output overflowed the static bound "
            f"(max {int(totals.max())} > {OUT}); re-run with a larger "
            f"out_factor")
    data = [np.asarray(jax.device_get(x)) for x in out[2:]]
    names = [f.name for f in lschema.fields] + \
        [f.name for f in rschema.fields]
    dtypes = [f.dtype for f in lschema.fields] + \
        [f.dtype for f in rschema.fields]
    arrays = []
    for ci in range(len(names)):
        d_all, v_all = data[2 * ci], data[2 * ci + 1]
        parts_d, parts_v = [], []
        for dev in range(n_dev):
            g = int(totals[dev])
            parts_d.append(d_all[dev][:g])
            parts_v.append(v_all[dev][:g])
        dv = np.concatenate(parts_d)
        vv = np.concatenate(parts_v)
        col = DeviceColumn(jnp.asarray(dv), jnp.asarray(vv), dtypes[ci])
        arrays.append(col.to_arrow(len(dv)))
    return pa.Table.from_arrays(arrays, names=names)
