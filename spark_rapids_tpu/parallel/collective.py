"""Multi-chip SPMD query execution over a device mesh.

This is the ICI/DCN replacement for the reference's shuffle transport
(SURVEY.md section 2.10 "TPU equivalent"): instead of UCX point-to-point RDMA
between executor processes (RapidsShuffleClient.doFetch), the whole exchange
is ONE XLA `all_to_all` collective inside a shard_map'd program — batches
stay in HBM, XLA schedules the ICI transfers, and DCN handles cross-slice
legs automatically for meshes spanning slices.

Distributed aggregation pipeline (per device, lockstep SPMD):
  1. local filter/project + first-pass segmented groupby  (compute, no comm)
  2. route each local group to owner = key_hash % n_devices
  3. all_to_all the routed group partials                 (ICI)
  4. merge-pass groupby over received partials            (compute)
  5. finalize -> each device owns a disjoint set of final groups
This is the same update/merge maths as the single-chip path (shared
exec/groupby_core.py), so distributing cannot change results.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..exprs.base import DVal, EvalContext, Expression
from ..exec.groupby_core import segmented_groupby
from ..types import Schema

__all__ = ["build_distributed_agg_step", "distributed_groupby"]

# Engine-INTERNAL routing hash for group->owner placement (placement here
# never needs Spark parity — unlike shuffle partitioning, which uses the
# Spark-exact Murmur3 in exprs/hash_fns.py). 32-bit mixing only, so it
# works for every device dtype including f64 (hashed via its f32 image;
# equal keys still hash equal, the only requirement) — TPU has no f64
# bitcast (hash_fns.py device notes).

_M1 = jnp.uint32(0x85EBCA6B)
_M2 = jnp.uint32(0xC2B2AE35)


def _mix32(h):
    h = h ^ (h >> jnp.uint32(16))
    h = h * _M1
    h = h ^ (h >> jnp.uint32(13))
    h = h * _M2
    h = h ^ (h >> jnp.uint32(16))
    return h


def _col_hash_u32(v: DVal):
    d = v.data
    if jnp.issubdtype(d.dtype, jnp.floating):
        f = d.astype(jnp.float32)
        f = jnp.where(f == 0.0, jnp.zeros_like(f), f)
        f = jnp.where(jnp.isnan(f), jnp.full_like(f, jnp.nan), f)
        h = jax.lax.bitcast_convert_type(f, jnp.uint32)
    elif d.dtype == jnp.bool_:
        h = d.astype(jnp.uint32)
    else:
        x = d.astype(jnp.int64)
        lo = (x & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (x >> jnp.int64(32)).astype(jnp.uint32)
        h = lo ^ _mix32(hi)
    # null contributes a fixed tag so null keys land together
    return jnp.where(v.validity, _mix32(h), jnp.uint32(42))


def _route_to_buffers(arrays, pid, padded_len: int, n_dev: int):
    """Pack rows into (n_dev, padded_len) send buffers by destination.

    Worst case (every row to one destination) still fits because the chunk
    size equals the local padded length; slot = pid*P + rank-within-pid,
    computed via one stable sort by pid (the contiguous-split trick)."""
    order = jnp.argsort(pid, stable=True)
    s_pid = jnp.take(pid, order)
    idx = jnp.arange(padded_len, dtype=jnp.int32)
    first_of_pid = jnp.logical_or(idx == 0, s_pid != jnp.roll(s_pid, 1))
    seg_start = jnp.where(first_of_pid, idx, 0)
    seg_start = jax.lax.associative_scan(jnp.maximum, seg_start)
    intra = idx - seg_start
    slot = jnp.where(s_pid < n_dev, s_pid * padded_len + intra,
                     n_dev * padded_len)
    outs = []
    for d, v in arrays:
        sd = jnp.take(d, order)
        sv = jnp.take(v, order)
        od = jnp.zeros((n_dev * padded_len,), dtype=d.dtype) \
            .at[slot].set(sd, mode="drop")
        ov = jnp.zeros((n_dev * padded_len,), dtype=jnp.bool_) \
            .at[slot].set(jnp.logical_and(sv, s_pid < n_dev), mode="drop")
        outs.append((od.reshape(n_dev, padded_len),
                     ov.reshape(n_dev, padded_len)))
    return outs


def build_distributed_agg_step(mesh: Mesh, schema: Schema,
                               key_exprs: Sequence[Expression],
                               aggs: Sequence,
                               local_padded: int,
                               pre_filter: Optional[Expression] = None,
                               axis: str = "data"):
    """Compile the full distributed query step: returns fn(cols, num_rows)
    where cols are GLOBAL (n_dev*local_padded,) arrays sharded on `axis` and
    num_rows is a (n_dev,) int32 vector of per-shard row counts. Output:
    per-device final group columns (global (n_dev*local_padded,)) and a
    (n_dev,) group-count vector."""
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    dtypes = [f.dtype for f in schema.fields]
    partial_counts = [len(a.partial_types(schema)) for a in aggs]

    def _compact(arrays, keep, length):
        """Move keep-rows to the front (same cumsum+scatter as the filter
        kernel); returns compacted arrays + count."""
        cnt = jnp.sum(keep).astype(jnp.int32)
        pos = jnp.where(keep, jnp.cumsum(keep) - 1, length)
        out = []
        for d, v in arrays:
            cd = jnp.zeros_like(d).at[pos].set(d, mode="drop")
            cv = jnp.zeros_like(v).at[pos].set(
                jnp.logical_and(v, keep), mode="drop")
            out.append((cd, cv))
        return out, cnt

    def local_step(nrows, *cols):
        P_ = local_padded
        nloc = nrows[0]
        dvals = [DVal(d, v, dt)
                 for d, v, dt in zip(cols[0::2], cols[1::2], dtypes)]
        ctx = EvalContext(schema, dvals, nloc, P_)
        # 1. local filter: evaluate predicate, compact surviving rows
        keys = [e.eval_device(ctx) for e in key_exprs]
        vals = [[e.eval_device(ctx) for e in a.input_exprs()] for a in aggs]
        flat = [(k.data, k.validity) for k in keys]
        for vs in vals:
            flat.extend((v.data, v.validity) for v in vs)
        if pre_filter is not None:
            keep = pre_filter.eval_device(ctx)
            keepb = jnp.logical_and(jnp.logical_and(keep.data, keep.validity),
                                    ctx.row_mask())
            flat, nloc = _compact(flat, keepb, P_)
        # rebuild DVals (post-compaction or as-is)
        ai = 0
        keys2, vals2 = [], []
        for k in keys:
            keys2.append(DVal(flat[ai][0], flat[ai][1], k.dtype))
            ai += 1
        for vs in vals:
            cur = []
            for v in vs:
                cur.append(DVal(flat[ai][0], flat[ai][1], v.dtype))
                ai += 1
            vals2.append(cur)
        # 2. first-pass local aggregation
        key_outs, partial_outs, n_groups = segmented_groupby(
            keys2, vals2, aggs, "update", nloc, P_)
        # 3. route groups to owners by key hash
        glive = jnp.arange(P_, dtype=jnp.int32) < n_groups
        if key_exprs:
            h = jnp.full(P_, jnp.uint32(42))
            for (kd, kv), k in zip(key_outs, keys2):
                h = _mix32(h * jnp.uint32(31)
                           + _col_hash_u32(DVal(kd, kv, k.dtype)))
            pid = jnp.where(glive, (h % jnp.uint32(n_dev)).astype(jnp.int32),
                            jnp.int32(n_dev))
        else:
            pid = jnp.where(glive, 0, n_dev)  # global agg -> device 0
        bufs = _route_to_buffers(key_outs + partial_outs, pid, P_, n_dev)
        # 4. ICI all_to_all: every device receives the groups it owns
        recv = []
        for d, v in bufs:
            rd = jax.lax.all_to_all(d, axis, 0, 0, tiled=False)
            rv = jax.lax.all_to_all(v, axis, 0, 0, tiled=False)
            recv.append((rd.reshape(n_dev * P_), rv.reshape(n_dev * P_)))
        # compact received group rows (validity marks real rows; count is
        # never null so every live group row has >=1 valid column)
        live = jnp.zeros(n_dev * P_, dtype=jnp.bool_)
        for _, v in recv:
            live = jnp.logical_or(live, v)
        comp, cnt = _compact(recv, live, n_dev * P_)
        # 5. merge pass over received partials
        rkeys = [DVal(comp[i][0], comp[i][1], k.dtype)
                 for i, k in enumerate(keys2)]
        rvals = []
        ai = len(keys2)
        for a, npart in zip(aggs, partial_counts):
            pts = a.partial_types(schema)
            rvals.append([DVal(comp[ai + j][0], comp[ai + j][1], pts[j])
                          for j in range(npart)])
            ai += npart
        mkey_outs, mpartial_outs, m_groups = segmented_groupby(
            rkeys, rvals, aggs, "merge", cnt, n_dev * P_)
        if not key_exprs:
            # the single global group lives on device 0 only
            m_groups = jnp.where(jax.lax.axis_index(axis) == 0,
                                 m_groups, 0)
        # 6. finalize
        glive2 = jnp.arange(n_dev * P_, dtype=jnp.int32) < m_groups
        outs = []
        for d, v in mkey_outs:
            outs.extend([d, jnp.logical_and(v, glive2)])
        ai = 0
        for a, npart in zip(aggs, partial_counts):
            pts = a.partial_types(schema)
            parts = [DVal(mpartial_outs[ai + j][0], mpartial_outs[ai + j][1],
                          pts[j]) for j in range(npart)]
            ai += npart
            f = a.finalize(parts)
            outs.extend([f.data, jnp.logical_and(f.validity, glive2)])
        return (m_groups.reshape(1),) + tuple(outs)

    in_specs = (P(axis),) + tuple(P(axis) for _ in range(2 * len(dtypes)))
    n_out = 1 + 2 * (len(key_exprs) + len(aggs))
    out_specs = (P(axis),) + tuple(P(axis) for _ in range(n_out - 1))

    fn = shard_map(local_step, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_vma=False)
    return jax.jit(fn), n_dev


def distributed_groupby(mesh: Mesh, table, key_names: List[str], aggs,
                        pre_filter=None, axis: str = "data"):
    """Host-friendly wrapper: Arrow table -> sharded arrays -> distributed
    step -> Arrow result table. Used by tests and the dryrun."""
    import pyarrow as pa
    from ..columnar import ColumnarBatch
    from ..columnar.bucketing import bucket_for
    from ..exprs.base import ColumnRef
    from ..types import to_arrow

    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    n = table.num_rows
    per = -(-n // n_dev)
    local_p = bucket_for(max(per, 1))
    schema = ColumnarBatch.from_arrow(table, pad=False).schema
    key_exprs = [ColumnRef(k) for k in key_names]
    step, _ = build_distributed_agg_step(mesh, schema, key_exprs, aggs,
                                         local_p, pre_filter, axis)
    # build per-shard padded arrays
    shards = [table.slice(i * per, per) for i in range(n_dev)]
    nrows = np.array([s.num_rows for s in shards], dtype=np.int32)
    cols_flat = []
    for f in schema.fields:
        ds, vs = [], []
        for s in shards:
            b = ColumnarBatch.from_arrow(s.select([f.name]))
            c = b.columns[0]
            d = np.asarray(jax.device_get(c.data))
            v = np.asarray(jax.device_get(c.validity))
            if d.shape[0] < local_p:
                d = np.pad(d, (0, local_p - d.shape[0]))
                v = np.pad(v, (0, local_p - v.shape[0]))
            ds.append(d[:local_p])
            vs.append(v[:local_p])
        cols_flat.append(jnp.asarray(np.concatenate(ds)))
        cols_flat.append(jnp.asarray(np.concatenate(vs)))
    sharding = NamedSharding(mesh, P(axis))
    nrows_dev = jax.device_put(jnp.asarray(nrows), sharding)
    cols_dev = [jax.device_put(c, sharding) for c in cols_flat]
    out = step(nrows_dev, *cols_dev)
    m_groups = np.asarray(jax.device_get(out[0]))
    data = [np.asarray(jax.device_get(x)) for x in out[1:]]
    # stitch per-device group slices
    names = key_names + [a.name_hint for a in aggs]
    dtypes = [schema[k].dtype for k in key_names] + \
        [a.data_type(schema) for a in aggs]
    chunk = n_dev * local_p
    arrays = []
    for ci in range(len(names)):
        d_all, v_all = data[2 * ci], data[2 * ci + 1]
        parts_d, parts_v = [], []
        for dev in range(n_dev):
            g = int(m_groups[dev])
            parts_d.append(d_all[dev * chunk: dev * chunk + g])
            parts_v.append(v_all[dev * chunk: dev * chunk + g])
        dv = np.concatenate(parts_d)
        vv = np.concatenate(parts_v)
        from ..columnar.column import DeviceColumn
        col = DeviceColumn(jnp.asarray(dv), jnp.asarray(vv), dtypes[ci])
        arrays.append(col.to_arrow(len(dv)))
    return pa.Table.from_arrays(arrays, names=names)
