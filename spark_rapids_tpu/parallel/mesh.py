"""Device-mesh management for multi-chip execution.

The reference's distribution model is Spark's (1 GPU per executor,
Plugin.scala:536; peers discovered via driver heartbeats, UCX point-to-point
RDMA). TPU-native replacement: a jax.sharding.Mesh over the slice —
exchange = XLA collectives on ICI (all_to_all / psum), cross-slice = DCN —
executed SPMD under shard_map (SURVEY.md section 2.10 TPU-equivalent note).

Mesh axes for a SQL engine:
  "data"  — row-shard parallelism (the executor/task analog)
Future pods: 2D ("data", "host") so intra-host ICI carries the all-to-all
and DCN only sees the cross-host reduction.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "row_sharding", "replicated", "Mesh", "P",
           "NamedSharding"]


def make_mesh(n_devices: Optional[int] = None, axis: str = "data",
              devices: Optional[Sequence] = None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def row_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard leading (row) dimension across the mesh."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
