"""Planner-driven distributed execution: planned queries run on the mesh.

This is the analog of the reference's planner inserting shuffle exchanges
(GpuShuffleExchangeExecBase.scala:167, prepareBatchShuffleDependency:277 →
GpuPartitioning.scala:37) so every downstream operator runs distributed.
TPU-first shape: instead of per-task exchanges through a shuffle service,
the planner compiles the WHOLE supported plan fragment — scan → filter →
project → join → aggregate — into ONE SPMD program under ``shard_map`` over
a ``jax.sharding.Mesh``; exchanges become ``all_to_all`` collectives inside
the program (ICI/DCN, batches never leave HBM), exactly the design the
reference approximates with UCX device-to-device shuffle
(RapidsShuffleClient.doFetch).

Lowering contract (maybe_distribute):
  * walks the physical plan for the largest subtree expressible as a
    distributed fragment containing at least one join or aggregation
    (a fragment without comm gains nothing from the mesh);
  * replaces it with DistributedPipelineExec; everything above (final sort,
    limit, write) keeps running on the host driver over the collected
    result — the same division of labour as the reference's CPU-fallback
    boundary, with honest explain() output;
  * unsupported leaves degrade gracefully: any unsupported subtree becomes
    a host-executed SOURCE whose result is sharded onto the mesh (the
    row-to-columnar boundary analog, GpuRowToColumnarExec).

String columns ride the mesh as int32 codes into a per-column GLOBAL sorted
dictionary built at shard time (the multi-chip extension of the engine's
DictColumn design, columnar/column.py): code equality/order equals string
equality/order on every device, and only final materialization decodes.

Static-shape discipline (XLA): every per-device relation has a padded
length fixed at trace time. Join outputs and routed aggregations carry
speculative bounds validated AFTER execution from the fetched counts; an
overflow rebuilds the program with doubled bounds and re-runs (the
mesh-level analog of the engine's speculative join sizing with sink
validation, columnar/batch.py SpeculativeOverflow).
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import TpuConf, register
from ..exec.base import TpuExec
from ..types import INT32, INT64, STRING, DataType, Schema, StructField

log = logging.getLogger("spark_rapids_tpu.distributed")

__all__ = ["maybe_distribute", "try_distribute", "distribution_gate",
           "DistributedPipelineExec",
           "DISTRIBUTED_ENABLED", "DISTRIBUTED_NUM_DEVICES"]

DISTRIBUTED_ENABLED = register(
    "spark.rapids.tpu.distributed.enabled", True,
    "Lower planned queries onto the session's device mesh: the supported "
    "plan fragment compiles to one SPMD program with all_to_all exchanges "
    "(ref GpuShuffleExchangeExecBase.scala:167 — the planner, not the user, "
    "makes queries distributed). ON by default since r4: a mesh is built "
    "automatically when >1 device is visible, and the planner skips the "
    "mesh for inputs below distributed.minRows (a per-collective dispatch "
    "floor no small input can pay back). An explicitly-supplied session "
    "mesh always distributes.", commonly_used=True)

DISTRIBUTED_MIN_ROWS = register(
    "spark.rapids.tpu.distributed.minRows", 262144,
    "Auto-mesh threshold: a conf-built (non-explicit) mesh is only used "
    "for queries whose in-memory scan inputs reach this many rows — below "
    "it the exchange/dispatch overhead outweighs the parallelism (the "
    "reference's CBO transition-cost revert applied to distribution). "
    "File scans are always considered large enough.")

DISTRIBUTED_NUM_DEVICES = register(
    "spark.rapids.tpu.distributed.numDevices", 0,
    "Mesh size for distributed execution; 0 = all visible devices.")

DISTRIBUTED_MAX_GROUPS = register(
    "spark.rapids.tpu.distributed.maxPartialGroups", 65536,
    "Static per-device bound on first-pass groups routed through the "
    "all_to_all exchange; exceeded bounds double and re-run (speculative "
    "sizing, validated at the sink).")

DISTRIBUTED_OUT_FACTOR = register(
    "spark.rapids.tpu.distributed.joinOutFactor", 2,
    "Initial join-output bound as a multiple of the probe-side shard size; "
    "exceeded bounds double and re-run.")

DISTRIBUTED_MAX_DICT = register(
    "spark.rapids.tpu.distributed.maxDictEntries", 100_000,
    "Cardinality cap for the per-column GLOBAL sorted string dictionary "
    "built at shard time. Above the cap the column rides as 64-bit "
    "string hashes instead (no driver-side string sort — the decode map "
    "sorts only the int64 hashes); hash-coded columns keep equality "
    "(grouping, filters) but not order.")

FUSED_PIPELINE = register(
    "spark.rapids.tpu.sql.fusedPipeline.enabled", True,
    "Single-chip queries whose plan contains a join compile the WHOLE "
    "supported fragment (scans -> filters -> joins -> aggregation) into "
    "ONE kernel via the fragment compiler on a 1-device mesh — one "
    "dispatch and a two-stream packed result fetch instead of several "
    "launches (ref GpuShuffleExchangeExecBase.scala:167: exchanges are "
    "not opt-in). ON by default since r3: with the packed sink + "
    "compiled-program cache the fused path measures faster than the "
    "operator pipeline (q3 0.21 s vs 0.38 s on the tunneled v5e, "
    "docs/performance.md). Unsupported or oversized plans fall back "
    "to the operator pipeline either way.", commonly_used=True)

#: learned speculative bounds per (fragment signature, bound key) —
#: the cross-query statistics that let repeat queries start with tight
#: static shapes (the fragment analog of exec/joins._TOTAL_STATS)
_FRAGMENT_STATS: Dict[Tuple, int] = {}

#: compiled SPMD programs keyed by (signature, n_dev, source layout,
#: resolved bounds): re-running the same query shape must NOT pay the
#: shard_map retrace + lowering again (measured ~5 s/query on the
#: fused q3 fragment — the whole win of one-dispatch execution was
#: being spent re-tracing it). Programs are cached only after their
#: bounds VALIDATE (an overflowed attempt's undersized program could
#: never match again) and the cache is entry-capped LRU — each entry
#: pins a compiled XLA executable.
_PROGRAM_CACHE: Dict[Tuple, List[tuple]] = {}
_PROGRAM_LRU: Dict[Tuple, int] = {}
_PROGRAM_TICK = [0]
_PROGRAM_CACHE_MAX = 64


def _program_cache_put(base_key, variant):
    _PROGRAM_CACHE.setdefault(base_key, []).append(variant)
    _PROGRAM_TICK[0] += 1
    _PROGRAM_LRU[base_key] = _PROGRAM_TICK[0]
    while sum(len(v) for v in _PROGRAM_CACHE.values()) \
            > _PROGRAM_CACHE_MAX:
        coldest = min(_PROGRAM_LRU, key=_PROGRAM_LRU.get)
        del _PROGRAM_CACHE[coldest]
        del _PROGRAM_LRU[coldest]

#: per-source device-array cache (encode + pad + H2D skipped on repeat
#: queries over the same in-memory table). Weak pin + finalizer evict on
#: table GC (the scan-cache pattern, exec/basic.py); byte-capped LRU.
import weakref  # noqa: E402

_SOURCE_PIN: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()
_SOURCE_ARRAYS: Dict[Tuple, tuple] = {}
_SOURCE_LRU: Dict[Tuple, int] = {}
_SOURCE_TICK = [0]


def _source_cache_limit(conf: TpuConf) -> int:
    # governed by the SAME conf as the operator scan cache: one budget
    # for "device arrays pinned for repeat scans", 0 disables both
    from ..exec.basic import SCAN_CACHE_MAX_BYTES
    return int(conf.get(SCAN_CACHE_MAX_BYTES))


def _source_evict(tid: int):
    for k in [k for k in _SOURCE_ARRAYS if k[0] == tid]:
        del _SOURCE_ARRAYS[k]
        _SOURCE_LRU.pop(k, None)


def _source_bytes(entry) -> int:
    _n, pairs, _d, _p = entry
    return sum(int(d.nbytes) + int(v.nbytes) for d, v in pairs)


def _source_cache_put(key, entry, limit: int):
    new_bytes = _source_bytes(entry)
    if limit <= 0 or new_bytes > limit:
        return
    total = sum(_source_bytes(e) for e in list(_SOURCE_ARRAYS.values()))
    while _SOURCE_ARRAYS and total + new_bytes > limit:
        coldest = min(_SOURCE_LRU, key=_SOURCE_LRU.get)
        total -= _source_bytes(_SOURCE_ARRAYS[coldest])
        del _SOURCE_ARRAYS[coldest]
        del _SOURCE_LRU[coldest]
    _SOURCE_ARRAYS[key] = entry
    _SOURCE_TICK[0] += 1
    _SOURCE_LRU[key] = _SOURCE_TICK[0]


def _source_cache_key(src, replicated: bool, n_dev: int, frag_fields):
    from ..exec.basic import InMemoryScanExec
    if not isinstance(src, InMemoryScanExec) or len(src.tables) != 1:
        return None
    t = src.tables[0]
    tid = id(t)
    if _SOURCE_PIN.get(tid) is not t:
        try:
            _SOURCE_PIN[tid] = t
        except TypeError:
            return None
        _source_evict(tid)          # stale entries under a reused id
        weakref.finalize(t, _source_evict, tid)
    sig = tuple((f.name, f.phys.name, f.dict_id is not None,
                 f.order_required)
                for f in frag_fields)
    return (tid, replicated, n_dev, sig)


# ---------------------------------------------------------------------------
# fragment IR
# ---------------------------------------------------------------------------

class _Field:
    """Physical field riding the mesh: logical dtype + device dtype
    (+ dictionary id for code-carried strings). ``order_required``
    (set during lowering when the field feeds an ORDER-sensitive op)
    forces the sorted-dictionary encode — the hash fallback keeps only
    equality."""

    __slots__ = ("name", "logical", "phys", "dict_id", "order_required")

    def __init__(self, name: str, logical: DataType, phys: DataType,
                 dict_id: Optional[int] = None):
        self.name = name
        self.logical = logical
        self.phys = phys
        self.dict_id = dict_id
        self.order_required = False


def _phys_schema(fields: Sequence[_Field]) -> Schema:
    return Schema([StructField(f.name, f.phys, True) for f in fields])


class _Frag:
    fields: List[_Field]
    replicated: bool = False

    def signature(self) -> str:
        raise NotImplementedError

    def emit(self, env) -> "_Rel":
        raise NotImplementedError


class _Rel:
    """Traced per-device relation inside the SPMD program."""

    __slots__ = ("pairs", "count", "padded", "keep")

    def __init__(self, pairs, count, padded: int, keep=None):
        self.pairs = pairs          # [(data, validity), ...]
        self.count = count          # traced scalar (rows live)
        self.padded = padded        # static per-device length
        self.keep = keep            # optional bool[padded] live mask

    def compacted(self, env):
        """Resolve a pending filter mask into front-packed rows."""
        if self.keep is None:
            return self
        from .collective import _compact_rows
        comp, cnt = _compact_rows(self.pairs, self.keep, self.padded)
        return _Rel(comp, cnt, self.padded)

    def live_mask(self, env):
        import jax.numpy as jnp
        base = jnp.arange(self.padded, dtype=jnp.int32) < self.count
        return base if self.keep is None else jnp.logical_and(base,
                                                              self.keep)


def _probe_low_cardinality(exec_node, name: str,
                           sample: int = 8192) -> bool:
    """Plan-time sample probe: True when the column looks low-cardinality
    (sorted-dictionary territory — int32 codes suffice). Conservative:
    anything unprobable is treated as potentially high-cardinality."""
    from ..exec.basic import InMemoryScanExec
    if not isinstance(exec_node, InMemoryScanExec) or not exec_node.tables:
        return False
    try:
        import pyarrow as pa
        t = exec_node.tables[0]
        n = t.num_rows
        # head + middle + tail slices: value-clustered data (logs sorted
        # by key) would fool a head-only sample into the int32/sorted
        # path and reintroduce the driver string sort the cap prevents
        k = max(sample // 3, 1)
        if n <= 3 * k:
            # small table: probe it whole — overlapping head/middle/tail
            # slices would triple-count rows and misclassify all-distinct
            # columns as low-cardinality
            col = _one_chunk(t.column(name).slice(0, n))
        else:
            parts = [_one_chunk(t.column(name).slice(off, k))
                     for off in (0, (n - k) // 2, n - k)]
            col = pa.concat_arrays(parts)
        de = col.dictionary_encode()
        return len(de.dictionary) <= max(col.length() // 2, 1)
    except Exception:
        return False


class _SourceFrag(_Frag):
    """A host-executed subtree whose collected result is sharded (or
    replicated, for broadcast build sides) onto the mesh."""

    def __init__(self, exec_node, index: int, replicated: bool,
                 planner: "_Planner"):
        self.exec_node = exec_node
        self.index = index
        self.replicated = replicated
        self.fields = []
        for f in exec_node.output_schema().fields:
            if f.dtype == STRING:
                # plan-time cardinality probe picks the code width:
                # int32 for low-cardinality columns (half the HBM and
                # exchange traffic), int64 where the hash fallback may
                # be needed at scale
                phys = (INT32 if _probe_low_cardinality(exec_node, f.name)
                        else INT64)
                fld = _Field(f.name, STRING, phys, planner.new_dict())
                planner.dict_fields[fld.dict_id] = fld
                self.fields.append(fld)
            else:
                self.fields.append(_Field(f.name, f.dtype, f.dtype))

    def signature(self) -> str:
        kinds = ",".join(f"{f.name}:{f.phys.name}" for f in self.fields)
        return f"src{self.index}[{int(self.replicated)};{kinds}]"

    def emit(self, env) -> _Rel:
        pairs, count, padded = env.source(self.index)
        return _Rel(pairs, count, padded)


class _LocalFrag(_Frag):
    """Device-local filter/project stages — no communication."""

    def __init__(self, child: _Frag, stages: List[tuple],
                 fields: List[_Field]):
        self.child = child
        self.stages = stages        # ("filter", cond) | ("project", exprs)
        self.fields = fields
        self.replicated = child.replicated

    def signature(self) -> str:
        ss = []
        for st in self.stages:
            if st[0] == "filter":
                ss.append(f"F({st[1].key()})")
            else:
                ss.append("P(" + ",".join(e.key() for e in st[1]) + ")")
        return f"local[{';'.join(ss)}]({self.child.signature()})"

    def emit(self, env) -> _Rel:
        import jax.numpy as jnp
        from ..exprs.base import DVal, EvalContext
        rel = self.child.emit(env)
        schema = _phys_schema(self.child.fields)
        dvals = [DVal(d, v, f.phys)
                 for (d, v), f in zip(rel.pairs, self.child.fields)]
        ctx = EvalContext(schema, dvals, rel.count, rel.padded)
        keep = rel.live_mask(env)
        fields = self.child.fields
        for st in self.stages:
            if st[0] == "filter":
                c = st[1].eval_device(ctx)
                keep = jnp.logical_and(keep,
                                       jnp.logical_and(c.data, c.validity))
            else:
                exprs = st[1]
                outs = [e.eval_device(ctx) for e in exprs]
                fields = st[2]
                schema = _phys_schema(fields)
                ctx = EvalContext(schema, outs, rel.count, rel.padded)
        pairs = [(dv.data, dv.validity) for dv in ctx.columns]
        return _Rel(pairs, rel.count, rel.padded, keep)


def _key_hash_rel(env, rel: _Rel, fields, key_exprs, key_np):
    import jax.numpy as jnp
    from ..exprs.base import DVal, EvalContext
    from .collective import _col_hash_u32, _mix32
    schema = _phys_schema(fields)
    dvals = [DVal(d, v, f.phys)
             for (d, v), f in zip(rel.pairs, fields)]
    ctx = EvalContext(schema, dvals, rel.count, rel.padded)
    h = jnp.full(rel.padded, jnp.uint32(42))
    for i, e in enumerate(key_exprs):
        k = e.eval_device(ctx)
        npdt = key_np[i] if key_np is not None else k.data.dtype
        kk = DVal(k.data.astype(npdt), k.validity, k.dtype)
        h = _mix32(h * jnp.uint32(31) + _col_hash_u32(kk))
    return h


def _route_rel(env, rel: _Rel, fields, key_exprs, key_np, bound_key):
    """Hash-route live rows to their key-owner device with one
    all_to_all (the exchange shared by routed joins, aggs, and windows —
    ref GpuShuffleExchangeExecBase.prepareBatchShuffleDependency:277)."""
    import jax
    import jax.numpy as jnp
    from .collective import _compact_rows, _route_to_buffers
    n_dev = env.n_dev
    rel = rel.compacted(env)
    if n_dev == 1:
        return rel
    P_ = rel.padded
    h = _key_hash_rel(env, rel, fields, key_exprs, key_np)
    live = rel.live_mask(env)
    pid = jnp.where(live, (h % jnp.uint32(n_dev)).astype(jnp.int32),
                    jnp.int32(n_dev))
    flat = list(rel.pairs) + [(jnp.ones(P_, jnp.int8), live)]
    bufs = _route_to_buffers(flat, pid, P_, n_dev)
    recv = []
    for d, v in bufs:
        rd = jax.lax.all_to_all(d, env.axis, 0, 0, tiled=False)
        rv = jax.lax.all_to_all(v, env.axis, 0, 0, tiled=False)
        recv.append((rd.reshape(n_dev * P_), rv.reshape(n_dev * P_)))
    live_recv = recv[-1][1]
    comp, cnt = _compact_rows(recv[:-1], live_recv, n_dev * P_)
    # received rows are speculatively re-bounded (hash balance makes
    # ~P_ the expectation; worst case n_dev*P_) — validated at the sink
    rb = min(env.bound(bound_key,
                       default=min(n_dev * P_, _bucket(2 * P_))),
             n_dev * P_)
    env.check(cnt, rb)
    comp = [(d[:rb], v[:rb]) for d, v in comp]
    return _Rel(comp, cnt, rb)


class _JoinFrag(_Frag):
    """Equi-join. ``routed``: both sides hash-route rows to key owners with
    one all_to_all each, then each device joins its co-partitioned slice
    (the UCX shuffled-join analog). Non-routed (broadcast): the build side
    is replicated, each device probes its local shard — no collective
    (GpuBroadcastHashJoinExecBase analog)."""

    def __init__(self, frag_id: int, left: _Frag, right: _Frag,
                 lkeys, rkeys, join_type: str, broadcast_build: bool,
                 condition=None):
        self.frag_id = frag_id
        self.left = left
        self.right = right
        self.lkeys = list(lkeys)
        self.rkeys = list(rkeys)
        self.join_type = join_type
        self.broadcast_build = broadcast_build
        #: residual non-equi condition (inner joins only: there it is
        #: exactly a post-join filter — ref GpuHashJoin compiled AST
        #: conditions)
        self.condition = condition
        self.fields = list(left.fields) + list(right.fields)
        self.replicated = left.replicated and right.replicated

    def signature(self) -> str:
        lk = ",".join(e.key() for e in self.lkeys)
        rk = ",".join(e.key() for e in self.rkeys)
        cond = self.condition.key() if self.condition is not None else ""
        return (f"join{self.frag_id}[{self.join_type};{int(self.broadcast_build)};"
                f"{lk};{rk};{cond}]({self.left.signature()},"
                f"{self.right.signature()})")

    def emit(self, env) -> _Rel:
        import jax.numpy as jnp
        from ..exec.joins import _build_count_kernel, _gather_index_kernel
        lrel = self.left.emit(env)
        rrel = self.right.emit(env)
        lschema = _phys_schema(self.left.fields)
        rschema = _phys_schema(self.right.fields)
        key_np = [np.promote_types(lk.data_type(lschema).np_dtype,
                                   rk.data_type(rschema).np_dtype)
                  for lk, rk in zip(self.lkeys, self.rkeys)]
        if self.broadcast_build or env.n_dev == 1 or self.replicated:
            lrel = lrel.compacted(env)
            rrel = rrel.compacted(env)
        else:
            lrel = _route_rel(env, lrel, self.left.fields, self.lkeys,
                              key_np, ("recv", self.frag_id, False))
            rrel = _route_rel(env, rrel, self.right.fields, self.rkeys,
                              key_np, ("recv", self.frag_id, True))
        count_k = _build_count_kernel(self.lkeys, self.rkeys,
                                      lschema, rschema, self.join_type)
        (s_orig, cnt_l, cnt_r, start_l, start_r, _pairs, offsets, total,
         _ng) = count_k(lrel.pairs, rrel.pairs, lrel.count, rrel.count,
                        lrel.padded, rrel.padded)
        out = env.bound(("join", self.frag_id),
                        default=_bucket(env.conf_out_factor
                                        * max(lrel.padded, rrel.padded)))
        env.check(total, out)
        nullable_l = self.join_type in ("right", "full")
        nullable_r = self.join_type in ("left", "full")
        semi_like = self.join_type in ("leftsemi", "leftanti")
        cfg = jnp.array([nullable_l, nullable_r, semi_like], dtype=jnp.int32)
        l_row, r_row = _gather_index_kernel(
            s_orig, cnt_l, cnt_r, start_l, start_r, offsets, cfg, out)
        out_live = jnp.arange(out, dtype=jnp.int64) < total
        pairs = []
        for d, v in lrel.pairs:
            idx = jnp.clip(l_row, 0, None)
            pairs.append((jnp.take(d, idx, mode="clip"),
                          jnp.logical_and(
                              jnp.take(v, idx, mode="clip"),
                              jnp.logical_and(out_live, l_row >= 0))))
        if semi_like:
            return _Rel(pairs, total, out)
        for d, v in rrel.pairs:
            idx = jnp.clip(r_row, 0, None)
            pairs.append((jnp.take(d, idx, mode="clip"),
                          jnp.logical_and(
                              jnp.take(v, idx, mode="clip"),
                              jnp.logical_and(out_live, r_row >= 0))))
        if self.condition is None:
            return _Rel(pairs, total, out)
        # inner-join residual condition == post-join filter: evaluate
        # over the gathered pair columns, pending rows carry a keep mask
        from ..exprs.base import DVal, EvalContext
        schema = _phys_schema(self.fields)
        dvals = [DVal(d, v, f.phys)
                 for (d, v), f in zip(pairs, self.fields)]
        ctx = EvalContext(schema, dvals, total, out)
        c = self.condition.eval_device(ctx)
        # seed with liveness: a condition whose validity is constant-true
        # (e.g. null-safe equality) must not resurrect padding rows
        keep = jnp.logical_and(jnp.logical_and(c.data, c.validity),
                               out_live)
        return _Rel(pairs, total, out, keep)


class _WindowFrag(_Frag):
    """Window functions on the mesh: rows hash-route to the device owning
    their PARTITION (one all_to_all), then each device runs the engine's
    window kernel over its complete partitions — the distributed analog of
    window/GpuWindowExec.scala:146 downstream of a hash exchange."""

    def __init__(self, frag_id: int, child: _Frag, window_exprs,
                 fields: List[_Field]):
        self.frag_id = frag_id
        self.child = child
        self.window_exprs = list(window_exprs)
        self.fields = fields
        self.replicated = child.replicated
        self._kern = None

    def signature(self) -> str:
        ws = ",".join(f"{type(e).__name__}|{n}"
                      for e, _s, n in self.window_exprs)
        return f"win{self.frag_id}[{ws}]({self.child.signature()})"

    def emit(self, env) -> _Rel:
        import jax.numpy as jnp
        from ..exec.window import _build_window_kernel
        rel = self.child.emit(env)
        part_keys = []
        for _fn, spec, _n in self.window_exprs:
            part_keys = list(spec.partition_by)
            break
        if env.n_dev == 1 or self.replicated:
            rel = rel.compacted(env)
        else:
            rel = _route_rel(env, rel, self.child.fields, part_keys,
                             None, ("win", self.frag_id))
        if self._kern is None:
            self._kern = _build_window_kernel(
                self.window_exprs, _phys_schema(self.child.fields))
        cols = [(d, v) for d, v in rel.pairs]
        outs = self._kern(cols, rel.count.astype(jnp.int32), rel.padded)
        pairs = list(rel.pairs) + [(d, v) for d, v in outs]
        return _Rel(pairs, rel.count, rel.padded)


class _AggFrag(_Frag):
    """Grouped/global aggregation: local first pass, groups hash-routed to
    owners via all_to_all, merge pass, finalize — the distributed 3-pass
    pipeline (GpuAggregateExec.scala:718 + exchange), sharing
    segmented_groupby with the single-chip exec so distribution cannot
    change results."""

    def __init__(self, frag_id: int, child: _Frag, groupings, aggs,
                 fields: List[_Field]):
        self.frag_id = frag_id
        self.child = child
        self.groupings = list(groupings)
        self.aggs = list(aggs)
        self.fields = fields
        self.replicated = child.replicated

    def signature(self) -> str:
        g = ",".join(e.key() for e in self.groupings)
        a = ",".join(a.key() for a in self.aggs)
        return (f"agg{self.frag_id}[{g};{a}]({self.child.signature()})")

    def emit(self, env) -> _Rel:
        import jax
        import jax.numpy as jnp
        from ..exec.groupby_core import segmented_groupby
        from ..exprs.base import DVal, EvalContext
        from .collective import (_col_hash_u32, _compact_rows, _mix32,
                                 _route_to_buffers)
        rel = self.child.emit(env)
        schema = _phys_schema(self.child.fields)
        dvals = [DVal(d, v, f.phys)
                 for (d, v), f in zip(rel.pairs, self.child.fields)]
        ctx = EvalContext(schema, dvals, rel.count, rel.padded)
        keys = [e.eval_device(ctx) for e in self.groupings]
        vals = [[e.eval_device(ctx) for e in a.input_exprs()]
                for a in self.aggs]
        key_outs, partial_outs, n_groups = segmented_groupby(
            keys, vals, self.aggs, "update", rel.count, rel.padded,
            row_mask=rel.live_mask(env))
        n_dev = env.n_dev
        ptypes = []
        for a in self.aggs:
            ptypes.extend(a.partial_types(schema))
        if n_dev > 1 and not self.replicated:
            # First/Last carry within-SHARD row positions; the merge after
            # the exchange breaks ties by position, so positions must be
            # GLOBAL (shard index * padded — sources shard row-contiguous,
            # so shard order IS row order). Without this, 88% of groups
            # returned another shard's first (caught by the r4 drive).
            from ..exprs.aggregates import First, Last
            base = (jax.lax.axis_index(env.axis).astype(jnp.int64)
                    * jnp.int64(rel.padded))
            ord_ = 0
            adj = list(partial_outs)
            for a in self.aggs:
                n_p = len(a.partial_types(schema))
                if isinstance(a, (First, Last)):
                    _vd, vv = adj[ord_]
                    pd_, pv = adj[ord_ + 1]
                    adj[ord_ + 1] = (jnp.where(vv, pd_ + base, pd_), pv)
                ord_ += n_p
            partial_outs = adj
        if n_dev == 1 or self.replicated:
            m_key_outs, m_partial_outs, m_groups = key_outs, partial_outs, \
                n_groups
            padded = rel.padded
        else:
            # slice first-pass groups to the speculative exchange bound
            gb = min(env.bound(("agg", self.frag_id),
                               default=min(rel.padded,
                                           env.conf_max_groups)),
                     rel.padded)
            env.check(n_groups, gb)
            s_keys = [(d[:gb], v[:gb]) for d, v in key_outs]
            s_parts = [(d[:gb], v[:gb]) for d, v in partial_outs]
            glive = jnp.arange(gb, dtype=jnp.int32) < n_groups
            if self.groupings:
                h = jnp.full(gb, jnp.uint32(42))
                for (kd, kv), k in zip(s_keys, keys):
                    h = _mix32(h * jnp.uint32(31)
                               + _col_hash_u32(DVal(kd, kv, k.dtype)))
                pid = jnp.where(glive,
                                (h % jnp.uint32(n_dev)).astype(jnp.int32),
                                jnp.int32(n_dev))
            else:
                pid = jnp.where(glive, 0, n_dev)
            flat = list(s_keys) + list(s_parts) + \
                [(jnp.ones(gb, jnp.int8), glive)]
            bufs = _route_to_buffers(flat, pid, gb, n_dev)
            recv = []
            for d, v in bufs:
                rd = jax.lax.all_to_all(d, env.axis, 0, 0, tiled=False)
                rv = jax.lax.all_to_all(v, env.axis, 0, 0, tiled=False)
                recv.append((rd.reshape(n_dev * gb),
                             rv.reshape(n_dev * gb)))
            live_recv = recv[-1][1]
            comp, cnt = _compact_rows(recv[:-1], live_recv, n_dev * gb)
            rkeys = [DVal(comp[i][0], comp[i][1], k.dtype)
                     for i, k in enumerate(keys)]
            rvals = []
            ai = len(keys)
            for a in self.aggs:
                n_p = len(a.partial_types(schema))
                rvals.append([DVal(comp[ai + j][0], comp[ai + j][1],
                                   ptypes[ai - len(keys) + j])
                              for j in range(n_p)])
                ai += n_p
            m_key_outs, m_partial_outs, m_groups = segmented_groupby(
                rkeys, rvals, self.aggs, "merge", cnt, n_dev * gb)
            if not self.groupings:
                m_groups = jnp.where(jax.lax.axis_index(env.axis) == 0,
                                     m_groups, 0)
            padded = n_dev * gb
        glive2 = jnp.arange(padded, dtype=jnp.int32) < m_groups
        pairs = []
        for d, v in m_key_outs:
            pairs.append((d, jnp.logical_and(v, glive2)))
        ai = 0
        for a in self.aggs:
            n_p = len(a.partial_types(schema))
            parts = [DVal(m_partial_outs[ai + j][0],
                          m_partial_outs[ai + j][1], ptypes[ai + j])
                     for j in range(n_p)]
            ai += n_p
            f = a.finalize(parts)
            pairs.append((f.data, jnp.logical_and(f.validity, glive2)))
        return _Rel(pairs, m_groups, padded)


def _bucket(n: int) -> int:
    from ..columnar.bucketing import bucket_for
    return bucket_for(max(int(n), 1))


# ---------------------------------------------------------------------------
# lowering: physical exec tree -> fragment IR
# ---------------------------------------------------------------------------

class _NotLowerable(Exception):
    pass


class _Planner:
    def __init__(self, conf: TpuConf, fused_mode: bool = False):
        self.conf = conf
        #: True for single-chip fused lowering (stricter gates apply:
        #: features living only in the operator path must not be lost)
        self.fused_mode = fused_mode
        self.sources: List[Tuple[object, bool]] = []   # (exec, replicated)
        self.n_dicts = 0
        self.n_frags = 0
        self.has_comm = False
        self.has_join = False
        #: dict_id -> the SOURCE _Field, so order-sensitive consumers
        #: can force the sorted-dictionary encode on it
        self.dict_fields: Dict[int, _Field] = {}
        #: bound-check key -> logical plan signature: the fragment's bound
        #: validation fetches measured sizes anyway — feed them to the
        #: cost model's _RUNTIME_ROWS so re-planning sees real join
        #: outputs even when the whole query ran fused
        self.key_sigs: Dict[Tuple, str] = {}

    def new_dict(self) -> int:
        self.n_dicts += 1
        return self.n_dicts - 1

    def frag_id(self) -> int:
        self.n_frags += 1
        return self.n_frags - 1

    def source(self, exec_node, replicated: bool) -> _SourceFrag:
        for f in exec_node.output_schema().fields:
            if f.dtype != STRING and not f.dtype.device_backed:
                # nested/binary columns have no fragment encoding (list
                # rectangles don't ride the exchange yet) — reject the
                # fragment; the operator pipeline handles these
                raise _NotLowerable(
                    f"source column {f.name}: {f.dtype.name}")
        idx = len(self.sources)
        self.sources.append((exec_node, replicated))
        return _SourceFrag(exec_node, idx, replicated, self)

    # -- helpers -----------------------------------------------------------
    def _expr_ok_f(self, e, fields: Sequence[_Field]) -> bool:
        """Device-supported and independent of dict-coded (string) cols."""
        from ..types import ArrayType
        schema = Schema([StructField(f.name, f.logical, True)
                         for f in fields])
        if e.fully_device_supported(schema) is not None:
            return False
        # list columns (rectangular layout) don't ride fragments yet:
        # their lanes would need the exchange/compaction to be W-aware
        if isinstance(e.data_type(schema), ArrayType) or any(
                isinstance(f.logical, ArrayType)
                for f in fields if f.name in set(e.references())):
            return False
        dict_names = {f.name for f in fields if f.dict_id is not None}
        return not (set(e.references()) & dict_names)

    def _expr_ok(self, e, frag: _Frag) -> bool:
        return self._expr_ok_f(e, frag.fields)

    def _passthrough_f(self, e, fields: Sequence[_Field]) \
            -> Optional[_Field]:
        """ColumnRef / Alias(ColumnRef) -> the referenced field."""
        from ..exprs.base import Alias, ColumnRef
        inner = e.children[0] if isinstance(e, Alias) else e
        if not isinstance(inner, ColumnRef):
            return None
        for f in fields:
            if f.name == inner.name:
                return f
        return None

    def _passthrough_field(self, e, frag: _Frag) -> Optional[_Field]:
        return self._passthrough_f(e, frag.fields)

    # -- node lowering -----------------------------------------------------
    def lower(self, node, replicated: bool = False) -> _Frag:
        from ..exec import basic as B
        from ..exec.aggregate import TpuHashAggregateExec
        from ..exec.joins import TpuBroadcastHashJoinExec, TpuHashJoinExec
        from ..shuffle.broadcast import BroadcastExchangeExec
        from ..shuffle.exchange import ShuffleExchangeExec

        if isinstance(node, ShuffleExchangeExec):
            # the SPMD program IS the exchange: shuffles lower to the
            # routing inside joins/aggs; a bare repartition is an identity
            # on the mesh
            return self.lower(node.children[0], replicated)

        if isinstance(node, B.TpuFilterExec):
            child = self.lower(node.children[0], replicated)
            if not self._expr_ok(node.condition, child):
                raise _NotLowerable(f"filter {node.condition.name_hint}")
            return _LocalFrag(child, [("filter", node.condition)],
                              child.fields)

        if isinstance(node, B.TpuProjectExec):
            child = self.lower(node.children[0], replicated)
            out_fields = []
            for e, f in zip(node.exprs, node.output_schema().fields):
                pf = self._passthrough_field(e, child)
                if pf is not None:
                    out_fields.append(_Field(f.name, pf.logical, pf.phys,
                                             pf.dict_id))
                elif self._expr_ok(e, child):
                    out_fields.append(_Field(f.name, f.dtype, f.dtype))
                else:
                    raise _NotLowerable(f"project {e.name_hint}")
            return _LocalFrag(child, [("project", list(node.exprs),
                                       out_fields)], out_fields)

        if isinstance(node, TpuBroadcastHashJoinExec):
            if node.join_type not in ("inner", "left", "right", "full",
                                      "leftsemi", "leftanti"):
                raise _NotLowerable(f"join type {node.join_type}")
            lc, rc = node.children
            if isinstance(rc, BroadcastExchangeExec):
                left = self.lower(lc, replicated)
                right = self.lower(rc.children[0], True)
            elif isinstance(lc, BroadcastExchangeExec):
                left = self.lower(lc.children[0], True)
                right = self.lower(rc, replicated)
            else:
                left = self.lower(lc, replicated)
                right = self.lower(rc, True)
            return self._make_join(node, left, right, broadcast=True)

        if isinstance(node, TpuHashJoinExec):
            left = self.lower(node.children[0], replicated)
            right = self.lower(node.children[1], replicated)
            return self._make_join(node, left, right, broadcast=False)

        if isinstance(node, TpuHashAggregateExec):
            return self._lower_agg(node, replicated)

        from ..exec.window import TpuWindowExec
        if isinstance(node, TpuWindowExec):
            return self._lower_window(node, replicated)

        # anything else becomes a host-executed source (scans always do)
        return self.source(node, replicated)

    def _lower_window(self, node, replicated: bool) -> _Frag:
        from ..exprs.window_fns import (DenseRank, Lag, Lead, NthValue,
                                        NTile, PercentRank, Rank,
                                        RowNumber)
        from ..exprs.aggregates import AggregateExpression
        child = self.lower(node.children[0], replicated)
        part_sig = None
        for fn, spec, _name in node.window_exprs:
            if not isinstance(fn, (RowNumber, Rank, DenseRank, NTile,
                                   PercentRank, NthValue, Lag, Lead,
                                   AggregateExpression)):
                raise _NotLowerable(f"window fn {type(fn).__name__}")
            # all exprs must share ONE partitioning: the routing
            # co-locates partitions for exactly one key set
            sig = tuple(k.key() for k in spec.partition_by)
            if part_sig is None:
                part_sig = sig
            elif sig != part_sig:
                raise _NotLowerable("window exprs with mixed partitioning")
            for k in spec.partition_by:
                pf = self._passthrough_field(k, child)
                if pf is None and not self._expr_ok(k, child):
                    raise _NotLowerable("window partition key")
            for o in spec.order_by:
                pf = self._passthrough_field(o.expr, child)
                if pf is None and not self._expr_ok(o.expr, child):
                    raise _NotLowerable("window order key")
                if pf is not None and pf.dict_id is not None:
                    # ordering by a string: only a SORTED dictionary's
                    # codes order like the strings
                    src = self.dict_fields.get(pf.dict_id)
                    if src is not None:
                        src.order_required = True
            fchild = getattr(fn, "child", None)
            if fchild is not None and not self._expr_ok(fchild, child):
                raise _NotLowerable("window value expression")
        cs = node.children[0].output_schema()
        out_fields = list(child.fields)
        for fn, _spec, name in node.window_exprs:
            dt = fn.data_type(cs)
            out_fields.append(_Field(name, dt, dt))
        self.has_comm = True
        return _WindowFrag(self.frag_id(), child, node.window_exprs,
                           out_fields)

    def _make_join(self, node, left: _Frag, right: _Frag,
                   broadcast: bool) -> _Frag:
        if node.join_type not in ("inner", "left", "right", "full",
                                  "leftsemi", "leftanti"):
            raise _NotLowerable(f"join type {node.join_type}")
        condition = getattr(node, "condition", None)
        if condition is not None:
            # only for INNER joins is the ON-condition equivalent to a
            # post-join filter; outer joins would change match semantics
            if node.join_type != "inner":
                raise _NotLowerable(
                    f"join condition on {node.join_type} join")
            if not self._expr_ok_f(condition,
                                   list(left.fields) + list(right.fields)):
                raise _NotLowerable("join condition not device-evaluable")
        from ..config import JOIN_BLOOM_FILTER
        if self.fused_mode and self.conf.get(JOIN_BLOOM_FILTER):
            # the runtime bloom filter is an operator-path optimization;
            # single-chip fusion must not silently drop it (on a REAL
            # mesh the collective exchange replaces it wholesale, so
            # multi-device lowering proceeds regardless)
            raise _NotLowerable("bloom-filtered joins keep the operator "
                               "pipeline")
        for k in node.left_keys:
            if not self._expr_ok(k, left):
                raise _NotLowerable(f"join key {k.name_hint}")
        for k in node.right_keys:
            if not self._expr_ok(k, right):
                raise _NotLowerable(f"join key {k.name_hint}")
        if broadcast and not right.replicated and not left.replicated:
            raise _NotLowerable("broadcast side not replicable")
        # a replicated side must never be on the EMITTING side of the join
        # while the other side is sharded: every device would emit its
        # unmatched/matched replicated rows independently (N-fold dupes)
        if right.replicated and not left.replicated \
                and node.join_type in ("right", "full"):
            raise _NotLowerable(
                f"{node.join_type} join emits replicated build rows")
        if left.replicated and not right.replicated \
                and node.join_type in ("left", "full", "leftsemi",
                                       "leftanti"):
            raise _NotLowerable(
                f"{node.join_type} join emits replicated probe rows")
        # any join benefits from the mesh: routed joins exchange, broadcast
        # joins probe in parallel across shards
        self.has_comm = True
        self.has_join = True
        frag = _JoinFrag(self.frag_id(), left, right, node.left_keys,
                         node.right_keys, node.join_type, broadcast,
                         condition=condition)
        sig = getattr(node, "plan_sig", None)
        if sig is not None:
            self.key_sigs[("join", frag.frag_id)] = sig
        # semi/anti joins emit probe-side fields only
        if node.join_type in ("leftsemi", "leftanti"):
            frag.fields = list(left.fields)
        return frag

    def _lower_agg(self, node, replicated: bool) -> _Frag:
        child = self.lower(node.children[0], replicated)
        # folded pre-stages (filter/project fused below the agg) re-lower
        # as explicit local stages so the SPMD program keeps the fusion
        if node.pre_stages:
            stages = []
            cur_fields = child.fields
            for st in node.pre_stages:
                if st[0] == "filter":
                    if not self._expr_ok_f(st[1], cur_fields):
                        raise _NotLowerable("agg pre-filter")
                    stages.append(("filter", st[1]))
                else:
                    out_fields = []
                    for e, f in zip(st[1], st[2].fields):
                        pf = self._passthrough_f(e, cur_fields)
                        if pf is not None:
                            out_fields.append(_Field(f.name, pf.logical,
                                                     pf.phys, pf.dict_id))
                        elif self._expr_ok_f(e, cur_fields):
                            out_fields.append(_Field(f.name, f.dtype,
                                                     f.dtype))
                        else:
                            raise _NotLowerable("agg pre-project")
                    stages.append(("project", list(st[1]), out_fields))
                    cur_fields = out_fields
            child = _LocalFrag(child, stages, cur_fields)
        out_fields = []
        groupings = []
        for g, f in zip(node.groupings, node._schema.fields):
            pf = self._passthrough_field(g, child)
            if pf is not None and pf.dict_id is not None:
                out_fields.append(_Field(f.name, STRING, pf.phys, pf.dict_id))
                from ..exprs.base import ColumnRef
                groupings.append(ColumnRef(pf.name))
                continue
            if not self._expr_ok(g, child):
                raise _NotLowerable(f"grouping {g.name_hint}")
            out_fields.append(_Field(f.name, f.dtype, f.dtype))
            groupings.append(g)
        schema = _phys_schema(child.fields)
        for a, f in zip(node.aggs, node._schema.fields[len(groupings):]):
            if not hasattr(a, "update") or a.distinct:
                raise _NotLowerable(f"aggregate {a.name_hint}")
            for e in a.input_exprs():
                if not self._expr_ok(e, child):
                    raise _NotLowerable(f"aggregate input {e.name_hint}")
            try:
                a.partial_types(schema)
            except Exception as exc:
                raise _NotLowerable(f"aggregate {a.name_hint}: {exc}")
            out_fields.append(_Field(f.name, f.dtype, f.dtype))
        self.has_comm = True
        return _AggFrag(self.frag_id(), child, groupings, node.aggs,
                        out_fields)


# ---------------------------------------------------------------------------
# the distributed exec
# ---------------------------------------------------------------------------

class _Env:
    """Per-trace environment handed to frag.emit: source arrays, bounds,
    and the overflow-check accumulator."""

    def __init__(self, mesh, axis: str, conf: TpuConf,
                 source_layout, bounds: Dict, sig: str = ""):
        self.mesh = mesh
        self.axis = axis
        self.n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        self.conf_max_groups = int(conf.get(DISTRIBUTED_MAX_GROUPS))
        self.conf_out_factor = int(conf.get(DISTRIBUTED_OUT_FACTOR))
        self._layout = source_layout    # idx -> (padded, n_fields)
        self._bounds = bounds           # key -> int (speculative bounds)
        self.sig = sig                  # fragment signature for stats
        self._inputs = None             # set per trace
        self.checks: List[Tuple] = []   # (traced count, static bound)

    def bound(self, key, default: int) -> int:
        b = self._bounds.get(key)
        if b is None:
            # learned cross-query statistic first (the fragment analog
            # of the joins' _TOTAL_STATS speculative sizing). The stat is
            # keyed by the bucketed DEFAULT too, so the same query shape
            # at a different input scale keeps its input-proportional
            # default instead of a stale too-small bound.
            b = _FRAGMENT_STATS.get(
                (self.sig, self.n_dev, key, _bucket(default)))
            if b is None:
                b = int(default)
            self._bounds[key] = b
        # record on EVERY call: retries rebuild the env with pre-filled
        # bounds, and the success-time stats write needs the default
        self._defaults = getattr(self, "_defaults", {})
        self._defaults[key] = int(default)
        return b

    def check(self, count, bound: int):
        self.checks.append((count, bound))

    def source(self, idx: int):
        padded, nf, off = self._layout[idx]
        nrows = self._inputs[off]
        pairs = [(self._inputs[off + 1 + 2 * i],
                  self._inputs[off + 2 + 2 * i]) for i in range(nf)]
        import jax.numpy as jnp
        return pairs, nrows[0], padded


class _BoundOverflow(Exception):
    def __init__(self, violations):
        self.violations = violations


def _one_chunk(col):
    import pyarrow as pa
    if isinstance(col, pa.ChunkedArray):
        return col.combine_chunks() if col.num_chunks != 1 else col.chunk(0)
    return col


def _encode_plain(col, phys):
    """Arrow column -> (data, validity) numpy pair with the same
    arrow->device casts as ColumnarBatch.from_arrow."""
    import pyarrow as pa
    import pyarrow.compute as pc
    from ..columnar.column import DeviceColumn
    arr = col
    if pa.types.is_date32(arr.type):
        arr = arr.cast(pa.int32())
    elif pa.types.is_timestamp(arr.type):
        arr = arr.cast(pa.int64())
    elif pa.types.is_decimal(arr.type):
        arr = pc.multiply_checked(
            arr.cast(pa.decimal128(38, arr.type.scale)),
            10 ** arr.type.scale).cast(pa.int64())
    mask = ~np.asarray(col.is_null())
    fill = False if pa.types.is_boolean(arr.type) else 0
    vals = arr.fill_null(fill).to_numpy(zero_copy_only=False)
    return DeviceColumn.host_prepare(vals, phys, mask=mask)


def _encode_string_global(cols, cap: int, ordered: bool,
                          code_dtype=np.int64):
    """Global string encoding across shards: ``cols`` = one Arrow
    column per shard. Returns (decode_entry, [(codes, valid)] per
    shard); decode_entry: ("sorted", uniq) | ("hashed", h_uniq, s_by_h).

    The row pass is Arrow ``dictionary_encode`` (O(n) hash table);
    everything after operates on DISTINCTS only. Low cardinality (or
    order-required fields): ONE sorted global dictionary — code order ==
    string order. Above ``cap`` (VERDICT r2 #6: a global string sort is
    a driver bottleneck at scale): codes are 64-bit hashes of the
    distinct values (pandas hash_array — stable across shards and
    processes); the decode map sorts only int64 hashes. Collisions are
    detected exactly and fall back to the sorted dictionary."""
    des, dvals, valids, idxs = [], [], [], []
    for c in cols:
        de = _one_chunk(c).dictionary_encode()
        des.append(de)
        dvals.append(np.asarray(
            de.dictionary.to_numpy(zero_copy_only=False), dtype=object))
        valids.append(~np.asarray(de.indices.is_null()))
        idxs.append(np.asarray(
            de.indices.fill_null(0).to_numpy(zero_copy_only=False),
            dtype=np.int64))

    def emit(rank_per_shard, dt):
        out = []
        for rank, idx, valid in zip(rank_per_shard, idxs, valids):
            codes = rank[idx].astype(dt) if len(rank) \
                else np.zeros(len(idx), dt)
            codes[~valid] = 0
            out.append((codes, valid))
        return out

    def sorted_path(distincts):
        uniq = np.unique(np.concatenate(distincts)) if distincts \
            else np.asarray([], dtype=object)
        if np.dtype(code_dtype).itemsize < 8 and len(uniq) >= (1 << 31):
            raise ValueError(
                "dictionary exceeds int32 code space (mis-probed "
                "cardinality); raise distributed.maxDictEntries or "
                "disable distribution for this query")
        ranks = [np.searchsorted(uniq, d).astype(np.int64)
                 for d in dvals]
        return ("sorted", uniq), emit(ranks, code_dtype)

    nonempty = [d for d in dvals if len(d)]
    bound = sum(len(d) for d in nonempty)     # distinct-count upper bound
    if ordered or bound <= cap \
            or np.dtype(code_dtype).itemsize < 8:
        # (32-bit code space cannot carry the 64-bit hash fallback —
        # the plan-time probe assigns int32 only to low-card columns)
        return sorted_path(nonempty)
    # hash path: hash only the DISTINCT values per shard
    import pandas as pd
    h_per = [pd.util.hash_array(d, categorize=False).view(np.int64)
             if len(d) else np.zeros(0, np.int64) for d in dvals]
    all_h = np.concatenate([h for h in h_per if len(h)])
    all_s = np.concatenate(nonempty)
    order = np.argsort(all_h, kind="stable")
    h_sorted, s_sorted = all_h[order], all_s[order]
    first = np.ones(len(h_sorted), bool)
    first[1:] = h_sorted[1:] != h_sorted[:-1]
    dup = ~first
    if dup.any() and (s_sorted[dup] != s_sorted[
            np.flatnonzero(dup) - 1]).any():
        # genuine 64-bit collision: correctness over speed
        return sorted_path(nonempty)
    h_uniq, s_uniq = h_sorted[first], s_sorted[first]
    if len(h_uniq) <= cap:
        # true cardinality is low: sorting <=cap distincts is cheap and
        # keeps code order == string order
        return sorted_path([s_uniq])
    return ("hashed", h_uniq, s_uniq), emit(h_per, np.int64)


class _ShardedTables:
    """Per-device pre-sharded source tables (row-group-partitioned scan):
    shard i's table goes to device i verbatim — no driver-side concat or
    re-slice."""

    def __init__(self, shards):
        self.shards = list(shards)

    def rows_per_shard(self):
        return [t.num_rows for t in self.shards]


class DistributedPipelineExec(TpuExec):
    """Physical operator executing a plan fragment as ONE SPMD program over
    the session mesh (see module docstring). Appears in explain() where the
    reference would show GpuShuffleExchangeExec-separated stages."""

    def __init__(self, root: _Frag, sources: List[Tuple[object, bool]],
                 mesh, conf: TpuConf, out_schema: Schema,
                 axis: str = "data", fallback=None):
        super().__init__([s for s, _ in sources])
        self.root = root
        self.sources = sources
        self.mesh = mesh
        self.conf = conf
        self.axis = axis
        self._schema = out_schema
        self._bounds: Dict = {}
        self.sig = root.signature()
        #: original operator subtree; runs instead when a source exceeds
        #: the shape-bucket ladder (fragments are single-batch programs)
        self.fallback = fallback
        self.n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))

    def output_schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return (f"DistributedPipeline[n_dev={self.n_dev}, "
                f"axis={self.axis}, frag={type(self.root).__name__}]")

    # -----------------------------------------------------------------------
    def do_execute(self, ctx):
        import pyarrow as pa
        from ..columnar import ColumnarBatch
        from ..columnar.bucketing import DEFAULT_BUCKETS
        from ..exec.basic import InMemoryScanExec
        max_rows = max(DEFAULT_BUCKETS)
        if self.fallback is not None:
            # fragments are single-batch programs; oversized inputs take
            # the multi-batch operator pipeline. Scan sources expose
            # their row counts WITHOUT executing anything — check them
            # first so fallback never double-runs the sources.
            for s, _ in self.sources:
                if isinstance(s, InMemoryScanExec) and \
                        sum(t.num_rows for t in s.tables) > max_rows:
                    yield from self.fallback.execute(ctx)
                    return
        tables = []
        for s, replicated in self.sources:
            shards = None
            if not replicated:
                from ..io.parquet import ParquetScanExec
                if isinstance(s, ParquetScanExec):
                    # row-group-partitioned scan: each shard reads only
                    # its assigned groups (VERDICT r2 #3; ref
                    # GpuMultiFileReader.scala:295)
                    shards = s.collect_row_group_shards(self.n_dev)
            tables.append(_ShardedTables(shards) if shards is not None
                          else s._collect_tables(ctx))
        if self.fallback is not None and any(
                (max(t.rows_per_shard()) if isinstance(t, _ShardedTables)
                 else t.num_rows) > max_rows for t in tables):
            # non-scan source turned out oversized: the sources ran
            # twice on this rare path — documented cost of the late check
            yield from self.fallback.execute(ctx)
            return
        out = self._run(ctx, tables)
        yield ColumnarBatch.from_arrow(out)

    def _mesh_key(self):
        return (tuple(str(d) for d in np.asarray(self.mesh.devices).flat),
                tuple(self.mesh.axis_names), self.axis)

    def _resolve_bound(self, key, default: int) -> int:
        """Host-side mirror of _Env.bound()'s resolution order, used to
        test whether a cached program's embedded bounds still apply."""
        b = self._bounds.get(key)
        if b is None:
            b = _FRAGMENT_STATS.get(
                (self.sig, self.n_dev, key, _bucket(default)))
        return int(default) if b is None else int(b)

    def _lookup_program(self, layout):
        layout_t = tuple(sorted((i, p, nf)
                                for i, (p, nf, _o) in layout.items()))
        base = (self.sig, self.n_dev, self._mesh_key(), layout_t)
        for variant in _PROGRAM_CACHE.get(base, []):
            (fn, out_specs, check_keys, bounds_flat, bound_items) = variant
            if all(self._resolve_bound(k, d) == r
                   for k, d, r in bound_items):
                _PROGRAM_TICK[0] += 1
                _PROGRAM_LRU[base] = _PROGRAM_TICK[0]
                return base, variant
        return base, None

    def _run(self, ctx, tables):
        import jax
        from ..columnar.packing import unpack_streams
        # deep fragments can surface undersized bounds one layer per
        # attempt (each clamped count hides the next layer's true size)
        for attempt in range(6):
            layout, inputs, dicts = self._shard_inputs(tables)
            base_key, cached = self._lookup_program(layout)
            if cached is not None:
                # repeat query shape: skip the shard_map retrace + XLA
                # lowering entirely (measured ~5 s on the fused q3
                # fragment) — the compiled executable is called directly
                (fn, out_specs, check_keys, bounds_flat,
                 bound_items) = cached
                self._out_specs = out_specs
                self._check_keys = check_keys
                defaults = {k: d for k, d, _ in bound_items}
                for k, _d, r in bound_items:
                    self._bounds[k] = r
                env = None
            else:
                env = _Env(self.mesh, self.axis, self.conf, layout,
                           self._bounds, self.sig)
                fn = self._build_program(env)
            outs = fn(*inputs)
            variant = None
            if env is not None:
                # trace happened inside the call above: snapshot the
                # program + its embedded bounds (cached below ONLY if
                # this attempt's bounds validate)
                bounds_flat = [b for _, b in env.checks]
                defaults = getattr(env, "_defaults", {})
                bound_items = [(k, defaults.get(k, 0),
                                self._bounds.get(k, defaults.get(k, 0)))
                               for k in self._check_keys
                               if k in defaults or k in self._bounds]
                variant = (fn, self._out_specs, self._check_keys,
                           bounds_flat, bound_items)
            # ONE device_get over the two packed streams (the operator
            # path's fetch_packed discipline, applied to the fragment)
            u32_all, f64_all = jax.device_get(outs)
            u32_all = np.asarray(u32_all)
            f64_all = np.asarray(f64_all)
            per_dev = [unpack_streams(u32_all[i], f64_all[i],
                                      self._out_specs)
                       for i in range(self.n_dev)]
            counts = np.asarray([int(p[0][0]) for p in per_dev])
            # per-device check values -> worst (max) over devices
            check_vals = np.stack([p[1] for p in per_dev]).max(axis=0)
            violations = [(i, int(v), b) for i, (v, b) in
                          enumerate(zip(check_vals, bounds_flat))
                          if v > b]
            if not violations:
                if variant is not None:
                    _program_cache_put(base_key, variant)
                # record observed sizes so the NEXT query of this shape
                # AND input scale starts with tight static bounds; a
                # running max avoids thrash on varying data
                key_sigs = getattr(self, "key_sigs", None) or {}
                for i, (v, b) in enumerate(zip(check_vals, bounds_flat)):
                    ck = self._check_keys[i]
                    sig = key_sigs.get(ck)
                    if sig is not None:
                        # measured fragment sizes -> the cost model, so
                        # re-planning this shape knows real join outputs
                        from ..plan.cost import record_runtime_rows
                        record_runtime_rows(sig, int(v))
                    dflt = defaults.get(ck)
                    if dflt is None:
                        continue
                    k = (self.sig, self.n_dev, ck, _bucket(dflt))
                    _FRAGMENT_STATS[k] = max(
                        _FRAGMENT_STATS.get(k, 0),
                        _bucket(max(int(v) * 3 // 2, 1)))
                return self._stitch_packed(per_dev, counts, dicts)
            # double every violated speculative bound and re-run (the
            # mesh-level SpeculativeOverflow retry)
            for i, v, b in violations:
                k = self._check_keys[i]
                self._bounds[k] = _bucket(max(2 * b, v))
            log.warning("distributed bounds overflowed (%s); retrying",
                        violations)
        raise RuntimeError("distributed pipeline failed to size its "
                           "speculative bounds after 6 attempts")

    # -----------------------------------------------------------------------
    def _shard_inputs(self, tables):
        """Arrow tables -> padded sharded/replicated device arrays.
        Returns (layout, flat_inputs, dicts). Per-source device arrays
        are cached by underlying-table identity, so repeat queries over
        the same in-memory data skip the encode + H2D entirely (the
        fragment analog of the operator scan cache)."""
        layout = {}
        flat = []
        dicts = {}
        off = 0
        for (src, replicated), table, frag_fields in zip(
                self.sources, tables, self._source_fields()):
            key = _source_cache_key(src, replicated, self.n_dev,
                                    frag_fields)
            cached = _SOURCE_ARRAYS.get(key) if key is not None else None
            if cached is not None:
                _SOURCE_TICK[0] += 1
                _SOURCE_LRU[key] = _SOURCE_TICK[0]
            else:
                cached = self._put_source(table, replicated, frag_fields)
                if key is not None:
                    _source_cache_put(key, cached,
                                      _source_cache_limit(self.conf))
            nrows, pairs_dev, pos_dicts, padded = cached
            flat.append(nrows)
            for d, v in pairs_dev:
                flat.append(d)
                flat.append(v)
            for pos, uniq in pos_dicts.items():
                dicts[frag_fields[pos].dict_id] = uniq
            layout[len(layout)] = (padded, len(pairs_dev), off)
            off += 1 + 2 * len(pairs_dev)
        return layout, flat, dicts

    def _put_source(self, table, replicated: bool, frag_fields):
        if isinstance(table, _ShardedTables):
            return self._put_source_shards(table.shards, frag_fields)
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        shard = NamedSharding(self.mesh, P(self.axis))
        repl = NamedSharding(self.mesh, P())
        n_dev = self.n_dev
        n = table.num_rows
        if replicated:
            padded = _bucket(n)
            nrows = jax.device_put(jnp.asarray(np.full(1, n, np.int32)),
                                   repl)
        else:
            per = -(-n // n_dev) if n else 1
            padded = _bucket(per)
            counts = np.asarray(
                [max(min(n - i * per, per), 0) for i in range(n_dev)],
                np.int32)
            nrows = jax.device_put(jnp.asarray(counts), shard)
        dicts: Dict = {}
        arrays = self._encode_columns(table, frag_fields, dicts)
        pos_dicts = {i: dicts[f.dict_id]
                     for i, f in enumerate(frag_fields)
                     if f.dict_id is not None}
        pairs_dev = []
        for d, v in arrays:
            if replicated:
                dp = np.zeros(padded, d.dtype)
                vp = np.zeros(padded, bool)
                dp[:n] = d
                vp[:n] = v
                pairs_dev.append((jax.device_put(jnp.asarray(dp), repl),
                                  jax.device_put(jnp.asarray(vp), repl)))
            else:
                per = -(-n // n_dev) if n else 1
                dp = np.zeros(n_dev * padded, d.dtype)
                vp = np.zeros(n_dev * padded, bool)
                for i in range(n_dev):
                    c = max(min(n - i * per, per), 0)
                    if c:
                        dp[i * padded:i * padded + c] = \
                            d[i * per:i * per + c]
                        vp[i * padded:i * padded + c] = \
                            v[i * per:i * per + c]
                pairs_dev.append((jax.device_put(jnp.asarray(dp), shard),
                                  jax.device_put(jnp.asarray(vp), shard)))
        return nrows, pairs_dev, pos_dicts, padded

    def _source_fields(self):
        out = []

        def walk(frag):
            if isinstance(frag, _SourceFrag):
                out.append((frag.index, frag.fields))
            elif isinstance(frag, _JoinFrag):
                walk(frag.left)
                walk(frag.right)
            elif isinstance(frag, (_LocalFrag, _AggFrag, _WindowFrag)):
                walk(frag.child)
        walk(self.root)
        out.sort()
        return [f for _, f in out]

    def _encode_columns(self, table, fields: List[_Field], dicts):
        """numpy (data, validity) per field; strings -> GLOBAL sorted
        dictionary codes (code order == string order on every device)."""
        cap = int(self.conf.get(DISTRIBUTED_MAX_DICT))
        arrays = []
        for f, col in zip(fields, table.columns):
            col = _one_chunk(col)
            if f.dict_id is not None:
                entry, codes = _encode_string_global(
                    [col], cap, f.order_required, f.phys.np_dtype)
                dicts[f.dict_id] = entry
                arrays.append(codes[0])
            else:
                arrays.append(_encode_plain(col, f.phys))
        return arrays

    def _put_source_shards(self, shards, frag_fields):
        """Pre-sharded (row-group-assigned) tables: shard i's rows land
        on device i directly; string dictionaries are built GLOBALLY
        across shards so codes stay comparable on every device."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        shard_sh = NamedSharding(self.mesh, P(self.axis))
        n_dev = self.n_dev
        assert len(shards) == n_dev, (len(shards), n_dev)
        counts = np.asarray([t.num_rows for t in shards], np.int32)
        padded = _bucket(max(int(counts.max()), 1))
        nrows = jax.device_put(jnp.asarray(counts), shard_sh)
        dicts: Dict = {}
        shard_cols: Dict[int, list] = {}   # pos -> [(d, v) per shard]
        cap = int(self.conf.get(DISTRIBUTED_MAX_DICT))
        for pos, f in enumerate(frag_fields):
            if f.dict_id is not None:
                entry, codes = _encode_string_global(
                    [t.columns[pos] for t in shards], cap,
                    f.order_required, f.phys.np_dtype)
                dicts[f.dict_id] = entry
                shard_cols[pos] = codes
            else:
                shard_cols[pos] = [
                    _encode_plain(_one_chunk(t.columns[pos]), f.phys)
                    for t in shards]
        pairs_dev = []
        for pos, f in enumerate(frag_fields):
            cols = shard_cols[pos]
            dt = cols[0][0].dtype
            dp = np.zeros(n_dev * padded, dt)
            vp = np.zeros(n_dev * padded, bool)
            for i, (d, v) in enumerate(cols):
                c = len(d)
                if c:
                    dp[i * padded:i * padded + c] = d
                    vp[i * padded:i * padded + c] = v
            pairs_dev.append((jax.device_put(jnp.asarray(dp), shard_sh),
                              jax.device_put(jnp.asarray(vp), shard_sh)))
        pos_dicts = {i: dicts[f.dict_id]
                     for i, f in enumerate(frag_fields)
                     if f.dict_id is not None}
        return nrows, pairs_dev, pos_dicts, padded

    # -----------------------------------------------------------------------
    def _build_program(self, env: _Env):
        import jax
        from jax.sharding import PartitionSpec as P

        from ._compat import shard_map
        from ..columnar.packing import pack_traced
        root = self.root
        self._check_keys = None
        self._out_specs = None

        def local(*inputs):
            import jax.numpy as jnp
            env._inputs = inputs
            env.checks = []
            rel = root.emit(env).compacted(env)
            # Sink discipline (r2 verdict #1): the fetch is sized by the
            # RESULT, not the padded program shapes — slice every output
            # column to a learned speculative result bound (validated
            # like every other bound; first run uses the padded size,
            # the recorded stat shrinks repeats), then pack everything
            # into the engine's two-stream format (columnar/packing.py)
            # so the whole result leaves the device in at most two
            # transfers instead of 2×columns×devices padded fetches.
            rb = min(env.bound(("result",), default=rel.padded),
                     rel.padded)
            env.check(rel.count, rb)
            flat = [rel.count.astype(jnp.int64).reshape(1)]
            # env.checks is never empty: the result-bound check above
            # is always present
            flat.append(jnp.concatenate(
                [c.astype(jnp.int64).reshape(1) for c, _ in env.checks]))
            for d, v in rel.pairs:
                flat.append(d[:rb])
                flat.append(v[:rb])
            self._out_specs = [(np.dtype(str(x.dtype)), tuple(x.shape))
                               for x in flat]
            u32, f64 = pack_traced(flat)
            return u32.reshape(1, -1), f64.reshape(1, -1)

        # specs: replicated sources P(), sharded P(axis)
        in_specs = []
        for idx, (src, replicated) in enumerate(self.sources):
            padded, nf, off = env._layout[idx]
            spec = P() if replicated else P(self.axis)
            in_specs.append(spec)
            in_specs.extend([spec] * (2 * nf))
        out_spec = P(self.axis)

        fn = shard_map(local, mesh=self.mesh, in_specs=tuple(in_specs),
                       out_specs=out_spec, check_vma=False)
        jit_fn = jax.jit(fn)
        # bind check keys in emit order: do a lightweight bound-key pass
        self._check_keys = self._collect_check_keys(env)
        return jit_fn

    def _collect_check_keys(self, env: _Env):
        """Deterministic (emit-order) keys for the overflow checks —
        mirrors the env.bound() calls inside emit()."""
        keys = []

        def walk(frag):
            if isinstance(frag, _SourceFrag):
                return
            if isinstance(frag, _LocalFrag):
                walk(frag.child)
                return
            if isinstance(frag, _JoinFrag):
                walk(frag.left)
                walk(frag.right)
                if not (frag.broadcast_build or env.n_dev == 1
                        or frag.replicated):
                    keys.append(("recv", frag.frag_id, False))
                    keys.append(("recv", frag.frag_id, True))
                keys.append(("join", frag.frag_id))
                return
            if isinstance(frag, _WindowFrag):
                walk(frag.child)
                if not (env.n_dev == 1 or frag.replicated):
                    keys.append(("win", frag.frag_id))
                return
            if isinstance(frag, _AggFrag):
                walk(frag.child)
                if not (env.n_dev == 1 or frag.replicated):
                    keys.append(("agg", frag.frag_id))
        walk(self.root)
        keys.append(("result",))    # the sink's result-bound check
        return keys

    # -----------------------------------------------------------------------
    def _stitch_packed(self, per_dev, counts, dicts):
        import pyarrow as pa
        from ..columnar.column import arrow_from_numpy
        n_dev = self.n_dev
        root = self.root
        take_first_only = root.replicated
        arrays = []
        for ci, (f, lf) in enumerate(zip(self._schema.fields, root.fields)):
            parts_d, parts_v = [], []
            devs = [0] if take_first_only else range(n_dev)
            for dev in devs:
                g = int(counts[dev])
                parts_d.append(per_dev[dev][2 + 2 * ci][:g])
                parts_v.append(per_dev[dev][3 + 2 * ci][:g])
            dv = np.concatenate(parts_d) if parts_d \
                else per_dev[0][2 + 2 * ci][:0]
            vv = np.concatenate(parts_v) if parts_v \
                else per_dev[0][3 + 2 * ci][:0]
            if lf.dict_id is not None:
                entry = dicts.get(lf.dict_id, ("sorted",
                                               np.asarray([], object)))
                if entry[0] == "sorted":
                    uniq = entry[1]
                    pos = np.clip(dv, 0, max(len(uniq) - 1, 0))
                else:                   # hash codes -> decode map
                    h_uniq, uniq = entry[1], entry[2]
                    pos = np.clip(np.searchsorted(h_uniq, dv), 0,
                                  max(len(uniq) - 1, 0))
                if len(uniq):
                    idx = pa.array(pos.astype(np.int64), mask=~vv)
                    arr = pa.array(uniq, type=pa.string()).take(idx)
                else:
                    arr = pa.nulls(len(dv), type=pa.string())
                arrays.append(arr)
            else:
                # arrays are already host numpy (device_get above) —
                # convert directly; a DeviceColumn round trip would pay
                # one H2D + one D2H tunnel crossing per result column
                arrays.append(arrow_from_numpy(dv, vv, lf.logical))
        names = [f.name for f in self._schema.fields]
        return pa.Table.from_arrays(arrays, names=names)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def _scan_input_rows(node):
    """Total in-memory scan rows under a physical node; file scans count
    as 'large' (None = unbounded)."""
    from ..exec.basic import InMemoryScanExec
    from ..io.file_scan import FileScanBase
    if isinstance(node, FileScanBase):
        return None
    total = 0
    if isinstance(node, InMemoryScanExec):
        total += sum(t.num_rows for t in node.tables)
    for c in getattr(node, "children", []):
        sub = _scan_input_rows(c)
        if sub is None:
            return None
        total += sub
    return total


def distribution_gate(physical, conf: TpuConf, auto: bool = False) -> bool:
    """Whether a mesh should be used for this plan. An explicitly-supplied
    mesh implies distribution is wanted; an AUTO mesh (built because
    distributed.enabled defaulted on with >1 device) only engages above
    the minRows threshold — the cost-model gate that lets the conf
    default ON without hurting small queries."""
    if not auto:
        return True
    rows = _scan_input_rows(physical)
    return rows is None or rows >= int(conf.get(DISTRIBUTED_MIN_ROWS))


def try_distribute(physical, conf: TpuConf, mesh):
    """Replace the largest lowerable subtree containing communication with
    a DistributedPipelineExec. Returns None when NOTHING lowered, so the
    caller can fall back to the single-chip fused pipeline instead of
    silently losing it."""
    if mesh is None:
        return None
    return _try_replace(physical, conf, mesh)


def maybe_distribute(physical, conf: TpuConf, mesh):
    """try_distribute, keeping the original plan when nothing lowered."""
    replaced = try_distribute(physical, conf, mesh)
    return replaced if replaced is not None else physical


_SINGLE_MESH = [None]


def maybe_fuse_single_chip(physical, conf: TpuConf):
    """Single-chip fused pipelines: a plan fragment containing a JOIN
    compiles to ONE kernel through the fragment compiler over a 1-device
    mesh — one dispatch instead of several per operator, the dominant
    cost on a latency-bound backend. Join-free plans keep the operator
    pipeline (the aggregate exec's fused single-fetch path is already
    one dispatch). Oversized inputs fall back at runtime."""
    if _SINGLE_MESH[0] is None:
        from .mesh import make_mesh
        _SINGLE_MESH[0] = make_mesh(1)
    replaced = _try_replace(physical, conf, _SINGLE_MESH[0],
                            require_join=True, keep_fallback=True)
    return replaced if replaced is not None else physical


def _try_replace(node, conf: TpuConf, mesh, require_join: bool = False,
                 keep_fallback: bool = False):
    new = _lower_node(node, conf, mesh, require_join, keep_fallback)
    if new is not None:
        return new
    changed = False
    new_children = []
    for c in getattr(node, "children", []):
        r = _try_replace(c, conf, mesh, require_join, keep_fallback)
        if r is not None and r is not c:
            changed = True
            new_children.append(r)
        else:
            new_children.append(c)
    if changed:
        node.children = new_children
    return node if changed else None


def _lower_node(node, conf: TpuConf, mesh, require_join: bool = False,
                keep_fallback: bool = False):
    planner = _Planner(conf, fused_mode=require_join)
    try:
        frag = planner.lower(node)
    except _NotLowerable as e:
        log.debug("not lowerable at %s: %s", type(node).__name__, e)
        return None
    if not planner.has_comm:
        return None                 # no join/agg: the mesh gains nothing
    if require_join and not planner.has_join:
        return None
    ex = DistributedPipelineExec(frag, planner.sources, mesh, conf,
                                 node.output_schema(),
                                 fallback=node if keep_fallback
                                 else None)
    ex.key_sigs = planner.key_sigs
    return ex
