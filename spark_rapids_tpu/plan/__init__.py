from . import logical
from .meta import PlanMeta
from .overrides import explain_potential_tpu_plan, plan_query, wrap_plan

__all__ = ["logical", "PlanMeta", "explain_potential_tpu_plan", "plan_query",
           "wrap_plan"]
