"""Cost-based optimizer (ref CostBasedOptimizer.scala; defaults from
RapidsConf.scala:2126-2156 — CPU exec 2.0e-4 s/row, GPU exec 1.0e-4 s/row,
plus row<->columnar transition costs).

After tagging, walk the meta tree bottom-up estimating per-subtree wall cost
under two placements (device vs host). A node that is TPU-capable but whose
device cost — including the transitions its placement would force — exceeds
its host cost is reverted with an explicit "cost-based" reason, exactly the
reference's "it is not worth moving this subtree to the GPU" behavior.

Row estimates are deliberately crude (the reference's are too): scans count
real rows, filters halve, aggregates collapse by ~the group-ratio guess,
joins multiply selectivity. The model's job is to catch egregious cases
(tiny subtree sandwiched between CPU sections), not to be a planner.
"""
from __future__ import annotations

import logging
from typing import Optional

from ..config import (CBO_ENABLED as OPTIMIZER_ENABLED,
                      CPU_EXEC_COST_PER_ROW as CPU_EXEC_COST,
                      TPU_EXEC_COST_PER_ROW as TPU_EXEC_COST,
                      TpuConf, register)
from . import logical as L
from .meta import PlanMeta

log = logging.getLogger(__name__)

TRANSITION_COST = register(
    "spark.rapids.tpu.sql.optimizer.transition.cost", 1.0e-8,
    "Estimated cost per row of a host<->device transition "
    "(row->columnar H2D or columnar->row D2H; ref "
    "spark.rapids.sql.optimizer.cpu.exec.rowToColumnarCost).",
    internal=True)

DEVICE_QUERY_FLOOR = register(
    "spark.rapids.tpu.sql.optimizer.device.queryFloorSeconds", 0.12,
    "Fixed wall cost a COLD device placement pays once per query: jit "
    "trace + (persistent-tier-miss) XLA compile + kernel dispatch + the "
    "D2H result fetch. Measured ~0.1-0.25 s on this tunneled backend "
    "(docs/performance.md); set near 0.002 on a directly-attached TPU. "
    "Split against dispatchFloorSeconds: a plan digest whose compiled "
    "executables are already warm in the two-tier executable cache "
    "(plan/exec_cache.py) pays only the dispatch component, so warm "
    "repeats — the serving case — are costed without the compile floor. "
    "Queries whose whole-plan host estimate beats device+floor revert to "
    "the host engine — the reference's CostBasedOptimizer transition "
    "revert generalized to the per-query floor that dominates small "
    "inputs on a tunnel.", commonly_used=True)

DEVICE_DISPATCH_FLOOR = register(
    "spark.rapids.tpu.sql.optimizer.device.dispatchFloorSeconds", 0.02,
    "The dispatch-only component of the per-query device floor: kernel "
    "launch + D2H result fetch with every executable already resolved "
    "from the live or persistent compile cache (plan/exec_cache.py). "
    "Charged instead of queryFloorSeconds when the plan digest is known "
    "compiled — the cache-aware re-costing that flips warm repeats of "
    "small queries onto the device. Never charged above "
    "queryFloorSeconds.", commonly_used=True)

#: vectorized per-row host cost by node kind (numpy/pyarrow kernels, NOT
#: the reference's per-row-interpreter 2e-4 — this engine's host twin is
#: columnar). Calibrated against measured 1M-row pandas times
#: (docs/performance.md headline table).
_HOST_ROW_COST = {
    L.LogicalScan: 0.0,          # both engines share the host decode
    L.ParquetScan: 0.0,
    L.Filter: 6.0e-9,
    L.Project: 8.0e-9,
    L.Join: 4.0e-8,              # hash probe per stream row
    L.Sort: 1.5e-7,
    # the CPU twin (CpuWindowExec, pandas per-window apply) measures
    # ~1e-5 s/row — NOT the host-sink numpy path, which belongs to
    # TpuWindowExec and prices itself (WINDOW_HOST_SINK_ROWS); a cheap
    # estimate here would revert windows onto the slow twin
    L.Window: 1.0e-5,
    L.Expand: 2.0e-8,
}
_HOST_ROW_DEFAULT = 2.0e-8


#: logical node type -> learned-cost-table kind name (the key space of
#: record_op_wall / learned_row_cost). One kind per operator family —
#: coarse on purpose: the learned table prices "what a Filter costs per
#: row on this machine", not one entry per query shape (shapes are the
#: engine walls' job).
_KIND_OF = {
    L.Filter: "Filter",
    L.Project: "Project",
    L.Aggregate: "Aggregate",
    L.Join: "Join",
    L.Sort: "Sort",
    L.Window: "Window",
    L.Expand: "Expand",
}


def node_kind(plan) -> Optional[str]:
    """Learned-cost kind for a logical node (None = not learned)."""
    return _KIND_OF.get(type(plan))


def _expr_weight(e) -> int:
    """Expression-tree node count: one vectorized host kernel pass per
    node is the cost unit (a 5-comparison filter costs ~5x one compare)."""
    return 1 + sum(_expr_weight(c) for c in getattr(e, "children", []))


def _host_node_cost(plan, rows_in: float, cpu_scale: float) -> float:
    """Vectorized host cost of one node over its INPUT rows. A TRUSTED
    learned host row cost for the node's kind (fed back from the host
    twin's measured per-operator self-times) replaces the static table —
    what this machine measured beats any calibration constant."""
    kind = node_kind(plan)
    if kind is not None:
        lc = learned_row_cost(kind, "host")
        if lc is not None:
            return lc * rows_in
    per_pass = 3.0e-9       # one numpy/arrow elementwise pass per row
    if isinstance(plan, L.Aggregate):
        if plan.groupings:
            c = 1.2e-7 + 2.0e-8 * len(plan.aggs)   # hash groupby
        else:
            c = 8.0e-9 * max(len(plan.aggs), 1)    # global reductions
        c += per_pass * sum(_expr_weight(a.child)
                            for a in plan.aggs
                            if getattr(a, "child", None) is not None)
        return c * rows_in * cpu_scale
    if isinstance(plan, L.Filter):
        return (per_pass * (1 + _expr_weight(plan.condition))
                * rows_in * cpu_scale)
    if isinstance(plan, L.Project):
        w = sum(_expr_weight(e) for e in plan.exprs)
        return per_pass * w * rows_in * cpu_scale
    return (_HOST_ROW_COST.get(type(plan), _HOST_ROW_DEFAULT)
            * rows_in * cpu_scale)


# ---------------------------------------------------------------------------
# adaptive runtime statistics (ref GpuCustomShuffleReaderExec / the
# reference's AQE stage stats, GpuOverrides.scala:4681-4730): execs record
# the MEASURED size of materialized plan subtrees keyed by a structural
# signature; the planner prefers these over the crude estimates below, so
# a join strategy mis-planned from estimates flips on the next planning
# of the same shape.
# ---------------------------------------------------------------------------

_RUNTIME_SIZES: dict = {}
_RUNTIME_SIZES_MAX = 4096

# In-memory tables are tagged with a CONTENT fingerprint (schema + row
# count + hashed head/tail slices), memoized per object id. Content tags
# are stable across processes — measured walls and row counts persist to
# the on-disk stats store (stats_store.py) and a fresh process plans a
# previously-seen query correctly on its FIRST execution (the cross-
# process analog of the reference's AQE stage statistics,
# GpuOverrides.scala:4691-4730). The id-memo is only a cache: a recycled
# object id can at worst recompute the fingerprint, never serve a stale
# one, because the memo pins the table object itself.
import weakref  # noqa: E402

_SIG_PIN: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()
_SIG_MEMO: dict = {}


def _drop_memo(tid: int):
    _SIG_MEMO.pop(tid, None)


def _fingerprint_table(t) -> str:
    import hashlib
    h = hashlib.blake2b(digest_size=10)
    h.update(str(t.schema).encode())
    h.update(str(t.num_rows).encode())
    n = t.num_rows
    for sl in (t.slice(0, 128), t.slice(max(n - 128, 0), 128),
               t.slice(n // 2, 64)):
        try:
            # hash VALUES of the sampled rows, never buffers: pyarrow
            # slices are zero-copy views whose .buffers() return the
            # UNTRIMMED parent buffers (hashing the whole table three
            # times, ~1.3 s at 20M rows, measured)
            import pickle
            h.update(pickle.dumps(sl.to_pydict(), protocol=4))
        except Exception:       # unpicklable cell types: length-only tag
            h.update(b"?")
    return h.hexdigest()


def _evict_local_sigs(tag: str):
    """Drop every stat whose signature embeds a process-local '#<id>#'
    tag when that object dies — a recycled id must never serve another
    table's measurements (the content-fingerprint path needs no eviction;
    this guards only the non-Arrow fallback)."""
    for store in (_RUNTIME_SIZES, _RUNTIME_ROWS):
        for k in [k for k in store if tag in k]:
            del store[k]
    for k in [k for k in _ENGINE_WALLS if tag in k[0]]:
        del _ENGINE_WALLS[k]


def _pin_table(t) -> str:
    tid = id(t)
    if _SIG_PIN.get(tid) is t and tid in _SIG_MEMO:
        return _SIG_MEMO[tid]
    try:
        fp = f"#{_fingerprint_table(t)}#"
    except Exception:
        fp = f"#{tid}#"              # non-arrow source: process-local tag
        try:
            _SIG_PIN[tid] = t
            _SIG_MEMO[tid] = fp
            weakref.finalize(t, _drop_memo, tid)
            weakref.finalize(t, _evict_local_sigs, fp)
        except TypeError:
            pass
        return fp
    try:
        _SIG_PIN[tid] = t
        _SIG_MEMO[tid] = fp
        weakref.finalize(t, _drop_memo, tid)
    except TypeError:
        pass
    return fp


def plan_signature(plan: L.LogicalPlan) -> str:
    """Structural signature of a logical subtree (stable across runs of
    the same query shape; scans key on table identity + schema)."""
    kids = ",".join(plan_signature(c) for c in plan.children)
    extra = ""
    if isinstance(plan, L.LogicalScan):
        extra = (f"{[_pin_table(t) for t in plan.tables]};"
                 f"{plan.schema().names()}")
    elif isinstance(plan, L.ParquetScan):
        # key on content fingerprint (mtime+size) and projected columns:
        # an appended file or a wider projection must not inherit a
        # stale measured size. Memo lifetime is a short freshness window,
        # not the node's lifetime — plan_signature runs several times
        # per planning and must not re-stat thousands of files each time,
        # but a node re-planned after its files changed must see them.
        import time
        memo = getattr(plan, "_sig_fingerprint", None)
        now = time.monotonic()
        if memo is not None and now - memo[1] < 2.0:
            fp = memo[0]
        else:
            import os
            parts = []
            for p in plan.paths:
                try:
                    st = os.stat(p)
                    parts.append(f"{p}@{st.st_mtime_ns}:{st.st_size}")
                except OSError:
                    parts.append(p)
            fp = ";".join(parts)
            plan._sig_fingerprint = (fp, now)
        extra = fp + f";{plan.columns}"
    elif isinstance(plan, L.Filter):
        extra = plan.condition.key()
    elif isinstance(plan, L.Project):
        extra = ",".join(e.key() for e in plan.exprs)
    elif isinstance(plan, L.Join):
        cond = plan.condition.key() if plan.condition is not None else ""
        extra = (f"{plan.join_type};"
                 + ",".join(e.key() for e in plan.left_keys) + ";"
                 + ",".join(e.key() for e in plan.right_keys)
                 + f";{cond};{plan.broadcast}")
    elif isinstance(plan, L.Aggregate):
        extra = (",".join(e.key() for e in plan.groupings) + ";"
                 + ",".join(a.key() for a in plan.aggs))
    return f"{type(plan).__name__}[{extra}]({kids})"


def record_runtime_size(sig: str, nbytes: int) -> None:
    if len(_RUNTIME_SIZES) >= _RUNTIME_SIZES_MAX \
            and sig not in _RUNTIME_SIZES:
        _RUNTIME_SIZES.pop(next(iter(_RUNTIME_SIZES)))
    # running max: re-planning must stay safe under varying batch counts
    _RUNTIME_SIZES[sig] = max(_RUNTIME_SIZES.get(sig, 0), int(nbytes))


def runtime_size(sig: str):
    return _RUNTIME_SIZES.get(sig)


#: measured output ROW counts per plan signature (same lifecycle/eviction
#: as _RUNTIME_SIZES): the adaptive feedback that fixes the crude
#: selectivity guesses below — a dimension filter measured at 30 rows
#: re-plans as 30 rows, not input/2 (ref AQE stage statistics,
#: GpuOverrides.scala:4681-4730)
_RUNTIME_ROWS: dict = {}


def record_runtime_rows(sig: str, rows: int) -> None:
    if len(_RUNTIME_ROWS) >= _RUNTIME_SIZES_MAX \
            and sig not in _RUNTIME_ROWS:
        _RUNTIME_ROWS.pop(next(iter(_RUNTIME_ROWS)))
    _RUNTIME_ROWS[sig] = max(_RUNTIME_ROWS.get(sig, 0), int(rows))
    if _persist_enabled():
        from . import stats_store
        stats_store.mark_dirty()


#: measured whole-query wall seconds per (plan signature, placement):
#: the ground truth that overrides the static floor model once an engine
#: has actually been tried — mispriced shapes self-correct on the next
#: planning. Values are (compile-free observations, min seconds).
#: Walls are keyed on executable-cache hit status at record time: only
#: COMPILE-FREE runs (zero in-process cache misses, zero backend-compile
#: seconds during the query) are ingested, so one observation suffices
#: for trust — the old >=2-observation workaround existed solely because
#: first-run walls smuggled their XLA compile (minutes on a remote
#: backend) into the measurement
_ENGINE_WALLS: dict = {}


def _persist_enabled() -> bool:
    import os
    return os.environ.get("SRTPU_STATS_PERSIST", "1") != "0"


def load_persisted_stats() -> None:
    """Merge the on-disk adaptive stats (stats_store.py) into the live
    dicts — idempotent, called lazily before the first read."""
    if _persist_enabled():
        from . import exec_cache, stats_store
        stats_store.load_into(_ENGINE_WALLS, _RUNTIME_ROWS, _OP_COSTS,
                              exec_cache._PLAN_DIGESTS)


def record_engine_wall(sig: str, placement: str, seconds: float,
                       compile_free: bool = True) -> None:
    """Record a measured whole-query wall. ``compile_free=False`` (the
    caller saw executable-cache misses or backend-compile time during
    the run) drops the sample: a compile-laden wall measures the cold
    start, not the engine, and must never gate the placement choice."""
    if not compile_free:
        return
    if len(_ENGINE_WALLS) >= _RUNTIME_SIZES_MAX \
            and (sig, placement) not in _ENGINE_WALLS:
        _ENGINE_WALLS.pop(next(iter(_ENGINE_WALLS)))
    k = (sig, placement)
    cnt, prev = _ENGINE_WALLS.get(k, (0, None))
    _ENGINE_WALLS[k] = (cnt + 1,
                        seconds if prev is None else min(prev, seconds))
    if _persist_enabled():
        from . import stats_store
        stats_store.mark_dirty()


def trusted_engine_wall(sig: str, placement: str):
    # >=1 observation: every recorded wall is already compile-free
    # (record_engine_wall keys on exec-cache hit status), so the first
    # sample is representative — the >=2 rule this replaces only guarded
    # against compile-poisoned first runs
    got = _ENGINE_WALLS.get((sig, placement))
    if got is None or got[0] < 1:
        return None
    return got[1]


#: learned per-row operator costs from LIVE self-times, keyed
#: (operator kind, placement) -> (rows processed, seconds): the metrics
#: registry already measures every operator's self time — feeding those
#: walls back here (metrics/analyze.record_learned_op_costs, plus the
#: fused-region wall from exec/wholestage.py) replaces the static
#: per-row guesses with what this machine actually measured, for device
#: AND host placements. Persisted with the other adaptive stats
#: (stats_store.py).
_OP_COSTS: dict = {}
#: rows an operator kind must have processed before its learned cost is
#: trusted (tiny samples are all dispatch floor, not per-row cost)
_OP_COST_MIN_ROWS = 65536
#: per-QUERY input-row minimum for the generic self-time feed
#: (record_op_wall min_rows): a query below this is dispatch-floor- and
#: iterator-overhead-dominated, so its per-row quotient would poison the
#: table no matter how many such samples accumulate
_OP_COST_SAMPLE_MIN_ROWS = 262144


def record_op_wall(kind: str, placement: str, rows: int,
                   seconds: float, compile_free: bool = True,
                   min_rows: int = 0) -> None:
    """Accumulate (rows, seconds) into the learned per-operator cost
    table. ``compile_free=False`` drops the sample — a wall that paid
    jit trace or XLA compile measures the cold start, not the operator
    (the executable-cache-hit keying that replaced the old trust-later
    workaround). ``min_rows`` drops under-scale samples (see
    _OP_COST_SAMPLE_MIN_ROWS)."""
    if rows <= 0 or seconds <= 0.0 or not compile_free \
            or rows < min_rows:
        return
    k = (kind, placement)
    r, s = _OP_COSTS.get(k, (0, 0.0))
    _OP_COSTS[k] = (r + int(rows), s + float(seconds))
    if _persist_enabled():
        from . import stats_store
        stats_store.mark_dirty()


def learned_row_cost(kind: str, placement: str):
    """Measured seconds/row for an operator kind, or None before the
    sample is trustworthy."""
    got = _OP_COSTS.get((kind, placement))
    if got is None or got[0] < _OP_COST_MIN_ROWS:
        return None
    return got[1] / got[0]


class RowsAccum:
    """Per-exec output-row accumulator for measured-rows feedback.

    One accumulator spans ALL batches of one execute() call, so a
    multi-batch exec records its true total (not the largest single
    batch). Lazy device counts add when the sink fetch resolves them —
    exec/base._record_rows tags each lazy batch with (accum, weakref to
    that exact batch); derived batches that copy or share the meta dict
    fail the identity check and never mis-attribute their counts."""

    __slots__ = ("sig", "total", "_lock")

    def __init__(self, sig: str):
        import threading
        self.sig = sig
        self.total = 0
        self._lock = threading.Lock()

    def add(self, n: int) -> None:
        with self._lock:
            self.total += int(n)
            record_runtime_rows(self.sig, self.total)


def estimate_rows(plan: L.LogicalPlan) -> float:
    """Cardinality estimate per logical node: measured (from a previous
    run of the same shape) when available, crude guess otherwise."""
    meas = _RUNTIME_ROWS.get(plan_signature(plan))
    if meas is not None:
        return float(meas)
    kids = [estimate_rows(c) for c in plan.children]
    if isinstance(plan, L.LogicalScan):
        return float(sum(t.num_rows for t in plan.tables))
    if isinstance(plan, L.ParquetScan):
        total = 0
        for p in plan.paths:
            try:
                import pyarrow.parquet as pq
                total += pq.ParquetFile(p).metadata.num_rows
            except Exception:
                total += 1_000_000
        return float(total)
    if isinstance(plan, L.RangeRel):
        return float(max(0, (plan.end - plan.start) // (plan.step or 1)))
    if isinstance(plan, L.Filter):
        return kids[0] * 0.5
    if isinstance(plan, L.Aggregate):
        return max(kids[0] * 0.1, 1.0) if plan.groupings else 1.0
    if isinstance(plan, (L.GlobalLimit, L.LocalLimit)):
        return float(min(plan.n, kids[0]))
    if isinstance(plan, L.Join):
        if plan.join_type in ("leftsemi", "leftanti", "existence"):
            return kids[0]
        if not plan.left_keys:
            return kids[0] * kids[1] * 0.1
        return max(kids[0], kids[1])
    if isinstance(plan, L.Sample):
        return kids[0] * plan.fraction
    if isinstance(plan, L.Expand):
        return kids[0] * len(plan.projections)
    if isinstance(plan, L.Union):
        return float(sum(kids))
    return kids[0] if kids else 1000.0


class _Cost:
    __slots__ = ("device", "host", "device_boundary")

    def __init__(self, device: float, host: float, device_boundary: bool):
        #: cheapest cost of this subtree ending device-resident / host-resident
        self.device = device
        self.host = host
        #: whether the subtree root runs on device in the device plan
        self.device_boundary = device_boundary


def apply_cost_optimizer(meta: PlanMeta, conf: TpuConf,
                         wall_sig: Optional[str] = None,
                         plan_digest: Optional[str] = None) -> str:
    """Revert TPU-capable nodes whose device placement is not worth it.

    Two decisions, both the reference's CostBasedOptimizer idea adapted to
    a tunneled accelerator (RapidsConf.scala:2126-2156):
      * per-subtree: a node whose host cost (incl. transitions) beats its
        device cost reverts (the reference's behavior verbatim);
      * whole-plan: ANY device placement pays the per-query floor ONCE —
        when the entire plan's host estimate beats best-device + floor,
        the whole query runs on the host engine. The floor is
        CACHE-AWARE: a ``plan_digest`` whose executables are already
        warm in the two-tier compile cache (plan/exec_cache.py) pays
        only the dispatch component (DEVICE_DISPATCH_FLOOR), not the
        cold trace+compile floor — warm repeats (the serving case) are
        re-costed without the compile they will not pay. Small inputs on
        a tunnel still lose to the dispatch floor no matter how fast the
        kernels are; measured row feedback (_RUNTIME_ROWS) makes the
        second planning of a shape exact.

    Per-node costs prefer the LEARNED per-operator row costs (device and
    host, record_op_wall) over the static tables once trusted.

    Mutates metas via will_not_work_on_tpu. Returns a one-line placement
    decision ("device (...)" / "host (...)") recording WHY, which
    EXPLAIN prints — a stage staying on host is explained by the plan
    output itself. Every COST_MODEL_HOST tag detail carries the device
    and host cost estimates behind the decision."""
    load_persisted_stats()
    # the registered defaults are per-row costs for the reference's
    # row-interpreter; this engine's host twin is vectorized — treat the
    # conf values as SCALES relative to the registered defaults so
    # existing knobs still steer the model
    cpu_scale = conf.get(CPU_EXEC_COST) / 2.0e-4
    tpu_c = conf.get(TPU_EXEC_COST) / 1.0e-4 * 2.0e-9
    # live per-operator self-times trump the static device guess — but
    # ONLY for the node kinds the measurement covers: fused regions
    # measure filter/project rows (record_op_wall from
    # exec/wholestage.py), so a cheap fused wall must not also discount
    # joins/sorts/aggregates it never timed
    fused_c = learned_row_cost("WholeStageExec", "device")
    trans_c = conf.get(TRANSITION_COST)
    cold_floor = float(conf.get(DEVICE_QUERY_FLOOR))
    # cache-aware floor: plan digest warm in the executable cache (live
    # tier or a previous process via the persistent tier) -> the compile
    # component is already paid, only dispatch+fetch remains
    warm_digest = False
    if plan_digest is not None:
        from . import exec_cache
        warm_digest = exec_cache.plan_digest_cached(plan_digest)
    dispatch_floor = min(float(conf.get(DEVICE_DISPATCH_FLOOR)),
                         cold_floor)
    floor = dispatch_floor if warm_digest else cold_floor

    pending_reverts = []     # (meta, reason): applied only if the
    # measured-wall arbitration below doesn't choose the device wholesale

    def walk(m: PlanMeta) -> _Cost:
        # costs scale with the rows a node PROCESSES (its input); a
        # groupby collapsing 2M rows to 7 groups still hashes 2M rows
        rows_in = (sum(estimate_rows(c.plan) for c in m.child_metas)
                   if m.child_metas else estimate_rows(m.plan))
        kids = [walk(c) for c in m.child_metas]
        host_node = _host_node_cost(m.plan, rows_in, cpu_scale)
        # scans decode on host for BOTH engines (the H2D is the floor's /
        # transition's job) — placement-neutral, never worth reverting
        kind = node_kind(m.plan)
        learned_dev = (learned_row_cost(kind, "device")
                       if kind is not None else None)
        if isinstance(m.plan, (L.LogicalScan, L.ParquetScan)):
            node_tpu_c = 0.0
        elif learned_dev is not None:
            # trusted measured device cost for this operator KIND
            # replaces the static guess outright (the learned cost
            # already includes the kernel's real dispatch wall)
            node_tpu_c = learned_dev
            if fused_c is not None and isinstance(m.plan,
                                                  (L.Filter, L.Project)):
                # fusible chains collapse into ONE dispatch + ONE
                # compaction (exec/wholestage.py): a per-kind cost
                # learned from STANDALONE operators (each paying its
                # own dispatch) overprices the fused execution, so the
                # region's measured per-row wall caps it
                node_tpu_c = min(node_tpu_c, fused_c)
        elif fused_c is not None and isinstance(m.plan,
                                                (L.Filter, L.Project)):
            # fusible node kinds price from the measured fused walls
            node_tpu_c = min(tpu_c, fused_c)
        else:
            node_tpu_c = tpu_c
        if not m.can_run_on_tpu:
            # host-only: children feeding it from device pay a D2H transition
            host = host_node + sum(
                min(k.host, k.device + trans_c * estimate_rows(cm.plan))
                for k, cm in zip(kids, m.child_metas))
            return _Cost(float("inf"), host, False)
        # device placement: children arriving host-side pay H2D
        device = node_tpu_c * rows_in + sum(
            min(k.device, k.host + trans_c * estimate_rows(cm.plan))
            for k, cm in zip(kids, m.child_metas))
        host = host_node + sum(
            min(k.host, k.device + trans_c * estimate_rows(cm.plan))
            for k, cm in zip(kids, m.child_metas))
        if host < device:
            # the COST_MODEL_HOST contract: the detail always carries
            # both estimates, so explain("placement") shows the numbers
            # behind the decision
            pending_reverts.append((m, (
                f"cost-based: device≈{device:.4f}s (incl. transitions) "
                f"exceeds host≈{host:.4f}s")))
            return _Cost(float("inf"), host, False)
        return _Cost(device, host, True)

    root = walk(meta)

    def pure_host(m: PlanMeta) -> float:
        rows_in = (sum(estimate_rows(c.plan) for c in m.child_metas)
                   if m.child_metas else estimate_rows(m.plan))
        return (_host_node_cost(m.plan, rows_in, cpu_scale)
                + sum(pure_host(c) for c in m.child_metas))

    host_only = pure_host(meta)
    best_mixed = min(root.device, root.host)
    host_est = host_only
    # model device estimate WITHOUT the per-node reverts applied: the
    # cost every node would pay if the whole plan ran device-side
    dev_model = root.device if root.device != float("inf") else best_mixed
    dev_est = best_mixed + floor
    how = "estimate"
    hw = dw = None
    if wall_sig is not None:
        # MEASURED whole-query walls trump the model: a shape that has
        # actually run on an engine is priced by what it cost, so
        # marginal mispredictions self-correct on the next planning
        hw = trusted_engine_wall(wall_sig, "host")
        dw = trusted_engine_wall(wall_sig, "device")
        if hw is not None:
            host_est, how = hw, "measured"
        if dw is not None:
            dev_est, how = dw, "measured"

    # whole-plan reversions record a coded wrapping tag on the root AND
    # flip each still-capable node — nodes carrying their own reasons
    # keep them (tags.revert_to_host; the explain("placement") contract)
    from .tags import WHOLE_PLAN_HOST_REVERT, revert_to_host

    def revert_all(m: PlanMeta, reason: str):
        revert_to_host(m, reason, code=WHOLE_PLAN_HOST_REVERT)

    # Bidirectional measured-wall arbitration (the per-node model alone
    # could only flip device->host; a slow host twin would then be chosen
    # forever with the measured walls ignored — caught when the r4 bench
    # kept q9 on a 1.4 s host plan while the device ran it in 0.2 s):
    #   * both walls trusted -> the faster engine wins wholesale;
    #   * only the host wall trusted, and the MODEL thinks the device
    #     could beat it -> run device once to learn its wall;
    #   * otherwise the model decides (per-node reverts + floor check).
    if hw is not None and dw is not None:
        if dw <= hw:
            log.debug("cost optimizer: measured device wall %.4fs beats "
                      "host %.4fs — device wholesale", dw, hw)
            return (f"device (measured device wall {dw:.4f}s beats host "
                    f"{hw:.4f}s)")
        revert_all(meta, (f"cost-based: measured host≈{hw:.4f}s beats "
                          f"device≈{dw:.4f}s"))
        return (f"host (measured host wall {hw:.4f}s beats device "
                f"{dw:.4f}s)")
    if hw is not None and dw is None \
            and dev_model + dispatch_floor < hw:
        # exploration prices the device at its WARM floor even when the
        # digest is cold: the compile is a one-time investment a serving
        # workload amortizes over every repeat, so a shape whose warm
        # repeats would beat the measured host wall is worth one
        # compile-paying run to learn its device wall
        log.debug("cost optimizer: exploring device (model %.4fs + "
                  "dispatch floor < measured host %.4fs)", dev_model, hw)
        return (f"device (exploring: model {dev_model:.4f}s + dispatch "
                f"floor {dispatch_floor:.4f}s < measured host "
                f"{hw:.4f}s)")
    if dw is not None and hw is None and host_only < dw:
        # symmetric: a device-first shape measuring slow must TRY the
        # host twin once, or it stays on the slow engine forever
        revert_all(meta, (f"cost-based: exploring host — model "
                          f"host≈{host_only:.4f}s < measured "
                          f"device≈{dw:.4f}s"))
        log.debug("cost optimizer: exploring host (model %.4fs < "
                  "measured device %.4fs)", host_only, dw)
        return (f"host (exploring: model {host_only:.4f}s < measured "
                f"device {dw:.4f}s)")
    from .tags import COST_MODEL_HOST
    for m, reason in pending_reverts:
        m.will_not_work_on_tpu(reason, code=COST_MODEL_HOST)
        log.debug("cost optimizer reverted %s", type(m.plan).__name__)
    floor_word = "warm dispatch floor" if warm_digest else "cold floor"
    if floor > 0 and host_est < dev_est:
        reason = (f"cost-based: whole-plan host {how} host≈{host_est:.4f}s "
                  f"beats device≈{dev_est:.4f}s (incl. {floor_word} "
                  f"{floor:.4f}s)")
        revert_all(meta, reason)
        log.debug("cost optimizer reverted whole plan to host (%s)", reason)
        return (f"host ({how} {host_est:.4f}s beats device "
                f"{dev_est:.4f}s incl. {floor_word})")
    return (f"device ({how}: device {dev_est:.4f}s incl. {floor_word} vs "
            f"host {host_est:.4f}s"
            + (f"; {len(pending_reverts)} subtree(s) reverted"
               if pending_reverts else "") + ")")
