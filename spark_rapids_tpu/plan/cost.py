"""Cost-based optimizer (ref CostBasedOptimizer.scala; defaults from
RapidsConf.scala:2126-2156 — CPU exec 2.0e-4 s/row, GPU exec 1.0e-4 s/row,
plus row<->columnar transition costs).

After tagging, walk the meta tree bottom-up estimating per-subtree wall cost
under two placements (device vs host). A node that is TPU-capable but whose
device cost — including the transitions its placement would force — exceeds
its host cost is reverted with an explicit "cost-based" reason, exactly the
reference's "it is not worth moving this subtree to the GPU" behavior.

Row estimates are deliberately crude (the reference's are too): scans count
real rows, filters halve, aggregates collapse by ~the group-ratio guess,
joins multiply selectivity. The model's job is to catch egregious cases
(tiny subtree sandwiched between CPU sections), not to be a planner.
"""
from __future__ import annotations

import logging
from typing import Optional

from ..config import (CBO_ENABLED as OPTIMIZER_ENABLED,
                      CPU_EXEC_COST_PER_ROW as CPU_EXEC_COST,
                      TPU_EXEC_COST_PER_ROW as TPU_EXEC_COST,
                      TpuConf, register)
from . import logical as L
from .meta import PlanMeta

log = logging.getLogger(__name__)

TRANSITION_COST = register(
    "spark.rapids.tpu.sql.optimizer.transition.cost", 1.0e-4,
    "Estimated cost per row of a host<->device transition "
    "(row->columnar H2D or columnar->row D2H; ref "
    "spark.rapids.sql.optimizer.cpu.exec.rowToColumnarCost).",
    internal=True)


# ---------------------------------------------------------------------------
# adaptive runtime statistics (ref GpuCustomShuffleReaderExec / the
# reference's AQE stage stats, GpuOverrides.scala:4681-4730): execs record
# the MEASURED size of materialized plan subtrees keyed by a structural
# signature; the planner prefers these over the crude estimates below, so
# a join strategy mis-planned from estimates flips on the next planning
# of the same shape.
# ---------------------------------------------------------------------------

_RUNTIME_SIZES: dict = {}
_RUNTIME_SIZES_MAX = 4096

# id-reuse guard (same hazard planner._source_cache_key handles): scan
# signatures embed id(table); when a table is GC'd, evict every stat
# whose signature mentions that id so a recycled address can never serve
# a stale measured size for an unrelated table.
import weakref  # noqa: E402

_SIG_PIN: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


def _evict_sigs_for(tid: int):
    tag = f"#{tid}#"
    for k in [k for k in _RUNTIME_SIZES if tag in k]:
        del _RUNTIME_SIZES[k]


def _pin_table(t) -> str:
    tid = id(t)
    if _SIG_PIN.get(tid) is not t:
        try:
            _SIG_PIN[tid] = t
        except TypeError:
            return f"#{tid}#"
        _evict_sigs_for(tid)        # stale stats under a reused id
        weakref.finalize(t, _evict_sigs_for, tid)
    return f"#{tid}#"


def plan_signature(plan: L.LogicalPlan) -> str:
    """Structural signature of a logical subtree (stable across runs of
    the same query shape; scans key on table identity + schema)."""
    kids = ",".join(plan_signature(c) for c in plan.children)
    extra = ""
    if isinstance(plan, L.LogicalScan):
        extra = (f"{[_pin_table(t) for t in plan.tables]};"
                 f"{plan.schema().names()}")
    elif isinstance(plan, L.ParquetScan):
        # key on content fingerprint (mtime+size) and projected columns:
        # an appended file or a wider projection must not inherit a
        # stale measured size. Memo lifetime is a short freshness window,
        # not the node's lifetime — plan_signature runs several times
        # per planning and must not re-stat thousands of files each time,
        # but a node re-planned after its files changed must see them.
        import time
        memo = getattr(plan, "_sig_fingerprint", None)
        now = time.monotonic()
        if memo is not None and now - memo[1] < 2.0:
            fp = memo[0]
        else:
            import os
            parts = []
            for p in plan.paths:
                try:
                    st = os.stat(p)
                    parts.append(f"{p}@{st.st_mtime_ns}:{st.st_size}")
                except OSError:
                    parts.append(p)
            fp = ";".join(parts)
            plan._sig_fingerprint = (fp, now)
        extra = fp + f";{plan.columns}"
    elif isinstance(plan, L.Filter):
        extra = plan.condition.key()
    elif isinstance(plan, L.Project):
        extra = ",".join(e.key() for e in plan.exprs)
    elif isinstance(plan, L.Join):
        cond = plan.condition.key() if plan.condition is not None else ""
        extra = (f"{plan.join_type};"
                 + ",".join(e.key() for e in plan.left_keys) + ";"
                 + ",".join(e.key() for e in plan.right_keys)
                 + f";{cond};{plan.broadcast}")
    elif isinstance(plan, L.Aggregate):
        extra = (",".join(e.key() for e in plan.groupings) + ";"
                 + ",".join(a.key() for a in plan.aggs))
    return f"{type(plan).__name__}[{extra}]({kids})"


def record_runtime_size(sig: str, nbytes: int) -> None:
    if len(_RUNTIME_SIZES) >= _RUNTIME_SIZES_MAX \
            and sig not in _RUNTIME_SIZES:
        _RUNTIME_SIZES.pop(next(iter(_RUNTIME_SIZES)))
    # running max: re-planning must stay safe under varying batch counts
    _RUNTIME_SIZES[sig] = max(_RUNTIME_SIZES.get(sig, 0), int(nbytes))


def runtime_size(sig: str):
    return _RUNTIME_SIZES.get(sig)


def estimate_rows(plan: L.LogicalPlan) -> float:
    """Crude cardinality estimate per logical node."""
    kids = [estimate_rows(c) for c in plan.children]
    if isinstance(plan, L.LogicalScan):
        return float(sum(t.num_rows for t in plan.tables))
    if isinstance(plan, L.ParquetScan):
        total = 0
        for p in plan.paths:
            try:
                import pyarrow.parquet as pq
                total += pq.ParquetFile(p).metadata.num_rows
            except Exception:
                total += 1_000_000
        return float(total)
    if isinstance(plan, L.RangeRel):
        return float(max(0, (plan.end - plan.start) // (plan.step or 1)))
    if isinstance(plan, L.Filter):
        return kids[0] * 0.5
    if isinstance(plan, L.Aggregate):
        return max(kids[0] * 0.1, 1.0) if plan.groupings else 1.0
    if isinstance(plan, (L.GlobalLimit, L.LocalLimit)):
        return float(min(plan.n, kids[0]))
    if isinstance(plan, L.Join):
        if plan.join_type in ("leftsemi", "leftanti", "existence"):
            return kids[0]
        if not plan.left_keys:
            return kids[0] * kids[1] * 0.1
        return max(kids[0], kids[1])
    if isinstance(plan, L.Sample):
        return kids[0] * plan.fraction
    if isinstance(plan, L.Expand):
        return kids[0] * len(plan.projections)
    if isinstance(plan, L.Union):
        return float(sum(kids))
    return kids[0] if kids else 1000.0


class _Cost:
    __slots__ = ("device", "host", "device_boundary")

    def __init__(self, device: float, host: float, device_boundary: bool):
        #: cheapest cost of this subtree ending device-resident / host-resident
        self.device = device
        self.host = host
        #: whether the subtree root runs on device in the device plan
        self.device_boundary = device_boundary


def apply_cost_optimizer(meta: PlanMeta, conf: TpuConf) -> None:
    """Revert TPU-capable nodes whose device placement is not worth the
    transitions. Mutates metas via will_not_work_on_tpu."""
    cpu_c = conf.get(CPU_EXEC_COST)
    tpu_c = conf.get(TPU_EXEC_COST)
    trans_c = conf.get(TRANSITION_COST)

    def walk(m: PlanMeta) -> _Cost:
        rows = estimate_rows(m.plan)
        kids = [walk(c) for c in m.child_metas]
        if not m.can_run_on_tpu:
            # host-only: children feeding it from device pay a D2H transition
            host = cpu_c * rows + sum(
                min(k.host, k.device + trans_c * estimate_rows(cm.plan))
                for k, cm in zip(kids, m.child_metas))
            return _Cost(float("inf"), host, False)
        # device placement: children arriving host-side pay H2D
        device = tpu_c * rows + sum(
            min(k.device, k.host + trans_c * estimate_rows(cm.plan))
            for k, cm in zip(kids, m.child_metas))
        host = cpu_c * rows + sum(
            min(k.host, k.device + trans_c * estimate_rows(cm.plan))
            for k, cm in zip(kids, m.child_metas))
        if host < device:
            m.will_not_work_on_tpu(
                f"cost-based: device cost {device:.4f} (incl. transitions) "
                f"exceeds host cost {host:.4f}")
            log.debug("cost optimizer reverted %s", type(m.plan).__name__)
            return _Cost(float("inf"), host, False)
        return _Cost(device, host, True)

    walk(meta)
