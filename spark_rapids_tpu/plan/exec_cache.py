"""Two-tier compiled-executable cache (ISSUE 6).

Every fused kernel the engine builds — whole-stage fusion regions
(exec/wholestage.py), projection/filter kernels (exprs/compiler.py),
string-rectangle chains — resolves through this module instead of
jitting ad hoc:

* **in-process tier** — a bounded LRU of live jitted callables keyed on
  (kernel digest, input dtypes, device kind). A repeat query of the
  same shape (new exec objects, same expressions) reuses the SAME
  callable, so jax's own trace cache serves every shape bucket it has
  already seen — zero retrace, zero recompile. This is the layer the
  r5 bench was missing: per-exec kernel dicts died with their query,
  so "warm" runs re-traced everything (string_transforms_100k: 17.3 s
  warm at 0.03x).
* **persistent tier** — JAX's on-disk compilation cache (serialized
  executables, configured process-wide in ``spark_rapids_tpu/__init__``
  and re-pointable per session via ``spark.rapids.tpu.compile.cache.dir``).
  A fresh process pays trace time but ZERO XLA compile for any module a
  previous process compiled. ``compile.cache.maxBytes`` bounds the tier
  with mtime-LRU eviction.

Observability: ``srtpu_compile_*`` metrics (registry inventory +
docs/monitoring.md) count in-process hits/misses, persistent-tier hits
and cumulative backend-compile seconds; the same events emit
``cat="compile"`` trace spans so ``tools/profile`` can attribute
cold-start time honestly. Both ride jax.monitoring, so they measure the
REAL XLA compile, not the (instant) jit-closure construction.

The blessed-modules contract is enforced by the ``adhoc-jit`` tpulint
rule: a ``jax.jit`` call site outside the compiler/cache modules
bypasses this cache and silently re-introduces per-query recompiles.
"""
from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional, Tuple

from ..config import register

__all__ = ["COMPILE_CACHE_DIR", "COMPILE_CACHE_MAX_BYTES",
           "get_or_build", "fused_key", "stats", "hit_rate",
           "reset_stats", "clear", "configure_from_conf",
           "trim_persistent", "device_kind", "record_plan_compiled",
           "plan_digest_cached", "compile_free_since"]

COMPILE_CACHE_DIR = register(
    "spark.rapids.tpu.compile.cache.dir", "",
    "Directory for the persistent compiled-executable tier (JAX's "
    "on-disk compilation cache: serialized XLA executables keyed by "
    "module fingerprint). Empty keeps the process default "
    "(SRTPU_COMPILE_CACHE, ~/.cache/srtpu_xla). Point every serving "
    "process of a fleet at a shared directory so a repeat query pays "
    "zero compile even in a fresh process (docs/tuning.md).",
    commonly_used=True)

COMPILE_CACHE_MAX_BYTES = register(
    "spark.rapids.tpu.compile.cache.maxBytes", 4 * 1024 * 1024 * 1024,
    "Size budget for the persistent executable tier; when exceeded the "
    "oldest entries (file mtime) are evicted after a compile writes new "
    "ones. <= 0 disables eviction (unbounded).", commonly_used=True)

#: in-process tier bound: distinct fused kernels alive at once. Each
#: entry is one Python callable (the executables behind it are owned by
#: jax's caches, which the test harness clears per module).
_LRU_MAX = 512

_LOCK = threading.Lock()
_LRU: "OrderedDict[Tuple, Callable]" = OrderedDict()  # tpulint: guarded-by _LOCK
# tpulint: guarded-by _LOCK
_STATS: Dict[str, float] = {"hits": 0, "misses": 0,
                            "persistent_hits": 0, "compile_s": 0.0}

#: last persistent-tier trim PER DIRECTORY, debounced (an eviction walk
#: per compile burst, not per kernel; two sessions on different dirs
#: must not consume each other's debounce window)
_LAST_TRIM: Dict[str, float] = {}    # tpulint: guarded-by _LOCK
_TRIM_DEBOUNCE_S = 30.0

#: callbacks invoked by clear(): front memos layered over this cache
#: (exprs/compiler._FRONT) register here so dropping the tier actually
#: releases every strong reference
_CLEAR_HOOKS = []                    # tpulint: guarded-by _LOCK

#: the process-default cache dir, captured before any session override:
#: a session with an EMPTY compile.cache.dir conf must get this default
#: back, not whichever directory the previous session pointed jax at
_PROC_DEFAULT_DIR = [None]           # tpulint: guarded-by _LOCK

#: plan digests (metrics/events.plan_digest) whose device execution
#: completed — every kernel the plan builds now lives in the in-process
#: tier and (serialized) in jax's persistent tier, so a repeat of the
#: digest pays the dispatch floor only, never the compile floor. The
#: set persists with the adaptive stats (plan/stats_store.py "plans"),
#: giving a fresh process the same warm-floor costing the persistent
#: executable tier gives it warm kernels. Keyed per device kind: an
#: executable compiled for one backend says nothing about another.
#: A dict-as-ordered-set (values unused): insertion order is the
#: recency proxy, so the cap evicts the OLDEST digest, never an
#: arbitrary hot one (the _ENGINE_WALLS idiom).
_PLAN_DIGESTS: dict = {}             # tpulint: guarded-by _LOCK
_PLAN_DIGESTS_MAX = 4096


def record_plan_compiled(digest: str) -> None:
    """Mark a plan digest's executables as resident in the cache tiers
    (called after a successful device execution of the plan)."""
    if not digest:
        return
    key = (str(digest), device_kind())
    with _LOCK:
        if key in _PLAN_DIGESTS:
            # refresh recency (move to end): a hot serving plan that
            # re-runs every second must not age into the "oldest" slot
            # just because it was registered first. No mark_dirty — the
            # SET is unchanged, only its order, not worth a save per
            # repeat query.
            _PLAN_DIGESTS.pop(key)
            _PLAN_DIGESTS[key] = None
            return
        # while, not if: a persisted-stats merge (load_into) can leave
        # the set over the cap, and delete-one-insert-one would keep it
        # there forever
        while len(_PLAN_DIGESTS) >= _PLAN_DIGESTS_MAX:
            del _PLAN_DIGESTS[next(iter(_PLAN_DIGESTS))]
        _PLAN_DIGESTS[key] = None
    from .cost import _persist_enabled
    if _persist_enabled():
        from . import stats_store
        stats_store.mark_dirty()


def warm_digests() -> list:
    """Snapshot of the warm (digest, device-kind) pairs, taken under
    the lock — the stats_store persist path must not iterate the live
    dict while record_plan_compiled mutates it."""
    with _LOCK:
        return list(_PLAN_DIGESTS)


def plan_digest_cached(digest: str) -> bool:
    """True when a previous device run of this plan digest (this process
    or, via the persisted stats, an earlier one sharing the cache dirs)
    left its executables warm — the planner's cache-aware floor check."""
    if not digest:
        return False
    from .cost import load_persisted_stats
    load_persisted_stats()
    with _LOCK:
        return (str(digest), device_kind()) in _PLAN_DIGESTS


def _invalidate_plan_digests() -> None:
    """Drop the warm-digest set because the persistent tier changed
    under it (trim eviction, cache-dir re-point): a digest must never
    vouch for executables that are no longer there — the planner would
    charge the dispatch floor to a plan about to pay a full cold
    compile. Conservative by design (clear()'s contract): the cold
    floor re-applies until a device run proves the kernels warm again."""
    with _LOCK:
        if not _PLAN_DIGESTS:
            return
        _PLAN_DIGESTS.clear()
    try:
        from .cost import _persist_enabled
        if _persist_enabled():
            from . import stats_store
            stats_store.mark_dirty()
    except Exception:  # pragma: no cover - persistence is best-effort
        pass


def compile_free_since(snapshot: dict) -> bool:
    """True when zero in-process cache misses AND zero backend-compile
    seconds accrued since ``snapshot`` (an earlier ``stats()`` result) —
    THE definition of a compile-free run, the only kind the learned
    cost model ingests (cost.record_engine_wall / record_op_wall). One
    helper so every feed site keys on the same counters."""
    now = stats()
    return (now["compile_s"] == snapshot["compile_s"]
            and now["misses"] == snapshot["misses"])


def device_kind() -> str:
    """Platform component of every cache key: an executable compiled
    for one backend must never be served to another."""
    try:
        import jax
        return jax.default_backend()
    except Exception:  # pragma: no cover - broken backend environments
        return "unknown"


def fused_key(digest: str, schema_sig: Tuple, extra: Tuple = ()) -> Tuple:
    """Cache key for a compiled region: (plan digest, input dtypes,
    device kind[, extras]). Shape buckets are NOT part of the key — the
    cached callable is a jitted function that re-specializes per static
    shape internally, so one entry serves every bucket."""
    return (digest, schema_sig, device_kind()) + tuple(extra)


def digest_of(*parts: str) -> str:
    """Stable short digest over structural signature strings (the
    PR-5 plan-digest idiom applied to physical kernel signatures)."""
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode("utf-8"))
        h.update(b"\x00")
    return h.hexdigest()[:16]


def get_or_build(key: Tuple, build: Callable[[], Callable],
                 label: str = "kernel") -> Callable:
    """Resolve ``key`` in the in-process tier; on miss, run ``build``
    (which must return the jitted callable) under a ``cat="compile"``
    trace span and insert it. Thread-safe; a racing duplicate build is
    harmless (last insert wins, both callables are equivalent)."""
    with _LOCK:
        fn = _LRU.get(key)
        if fn is not None:
            _LRU.move_to_end(key)
            _STATS["hits"] += 1
            hit = True
        else:
            _STATS["misses"] += 1
            hit = False
    if hit:
        _registry_inc("srtpu_compile_cache_hits_total")
        return fn
    _registry_inc("srtpu_compile_cache_misses_total")
    from ..trace import core as trace_core
    tr = trace_core.TRACER
    t0 = tr.now() if tr is not None else 0
    fn = build()
    if tr is not None:
        tr.complete(f"compile.build.{label}", t0, cat="compile",
                    args={"key": str(key[0])})
    with _LOCK:
        _LRU[key] = fn
        while len(_LRU) > _LRU_MAX:
            _LRU.popitem(last=False)
    return fn


def get_or_build_jit(name: str, fn: Callable, **jit_kwargs) -> Callable:
    """Blessed ``jax.jit`` wrapper for NAMED module-level kernels: the
    compiled callable resolves through the in-process tier keyed on
    (name, jit options, device kind), so every holder shares one
    callable and the ``srtpu_compile_*`` metrics see the compile.  This
    is the migration target for the grandfathered ad-hoc
    ``jax.jit(module_fn)`` sites the ``adhoc-jit`` rule tracks
    (docs/static_analysis.md)."""
    import jax

    def build():
        return jax.jit(fn, **jit_kwargs)

    # jit options are part of the identity: two sites sharing a name
    # but differing in e.g. donate_argnums must not share a callable
    opts = tuple(sorted((k, repr(v)) for k, v in jit_kwargs.items()))
    return get_or_build(fused_key(name, opts), build, label=name)


def stats() -> Dict[str, float]:
    """Copy of the process-lifetime cache counters (bench.py diffs
    these around each rung for the cold/warm compile split)."""
    with _LOCK:
        return dict(_STATS)


def hit_rate() -> Optional[float]:
    """In-process tier hit rate over the process lifetime, or None
    before the first lookup — the ops ``/healthz`` exec-cache verdict
    input (a warm serving process living below ~0.5 is recompiling
    kernels it should be reusing)."""
    st = stats()
    lookups = st["hits"] + st["misses"]
    return (st["hits"] / lookups) if lookups else None


def reset_stats() -> None:
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0 if k != "compile_s" else 0.0


def register_clear_hook(fn: Callable[[], None]) -> None:
    """Register a callback run by clear() — front memos layered over
    this cache use it so clear() releases THEIR references too.
    Idempotent per callback."""
    with _LOCK:
        if fn not in _CLEAR_HOOKS:
            _CLEAR_HOOKS.append(fn)


def clear() -> None:
    """Drop the in-process tier, every registered front memo, and the
    warm-plan-digest set (tests; the persistent tier survives — dropping
    the digests is conservative: the planner re-applies the cold floor
    until a run proves the kernels warm again)."""
    with _LOCK:
        _LRU.clear()
        _PLAN_DIGESTS.clear()
        hooks = list(_CLEAR_HOOKS)
    for fn in hooks:
        fn()


def _registry_inc(name: str, amount=1) -> None:
    from ..metrics.registry import REGISTRY
    if REGISTRY is not None:
        REGISTRY.counter(name).inc(amount)


# ---------------------------------------------------------------------------
# persistent tier: conf hookup + size budget
# ---------------------------------------------------------------------------

def configure_from_conf(conf) -> Optional[str]:
    """Point jax's persistent compilation cache at the conf'd directory
    (when set) and schedule a size trim. One conf lookup per
    ExecContext construction — the metrics/tracer installation pattern.
    Returns the active cache dir (or None when persistence is off)."""
    import jax
    cur = jax.config.jax_compilation_cache_dir
    # check-then-set under the lock: two ExecContexts constructed
    # concurrently must agree on ONE process default, not race to
    # capture each other's override as "the default"
    with _LOCK:
        if _PROC_DEFAULT_DIR[0] is None:
            _PROC_DEFAULT_DIR[0] = cur or ""
        default_dir = _PROC_DEFAULT_DIR[0]
    want = (str(conf.get(COMPILE_CACHE_DIR) or "").strip()
            or default_dir)
    if want != (cur or ""):
        try:
            jax.config.update("jax_compilation_cache_dir", want or None)
            cur = want
            # the persistent tier the warm digests vouch for just moved
            _invalidate_plan_digests()
        except Exception:  # pragma: no cover - cache is an optimization
            pass
    if cur:
        max_bytes = int(conf.get(COMPILE_CACHE_MAX_BYTES))
        now = time.monotonic()
        # the debounce check-then-set is atomic, or two concurrent
        # sessions both pass the window test and stat-walk the (shared,
        # possibly NFS) cache dir twice
        with _LOCK:
            due = max_bytes > 0 and \
                now - _LAST_TRIM.get(cur, 0.0) >= _TRIM_DEBOUNCE_S
            if due:
                _LAST_TRIM[cur] = now
        if due:
            # background thread: the stat walk of a large shared cache
            # dir (possibly NFS) must not block query start — this is
            # called from ExecContext construction
            threading.Thread(target=trim_persistent,
                             args=(cur, max_bytes), daemon=True,
                             name="srtpu-exec-cache-trim").start()
    return cur or None


def trim_persistent(cache_dir: str, max_bytes: int) -> int:
    """Evict oldest-mtime files until the directory fits ``max_bytes``.
    Returns the number of files removed. Tolerates concurrent writers,
    unreadable/corrupt entries and vanished files — eviction is an
    optimization and must never raise into a query."""
    removed = 0
    try:
        entries = []
        for dirpath, _dirs, files in os.walk(cache_dir):
            for fn in files:
                p = os.path.join(dirpath, fn)
                try:
                    st = os.stat(p)
                    entries.append((st.st_mtime, st.st_size, p))
                except OSError:
                    continue
        total = sum(s for _, s, _ in entries)
        if total <= max_bytes:
            return 0
        for _mt, size, p in sorted(entries):
            try:
                os.unlink(p)
                removed += 1
                total -= size
            except OSError:
                continue
            if total <= max_bytes:
                break
    except OSError:  # pragma: no cover - directory races
        pass
    if removed:
        # which plans lost executables is unknowable at file level —
        # drop every warm digest rather than let one vouch for a
        # compile the evicted entries no longer cover
        _invalidate_plan_digests()
    return removed


# ---------------------------------------------------------------------------
# compile-time accounting: jax.monitoring bridge
# ---------------------------------------------------------------------------
# XLA compiles lazily at first dispatch, so build() timing above would
# read ~0. jax emits monitoring events around the REAL work:
#   /jax/core/compile/backend_compile_duration   — seconds of XLA compile
#   /jax/compilation_cache/cache_hits            — persistent-tier reads
# The listeners are registered once at import and cost one dict update
# per COMPILE (never per batch); metric mirroring is one branch when the
# registry is off — the trace/metrics disabled-path contract.

_LISTENERS_ON = [False]


def _on_event(event: str, **kw) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        with _LOCK:
            _STATS["persistent_hits"] += 1
        _registry_inc("srtpu_compile_persistent_hits_total")


def _on_duration(event: str, duration: float, **kw) -> None:
    if event == "/jax/core/compile/backend_compile_duration":
        with _LOCK:
            _STATS["compile_s"] += float(duration)
        _registry_inc("srtpu_compile_seconds_total", float(duration))
        from ..trace import core as trace_core
        tr = trace_core.TRACER
        if tr is not None:
            t1 = tr.now()
            tr.complete("compile.backend", t1 - int(duration * 1e9), t1,
                        cat="compile", args={"seconds": round(duration, 4)})


def _install_listeners() -> None:
    if _LISTENERS_ON[0]:
        return
    try:
        from jax import monitoring
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
        _LISTENERS_ON[0] = True
    except Exception:  # pragma: no cover - accounting only, never fatal
        pass


_install_listeners()
