"""Logical plan nodes.

The reference is a plugin over Spark Catalyst and consumes Catalyst plans
(GpuOverrides.scala:4480 wrapAndTagPlan). Standalone on TPU we own the plan
representation: a small Catalyst-shaped logical algebra produced by the
DataFrame API (api/dataframe.py), tagged and converted by plan/overrides.py.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..types import (BOOL, INT64, DataType, Schema, StructField)
from ..exprs.base import Alias, ColumnRef, Expression

__all__ = ["LogicalPlan", "LogicalScan", "ParquetScan", "Project", "Filter",
           "Aggregate", "Sort", "SortOrder", "GlobalLimit", "LocalLimit",
           "Join", "Union", "RangeRel", "Sample", "Expand", "Window",
           "WindowSpec", "Repartition", "WriteFile"]


class LogicalPlan:
    children: List["LogicalPlan"] = []

    def schema(self) -> Schema:
        raise NotImplementedError

    def node_name(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + self.describe() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def describe(self) -> str:
        return self.node_name()


class LogicalScan(LogicalPlan):
    """In-memory source: a list of Arrow tables (one per partition).
    ``columns`` (set by the pruning pass) narrows the scan without
    replacing the tables, so the exec's device cache keys on the original
    table object."""

    def __init__(self, tables, schema: Schema,
                 columns: Optional[List[str]] = None):
        self.tables = list(tables)
        self._schema = schema
        self.columns = columns
        self.children = []

    def schema(self) -> Schema:
        if self.columns is None:
            return self._schema
        return Schema([self._schema[c] for c in self.columns])

    def estimated_size_bytes(self) -> int:
        return sum(t.nbytes for t in self.tables)

    def describe(self):
        return f"LogicalScan[{len(self.tables)} partitions]({self.schema()})"


class ParquetScan(LogicalPlan):
    """File source (ref GpuParquetScan.scala). Partitioning into tasks is
    decided at physical planning (io/parquet.py)."""

    def __init__(self, paths: Sequence[str], schema: Schema,
                 columns: Optional[List[str]] = None):
        self.paths = list(paths)
        self._schema = schema
        self.columns = columns
        self.children = []

    def schema(self) -> Schema:
        if self.columns is None:
            return self._schema
        return Schema([self._schema[c] for c in self.columns])


    def describe(self):
        return f"{type(self).__name__}[{len(self.paths)} files]"


class OrcScan(ParquetScan):
    """ORC file source (ref GpuOrcScan.scala)."""


class AvroScan(ParquetScan):
    """Avro file source (ref GpuAvroScan.scala)."""


class Project(LogicalPlan):
    def __init__(self, exprs: Sequence[Expression], child: LogicalPlan):
        self.exprs = list(exprs)
        self.children = [child]

    def schema(self) -> Schema:
        cs = self.children[0].schema()
        return Schema([StructField(e.name_hint, e.data_type(cs), True)
                       for e in self.exprs])

    def describe(self):
        return "Project[" + ", ".join(e.name_hint for e in self.exprs) + "]"


class Filter(LogicalPlan):
    def __init__(self, condition: Expression, child: LogicalPlan):
        self.condition = condition
        self.children = [child]

    def schema(self) -> Schema:
        return self.children[0].schema()

    def describe(self):
        return f"Filter[{self.condition.name_hint}]"


class Aggregate(LogicalPlan):
    """groupings: list of (expr, name); aggs: list of AggregateExpression
    (exprs/aggregates.py) each with an output name."""

    def __init__(self, groupings, aggs, child: LogicalPlan,
                 many_groups_hint: bool = False,
                 int_key_cards=None):
        self.groupings = list(groupings)
        self.aggs = list(aggs)
        #: planner knows this aggregate is high-cardinality (e.g. the
        #: inner dedup pass of a DISTINCT expansion groups by the distinct
        #: value): the exec skips its optimistic single-fetch fast path,
        #: whose kernel compile + fetch would be wasted
        self.many_groups_hint = many_groups_hint
        #: per-grouping PROVEN cardinality: entry k (an int) promises the
        #: key's values lie in [0, k) — set only by rewrites that
        #: construct the key themselves (the union-of-aggregates branch
        #: id). Lets the exec use direct one-hot addressing with NO sort
        #: (the cudf hash-groupby trade; exec/aggregate.py direct core).
        self.int_key_cards = (list(int_key_cards)
                              if int_key_cards is not None
                              else [None] * len(self.groupings))
        self.children = [child]

    def schema(self) -> Schema:
        cs = self.children[0].schema()
        fields = [StructField(e.name_hint, e.data_type(cs), True)
                  for e in self.groupings]
        fields += [StructField(a.name_hint, a.data_type(cs), True)
                   for a in self.aggs]
        return Schema(fields)

    def describe(self):
        g = ", ".join(e.name_hint for e in self.groupings)
        a = ", ".join(a.name_hint for a in self.aggs)
        return f"Aggregate[keys=[{g}], aggs=[{a}]]"


class SortOrder:
    def __init__(self, expr: Expression, ascending: bool = True,
                 nulls_first: Optional[bool] = None):
        self.expr = expr
        self.ascending = ascending
        # Spark default: nulls first for asc, nulls last for desc
        self.nulls_first = nulls_first if nulls_first is not None else ascending

    def __repr__(self):
        d = "ASC" if self.ascending else "DESC"
        n = "NULLS FIRST" if self.nulls_first else "NULLS LAST"
        return f"{self.expr.name_hint} {d} {n}"


class Sort(LogicalPlan):
    def __init__(self, orders: Sequence[SortOrder], child: LogicalPlan,
                 global_sort: bool = True):
        self.orders = list(orders)
        self.global_sort = global_sort
        self.children = [child]

    def schema(self) -> Schema:
        return self.children[0].schema()

    def describe(self):
        return f"Sort[{', '.join(map(repr, self.orders))}]"


class GlobalLimit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        self.n = n
        self.children = [child]

    def schema(self):
        return self.children[0].schema()

    def describe(self):
        return f"GlobalLimit[{self.n}]"


class LocalLimit(GlobalLimit):
    def describe(self):
        return f"LocalLimit[{self.n}]"


class Join(LogicalPlan):
    JOIN_TYPES = ("inner", "left", "right", "full", "leftsemi", "leftanti",
                  "cross", "existence")

    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 join_type: str, left_keys: Sequence[Expression] = (),
                 right_keys: Sequence[Expression] = (),
                 condition: Optional[Expression] = None,
                 broadcast: Optional[str] = None):
        jt = join_type.lower().replace("_", "")
        if jt == "leftouter":
            jt = "left"
        if jt == "rightouter":
            jt = "right"
        if jt in ("fullouter", "outer"):
            jt = "full"
        if jt == "semi":
            jt = "leftsemi"
        if jt == "anti":
            jt = "leftanti"
        assert jt in self.JOIN_TYPES, join_type
        assert broadcast in (None, "left", "right"), broadcast
        self.join_type = jt
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.condition = condition
        self.broadcast = broadcast
        self.children = [left, right]

    def schema(self) -> Schema:
        l, r = self.children[0].schema(), self.children[1].schema()
        if self.join_type in ("leftsemi", "leftanti"):
            return l
        if self.join_type == "existence":
            return Schema(list(l.fields) +
                          [StructField("exists", BOOL, nullable=False)])
        # outer sides become nullable
        return Schema(list(l.fields) + list(r.fields))

    def describe(self):
        k = ", ".join(f"{a.name_hint}={b.name_hint}"
                      for a, b in zip(self.left_keys, self.right_keys))
        return f"Join[{self.join_type}, keys=({k})]"


class Union(LogicalPlan):
    def __init__(self, children: Sequence[LogicalPlan]):
        self.children = list(children)

    def schema(self):
        return self.children[0].schema()

    def describe(self):
        return f"Union[{len(self.children)}]"


class RangeRel(LogicalPlan):
    """ref GpuRangeExec (basicPhysicalOperators.scala:1137)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 num_partitions: int = 1, name: str = "id"):
        self.start, self.end, self.step = start, end, step
        self.num_partitions = num_partitions
        self.name = name
        self.children = []

    def schema(self):
        return Schema([StructField(self.name, INT64, False)])

    def describe(self):
        return f"Range[{self.start},{self.end},{self.step}]"


class Sample(LogicalPlan):
    def __init__(self, fraction: float, seed: int, child: LogicalPlan):
        self.fraction = fraction
        self.seed = seed
        self.children = [child]

    def schema(self):
        return self.children[0].schema()


class Expand(LogicalPlan):
    """ref GpuExpandExec: each input row emits one row per projection set."""

    def __init__(self, projections: Sequence[Sequence[Expression]],
                 names: Sequence[str], child: LogicalPlan):
        self.projections = [list(p) for p in projections]
        self.names = list(names)
        self.children = [child]

    def schema(self):
        cs = self.children[0].schema()
        return Schema([StructField(n, e.data_type(cs), True)
                       for n, e in zip(self.names, self.projections[0])])


class BranchAlign(LogicalPlan):
    """Assemble the union-of-aggregates result: the child is a grouped
    aggregate keyed by a branch-id column (first field); output has
    exactly ``n`` rows in branch order, with empty branches filled by
    empty-aggregate defaults (count -> 0, everything else -> NULL). Rows
    are tiny (one per branch): a host op by construction."""

    def __init__(self, n: int, fill_zero: Sequence[bool],
                 child: LogicalPlan):
        self.n = n
        self.fill_zero = list(fill_zero)
        self.children = [child]

    def schema(self) -> Schema:
        cs = self.children[0].schema()
        return Schema(list(cs.fields)[1:])       # drop the bid key

    def describe(self):
        return f"BranchAlign[n={self.n}]"


class DistinctFlag(LogicalPlan):
    """Appends a boolean column that is True on the stream-global FIRST
    occurrence of each (key_exprs, value_expr) combination and False
    elsewhere (NULL values never flag). Produced by the hash-distinct
    rewrite (rewrites.py _rewrite_distinct_hash); executed by the
    sort-free persistent-hash-table operator (exec/distinct_flag.py).
    Reference analog: cudf's hash-based distinct aggregation that the
    reference lowers count-distinct onto."""

    def __init__(self, key_exprs: Sequence[Expression],
                 value_expr: Expression, flag_name: str,
                 child: LogicalPlan):
        self.key_exprs = list(key_exprs)
        self.value_expr = value_expr
        self.flag_name = flag_name
        self.children = [child]

    def schema(self) -> Schema:
        from ..types import BOOL
        cs = self.children[0].schema()
        return Schema(list(cs.fields)
                      + [StructField(self.flag_name, BOOL, True)])

    def describe(self):
        k = ", ".join(e.name_hint for e in self.key_exprs)
        return (f"DistinctFlag[keys=[{k}], "
                f"value={self.value_expr.name_hint}]")


class Generate(LogicalPlan):
    """Generator application: explode/posexplode/stack (ref GpuGenerateExec).

    required_cols: child column names passed through alongside the generator
    output (ref requiredChildOutput)."""

    def __init__(self, generator, required_cols: Sequence[str],
                 child: LogicalPlan, output_names: Optional[Sequence[str]] = None):
        self.generator = generator
        self.required_cols = list(required_cols)
        self.output_names = list(output_names) if output_names else None
        self.children = [child]

    def schema(self):
        cs = self.children[0].schema()
        gen_fields = self.generator.generator_output(cs)
        if self.output_names:
            gen_fields = [StructField(n, f.dtype, f.nullable)
                          for n, f in zip(self.output_names, gen_fields)]
        return Schema([cs.fields[cs.index_of(c)] for c in self.required_cols]
                      + gen_fields)


class WindowSpec:
    def __init__(self, partition_by: Sequence[Expression] = (),
                 order_by: Sequence[SortOrder] = (),
                 frame: Optional[Tuple] = None):
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        self.frame = frame  # (kind, lower, upper) or None


class Window(LogicalPlan):
    """ref window/GpuWindowExec.scala:146."""

    def __init__(self, window_exprs, child: LogicalPlan):
        # window_exprs: list of (agg_or_rank_expr, WindowSpec, out_name)
        self.window_exprs = list(window_exprs)
        self.children = [child]

    def schema(self):
        cs = self.children[0].schema()
        fields = list(cs.fields)
        for e, spec, name in self.window_exprs:
            fields.append(StructField(name, e.data_type(cs), True))
        return Schema(fields)


class Repartition(LogicalPlan):
    """Exchange request (ref GpuShuffleExchangeExecBase).

    ``num_partitions`` None means "use the conf default"; only then may
    adaptive execution coalesce the output (``adaptive_ok``)."""

    def __init__(self, num_partitions: Optional[int],
                 keys: Sequence[Expression], child: LogicalPlan,
                 mode: str = "hash", adaptive_ok: bool = False):
        if num_partitions is not None and num_partitions <= 0:
            raise ValueError(
                f"repartition count must be positive, got {num_partitions}")
        self.num_partitions = num_partitions
        self.adaptive_ok = adaptive_ok
        self.keys = list(keys)
        self.mode = mode  # hash / roundrobin / range / single
        self.children = [child]

    def schema(self):
        return self.children[0].schema()

    def describe(self):
        return f"Repartition[{self.mode}, n={self.num_partitions}]"


class WriteFile(LogicalPlan):
    def __init__(self, path: str, file_format: str, child: LogicalPlan,
                 mode: str = "overwrite", partition_by: Sequence[str] = (),
                 options: Optional[dict] = None):
        self.path = path
        self.file_format = file_format
        self.mode = mode
        self.partition_by = list(partition_by)
        #: format-specific writer options (e.g. hive text field_delim /
        #: null_value) so reads and writes can round-trip non-defaults
        self.options = dict(options or {})
        self.children = [child]

    def schema(self):
        return self.children[0].schema()


class MapInPandas(LogicalPlan):
    """ref GpuMapInPandasExec (execution/python/)."""

    def __init__(self, fn, out_schema: Schema, child: LogicalPlan):
        self.fn = fn
        self._out = out_schema
        self.children = [child]

    def schema(self) -> Schema:
        return self._out

    def describe(self):
        return f"MapInPandas[{getattr(self.fn, '__name__', 'fn')}]"


class FlatMapGroupsInPandas(LogicalPlan):
    """ref GpuFlatMapGroupsInPandasExec."""

    def __init__(self, keys, fn, out_schema: Schema, child: LogicalPlan):
        self.keys = list(keys)
        self.fn = fn
        self._out = out_schema
        self.children = [child]

    def schema(self) -> Schema:
        return self._out

    def describe(self):
        return f"FlatMapGroupsInPandas[keys={self.keys}]"
