"""Plan-meta tagging tree (ref RapidsMeta.scala:83 SparkPlanMeta:598).

Each logical node is wrapped in a Meta that records *why* it cannot run on
the TPU (willNotWorkOnTpu), mirrors the reference's tag-then-convert flow
(GpuOverrides.wrapAndTagPlan:4480 -> doConvertPlan:4486), and produces the
explain output (`spark.rapids.tpu.sql.explain=NOT_ON_TPU`, ref
GpuOverrides.scala:4829-4838).
"""
from __future__ import annotations

import collections
import threading
from typing import List, Optional

from ..config import TpuConf
from ..exec.base import TpuExec

__all__ = ["PlanMeta", "fallback_counts", "reset_fallback_counts"]

#: process-wide histogram of fallback reasons observed at tag time — the
#: runtime companion of tools/supported_ops.fallback_histogram (which is
#: static registry coverage). Keyed by "<PlanClass>: <reason>" for execs and
#: "expr: <note>" for expression host-fallbacks (VERDICT r2 #9: report a
#: fallback-reason histogram from real workloads).
_FB_LOCK = threading.Lock()
_FALLBACKS: collections.Counter = collections.Counter()  # tpulint: guarded-by _FB_LOCK


def fallback_counts() -> dict:
    with _FB_LOCK:
        return dict(_FALLBACKS)


def reset_fallback_counts() -> None:
    with _FB_LOCK:
        _FALLBACKS.clear()


#: bound on distinct histogram keys: reasons embed query-specific text
#: (column names etc.), so a long-lived process planning many distinct
#: queries must not grow without limit — overflow folds into one bucket
_FALLBACK_KEY_CAP = 1024


def _record_fallback(key: str) -> None:
    with _FB_LOCK:
        if key not in _FALLBACKS and len(_FALLBACKS) >= _FALLBACK_KEY_CAP:
            key = "<other> (fallback-reason key cap reached)"
        _FALLBACKS[key] += 1


class PlanMeta:
    def __init__(self, plan, conf: TpuConf, parent: Optional["PlanMeta"]):
        self.plan = plan
        self.conf = conf
        self.parent = parent
        self.reasons: List[str] = []
        self.expr_notes: List[str] = []   # per-expression host-fallback notes
        #: coded PlacementTags parallel to reasons/expr_notes (plan/tags.py);
        #: plan_tags hold whole-plan wrapping reversions (tags.revert_to_host)
        self.tags: List = []
        self.expr_tags: List = []
        self.plan_tags: List = []
        #: tag dedup keys: (text, code, expr) — the free text alone is
        #: NOT enough (two sites may emit identical text under different
        #: codes, and the second tag must still reach the report)
        self._tag_keys: set = set()
        self._note_keys: set = set()
        self.child_metas: List[PlanMeta] = []

    # ------------------------------------------------------------- tagging
    def will_not_work_on_tpu(self, reason: str, code: str,
                             expr: Optional[str] = None):
        from .tags import make_tag
        key = (reason, code, expr)
        if key not in self._tag_keys:
            # tag FIRST: an unregistered code must raise without leaving
            # a half-recorded (reason without tag) meta behind
            self.tags.append(make_tag(code, reason,
                                      node=type(self.plan).__name__,
                                      expr=expr))
            self._tag_keys.add(key)
            if reason not in self.reasons:
                self.reasons.append(reason)
                _record_fallback(f"{type(self.plan).__name__}: {reason}")

    def note_expr_fallback(self, note: str, code: str,
                           expr: Optional[str] = None):
        from .tags import make_tag
        key = (note, code, expr)
        if key not in self._note_keys:
            self.expr_tags.append(make_tag(code, note,
                                           node=type(self.plan).__name__,
                                           expr=expr))
            self._note_keys.add(key)
            if note not in self.expr_notes:
                self.expr_notes.append(note)
                _record_fallback(f"expr: {note}")

    @property
    def can_run_on_tpu(self) -> bool:
        return not self.reasons

    def tag(self):
        from .op_confs import exec_disabled, exec_conf_key
        from .tags import CONF_DISABLED
        if not self.conf.sql_enabled:
            self.will_not_work_on_tpu(
                "spark.rapids.tpu.sql.enabled is false",
                code=CONF_DISABLED)
        elif exec_disabled(self.conf, self.plan):
            self.will_not_work_on_tpu(
                f"{exec_conf_key(self.plan)} is false",
                code=CONF_DISABLED)
        else:
            self.tag_self()
        for c in self.child_metas:
            c.tag()

    def tag_self(self):
        """Node-specific checks (TypeSig etc.); override."""

    # ------------------------------------------------------------ convert
    def convert(self) -> TpuExec:
        children = [c.convert() for c in self.child_metas]
        if self.can_run_on_tpu:
            return self.convert_to_tpu(children)
        return self.convert_to_cpu(children)

    def convert_to_tpu(self, children) -> TpuExec:
        raise NotImplementedError

    def convert_to_cpu(self, children) -> TpuExec:
        raise NotImplementedError

    # ------------------------------------------------------------- explain
    def explain(self, indent: int = 0, only_not_on_tpu: bool = True) -> str:
        lines = []
        name = type(self.plan).__name__
        if self.reasons:
            lines.append("  " * indent +
                         f"!Exec <{name}> cannot run on TPU because " +
                         "; ".join(self.reasons))
        elif not only_not_on_tpu:
            lines.append("  " * indent + f"*Exec <{name}> will run on TPU")
        for note in self.expr_notes:
            lines.append("  " * (indent + 1) + "!Expression " + note)
        for c in self.child_metas:
            sub = c.explain(indent + 1, only_not_on_tpu)
            if sub:
                lines.append(sub)
        return "\n".join(l for l in lines if l)
