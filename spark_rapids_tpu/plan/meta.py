"""Plan-meta tagging tree (ref RapidsMeta.scala:83 SparkPlanMeta:598).

Each logical node is wrapped in a Meta that records *why* it cannot run on
the TPU (willNotWorkOnTpu), mirrors the reference's tag-then-convert flow
(GpuOverrides.wrapAndTagPlan:4480 -> doConvertPlan:4486), and produces the
explain output (`spark.rapids.tpu.sql.explain=NOT_ON_TPU`, ref
GpuOverrides.scala:4829-4838).
"""
from __future__ import annotations

from typing import List, Optional

from ..config import TpuConf
from ..exec.base import TpuExec

__all__ = ["PlanMeta"]


class PlanMeta:
    def __init__(self, plan, conf: TpuConf, parent: Optional["PlanMeta"]):
        self.plan = plan
        self.conf = conf
        self.parent = parent
        self.reasons: List[str] = []
        self.expr_notes: List[str] = []   # per-expression host-fallback notes
        self.child_metas: List[PlanMeta] = []

    # ------------------------------------------------------------- tagging
    def will_not_work_on_tpu(self, reason: str):
        if reason not in self.reasons:
            self.reasons.append(reason)

    def note_expr_fallback(self, note: str):
        if note not in self.expr_notes:
            self.expr_notes.append(note)

    @property
    def can_run_on_tpu(self) -> bool:
        return not self.reasons

    def tag(self):
        if not self.conf.sql_enabled:
            self.will_not_work_on_tpu(
                "spark.rapids.tpu.sql.enabled is false")
        else:
            self.tag_self()
        for c in self.child_metas:
            c.tag()

    def tag_self(self):
        """Node-specific checks (TypeSig etc.); override."""

    # ------------------------------------------------------------ convert
    def convert(self) -> TpuExec:
        children = [c.convert() for c in self.child_metas]
        if self.can_run_on_tpu:
            return self.convert_to_tpu(children)
        return self.convert_to_cpu(children)

    def convert_to_tpu(self, children) -> TpuExec:
        raise NotImplementedError

    def convert_to_cpu(self, children) -> TpuExec:
        raise NotImplementedError

    # ------------------------------------------------------------- explain
    def explain(self, indent: int = 0, only_not_on_tpu: bool = True) -> str:
        lines = []
        name = type(self.plan).__name__
        if self.reasons:
            lines.append("  " * indent +
                         f"!Exec <{name}> cannot run on TPU because " +
                         "; ".join(self.reasons))
        elif not only_not_on_tpu:
            lines.append("  " * indent + f"*Exec <{name}> will run on TPU")
        for note in self.expr_notes:
            lines.append("  " * (indent + 1) + "!Expression " + note)
        for c in self.child_metas:
            sub = c.explain(indent + 1, only_not_on_tpu)
            if sub:
                lines.append(sub)
        return "\n".join(l for l in lines if l)
