"""Per-operator enable confs, auto-registered from the live registries.

Reference analog: GpuOverrides creates one ``spark.rapids.sql.expression.X``
conf per ExprRule and one ``spark.rapids.sql.exec.X`` conf per ExecRule
(GpuOverrides.scala:3935 expression map, :4121 exec map; the confs appear in
docs/additional-functionality/advanced_configs.md) — setting one to false
forces that operator off the accelerator with an explain reason.

Here the registries are the Python class inventories: every concrete
``Expression`` subclass gets ``spark.rapids.tpu.sql.expression.<Name>`` and
every logical-plan rule gets ``spark.rapids.tpu.sql.exec.<Name>``.  The
expression confs feed ``exprs.base.set_disabled_expressions`` (consulted by
the same ``fully_device_supported`` check the execs use at run time, so a
disabled expression is host-evaluated end to end); the exec confs are
checked in ``PlanMeta.tag`` (a disabled exec converts to its CPU twin and
shows up in explain output).
"""
from __future__ import annotations

import threading
from typing import Dict, List

from .. import config as C

__all__ = ["ensure_op_confs", "install_from_conf", "exec_conf_key",
           "EXPR_CONF_PREFIX", "EXEC_CONF_PREFIX"]

EXPR_CONF_PREFIX = "spark.rapids.tpu.sql.expression."
EXEC_CONF_PREFIX = "spark.rapids.tpu.sql.exec."

_LOCK = threading.RLock()
_DONE = False        # tpulint: guarded-by _LOCK


def _expression_names() -> List[str]:
    from ..tools.supported_ops import _load_registries, _all_subclasses
    import inspect
    from ..exprs.base import Expression
    from ..exprs.aggregates import AggregateExpression
    _load_registries()
    names = set()
    for root in (Expression, AggregateExpression):
        for cls in _all_subclasses(root):
            if cls.__name__.startswith("_") or inspect.isabstract(cls):
                continue
            names.add(cls.__name__)
    return sorted(names)


def _exec_names() -> List[str]:
    from .overrides import _RULES
    return sorted(cls.__name__ for cls in _RULES)


def ensure_op_confs() -> None:
    """Idempotently register the per-op confs (called by plan_query and by
    the docs generator so docs/configs.md lists every knob)."""
    global _DONE
    with _LOCK:
        if _DONE:
            return
        for n in _expression_names():
            key = EXPR_CONF_PREFIX + n
            if key not in C._REGISTRY:
                C.register(key, True,
                           f"Enable expression {n} on the TPU; false forces "
                           "host evaluation (ref GpuOverrides.scala:3935 "
                           "per-ExprRule confs).")
        for n in _exec_names():
            key = EXEC_CONF_PREFIX + n
            if key not in C._REGISTRY:
                C.register(key, True,
                           f"Enable the {n} operator on the TPU; false "
                           "converts it to the CPU twin (ref "
                           "GpuOverrides.scala:4121 per-ExecRule confs).")
        # only a fully-registered registry marks done: a failure above is
        # retried on the next call instead of silently skipping forever
        _DONE = True


def exec_conf_key(plan) -> str:
    return EXEC_CONF_PREFIX + type(plan).__name__


def _falsy(v) -> bool:
    if isinstance(v, bool):
        return not v
    return str(v).strip().lower() in ("false", "0", "no", "off")


def install_from_conf(conf: C.TpuConf) -> None:
    """Install the (thread-local) disabled-expression set for this query.

    Called at plan time for tagging and again by the execution sink, so the
    runtime device/host decision always reflects THIS query's conf even when
    other sessions plan in between. Only raw conf keys are scanned — per-op
    confs are deliberately not resolvable from environment variables (the
    upper-cased env name cannot be mapped back to the case-sensitive class
    name); everything else keeps ConfEntry's env fallback.
    """
    ensure_op_confs()
    disabled = set()
    for k, v in conf.raw.items():
        if k.startswith(EXPR_CONF_PREFIX) and _falsy(v):
            disabled.add(k[len(EXPR_CONF_PREFIX):])
    from ..exprs.base import set_disabled_expressions
    set_disabled_expressions(disabled)


def exec_disabled(conf: C.TpuConf, plan) -> bool:
    v = conf.raw.get(exec_conf_key(plan))
    return v is not None and _falsy(v)
