"""The override rule registry: logical plan -> tagged meta -> physical exec.

Reference analog: GpuOverrides.scala (object :438) — wrapAndTagPlan (:4480),
doConvertPlan (:4486), applyOverrides (:4813), and the per-node ExecRule map
(:4121). Explain-only mode honours spark.rapids.tpu.sql.mode
(GpuOverrides.scala:4701).
"""
from __future__ import annotations

import copy
import logging
from typing import Callable, Dict, Type

from ..config import TpuConf
from ..types import Schema
from ..exec import basic as B
from ..exec import aggregate as A
from ..exec import sort as S
from ..exec.base import TpuExec
from . import logical as L
from . import tags as T
from .meta import PlanMeta

log = logging.getLogger("spark_rapids_tpu.overrides")

_RULES: Dict[Type, Type[PlanMeta]] = {}


def rule(plan_cls):
    def deco(meta_cls):
        _RULES[plan_cls] = meta_cls
        return meta_cls
    return deco


def wrap_plan(plan: L.LogicalPlan, conf: TpuConf,
              parent=None) -> PlanMeta:
    meta_cls = _RULES.get(type(plan))
    if meta_cls is None:
        meta_cls = _FallbackMeta
    m = meta_cls(plan, conf, parent)
    m.child_metas = [wrap_plan(c, conf, m) for c in plan.children]
    return m


def plan_query(plan: L.LogicalPlan, conf: TpuConf, mesh=None,
               mesh_auto: bool = False) -> TpuExec:
    """tag -> cost-optimize -> (explain) -> convert (ref
    applyOverrides:4813, getOptimizations:4827) -> distribute onto the mesh
    when one is configured (ref GpuShuffleExchangeExecBase: the planner —
    not the user — makes queries distributed)."""
    from .rewrites import prune_columns
    from .op_confs import install_from_conf
    from .cost import OPTIMIZER_ENABLED, plan_signature
    install_from_conf(conf)
    # signature of the plan AS THE USER BUILT IT: the execution sink
    # records measured walls under this same pre-rewrite signature
    # (api/dataframe._execute_wrapped), so lookup and record must agree
    wall_sig = plan_signature(plan)
    digest = None
    if conf.get(OPTIMIZER_ENABLED):
        # structural plan digest (the PR-5 event-log key): the cost
        # model's cache-aware floor asks the executable cache whether
        # this digest's kernels are already compiled — recorded by the
        # sink under the same pre-rewrite digest, so lookup and record
        # must agree. Computed only when the optimizer will consume it
        # (a full-tree hash per planning otherwise buys nothing); the
        # sink reuses it via physical.plan_digest.
        from ..metrics.events import plan_digest
        digest = plan_digest(plan)
    if conf.sql_enabled:
        # TPU-targeted rewrites (distinct-agg expansion, union-of-aggs
        # single-pass) BEFORE pruning: the union rewrite keys on shared
        # scan identity, which pruning's per-branch copies would break.
        # The host oracle path keeps native semantics so differential
        # tests check the rewrites themselves. The sort-free hash
        # distinct applies only off-mesh: the distributed fragment
        # compiler lowers the two-level Aggregate form, not the
        # stateful DistinctFlag operator.
        from .rewrites import HASH_DISTINCT_ENABLED, rewrite_plan
        plan0 = plan               # the user's shape, pre-rewrite
        plan = rewrite_plan(
            plan, hash_distinct=(mesh is None
                                 and conf.get(HASH_DISTINCT_ENABLED)))
    else:
        plan0 = plan
    rewritten = plan is not plan0
    plan = prune_columns(plan)
    meta = wrap_plan(plan, conf)
    meta.tag()
    from .cost import apply_cost_optimizer
    decision = None
    if conf.get(OPTIMIZER_ENABLED):
        decision = apply_cost_optimizer(meta, conf, wall_sig=wall_sig,
                                        plan_digest=digest)
        if rewritten and not _any_device_meta(meta):
            # whole-plan host reversion: the TPU-targeted rewrites
            # (distinct expansion/flag, union single-pass) only help
            # the DEVICE engine — their CPU twins are slower than the
            # native host shapes (e.g. a per-row flag pass vs pandas
            # nunique). Re-plan the user's ORIGINAL plan for the host
            # twins; the measured wall still records under wall_sig,
            # so arbitration stays consistent.
            meta = wrap_plan(prune_columns(plan0), conf)
            meta.tag()
            T.revert_to_host(
                meta, "cost-based: whole-plan host placement "
                      "(native shape, no device rewrites)",
                code=T.WHOLE_PLAN_HOST_REVERT)
            decision = ("host (whole-plan host placement: native "
                        "shape, no device rewrites)")
    # coded placement report (plan/tags.py): assembled AFTER tagging and
    # cost optimization so it records the final verdicts; the plan-time
    # INPUT row estimate (summed over scan leaves — the work scale, not
    # the often-tiny aggregate output) rides along for the qualify
    # tool's learned-cost join
    try:
        from .cost import estimate_rows

        def _leaf_rows(p):
            if not p.children:
                return estimate_rows(p)
            return sum(_leaf_rows(c) for c in p.children)

        est_rows = int(_leaf_rows(plan))
    except Exception:  # noqa: BLE001 - diagnostics never fail planning
        est_rows = None
    report = T.build_report(meta, decision=decision, est_rows=est_rows)
    explain = conf.explain
    if explain in ("NOT_ON_TPU", "ALL"):
        out = meta.explain(only_not_on_tpu=(explain == "NOT_ON_TPU"))
        if out:
            log.warning("\n%s", out)
    pexplain = str(conf.get(T.PLACEMENT_EXPLAIN)).upper()
    # NOT_ON_DEVICE is silent for all-device plans (render() always
    # emits at least the verdict line, so gate on recorded tags — the
    # legacy mode's "nothing on host, nothing to say" contract)
    if pexplain == "ALL" or (pexplain == "NOT_ON_DEVICE"
                             and report.counts()):
        log.warning("\n%s", report.render(
            only_not_on_device=(pexplain == "NOT_ON_DEVICE")))
    physical = meta.convert()
    if conf.sql_enabled:
        from ..parallel.planner import (FUSED_PIPELINE, distribution_gate,
                                        maybe_fuse_single_chip,
                                        try_distribute)
        distributed = None
        if mesh is not None and distribution_gate(physical, conf,
                                                  auto=mesh_auto):
            distributed = try_distribute(physical, conf, mesh)
        if distributed is not None:
            physical = distributed
        elif conf.get(FUSED_PIPELINE):
            # no mesh, auto-mesh below the row threshold, OR nothing in
            # the plan lowered onto the mesh: single-chip fused pipelines
            # still apply (losing them regressed latency-bound joins)
            physical = maybe_fuse_single_chip(physical, conf)
    # whole-stage fusion LAST, over whatever the mesh/fragment lowering
    # left as an operator pipeline: maximal device filter/project chains
    # become one compiled program each (exec/wholestage.py)
    from ..exec.wholestage import fuse_whole_stages
    physical = fuse_whole_stages(physical, conf)
    #: why the cost optimizer placed this plan where it did — EXPLAIN
    #: prints it, so "why is this stage on host" is answerable from the
    #: plan output alone (satellite of ISSUE 6)
    physical.placement_decision = decision
    #: the coded per-operator report (ISSUE 7): explain("placement"),
    #: the fallback metric family, and queryStart event records all
    #: read it off the physical plan
    physical.placement_report = report
    #: pre-rewrite structural digest (None when the optimizer is off):
    #: the sink reuses it to mark the digest warm after a device run
    #: (exec_cache.record_plan_compiled) instead of re-hashing the tree
    physical.plan_digest = digest
    return physical


#: logical nodes whose execs are engine-shared pass-throughs: their
#: placement says nothing about which engine runs the real compute
_NEUTRAL_PLANS = (L.LogicalScan, L.ParquetScan, L.Union, L.GlobalLimit,
                  L.BranchAlign)


def _any_device_meta(meta: PlanMeta) -> bool:
    """True when some non-neutral node still plans onto the device
    (scans/unions/limits are engine-shared — they don't count; must
    stay consistent with dataframe._on_device's placement check)."""
    if meta.can_run_on_tpu and not isinstance(meta.plan, _NEUTRAL_PLANS):
        return True
    return any(_any_device_meta(c) for c in meta.child_metas)


def explain_potential_tpu_plan(plan: L.LogicalPlan, conf: TpuConf) -> str:
    """Public ExplainPlan API analog (ref ExplainPlan.scala:28)."""
    from .op_confs import install_from_conf
    install_from_conf(conf)
    meta = wrap_plan(plan, conf)
    meta.tag()
    return meta.explain(only_not_on_tpu=False) or "<entire plan runs on TPU>"


def _list_key_reason(expr, schema):
    """Keys (join/group/partition/window) cannot be list-typed: the key
    hash/compare kernels are 1D. List-typed VALUES are fine in project/
    filter pipelines (columnar/nested.py); Spark allows array keys, so a
    list key converts the exec to its CPU twin."""
    from ..types import ArrayType
    if isinstance(expr.data_type(schema), ArrayType):
        return "list-typed keys compare on host"
    return None


class _FallbackMeta(PlanMeta):
    def tag_self(self):
        self.will_not_work_on_tpu(
            f"no TPU rule registered for {type(self.plan).__name__}",
            code=T.OP_UNSUPPORTED)

    def convert_to_cpu(self, children):
        raise NotImplementedError(
            f"no conversion for {type(self.plan).__name__}")


@rule(L.LogicalScan)
class ScanMeta(PlanMeta):
    def convert_to_tpu(self, children):
        return B.InMemoryScanExec(self.plan.tables, self.plan._schema,
                                  columns=self.plan.columns)

    convert_to_cpu = convert_to_tpu  # scan is shared (host decode either way)


@rule(L.ParquetScan)
class ParquetScanMeta(PlanMeta):
    def convert_to_tpu(self, children):
        from ..io.parquet import ParquetScanExec
        return ParquetScanExec(self.plan.paths, self.plan.schema(),
                               self.plan.columns, self.conf)

    convert_to_cpu = convert_to_tpu


@rule(L.OrcScan)
class OrcScanMeta(PlanMeta):
    def convert_to_tpu(self, children):
        from ..io.orc import OrcScanExec
        return OrcScanExec(self.plan.paths, self.plan.schema(),
                           self.plan.columns, self.conf)

    convert_to_cpu = convert_to_tpu


@rule(L.AvroScan)
class AvroScanMeta(PlanMeta):
    def convert_to_tpu(self, children):
        from ..io.avro import AvroScanExec
        return AvroScanExec(self.plan.paths, self.plan.schema(),
                            self.plan.columns, self.conf)

    convert_to_cpu = convert_to_tpu


@rule(L.Project)
class ProjectMeta(PlanMeta):
    def tag_self(self):
        schema = self.plan.children[0].schema()
        for e in self.plan.exprs:
            r = e.fully_device_supported(schema)
            if r:
                # per-expression fallback stays inside TpuProjectExec;
                # recorded for explain parity with the reference
                self.note_expr_fallback(f"<{e.name_hint}> runs on host: {r}",
                                        code=T.EXPR_UNSUPPORTED,
                                        expr=e.name_hint)

    def convert_to_tpu(self, children):
        return B.TpuProjectExec(self.plan.exprs, children[0])

    def convert_to_cpu(self, children):
        return B.CpuProjectExec(self.plan.exprs, children[0])


@rule(L.Filter)
class FilterMeta(PlanMeta):
    def tag_self(self):
        schema = self.plan.children[0].schema()
        r = self.plan.condition.fully_device_supported(schema)
        if r:
            # string predicates over dict-coded columns still run on the
            # device via dictionary evaluation (compiler.py
            # DictFilterEvaluator; ref stringFunctions.scala families)
            from ..exprs.compiler import build_dict_filter
            if build_dict_filter(self.plan.condition, schema) is not None:
                self.note_expr_fallback(
                    "string predicate evaluated over the dictionary",
                    code=T.EXPR_DICT_EVAL,
                    expr=self.plan.condition.name_hint)
                return
            self.will_not_work_on_tpu(f"filter condition: {r}",
                                      code=T.EXPR_UNSUPPORTED,
                                      expr=self.plan.condition.name_hint)

    def convert_to_tpu(self, children):
        self._push_down_predicate(children[0])
        ex = B.TpuFilterExec(self.plan.condition, children[0])
        from .cost import plan_signature
        ex.plan_sig = plan_signature(self.plan)   # measured-rows feedback
        return ex

    def convert_to_cpu(self, children):
        self._push_down_predicate(children[0])
        ex = B.CpuFilterExec(self.plan.condition, children[0])
        from .cost import plan_signature
        ex.plan_sig = plan_signature(self.plan)
        return ex

    def _push_down_predicate(self, child_exec):
        """Predicate pushdown into file scans for row-group / delta-file
        skipping (ref GpuParquetScan filterBlocks:670 + delta data
        skipping) and into cached scans for batch skipping via the
        embedded parquet statistics (ref ParquetCachedBatchSerializer).
        The filter itself still runs — pruning is conservative, so this
        is purely an IO reduction."""
        from ..exec.cached import ParquetCachedScanExec
        from ..io.file_scan import FileScanBase
        cond = self.plan.condition
        refs = set(cond.references())
        node = child_exec
        # look through projections that pass the referenced columns
        # through unchanged (the exec's own passthrough map, restricted
        # to un-renamed columns)
        while isinstance(node, B.TpuProjectExec):
            same_name = {n for i, n in node.passthrough.items()
                         if node.exprs[i].name_hint == n}
            if not refs <= same_name:
                return
            node = node.children[0]
        if (isinstance(node, (FileScanBase, ParquetCachedScanExec))
                and node.predicate is None):
            names = set(node.output_schema().names())
            if refs <= names:
                node.set_predicate(cond)


@rule(L.Aggregate)
class AggregateMeta(PlanMeta):
    def tag_self(self):
        from ..types import STRING
        schema = self.plan.children[0].schema()
        for g in self.plan.groupings:
            r = g.fully_device_supported(schema)
            lk = None if r else _list_key_reason(g, schema)
            # string group keys stay on the TPU path: the exec
            # dictionary-encodes them to device int32 codes (evaluated on
            # host, grouped on device, decoded at finalize)
            if (r or lk) and g.data_type(schema) != STRING:
                self.will_not_work_on_tpu(
                    f"grouping <{g.name_hint}>: {r or lk}",
                    code=(T.EXPR_UNSUPPORTED if r else T.LIST_KEY_HOST),
                    expr=g.name_hint)
        for a in self.plan.aggs:
            r = a.device_unsupported_reason(schema)
            if r:
                self.will_not_work_on_tpu(f"aggregate <{a.name_hint}>: {r}",
                                          code=T.EXPR_UNSUPPORTED,
                                          expr=a.name_hint)
            if not hasattr(a, "update"):
                self.will_not_work_on_tpu(
                    f"aggregate <{a.name_hint}> has no device implementation",
                    code=T.EXPR_UNSUPPORTED, expr=a.name_hint)
            if a.distinct:
                # reaches here only when rewrites.py could not expand it
                # (multiple distinct columns / non-decomposable mix)
                self.will_not_work_on_tpu(
                    f"aggregate <{a.name_hint}>: DISTINCT form not "
                    "expandable to the two-level device aggregation",
                    code=T.AGG_DISTINCT_HOST, expr=a.name_hint)

    def convert_to_tpu(self, children):
        hint = getattr(self.plan, "many_groups_hint", False)
        cards = getattr(self.plan, "int_key_cards", None)
        from ..exec.wholestage import AGG_FUSION_ENABLED
        if self.conf.get(AGG_FUSION_ENABLED):
            child, stages, eval_schema = self._fold_stages(children[0])
        else:
            # unfused reference path (byte-identical results, one
            # dispatch + one compaction per stage) — the differential
            # oracle for the fused partial-agg kernel
            child, stages, eval_schema = children[0], None, None
        if not self.plan.groupings:
            self._widen_scan_batches(child if stages else children[0])
        if stages:
            return A.TpuHashAggregateExec(self.plan.groupings,
                                          self.plan.aggs, child,
                                          pre_stages=stages,
                                          eval_schema=eval_schema,
                                          many_groups_hint=hint,
                                          int_key_cards=cards)
        return A.TpuHashAggregateExec(self.plan.groupings, self.plan.aggs,
                                      children[0], many_groups_hint=hint,
                                      int_key_cards=cards)

    def _widen_scan_batches(self, node):
        """A GLOBAL aggregation's steady-state cost is per-dispatch
        latency (the update kernel is elementwise + reductions): feed it
        the widest batches the memory runtime allows. A single input
        batch upgrades the whole query to the fused one-dispatch
        one-fetch path (_fast_single_batch). Group-keyed aggregations
        keep the default width — wider batches would inflate their
        per-batch group buckets."""
        from ..config import AGG_WIDE_BATCH_ROWS
        from ..exec.distinct_flag import HashDistinctFlagExec
        wide = int(self.conf.get(AGG_WIDE_BATCH_ROWS))
        while isinstance(node, (B.TpuFilterExec, B.TpuProjectExec,
                                HashDistinctFlagExec)):
            node = node.children[0]
        if isinstance(node, B.InMemoryScanExec):
            if wide <= 0:
                # auto ceiling (ADVICE r5): "whole partition" is only
                # safe while the batch plausibly fits device memory —
                # gate the widening on estimated bytes against half the
                # HBM budget instead of widening unconditionally and
                # leaning on OOM retry/split churn to survive it
                total = max((t.num_rows for t in node.tables), default=0)
                wide = min(total, self._wide_batch_row_cap(node))
            node.batch_rows = max(node.batch_rows, wide, 1)

    def _wide_batch_row_cap(self, scan) -> int:
        """Estimated-byte gate for scan widening: rows such that one
        batch of this scan's schema stays within HALF the device budget.
        Per-row bytes are the LARGER of the schema estimate (fixed-width
        lanes + validity) and the scan's actual Arrow bytes per row, so
        variable-width columns (strings: dict codes or byte rectangles
        on device) are costed from their real data, not a flat guess."""
        import numpy as np
        from ..mem.manager import MemoryManager
        row_bytes = 0
        for f in scan.output_schema():
            np_dt = getattr(f.dtype, "np_dtype", None)
            row_bytes += (np.dtype(np_dt).itemsize if np_dt is not None
                          else 16) + 1     # +1: validity lane
        total_rows = sum(t.num_rows for t in scan.tables)
        if total_rows:
            cols = scan.columns
            data_bytes = sum(
                (t.select(cols) if cols is not None else t).nbytes
                for t in scan.tables)
            row_bytes = max(row_bytes, -(-data_bytes // total_rows))
        budget = MemoryManager.get(self.conf).budget
        return max(1, (budget // 2) // max(1, row_bytes))

    def _fold_stages(self, child):
        """Fold a chain of device-only Filter/Project execs below the
        aggregate INTO its update kernel: scan→filter→project→groupby
        becomes one XLA computation — no per-stage compaction kernels or
        host syncs (the device round trip is the unit of cost on TPU)."""
        from ..exprs.base import ColumnRef
        from ..types import STRING
        eval_schema = child.output_schema()
        stages, node = [], child
        while True:
            if (isinstance(node, B.TpuFilterExec)
                    and node.condition.fully_device_supported(
                        node.children[0].output_schema()) is None):
                stages.append(("filter", node.condition))
                node = node.children[0]
            elif isinstance(node, B.TpuProjectExec) and not node.host_idx:
                stages.append(("project", node.exprs, node.output_schema()))
                node = node.children[0]
            else:
                break
        if not stages:
            return child, None, None
        # string group keys are dictionary-encoded OUTSIDE the kernel from
        # the folded input batch — they must be plain refs (possibly
        # aliased) present there. Int-carded keys (int_key_cards) need
        # the same: their direct-addressing operands read the key COLUMN
        # from the batch, so folding away the projection that produces it
        # would silently demote the plan to the sort path.
        from ..exprs.base import Alias
        in_names = set(node.output_schema().names())
        cards = getattr(self.plan, "int_key_cards",
                        [None] * len(self.plan.groupings))
        for gi, g in enumerate(self.plan.groupings):
            inner = g.children[0] if isinstance(g, Alias) else g
            needs_column = (g.data_type(eval_schema) == STRING
                            or (gi < len(cards) and cards[gi]))
            if needs_column and not (isinstance(inner, ColumnRef)
                                     and inner.name in in_names):
                return child, None, None
        stages.reverse()
        return node, stages, eval_schema

    def convert_to_cpu(self, children):
        return A.CpuAggregateExec(self.plan.groupings, self.plan.aggs,
                                  children[0])


@rule(L.Sort)
class SortMeta(PlanMeta):
    def tag_self(self):
        schema = self.plan.children[0].schema()
        for o in self.plan.orders:
            r = o.expr.fully_device_supported(schema)
            if r:
                self.will_not_work_on_tpu(
                    f"sort key <{o.expr.name_hint}>: {r}",
                    code=T.EXPR_UNSUPPORTED, expr=o.expr.name_hint)
        for f in schema.fields:
            if not f.dtype.device_backed:
                self.will_not_work_on_tpu(
                    f"column {f.name}: {f.dtype.name} payload is host-only",
                    code=T.DTYPE_HOST_ONLY)

    def convert_to_tpu(self, children):
        return S.TpuSortExec(self.plan.orders, children[0],
                             self.plan.global_sort)

    def convert_to_cpu(self, children):
        return S.CpuSortExec(self.plan.orders, children[0],
                             self.plan.global_sort)


@rule(L.GlobalLimit)
class LimitMeta(PlanMeta):
    def convert_to_tpu(self, children):
        return B.LimitExec(self.plan.n, children[0])

    convert_to_cpu = convert_to_tpu


@rule(L.LocalLimit)
class LocalLimitMeta(LimitMeta):
    pass


@rule(L.Union)
class UnionMeta(PlanMeta):
    def convert_to_tpu(self, children):
        return B.UnionExec(children)

    convert_to_cpu = convert_to_tpu


@rule(L.RangeRel)
class RangeMeta(PlanMeta):
    def convert_to_tpu(self, children):
        p = self.plan
        return B.TpuRangeExec(p.start, p.end, p.step, p.name)

    convert_to_cpu = convert_to_tpu


@rule(L.Sample)
class SampleMeta(PlanMeta):
    def convert_to_tpu(self, children):
        return B.TpuSampleExec(self.plan.fraction, self.plan.seed, children[0])

    convert_to_cpu = convert_to_tpu


@rule(L.Expand)
class ExpandMeta(PlanMeta):
    def tag_self(self):
        schema = self.plan.children[0].schema()
        for p in self.plan.projections:
            for e in p:
                r = e.fully_device_supported(schema)
                if r:
                    self.will_not_work_on_tpu(f"expand <{e.name_hint}>: {r}",
                                              code=T.EXPR_UNSUPPORTED,
                                              expr=e.name_hint)

    def convert_to_tpu(self, children):
        return B.TpuExpandExec(self.plan.projections, self.plan.names,
                               children[0])

    def convert_to_cpu(self, children):
        raise NotImplementedError("CPU expand fallback not implemented")


@rule(L.DistinctFlag)
class DistinctFlagMeta(PlanMeta):
    def tag_self(self):
        schema = self.plan.children[0].schema()
        for e in self.plan.key_exprs + [self.plan.value_expr]:
            r = e.fully_device_supported(schema)
            if r:
                self.will_not_work_on_tpu(
                    f"distinct-flag <{e.name_hint}>: {r}",
                    code=T.EXPR_UNSUPPORTED, expr=e.name_hint)

    def convert_to_tpu(self, children):
        from ..exec.distinct_flag import HashDistinctFlagExec
        p = self.plan
        return HashDistinctFlagExec(p.key_exprs, p.value_expr,
                                    p.flag_name, children[0])

    def convert_to_cpu(self, children):
        from ..exec.distinct_flag import CpuDistinctFlagExec
        p = self.plan
        return CpuDistinctFlagExec(p.key_exprs, p.value_expr,
                                   p.flag_name, children[0])


@rule(L.Generate)
class GenerateMeta(PlanMeta):
    def tag_self(self):
        from ..exprs.base import Unsupported
        schema = self.plan.children[0].schema()
        try:
            self.plan.generator.generator_output(schema)
        except Unsupported as e:
            self.will_not_work_on_tpu(str(e), code=T.EXPR_UNSUPPORTED)

    def convert_to_tpu(self, children):
        from ..exec.generate import TpuGenerateExec
        p = self.plan
        return TpuGenerateExec(p.generator, p.required_cols, children[0],
                               p.output_names)

    convert_to_cpu = convert_to_tpu


@rule(L.Join)
class JoinMeta(PlanMeta):
    def tag_self(self):
        ls = self.plan.children[0].schema()
        rs = self.plan.children[1].schema()
        for side, keys, schema in (("left", self.plan.left_keys, ls),
                                   ("right", self.plan.right_keys, rs)):
            for k in keys:
                r = k.fully_device_supported(schema)
                lk = None if r else _list_key_reason(k, schema)
                if r or lk:
                    self.will_not_work_on_tpu(
                        f"{side} key <{k.name_hint}>: {r or lk}",
                        code=(T.EXPR_UNSUPPORTED if r else T.LIST_KEY_HOST),
                        expr=k.name_hint)
        if self.plan.condition is not None:
            joined = Schema(list(ls.fields) + list(rs.fields))
            r = self.plan.condition.fully_device_supported(joined)
            if r:
                self.will_not_work_on_tpu(
                    f"join condition <{self.plan.condition.name_hint}>: {r}",
                    code=T.EXPR_UNSUPPORTED,
                    expr=self.plan.condition.name_hint)

    def _auto_broadcast(self):
        """Pick a broadcast side from plan-time size estimates when the
        user gave no hint (ref Spark autoBroadcastJoinThreshold + the
        reference's AQE join-strategy switching,
        GpuOverrides.scala:4681)."""
        from ..config import AUTO_BROADCAST_THRESHOLD
        from .cost import plan_signature, runtime_size
        from .rewrites import estimated_size_bytes
        p = self.plan
        thr = int(self.conf.get(AUTO_BROADCAST_THRESHOLD))
        if thr <= 0:
            return None

        def side_size(child):
            # MEASURED size from a previous materialization of this
            # subtree beats any estimate (the AQE stage-stats analog,
            # ref GpuCustomShuffleReaderExec)
            meas = runtime_size(plan_signature(child))
            est = estimated_size_bytes(child)
            return (meas if meas is not None else est), meas, est
        r_ok = p.join_type in ("inner", "left", "leftsemi", "leftanti")
        l_ok = p.join_type in ("inner", "right")
        rs, rm, re_ = side_size(p.children[1]) if r_ok else (None,) * 3
        ls, lm, le = side_size(p.children[0]) if l_ok else (None,) * 3
        cand, est_cand = [], []
        for sz, est, side in ((rs, re_, "right"), (ls, le, "left")):
            if sz is not None and sz <= thr:
                cand.append((sz, side))
            if est is not None and est <= thr:
                est_cand.append((est, side))
        choice = min(cand)[1] if cand else None
        est_choice = min(est_cand)[1] if est_cand else None
        if choice != est_choice:
            self._aqe_broadcast_decision(choice, est_choice, thr,
                                         {"right": rm, "left": lm})
        return choice

    def _aqe_broadcast_decision(self, choice, est_choice, thr, measured):
        """AQE join-strategy switch surfaced as a decision: a MEASURED
        side size flipped the broadcast pick away from what the
        plan-time estimate alone would have chosen."""
        from .. import aqe as aqe_mod
        log = aqe_mod.LOG
        if log is None:
            return
        try:  # tpulint: never-raise
            from ..aqe import AQE_BROADCAST_DEMOTE_ENABLED
            if choice is None:
                # estimate said broadcast, measurement came in over
                if not self.conf.get(AQE_BROADCAST_DEMOTE_ENABLED):
                    return
                log.record(aqe_mod.make_decision(
                    aqe_mod.BROADCAST_DEMOTE,
                    detail=f"{est_choice} side measured "
                           f"{measured.get(est_choice)}B > threshold "
                           f"{thr}B -> shuffled join", parts=1))
            else:
                # estimate said shuffle (or the other side), the
                # measured side came in under the threshold
                log.record(aqe_mod.make_decision(
                    aqe_mod.BROADCAST_PROMOTE,
                    detail=f"{choice} side measured "
                           f"{measured.get(choice)}B <= threshold "
                           f"{thr}B -> broadcast join", parts=1))
        except Exception:
            pass

    def convert_to_tpu(self, children):
        from ..exec.joins import (TpuBroadcastHashJoinExec, TpuHashJoinExec,
                                  TpuNestedLoopJoinExec)
        from ..shuffle.broadcast import BroadcastExchangeExec
        p = self.plan
        if p.join_type == "cross" or not p.left_keys:
            # no equi keys: nested loop (ref GpuBroadcastNestedLoopJoinExec)
            return TpuNestedLoopJoinExec(children[0], children[1],
                                         p.join_type, p.condition)
        if p.broadcast is None:
            p = copy.copy(p)
            p.broadcast = self._auto_broadcast()
        from .cost import plan_signature
        sigs = (plan_signature(p.children[0]),
                plan_signature(p.children[1]))
        if p.broadcast == "right":
            j = TpuBroadcastHashJoinExec(
                children[0], BroadcastExchangeExec(children[1]), p.join_type,
                p.left_keys, p.right_keys, p.condition, build_side="right")
        elif p.broadcast == "left":
            j = TpuBroadcastHashJoinExec(
                BroadcastExchangeExec(children[0]), children[1], p.join_type,
                p.left_keys, p.right_keys, p.condition, build_side="left")
        else:
            j = TpuHashJoinExec(children[0], children[1], p.join_type,
                                p.left_keys, p.right_keys, p.condition)
        # runtime-stats hookup: the exec records each side's MEASURED
        # bytes under these signatures when it materializes them, and the
        # join's OUTPUT rows under its own (the cost model's join-output
        # estimates are the crudest — measured feedback re-plans e.g. a
        # dimension-filtered join at its real, tiny output size)
        j.side_sigs = sigs
        j.plan_sig = plan_signature(self.plan)
        return j

    def convert_to_cpu(self, children):
        from ..exec.joins import CpuJoinExec
        from .cost import plan_signature
        p = self.plan
        ex = CpuJoinExec(children[0], children[1], p.join_type,
                         p.left_keys, p.right_keys, p.condition)
        ex.plan_sig = plan_signature(self.plan)
        return ex


@rule(L.Repartition)
class RepartitionMeta(PlanMeta):
    def tag_self(self):
        schema = self.plan.children[0].schema()
        for k in self.plan.keys:
            r = k.fully_device_supported(schema)
            lk = None if r else _list_key_reason(k, schema)
            if r or lk:
                self.will_not_work_on_tpu(
                    f"partition key <{k.name_hint}>: {r or lk}",
                    code=(T.EXPR_UNSUPPORTED if r else T.LIST_KEY_HOST),
                    expr=k.name_hint)
            if self.plan.mode == "hash":
                # device murmur3 covers fewer types than device storage
                # (e.g. DOUBLE hashes on host only — hash_fns device notes)
                from ..exprs.hash_fns import device_hashable
                hr = device_hashable.reason_not_supported(k.data_type(schema))
                if hr:
                    self.will_not_work_on_tpu(
                        f"hash partition key <{k.name_hint}>: {hr}",
                        code=T.HASH_KEY_HOST, expr=k.name_hint)

    def _num_parts(self):
        from ..config import DEFAULT_SHUFFLE_PARTITIONS
        n = self.plan.num_partitions
        return n if n is not None \
            else int(self.conf.get(DEFAULT_SHUFFLE_PARTITIONS))

    def convert_to_tpu(self, children):
        from ..shuffle.exchange import ShuffleExchangeExec
        p = self.plan
        return ShuffleExchangeExec(
            children[0], self._num_parts(), p.keys, p.mode, self.conf,
            adaptive_ok=p.adaptive_ok)

    def convert_to_cpu(self, children):
        from ..shuffle.exchange import CpuShuffleExchangeExec
        p = self.plan
        return CpuShuffleExchangeExec(children[0], self._num_parts(),
                                      p.keys, p.mode)


@rule(L.BranchAlign)
class BranchAlignMeta(PlanMeta):
    def convert_to_tpu(self, children):
        p = self.plan
        return B.BranchAlignExec(p.n, p.fill_zero, children[0])

    convert_to_cpu = convert_to_tpu


@rule(L.WriteFile)
class WriteMeta(PlanMeta):
    def convert_to_tpu(self, children):
        from ..io.writers import FileWriteExec
        p = self.plan
        return FileWriteExec(children[0], p.path, p.file_format, p.mode,
                             p.partition_by, getattr(p, "options", None))

    convert_to_cpu = convert_to_tpu


@rule(L.Window)
class WindowMeta(PlanMeta):
    def tag_self(self):
        schema = self.plan.children[0].schema()
        from ..types import ArrayType
        for f in schema.fields:
            if isinstance(f.dtype, ArrayType):
                # list payloads don't ride the window kernels (they own
                # their 1D column layout); CPU window handles them
                self.will_not_work_on_tpu(
                    f"column {f.name}: list payload is host-only in windows",
                    code=T.DTYPE_HOST_ONLY)
        for e, spec, name in self.plan.window_exprs:
            for pk in spec.partition_by:
                r = pk.fully_device_supported(schema)
                lk = None if r else _list_key_reason(pk, schema)
                if r or lk:
                    self.will_not_work_on_tpu(
                        f"window partition key: {r or lk}",
                        code=(T.EXPR_UNSUPPORTED if r else T.LIST_KEY_HOST),
                        expr=pk.name_hint)

    def convert_to_tpu(self, children):
        from ..exec.window import TpuWindowExec
        # terminal (root) windows feed a host collect: the cost model may
        # run their kernel on host XLA (see WINDOW_HOST_SINK_ROWS)
        return TpuWindowExec(self.plan.window_exprs, children[0],
                             host_sink=self.parent is None)

    def convert_to_cpu(self, children):
        from ..exec.window import CpuWindowExec
        return CpuWindowExec(self.plan.window_exprs, children[0])


@rule(L.MapInPandas)
class MapInPandasMeta(PlanMeta):
    def convert_to_tpu(self, children):
        from ..exec.python_execs import MapInPandasExec
        return MapInPandasExec(children[0], self.plan.fn, self.plan.schema())

    convert_to_cpu = convert_to_tpu


@rule(L.FlatMapGroupsInPandas)
class FlatMapGroupsInPandasMeta(PlanMeta):
    def convert_to_tpu(self, children):
        from ..exec.python_execs import FlatMapGroupsInPandasExec
        return FlatMapGroupsInPandasExec(children[0], self.plan.keys,
                                         self.plan.fn, self.plan.schema())

    convert_to_cpu = convert_to_tpu
