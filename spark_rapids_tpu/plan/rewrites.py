"""Logical plan rewrites applied before TPU planning.

Distinct aggregates (ref Spark's RewriteDistinctAggregates, which the
reference accelerates post-rewrite: GpuHashAggregateExec only ever sees
the expanded two-level form): an Aggregate containing `agg(DISTINCT e)` is
rewritten into

    Project(restore names/order)
      Aggregate(G, merge partials + distinct aggs over e)   -- outer
        Aggregate(G + [e], partials of non-distinct aggs)   -- inner dedup

which runs entirely on the device groupby pipeline. The rewrite applies
when every distinct agg shares ONE child expression and all aggs are
decomposable (Sum/Count/CountStar/Min/Max/Average); otherwise the plan is
left alone and the host aggregate computes distinct natively (the planner
tags it off-device).

Only applied when planning for the TPU: the host oracle path keeps its
native pandas distinct so differential tests check the rewrite itself.
"""
from __future__ import annotations

import copy
from typing import Optional

from ..exprs import aggregates as AG
from ..exprs.arithmetic import Divide
from ..exprs.base import Alias, ColumnRef, Literal
from ..exprs.cast import Cast
from ..exprs.conditional import Coalesce
from ..types import FLOAT64, INT64
from . import logical as L

__all__ = ["rewrite_plan", "prune_columns", "HASH_DISTINCT_ENABLED"]

from ..config import register

HASH_DISTINCT_ENABLED = register(
    "spark.rapids.tpu.sql.hashDistinct.enabled", True,
    "Rewrite count/sum/avg(DISTINCT e) over fixed-width types into a "
    "single-level aggregate guarded by a hash-table first-occurrence "
    "flag (exec/distinct_flag.py) instead of the two-level sort "
    "expansion — no lax.sort in any resulting kernel, so modules "
    "compile in seconds and the whole pipeline dispatches without "
    "per-batch syncs (ref: cudf hash-based distinct aggregation). "
    "Applies only when the plan is not lowered onto a device mesh.")


# ---------------------------------------------------------------------------
# column pruning (projection pushdown into scans)
# ---------------------------------------------------------------------------
# The reference gets pruning for free from Catalyst; standalone we push the
# required-column set top-down and trim LogicalScan/file scans. On a
# tunneled TPU this directly cuts H2D bytes — often the dominant cost.

def _expr_refs(e, out: set):
    if e is None:
        return
    if hasattr(e, "references") and not getattr(e, "children", None):
        for n in e.references():
            out.add(n)
        return
    if isinstance(e, ColumnRef):
        out.add(e.name)
        return
    for c in getattr(e, "children", ()):  # Expression tree
        _expr_refs(c, out)


def _agg_refs(a, out: set):
    # input_exprs() covers multi-input aggregates (min_by's ordering)
    for e in a.input_exprs():
        _expr_refs(e, out)


def prune_columns(plan: L.LogicalPlan,
                  required: Optional[set] = None) -> L.LogicalPlan:
    """required = names needed from this node's output; None = all."""
    def rebuilt(node, new_children):
        if all(n is o for n, o in zip(new_children, node.children)):
            return node
        node = copy.copy(node)
        node.children = new_children
        return node

    if isinstance(plan, L.LogicalScan):
        names = plan.schema().names()
        if required is None or set(names) <= required:
            return plan
        keep = [n for n in names if n in required]
        if not keep:        # degenerate count(*)-style: keep one column
            keep = names[:1]
        return L.LogicalScan(plan.tables, plan._schema, columns=keep)
    if isinstance(plan, L.ParquetScan):  # covers Orc/Avro subclasses
        names = plan.schema().names()
        if required is not None and not set(names) <= required:
            keep = [n for n in names if n in required] or names[:1]
            plan = copy.copy(plan)
            plan.columns = keep
        return plan
    if isinstance(plan, L.Project):
        exprs = plan.exprs
        if required is not None:
            kept = [e for e in exprs if e.name_hint in required]
            exprs = kept if kept else exprs[:1]
        child_req: set = set()
        for e in exprs:
            _expr_refs(e, child_req)
        child = prune_columns(plan.children[0], child_req)
        if exprs is not plan.exprs or child is not plan.children[0]:
            return L.Project(exprs, child)
        return plan
    if isinstance(plan, L.Filter):
        child_req = None if required is None else set(required)
        if child_req is not None:
            _expr_refs(plan.condition, child_req)
        return rebuilt(plan, [prune_columns(plan.children[0], child_req)])
    if isinstance(plan, L.Aggregate):
        child_req: set = set()
        for g in plan.groupings:
            _expr_refs(g, child_req)
        for a in plan.aggs:
            _agg_refs(a, child_req)
        return rebuilt(plan, [prune_columns(plan.children[0], child_req)])
    if isinstance(plan, L.Sort):
        child_req = None if required is None else set(required)
        if child_req is not None:
            for o in plan.orders:
                _expr_refs(o.expr, child_req)
        return rebuilt(plan, [prune_columns(plan.children[0], child_req)])
    if isinstance(plan, L.DistinctFlag):
        child_req = None if required is None \
            else set(required) - {plan.flag_name}
        if child_req is not None:
            for e in plan.key_exprs:
                _expr_refs(e, child_req)
            _expr_refs(plan.value_expr, child_req)
        return rebuilt(plan, [prune_columns(plan.children[0], child_req)])
    if isinstance(plan, (L.GlobalLimit, L.LocalLimit, L.Sample)):
        return rebuilt(plan, [prune_columns(plan.children[0], required)])
    if isinstance(plan, L.Repartition):
        child_req = None if required is None else set(required)
        if child_req is not None:
            for k in plan.keys:
                _expr_refs(k, child_req)
        return rebuilt(plan, [prune_columns(plan.children[0], child_req)])
    from ..exec.cached import CachedRelation
    if isinstance(plan, CachedRelation):
        names = plan.schema().names()
        if required is None or set(names) <= required:
            return plan
        keep = [n for n in names if n in required] or names[:1]
        return CachedRelation(plan.blobs, plan._schema, columns=keep)
    if isinstance(plan, L.Union):
        # children share column names positionally only when schemas align;
        # prune identically by name
        return rebuilt(plan, [prune_columns(c, required)
                              for c in plan.children])
    if isinstance(plan, L.Join):
        lnames = set(plan.children[0].schema().names())
        rnames = set(plan.children[1].schema().names())
        if required is None:
            lreq, rreq = None, None
        else:
            lreq = {n for n in required if n in lnames}
            rreq = {n for n in required if n in rnames}
            cond_refs: set = set()
            for k in plan.left_keys:
                _expr_refs(k, cond_refs)
            for k in plan.right_keys:
                _expr_refs(k, cond_refs)
            _expr_refs(plan.condition, cond_refs)
            lreq |= cond_refs & lnames
            rreq |= cond_refs & rnames
        return rebuilt(plan, [prune_columns(plan.children[0], lreq),
                              prune_columns(plan.children[1], rreq)])
    # Window/Generate/Expand/WriteFile/unknown: conservative — children
    # keep everything
    return rebuilt(plan, [prune_columns(c, None) for c in plan.children])


def estimated_size_bytes(plan: L.LogicalPlan) -> Optional[int]:
    """Plan-time size estimate (ref Spark SizeInBytesOnlyStatsPlan /
    the reference's AQE stage statistics): known for in-memory and file
    scans, propagated through size-preserving unary nodes, None where
    unknowable. Filters keep the child estimate (conservative — Spark's
    default without column stats)."""
    own = getattr(plan, "estimated_size_bytes", None)
    if own is not None:                # LogicalScan, CachedRelation, ...
        return own()
    if isinstance(plan, L.ParquetScan):
        import os
        try:
            return sum(os.path.getsize(p) for p in plan.paths)
        except OSError:
            return None
    if isinstance(plan, (L.Filter, L.Sort, L.Repartition, L.Sample,
                         L.LocalLimit, L.GlobalLimit, L.Project)):
        return estimated_size_bytes(plan.children[0])
    return None


def rewrite_plan(plan: L.LogicalPlan,
                 hash_distinct: bool = False) -> L.LogicalPlan:
    """``hash_distinct``: prefer the sort-free hash-table distinct flag
    over the two-level sort expansion. The caller enables it only when
    the plan will NOT lower onto a device mesh (the distributed fragment
    compiler understands the two-level Aggregate form, not the stateful
    DistinctFlag operator)."""
    if isinstance(plan, L.Union):
        new = _rewrite_union_agg(plan)
        if new is not None:
            # the single-pass form contains a (possibly distinct) grouped
            # aggregate that still needs the standard rewrites
            return rewrite_plan(new, hash_distinct)
    new_children = [rewrite_plan(c, hash_distinct)
                    for c in plan.children]
    if any(n is not o for n, o in zip(new_children, plan.children)):
        plan = copy.copy(plan)
        plan.children = new_children
    if isinstance(plan, L.Aggregate) and any(
            getattr(a, "distinct", False) for a in plan.aggs):
        new = _rewrite_distinct_hash(plan) if hash_distinct else None
        if new is None:
            new = _rewrite_distinct(plan)
        if new is not None:
            plan = new
    return plan


_DECOMPOSABLE = (AG.Sum, AG.Count, AG.CountStar, AG.Min, AG.Max, AG.Average)
_DISTINCT_OK = (AG.Count, AG.Sum, AG.Average)


# ---------------------------------------------------------------------------
# single-pass rewrite for unions of global aggregates over one shared scan
# (the TPC-DS q28 shape: k disjoint-filter branches each computing
# avg/count/count-distinct). Reference analog: RewriteDistinctAggregates'
# Expand-based multi-distinct plan (GpuExpandExec + GpuAggregateExec merge
# machinery, GpuAggregateExec.scala:718). k independent scans+sorts become
# ONE grouped aggregation keyed by a branch id:
#
#   Project(outputs, bid dropped)
#     Sort(bid)                               -- union branch order
#       Join(left: Range(0..k), agg, on bid)  -- rows for EMPTY branches
#         Aggregate([bid], shared aggs)
#           Filter(bid IS NOT NULL)
#           <tag>: Project(+CASE bid) when branch filters are provably
#                  disjoint (1x rows), else Expand (one copy per matching
#                  branch — correct under overlap)
#             <shared child>
# ---------------------------------------------------------------------------

def _flatten_union(plan, out):
    for c in plan.children:
        if isinstance(c, L.Union):
            _flatten_union(c, out)
        else:
            out.append(c)


def _conjuncts(e, out):
    from ..exprs.logical import And
    if isinstance(e, And):
        for c in e.children:
            _conjuncts(c, out)
    else:
        out.append(e)


def _branch_interval(cond):
    """(col, lo, hi) when the condition's top-level conjuncts pin one
    column into a closed interval; None otherwise."""
    from ..exprs.comparison import (GreaterThan, GreaterThanOrEqual,
                                    LessThan, LessThanOrEqual)
    cs: list = []
    _conjuncts(cond, cs)
    lo = hi = col = None
    for c in cs:
        l, r = getattr(c, "children", (None, None))[:2] \
            if len(getattr(c, "children", ())) == 2 else (None, None)
        if not (isinstance(l, ColumnRef) and isinstance(r, Literal)):
            continue
        if isinstance(c, GreaterThanOrEqual):
            b = r.value
        elif isinstance(c, GreaterThan):
            b = r.value  # open bound: treat as lo (conservative for ints)
        elif isinstance(c, (LessThanOrEqual, LessThan)):
            if col is None or col == l.name:
                col = l.name
                hi = r.value if hi is None else min(hi, r.value)
            continue
        else:
            continue
        if col is None or col == l.name:
            col = l.name
            lo = b if lo is None else max(lo, b)
    if col is None or lo is None or hi is None:
        return None
    return (col, lo, hi)


def _branches_disjoint(conds) -> bool:
    ivs = [_branch_interval(c) for c in conds]
    if any(iv is None for iv in ivs):
        return False
    col = ivs[0][0]
    if any(iv[0] != col for iv in ivs):
        return False
    spans = sorted((iv[1], iv[2]) for iv in ivs)
    return all(spans[i][1] < spans[i + 1][0] for i in range(len(spans) - 1))


def _rewrite_union_agg(union: L.Union) -> Optional[L.LogicalPlan]:
    branches: list = []
    _flatten_union(union, branches)
    if len(branches) < 2:
        return None
    conds = []
    shared = None
    for b in branches:
        if not (isinstance(b, L.Aggregate) and not b.groupings
                and len(b.children) == 1
                and isinstance(b.children[0], L.Filter)):
            return None
        f = b.children[0]
        if shared is None:
            shared = f.children[0]
        elif f.children[0] is not shared:
            return None          # branches must scan the SAME relation
        conds.append(f.condition)
    # agg lists must be structurally identical across branches
    a0 = branches[0].aggs
    for b in branches[1:]:
        if len(b.aggs) != len(a0):
            return None
        for x, y in zip(a0, b.aggs):
            if type(x) is not type(y) or x.distinct != y.distinct \
                    or x.name_hint != y.name_hint:
                return None
            cx = getattr(x, "child", None)
            cy = getattr(y, "child", None)
            if (cx is None) != (cy is None):
                return None
            if cx is not None and cx.key() != cy.key():
                return None
    for a in a0:
        if a.distinct and type(a) not in _DISTINCT_OK:
            return None
        if not a.distinct and type(a) not in _DECOMPOSABLE:
            return None
    # a single distinct child expression at most (matches _rewrite_distinct)
    if len({a.child.key() for a in a0 if a.distinct}) > 1:
        return None

    from ..exprs.comparison import IsNotNull
    from ..exprs.conditional import CaseWhen
    k = len(branches)
    bid = "__ua_bid"
    needed: set = set()
    for a in a0:
        _agg_refs(a, needed)
    cs = shared.schema()
    keep = [n for n in cs.names() if n in needed] or cs.names()[:1]
    refs = [ColumnRef(n) for n in keep]
    if _branches_disjoint(conds):
        tag = CaseWhen([(c, Literal(i, INT64)) for i, c in enumerate(conds)])
        tagged = L.Project(refs + [Alias(tag, bid)], shared)
    else:
        projections = [refs + [Alias(CaseWhen([(c, Literal(i, INT64))]),
                                     bid)]
                       for i, c in enumerate(conds)]
        tagged = L.Expand(projections, keep + [bid], shared)
    filtered = L.Filter(IsNotNull(ColumnRef(bid)), tagged)
    # the branch id is OUR construction: literals 0..k-1 (or null) — the
    # exec may group it by direct addressing, no sort
    agg = L.Aggregate([ColumnRef(bid)],
                      [copy.copy(a) for a in a0], filtered,
                      int_key_cards=[k])
    # branch-ordered assembly with empty-branch defaults is a tiny host
    # op (<= k rows) — cheaper than a join+sort tail, which would cost
    # several device dispatches on a latency-bound backend
    fill_zero = [isinstance(a, (AG.Count, AG.CountStar)) for a in a0]
    return L.BranchAlign(k, fill_zero, agg)


#: fixed-width device-backed types whose bit patterns the hash-distinct
#: table stores exactly (strings/decimals/arrays stay on the sort path)
_HASHABLE_TYPE_NAMES = frozenset(
    ["boolean", "tinyint", "smallint", "int", "bigint", "float",
     "double", "date", "timestamp"])


def _rewrite_distinct_hash(agg: L.Aggregate) -> Optional[L.LogicalPlan]:
    """Sort-free distinct: ``count(DISTINCT e) GROUP BY g`` becomes
    ``count(CASE WHEN __hd THEN e END) GROUP BY g`` over a DistinctFlag
    operator marking first (g, e) occurrences via a persistent device
    hash table (exec/distinct_flag.py). One level — non-distinct aggs
    pass through untouched — and no lax.sort in any resulting module
    (a sort's compile time multiplies with everything fused around it,
    docs/performance.md r4). Applies to at most one grouping key and one
    distinct child, both fixed-width numeric."""
    cs = agg.children[0].schema()
    d_keys = {a.child.key() for a in agg.aggs if a.distinct}
    if len(d_keys) != 1 or len(agg.groupings) > 1:
        return None
    for a in agg.aggs:
        if a.distinct and type(a) not in _DISTINCT_OK:
            return None
    d_expr = next(a.child for a in agg.aggs if a.distinct)
    try:
        if d_expr.data_type(cs).name not in _HASHABLE_TYPE_NAMES:
            return None
        for g in agg.groupings:
            if g.data_type(cs).name not in _HASHABLE_TYPE_NAMES:
                return None
    except Exception:
        return None
    from ..exprs.conditional import CaseWhen
    flag = "__hd_flag"
    new_aggs = []
    for a in agg.aggs:
        if not a.distinct:
            new_aggs.append(a)
            continue
        guarded = CaseWhen([(ColumnRef(flag), a.child)])
        new_aggs.append(type(a)(guarded).with_name(a.name_hint))
    flagged = L.DistinctFlag(list(agg.groupings), d_expr, flag,
                             agg.children[0])
    return L.Aggregate(agg.groupings, new_aggs, flagged,
                       many_groups_hint=agg.many_groups_hint,
                       int_key_cards=agg.int_key_cards)


def _rewrite_distinct(agg: L.Aggregate) -> Optional[L.LogicalPlan]:
    cs = agg.children[0].schema()
    d_keys = {a.child.key() for a in agg.aggs if a.distinct}
    if len(d_keys) != 1:
        return None          # multiple distinct columns: host handles it
    for a in agg.aggs:
        if a.distinct and type(a) not in _DISTINCT_OK:
            return None
        if not a.distinct and type(a) not in _DECOMPOSABLE:
            return None
    d_expr = next(a.child for a in agg.aggs if a.distinct)
    dname = "__da_d"

    inner_aggs, outer_aggs, projections = [], [], []
    for g in agg.groupings:
        projections.append(ColumnRef(g.name_hint))
    for i, a in enumerate(agg.aggs):
        out = a.name_hint
        t = f"__da_t{i}"
        if a.distinct:
            # the inner agg dedups (G, e); plain agg over e finishes it
            outer_aggs.append(type(a)(ColumnRef(dname)).with_name(t))
            projections.append(Alias(ColumnRef(t), out))
        elif isinstance(a, AG.Average):
            ps, pc = f"__da_p{i}_s", f"__da_p{i}_c"
            inner_aggs.append(AG.Sum(Cast(a.child, FLOAT64)).with_name(ps))
            inner_aggs.append(AG.Count(a.child).with_name(pc))
            ts, tc = f"__da_t{i}_s", f"__da_t{i}_c"
            outer_aggs.append(AG.Sum(ColumnRef(ps)).with_name(ts))
            outer_aggs.append(AG.Sum(ColumnRef(pc)).with_name(tc))
            projections.append(Alias(
                Divide(ColumnRef(ts), Cast(ColumnRef(tc), FLOAT64)), out))
        elif isinstance(a, (AG.CountStar, AG.Count)):
            p = f"__da_p{i}"
            inner = (AG.CountStar() if isinstance(a, AG.CountStar)
                     else AG.Count(a.child))
            inner_aggs.append(inner.with_name(p))
            outer_aggs.append(AG.Sum(ColumnRef(p)).with_name(t))
            projections.append(Alias(
                Coalesce(ColumnRef(t), Literal(0, INT64)), out))
        else:                  # Sum / Min / Max merge with themselves
            p = f"__da_p{i}"
            cls = type(a)
            inner_aggs.append(cls(a.child).with_name(p))
            outer_aggs.append(cls(ColumnRef(p)).with_name(t))
            projections.append(Alias(ColumnRef(t), out))

    inner_groupings = list(agg.groupings) + [Alias(d_expr, dname)]
    inner = L.Aggregate(inner_groupings, inner_aggs, agg.children[0],
                        many_groups_hint=True,
                        int_key_cards=agg.int_key_cards + [None])
    outer_groupings = [ColumnRef(g.name_hint) for g in agg.groupings]
    outer = L.Aggregate(outer_groupings, outer_aggs, inner,
                        int_key_cards=agg.int_key_cards)
    return L.Project(projections, outer)
