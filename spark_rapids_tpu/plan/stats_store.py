"""On-disk persistence for adaptive planning statistics (VERDICT r3 #7).

The cost model learns measured whole-query walls per engine placement
(`_ENGINE_WALLS`) and measured output row counts per plan-subtree signature
(`_RUNTIME_ROWS`) — the reference's AQE stage statistics
(GpuOverrides.scala:4691-4730) generalized across queries. Until r4 those
lived only in process memory, so every cold process re-paid each
misprediction (a 2.2 s device detour on TPC-DS q3 before the measured-wall
flip). Plan signatures are content-addressed (cost._fingerprint_table), so
they mean the same thing in the next process; this module gives them the
same lifetime the XLA compile cache gives kernels.

Format: one JSON file next to the XLA cache —
  {"version": 2, "walls": [[sig, placement, count, min_s], ...],
   "rows": [[sig, rows], ...],
   "ops": [[op_kind, placement, rows, seconds], ...],
   "plans": [[plan_digest, device_kind], ...]}
("ops" are the learned per-operator row costs, cost.record_op_wall;
"plans" the compiled-plan-digest set behind the cache-aware device
floor, exec_cache.record_plan_compiled; older files without either key
load fine.) Version 2 records COMPILE-FREE observation counts (trusted
at >=1); version-1 files recorded raw counts whose first observation
could embed a full XLA compile, so their counts load as count-1 — a v1
single-observation wall stays untrusted (the old >=2 rule preserved),
a v1 multi-observation wall stays trusted. v1 "ops" quotients (no
compile-free keying, not subtractable) are dropped entirely.
Writes are atomic (tmp + rename) and debounced; entries are capped with
insertion order as the recency proxy. Process-local signatures (the
"#<id>#" fallback for non-Arrow sources) are never persisted.
"""
from __future__ import annotations

import atexit
import json
import os
import re
import threading
import time

_CAP = 2048
_DEBOUNCE_S = 5.0
_LOCAL_TAG = re.compile(r"#\d+#")

_lock = threading.Lock()
#: serializes whole-file writes: two concurrent save()s could otherwise
#: os.replace in snapshot-age order reversed, persisting the STALER one
#: while both clear _dirty (the fresher data then never lands). Always
#: taken BEFORE _lock, never while holding it.
_save_lock = threading.Lock()
_loaded = False      # tpulint: guarded-by _lock
_dirty = False       # tpulint: guarded-by _lock
_last_save = 0.0     # tpulint: guarded-by _lock


def _path() -> str:
    p = os.environ.get("SRTPU_STATS_PATH")
    if p:
        return os.path.expanduser(p)
    cache = os.environ.get("SRTPU_XLA_CACHE_DIR",
                           os.path.expanduser("~/.cache/srtpu_xla"))
    return os.path.join(cache, "adaptive_stats.json")


def store_path() -> str:
    """Public location of the adaptive-stats file — siblings (the
    regression sentinel's baseline table, ops/sentinel.py) persist in
    the same directory so one SRTPU_STATS_PATH override relocates the
    whole learned-state family."""
    return _path()


def _persistable(sig: str) -> bool:
    return not _LOCAL_TAG.search(sig)


def load_into(walls: dict, rows: dict, ops: dict = None,
              plans: dict = None) -> None:
    """Merge persisted stats into the live dicts (live entries win).
    Corrupt or truncated files are tolerated — the caller starts with a
    fresh table, never a crash (adaptive stats are an optimization)."""
    global _loaded
    with _lock:
        if _loaded:
            return
        _loaded = True
    try:
        with open(_path()) as f:
            j = json.load(f)
    except (OSError, ValueError):
        return
    version = j.get("version") if isinstance(j, dict) else None
    if version not in (1, 2):
        return
    # v1 wall counts include the (possibly compile-poisoned) first
    # observation — discount it so the lowered >=1 trust threshold can
    # never retroactively trust a stale single-compile-run wall
    discount = 1 if version == 1 else 0
    try:
        for sig, placement, cnt, s in j.get("walls", []):
            k = (sig, placement)
            if k not in walls:
                walls[k] = (max(int(cnt) - discount, 0), float(s))
        for sig, n in j.get("rows", []):
            if sig not in rows:
                rows[sig] = int(n)
        if ops is not None and version >= 2:
            # learned per-operator row costs (cost.record_op_wall): a
            # fresh process prices operators from previously-measured
            # walls, device AND host. v1 "ops" entries are DROPPED, not
            # discounted: unlike walls (count-keyed, so one poisoned
            # observation can be subtracted) they are accumulated
            # (rows, seconds) quotients recorded with no compile-free
            # keying — a cold 17s-compile fused run baked into a v1
            # quotient would load straight into trusted territory
            for kind, placement, r, s in j.get("ops", []):
                k = (kind, placement)
                if k not in ops:
                    ops[k] = (int(r), float(s))
        if plans is not None:
            # compiled plan digests (exec_cache.record_plan_compiled):
            # a fresh process applies the warm dispatch-only floor to
            # every shape whose executables the persistent compile
            # cache already holds
            for ent in j.get("plans", []):
                if isinstance(ent, (list, tuple)) and len(ent) == 2:
                    plans.setdefault((str(ent[0]), str(ent[1])))
    except (TypeError, ValueError):
        # malformed entries mid-file: keep whatever merged cleanly
        return


def mark_dirty() -> None:
    global _dirty
    now = time.monotonic()
    # flag-set and debounce check are atomic: two writers racing here
    # could both read a stale _last_save and double-save (harmless) or
    # interleave with save()'s flag reset and LOSE the dirty mark (a
    # dropped persist)
    with _lock:
        _dirty = True
        due = now - _last_save >= _DEBOUNCE_S
    if due:
        save()


def save() -> None:
    global _dirty, _last_save
    # tpulint: disable=lock-discipline — lock-free by design: racy
    # early-out double-check; re-checked under _save_lock below
    if not _dirty:
        return
    with _save_lock:
        _save_serialized()


def _save_serialized() -> None:
    """The body of save(), holding _save_lock: snapshot, write,
    flag-reset happen as one unit so a staler snapshot can never
    overwrite a fresher file."""
    global _dirty, _last_save
    with _lock:
        if not _dirty:
            return
        # claim the flag BEFORE snapshotting: a record_* that dirties
        # the stats mid-write re-marks and the NEXT save persists it,
        # instead of this save clearing a mark its snapshot missed
        _dirty = False
    from . import cost, exec_cache
    # merge the on-disk state first: a process that never planned (e.g.
    # optimizer disabled) would otherwise TRUNCATE the accumulated store
    # to just its own entries on the first debounced save
    cost.load_persisted_stats()
    # cost's dicts have no lock of their own (their writers are the
    # query threads) and _PLAN_DIGESTS is guarded by exec_cache._LOCK,
    # not ours — so snapshot each under the right regime: the digests
    # through exec_cache's locked accessor, the cost dicts with a
    # bounded retry on the resize-mid-iteration race
    for _attempt in range(4):
        try:
            walls = [[sig, pl, c, s]
                     for (sig, pl), (c, s) in
                     list(cost._ENGINE_WALLS.items())
                     if _persistable(sig)][-_CAP:]
            rows = [[sig, n] for sig, n in
                    list(cost._RUNTIME_ROWS.items())
                    if _persistable(sig)][-_CAP:]
            ops = [[kind, pl, r, s]
                   for (kind, pl), (r, s) in list(cost._OP_COSTS.items())]
            break
        except RuntimeError:     # dict changed size during iteration
            continue
    else:
        with _lock:
            _dirty = True        # keep the claim; try again next time
        return
    # insertion order IS the recency order (record_plan_compiled
    # refreshes repeats to the end), so persist it — sorting would
    # replace recency with lexicographic order on reload — and keep
    # the NEWEST entries when over the cap (the walls idiom)
    plans = [[dig, dk] for dig, dk in
             exec_cache.warm_digests()][-exec_cache._PLAN_DIGESTS_MAX:]
    path = _path()
    tmp = path + f".tmp{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({"version": 2, "walls": walls, "rows": rows,
                       "ops": ops, "plans": plans}, f)
        os.replace(tmp, path)
        with _lock:
            _last_save = time.monotonic()
    except OSError:
        with _lock:
            _dirty = True        # nothing landed; keep the data claimed
        try:
            os.unlink(tmp)
        except OSError:
            pass


atexit.register(save)
