"""Coded placement diagnostics — the NOT_ON_GPU explain subsystem.

Reference analog: GpuOverrides tags every operator it cannot replace
with a per-operator reason surfaced by
``spark.rapids.sql.explain=NOT_ON_GPU`` (GpuOverrides.scala:4829-4838,
``ExplainPlan``), and the Qualification tool mines event logs to rank
what to fix next. Until ISSUE 7 those reasons existed here only as
free-text strings dropped before anything could aggregate them — so a
bench round with 9 of 12 rungs on ``placement: "host"`` could not say
*why* from its artifacts alone.

This module is the structured half of that diagnostic:

* a **closed registry of reason codes** (``REASON_CODES``) — every
  ``will_not_work_on_tpu`` / ``note_expr_fallback`` / cost-optimizer
  reversion site records a :class:`PlacementTag` carrying a registered
  code next to its free-text detail (creating a tag with an unknown
  code raises, the metric-inventory / conf-registry pattern; the
  ``reason-code-drift`` tpulint rule enforces the call sites);
* a per-query :class:`PlacementReport` built from the tagged meta tree
  (``plan/overrides.plan_query``) and attached to the physical plan
  next to ``placement_decision``. Surfaced by ``df.explain("placement")``,
  printed at planning time by ``spark.rapids.tpu.explain``
  (NOT_ON_DEVICE / ALL — the reference's NOT_ON_GPU mode), counted into
  the ``srtpu_placement_fallback_total{code,op}`` metric family,
  summarized onto ``queryStart`` event-log records, and mined offline by
  ``python -m spark_rapids_tpu.tools.qualify`` (docs/placement.md).
"""
from __future__ import annotations

import collections
from typing import Dict, List, Optional

from ..config import register

__all__ = ["PLACEMENT_EXPLAIN", "REASON_CODES", "PlacementTag",
           "PlacementReport", "make_tag", "build_report", "revert_to_host",
           "EXPR_UNSUPPORTED", "DTYPE_HOST_ONLY", "LIST_KEY_HOST",
           "HASH_KEY_HOST", "AGG_DISTINCT_HOST", "EXPR_DICT_EVAL",
           "OP_UNSUPPORTED", "CONF_DISABLED", "COST_MODEL_HOST",
           "WHOLE_PLAN_HOST_REVERT", "OOM_PRESSURE_HOST"]

PLACEMENT_EXPLAIN = register(
    "spark.rapids.tpu.explain", "NONE",
    "NONE / NOT_ON_DEVICE / ALL: print the coded placement report "
    "(plan/tags.py) at query planning time — the reference's "
    "spark.rapids.sql.explain=NOT_ON_GPU mode with machine-readable "
    "reason codes. NOT_ON_DEVICE prints only host-placed operators and "
    "their reason codes; ALL prints every operator's verdict. "
    "df.explain(\"placement\") renders the same report on demand; "
    "python -m spark_rapids_tpu.tools.qualify mines the codes from the "
    "query-history event log (docs/placement.md).", commonly_used=True)

# --------------------------------------------------------------------------
# the closed reason-code registry (docs/placement.md mirrors this table)
# --------------------------------------------------------------------------

EXPR_UNSUPPORTED = "EXPR_UNSUPPORTED"
DTYPE_HOST_ONLY = "DTYPE_HOST_ONLY"
LIST_KEY_HOST = "LIST_KEY_HOST"
HASH_KEY_HOST = "HASH_KEY_HOST"
AGG_DISTINCT_HOST = "AGG_DISTINCT_HOST"
EXPR_DICT_EVAL = "EXPR_DICT_EVAL"
OP_UNSUPPORTED = "OP_UNSUPPORTED"
CONF_DISABLED = "CONF_DISABLED"
COST_MODEL_HOST = "COST_MODEL_HOST"
WHOLE_PLAN_HOST_REVERT = "WHOLE_PLAN_HOST_REVERT"
OOM_PRESSURE_HOST = "OOM_PRESSURE_HOST"

#: code -> one-line meaning; the single source the explain renderers,
#: the qualify CLI and docs/placement.md share. CLOSED: make_tag raises
#: on anything not listed here, so "zero UNKNOWN codes" is structural.
REASON_CODES: Dict[str, str] = {
    EXPR_UNSUPPORTED:
        "an expression has no device implementation for its input "
        "types (filter condition, projection, grouping, aggregate, "
        "sort key, join key/condition, generator, ...)",
    DTYPE_HOST_ONLY:
        "a column's dtype payload is host-only for this operator "
        "(e.g. list payloads in windows, non-device-backed sort "
        "payloads)",
    LIST_KEY_HOST:
        "a join/group/partition/window KEY is list-typed; the key "
        "hash/compare kernels are 1D, so the operator runs its CPU "
        "twin (list VALUES in project/filter pipelines are fine)",
    HASH_KEY_HOST:
        "a hash-partition key's type is outside the device murmur3 "
        "coverage (narrower than device storage, e.g. DOUBLE keys)",
    AGG_DISTINCT_HOST:
        "a DISTINCT aggregate form was not expandable to the "
        "two-level device aggregation (multiple distinct columns or a "
        "non-decomposable mix)",
    EXPR_DICT_EVAL:
        "a string predicate is evaluated over the column dictionary "
        "(the batch stays device-resident; only the tiny dictionary "
        "pass runs on host)",
    OP_UNSUPPORTED:
        "no TPU rule is registered for the logical operator",
    CONF_DISABLED:
        "device placement was disabled by configuration "
        "(spark.rapids.tpu.sql.enabled or a per-operator "
        "spark.rapids.tpu.sql.exec.* conf)",
    COST_MODEL_HOST:
        "the cost optimizer reverted the subtree: estimated device "
        "cost including transitions exceeds the host cost",
    WHOLE_PLAN_HOST_REVERT:
        "the cost optimizer reverted the WHOLE plan to the host "
        "engine (per-query device floor, measured-wall arbitration, "
        "or the native-shape re-plan after TPU-targeted rewrites)",
    OOM_PRESSURE_HOST:
        "device memory pressure degraded execution to the host at "
        "RUNTIME: the OOM escalation ladder (retry -> split -> "
        "cross-session pressure spill) was exhausted and the starving "
        "operator — or, at the query rung, the whole query — ran on "
        "the host backend under an unbudgeted grant instead of "
        "failing (mem/retry.py; the only code recorded after "
        "planning, so it appears on the EXECUTED query's report, the "
        "queryEnd event record and srtpu_oom_host_fallback_total)",
}


class PlacementTag:
    """One coded not-on-device reason: ``code`` is a REASON_CODES key,
    ``detail`` the human free-text, ``node``/``expr`` the logical
    operator class name and expression name hint (strings only — tags
    ride pickled plans to shuffle workers and JSON event records)."""

    __slots__ = ("code", "detail", "node", "expr")

    def __init__(self, code: str, detail: str,
                 node: Optional[str] = None, expr: Optional[str] = None):
        self.code = code
        self.detail = detail
        self.node = node
        self.expr = expr

    def __repr__(self):
        return f"PlacementTag({self.code}, {self.detail!r})"

    def __getstate__(self):
        return (self.code, self.detail, self.node, self.expr)

    def __setstate__(self, st):
        self.code, self.detail, self.node, self.expr = st


def make_tag(code: str, detail: str, node: Optional[str] = None,
             expr: Optional[str] = None) -> PlacementTag:
    """The only constructor call sites should use: enforces the closed
    registry, so an UNKNOWN code can never reach a report."""
    if code not in REASON_CODES:
        raise ValueError(
            f"placement reason code {code!r} is not registered in "
            "plan/tags.py REASON_CODES — add it to the closed registry "
            "(and docs/placement.md) before use")
    return PlacementTag(code, detail, node=node, expr=expr)


def revert_to_host(meta, reason: str, code: str) -> None:
    """Whole-subtree host reversion that PRESERVES per-node tags
    (ISSUE 7 satellite): the reversion is recorded once as a plan-level
    *wrapping* tag on the subtree root, and per node only
    still-device-capable nodes receive it — a node already carrying its
    own recorded reasons keeps them untouched, so
    ``explain("placement")`` shows BOTH the wrapping reversion and the
    original per-node causes instead of the reversion text clobbering
    everything."""
    meta.plan_tags.append(
        make_tag(code, reason, node=type(meta.plan).__name__))

    def walk(m):
        if m.can_run_on_tpu:
            m.will_not_work_on_tpu(reason, code=code)
        for c in m.child_metas:
            walk(c)

    walk(meta)


class _Entry:
    """One plan node's verdict in a report (strings + tags only)."""

    __slots__ = ("node", "depth", "device", "neutral", "tags", "expr_tags")

    def __init__(self, node, depth, device, neutral, tags, expr_tags):
        self.node = node
        self.depth = depth
        self.device = device
        self.neutral = neutral
        self.tags = tags
        self.expr_tags = expr_tags

    def __getstate__(self):
        return (self.node, self.depth, self.device, self.neutral,
                self.tags, self.expr_tags)

    def __setstate__(self, st):
        (self.node, self.depth, self.device, self.neutral,
         self.tags, self.expr_tags) = st


class PlacementReport:
    """Per-query roll-up of placement tags, in plan-tree preorder.

    ``plan_tags`` are the wrapping whole-plan reversions
    (:func:`revert_to_host`); ``entries`` one record per logical node
    with its own blocking tags and per-expression fallback notes.
    ``verdict`` is "device" when any non-neutral node still plans onto
    the device (the ``dataframe._on_device`` placement check applied at
    plan time), else "host".
    """

    __slots__ = ("entries", "plan_tags", "decision", "verdict", "est_rows")

    def __init__(self, entries: List[_Entry], plan_tags: List[PlacementTag],
                 decision: Optional[str], verdict: str,
                 est_rows: Optional[int] = None):
        self.entries = entries
        self.plan_tags = plan_tags
        self.decision = decision
        self.verdict = verdict
        self.est_rows = est_rows

    def __getstate__(self):
        return (self.entries, self.plan_tags, self.decision, self.verdict,
                self.est_rows)

    def __setstate__(self, st):
        (self.entries, self.plan_tags, self.decision, self.verdict,
         self.est_rows) = st

    # ------------------------------------------------------------ roll-ups
    def all_tags(self) -> List[PlacementTag]:
        out = list(self.plan_tags)
        for e in self.entries:
            out.extend(e.tags)
            out.extend(e.expr_tags)
        return out

    def counts(self) -> Dict[str, int]:
        """code -> occurrences, across node, expression and plan-level
        tags."""
        c: collections.Counter = collections.Counter()
        for t in self.all_tags():
            c[t.code] += 1
        return dict(c)

    def op_code_counts(self) -> Dict[tuple, int]:
        """(operator, code) -> occurrences — the metric family's label
        set (plan-level tags count under the root operator)."""
        c: collections.Counter = collections.Counter()
        for t in self.all_tags():
            c[(t.node or "?", t.code)] += 1
        return dict(c)

    def format_counts(self) -> str:
        items = sorted(self.counts().items(), key=lambda kv: (-kv[1], kv[0]))
        return ", ".join(f"{code} x{n}" for code, n in items)

    def summary(self) -> dict:
        """JSON-able summary for event-log queryStart records (what
        tools/qualify mines): verdict, code->count, per-op code->count,
        and the plan-time row estimate the qualify tool joins against
        learned per-row costs."""
        ops: Dict[str, Dict[str, int]] = {}
        for (op, code), n in sorted(self.op_code_counts().items()):
            ops.setdefault(op, {})[code] = n
        return {"verdict": self.verdict,
                "codes": dict(sorted(self.counts().items())),
                "ops": ops,
                "estRows": self.est_rows}

    # ------------------------------------------------------------- render
    def render(self, only_not_on_device: bool = False) -> str:
        """The ``explain("placement")`` tree: per-operator device/host
        verdicts with their reason codes, wrapping plan-level tags
        first. ``only_not_on_device`` mirrors the reference's
        NOT_ON_GPU mode (host rows and plan tags only)."""
        lines = [f"placement verdict: {self.verdict}"]
        counts = self.format_counts()
        if counts:
            lines.append(f"fallbacks: {counts}")
        for t in self.plan_tags:
            lines.append(f"[{t.code}] {t.detail} (wraps the whole plan)")
        for e in self.entries:
            pad = "  " * e.depth
            # NOT_ON_DEVICE keeps device rows only when they carry
            # per-expression fallback notes (partial host work)
            if e.device and only_not_on_device and not e.expr_tags:
                continue
            marker, where = ("*", "device") if e.device else ("!", "host")
            lines.append(f"{pad}{marker}Exec <{e.node}> on {where}")
            for t in list(e.tags) + list(e.expr_tags):
                lines.append(f"{pad}    [{t.code}] {t.detail}")
        return "\n".join(lines)


def build_report(meta, decision: Optional[str] = None,
                 est_rows: Optional[int] = None) -> PlacementReport:
    """Assemble a PlacementReport from a tagged (and cost-optimized)
    PlanMeta tree — called by ``plan_query`` right before conversion."""
    from .overrides import _NEUTRAL_PLANS  # function-level: no cycle
    entries: List[_Entry] = []
    device_seen = False

    def walk(m, depth):
        nonlocal device_seen
        neutral = isinstance(m.plan, _NEUTRAL_PLANS)
        if m.can_run_on_tpu and not neutral:
            device_seen = True
        entries.append(_Entry(type(m.plan).__name__, depth,
                              m.can_run_on_tpu, neutral,
                              list(m.tags), list(m.expr_tags)))
        for c in m.child_metas:
            walk(c, depth + 1)

    walk(meta, 0)
    return PlacementReport(entries, list(meta.plan_tags), decision,
                           "device" if device_seen else "host",
                           est_rows=est_rows)
