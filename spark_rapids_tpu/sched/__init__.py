"""Multi-tenant scheduling layer (ISSUE 18).

The query-serving front door: every ``_execute_wrapped`` query passes
through the :mod:`.admission` controller before it can touch the device
semaphore. The subsystem rations *entry* the way the reference stack's
``GpuSemaphore`` rations concurrent device tasks — but one level up,
where a request can still be cheaply refused instead of wedging the
runtime ("Accelerating Presto with GPUs" is the concurrent-query
admission blueprint):

* priority-queued admission — per-tenant priority classes, FIFO within
  a class, configurable max in-flight and max queued
  (``spark.rapids.tpu.admission.*``);
* deadline-aware queueing — a query whose
  ``spark.rapids.tpu.query.timeout`` budget would expire while queued
  is rejected immediately, not admitted to fail later;
* graceful shedding — while the process is pressure-degraded (the
  ``/healthz`` memory/semaphore verdicts: HBM > 95 %, a live or
  recently-drained pressure-grant pool, a wedged holder) new
  low-priority admissions are refused with a structured
  :class:`~.admission.AdmissionRejected` carrying a retry-after hint.

Contract (the trace/metrics/ops pattern): disabled, the controller is
``None`` and every query pays one module-global load + branch.
"""
from __future__ import annotations

from .admission import (AdmissionController, AdmissionRejected,
                        AdmissionTicket, active_admission,
                        ensure_admission_from_conf, install_admission,
                        shed_reason)

__all__ = ["AdmissionController", "AdmissionRejected", "AdmissionTicket",
           "active_admission", "ensure_admission_from_conf",
           "install_admission", "shed_reason"]
