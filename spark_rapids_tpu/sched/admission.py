"""Admission controller: priority queueing, deadlines, shedding.

Reference analog: the bounded concurrent-task admission of
``GpuSemaphore`` (GpuSemaphore.scala:51) plus the concurrent-query
scheduling model of "Accelerating Presto with GPUs" — a front door that
decides whether a query may even start competing for the device, so an
overload burst degrades into structured refusals instead of a pile-up
on the semaphore.

Mechanics:

* one :class:`threading.Condition` guards the whole scheduler state
  (in-flight count, queued-ticket table, hold-time estimator);
* each ``admit()`` creates a ticket and waits until it is the *head* of
  the queue — the queued ticket with the highest effective priority,
  FIFO within a class — AND an in-flight slot is free;
* effective priority ages upward every
  ``spark.rapids.tpu.admission.agingMs`` spent queued, so a continuous
  stream of high-priority admissions can delay but never indefinitely
  starve a low-priority ticket;
* a ticket whose query deadline would expire while queued (estimated
  from an EWMA of recent admission hold times) is rejected up front;
  one that outlives its deadline in the queue is rejected on wake;
* while the process is pressure-degraded (:func:`shed_reason` — the
  same HBM/pressure-grant/wedge conditions the ops ``/healthz``
  memory and semaphore verdicts read) admissions with priority below
  ``spark.rapids.tpu.admission.shed.priorityFloor`` are refused.

Every refusal raises :class:`AdmissionRejected` carrying a machine
``reason`` and a ``retry_after_s`` hint, and is counted into
``srtpu_admission_rejected_total{reason=...}``. A rejection burst past
``spark.rapids.tpu.admission.shed.burst`` inside
``spark.rapids.tpu.admission.shed.windowMs`` fires the flight
recorder's ``admission_shed`` trigger naming the pressured section.

The reject path is leak-free by construction: a ticket is removed from
the queued table in the same critical section that decides to reject
it, and rejection happens strictly before the in-flight count is
incremented — a refused query can never strand a slot or a queued
deadline timer (release() on a never-admitted ticket is a no-op).
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..config import register

__all__ = ["AdmissionController", "AdmissionRejected", "AdmissionTicket",
           "install_admission", "ensure_admission_from_conf",
           "active_admission", "shed_reason",
           "ADMISSION_ENABLED", "ADMISSION_MAX_IN_FLIGHT",
           "ADMISSION_MAX_QUEUED", "ADMISSION_AGING_MS",
           "ADMISSION_RETRY_AFTER_MS", "ADMISSION_SHED_PRIORITY_FLOOR",
           "ADMISSION_SHED_BURST", "ADMISSION_SHED_WINDOW_MS",
           "TENANT_ID", "TENANT_PRIORITY", "TENANT_HBM_SHARE"]

log = logging.getLogger(__name__)

ADMISSION_ENABLED = register(
    "spark.rapids.tpu.admission.enabled", False,
    "Route every materializing query through the multi-tenant admission "
    "controller (sched/admission.py): priority-queued entry over the "
    "device semaphore, deadline-aware queueing and graceful shedding "
    "under pressure (docs/serving.md). Off by default: no controller is "
    "installed and each query pays one module-global load + branch.",
    commonly_used=True)

ADMISSION_MAX_IN_FLIGHT = register(
    "spark.rapids.tpu.admission.maxInFlight", 0,
    "Queries admitted concurrently past the controller; 0 means match "
    "spark.rapids.tpu.sql.concurrentTpuTasks (admission then mirrors "
    "the device-semaphore width one level up, where refusal is still "
    "cheap).")

ADMISSION_MAX_QUEUED = register(
    "spark.rapids.tpu.admission.maxQueued", 32,
    "Queries allowed to wait for admission; one more is refused with "
    "AdmissionRejected(reason=queue_full) and a retry-after hint "
    "instead of deepening the pile-up.")

ADMISSION_AGING_MS = register(
    "spark.rapids.tpu.admission.agingMs", 1000,
    "Milliseconds of queued wait per one step of priority aging: a "
    "queued ticket's effective priority rises by one class per "
    "interval, so high-priority streams cannot indefinitely starve a "
    "low-priority query. <= 0 disables aging.")

ADMISSION_RETRY_AFTER_MS = register(
    "spark.rapids.tpu.admission.retryAfterMs", 100,
    "Base retry-after hint (milliseconds) carried by AdmissionRejected; "
    "queue_full refusals scale it by the queue depth.")

ADMISSION_SHED_PRIORITY_FLOOR = register(
    "spark.rapids.tpu.admission.shed.priorityFloor", 2,
    "While the process is pressure-degraded (the /healthz memory/"
    "semaphore conditions), new admissions with tenant priority "
    "STRICTLY BELOW this are shed with AdmissionRejected(reason=shed). "
    "The default (2) sheds default-priority (1) tenants and lets "
    "priority >= 2 tenants through.")

ADMISSION_SHED_BURST = register(
    "spark.rapids.tpu.admission.shed.burst", 8,
    "Rejections inside admission.shed.windowMs that count as a shed "
    "burst: the flight recorder's admission_shed trigger dumps one "
    "bundle naming the pressured section (docs/ops.md).")

ADMISSION_SHED_WINDOW_MS = register(
    "spark.rapids.tpu.admission.shed.windowMs", 1000,
    "Window (milliseconds) over which admission rejections are counted "
    "toward the admission_shed flight-recorder burst threshold.")

TENANT_ID = register(
    "spark.rapids.tpu.tenant.id", "",
    "Tenant this session's queries run as: the admission controller's "
    "priority/fairness unit and the memory manager's quota unit "
    "(docs/serving.md). Empty means the anonymous default tenant (no "
    "quota attribution).", commonly_used=True)

TENANT_PRIORITY = register(
    "spark.rapids.tpu.tenant.priority", 1,
    "Admission priority class of this session's tenant; higher admits "
    "first. FIFO within a class; queued tickets age upward per "
    "spark.rapids.tpu.admission.agingMs.")

TENANT_HBM_SHARE = register(
    "spark.rapids.tpu.tenant.hbmShare", 0.0,
    "Fraction (0..1] of the HBM budget this tenant may keep resident "
    "in retained device buffers; 0 disables the quota. A breach first "
    "spills the tenant's OWN spillables, then raises into the tenant's "
    "own rung-1/2 retry ladder — it can never force a rung-3 "
    "cross-session spill on other tenants (mem/manager.py).")


class AdmissionRejected(RuntimeError):
    """A query was refused at the admission front door.

    Structured fields (the serving contract, docs/serving.md):

    * ``reason`` — ``queue_full`` / ``deadline`` / ``shed`` / ``chaos``;
    * ``retry_after_s`` — hint: seconds after which a retry has a
      reasonable chance (load balancers map it to Retry-After);
    * ``tenant`` — the refused tenant id (None for anonymous).
    """

    def __init__(self, reason: str, detail: str,
                 retry_after_s: float = 0.1,
                 tenant: Optional[str] = None):
        super().__init__(f"admission rejected ({reason}): {detail} "
                         f"[retry after {retry_after_s:.3f}s]")
        self.reason = reason
        self.retry_after_s = float(retry_after_s)
        self.tenant = tenant


class AdmissionTicket:
    """One query's pass through the controller. ``queued_ms`` is the
    wait the query paid before admission (0.0 for an uncontended fast
    path); ``release()`` via the controller is idempotent."""

    __slots__ = ("tenant", "priority", "seq", "enqueued_at", "queued_ms",
                 "deadline", "admitted", "released")

    def __init__(self, tenant: Optional[str], priority: int, seq: int,
                 deadline: Optional[float]):
        self.tenant = tenant
        self.priority = int(priority)
        self.seq = seq
        self.enqueued_at = time.monotonic()
        self.queued_ms = 0.0
        self.deadline = deadline
        self.admitted = False
        self.released = False


def shed_reason() -> Optional[str]:
    """Why the process is pressure-degraded, or None when healthy — the
    SAME conditions the ops ``/healthz`` memory and semaphore verdicts
    read (ops/server.py thresholds, including the pressure-grant clear
    horizon), so shedding and the 503 the load balancer sees always
    agree."""
    from ..mem.manager import MemoryManager
    from ..ops import server as ops_server
    st = MemoryManager.stats_all()
    budget = st.get("budget") or 0
    used = st.get("device_used") or 0
    if st.get("pressure_granted"):
        return "memory: pressure-grant pool active"
    idle = st.get("pressure_grant_idle_s")
    if idle is not None and idle < ops_server._GRANT_CLEAR_HORIZON_S:
        return (f"memory: pressure-grant pool drained only "
                f"{idle:.2f}s ago")
    if budget > 0 and used > ops_server._HBM_DEGRADED_FRACTION * budget:
        return (f"memory: HBM {used}/{budget} B past the "
                "degraded fraction")
    from ..mem import semaphore as sem_mod
    census = sem_mod.wedged_census()
    if census["dead"] or census["overdue"]:
        return (f"semaphore: {census['dead']} dead / "
                f"{census['overdue']} overdue holder(s)")
    # SLO burn -> shed coupling (ISSUE 20): while a multi-window burn
    # alert is live (and slo.shed.enabled), the process sheds below the
    # priority floor exactly as it does under memory pressure — the
    # error budget is a resource too (ops/slo.py, docs/serving.md)
    from ..ops import slo as slo_mod
    slo = slo_mod.TRACKER
    if slo is not None:
        hint = slo.shed_hint()
        if hint:
            return f"slo: error-budget burn alert live ({hint})"
    return None


class AdmissionController:
    """Priority-queued, deadline-aware, shedding admission gate.

    One condition variable guards all scheduler state; every waiter
    re-evaluates headship on wake, so a released slot always goes to
    the queued ticket with the highest effective (aged) priority,
    FIFO within a class."""

    def __init__(self, max_in_flight: int, max_queued: int,
                 aging_ms: int = 1000, retry_after_ms: int = 100,
                 shed_priority_floor: int = 2, shed_burst: int = 8,
                 shed_window_ms: int = 1000):
        self.max_in_flight = max(1, int(max_in_flight))
        self.max_queued = max(0, int(max_queued))
        self.aging_ms = int(aging_ms)
        self.retry_after_ms = max(1, int(retry_after_ms))
        self.shed_priority_floor = int(shed_priority_floor)
        self.shed_burst = max(1, int(shed_burst))
        self.shed_window_ms = max(1, int(shed_window_ms))
        self._cv = threading.Condition()
        self._seq = itertools.count(1)
        self.in_flight = 0                 # tpulint: guarded-by _cv
        self._queued: List[AdmissionTicket] = []  # tpulint: guarded-by _cv
        #: EWMA of seconds an admitted query holds its slot — the
        #: queue-wait estimator behind up-front deadline rejection
        self._hold_ewma_s = 0.0            # tpulint: guarded-by _cv
        self.admitted_total = 0            # tpulint: guarded-by _cv
        self.rejected: Dict[str, int] = {}  # tpulint: guarded-by _cv
        #: monotonic instants of recent rejections (burst detector)
        self._reject_times: deque = deque(
            maxlen=self.shed_burst)        # tpulint: guarded-by _cv

    # ------------------------------------------------------------ admit
    def admit(self, tenant: Optional[str] = None, priority: int = 1,
              deadline: Optional[float] = None) -> AdmissionTicket:
        """Block until admitted; raise :class:`AdmissionRejected` when
        the queue is full, the deadline cannot be met, pressure sheds
        this priority class, or chaos injects a refusal. ``deadline``
        is a ``time.monotonic`` instant (the query's cooperative
        timeout instant), None for no deadline."""
        try:
            return self._admit(tenant, priority, deadline)
        except AdmissionRejected as e:
            # metric export and the burst flight dump run strictly
            # OUTSIDE _cv: a bundle's metrics section samples this
            # controller, and sampling under our own lock would deadlock
            self._note_rejected(e)
            raise

    def _admit(self, tenant: Optional[str], priority: int,
               deadline: Optional[float]) -> AdmissionTicket:
        from ..aux.fault import active_chaos
        ctl = active_chaos()
        if ctl is not None:
            if ctl.wants("admit.delay"):
                ctl.maybe_delay("admit.delay")
            if ctl.wants("admit.reject") and ctl.fires("admit.reject"):
                self._reject("chaos", tenant,
                             "chaos: injected admit.reject")
        now = time.monotonic()
        if deadline is not None and now >= deadline:
            self._reject("deadline", tenant,
                         "query deadline already passed at admission")
        shed = shed_reason()
        if shed is not None and int(priority) < self.shed_priority_floor:
            self._reject(
                "shed", tenant,
                f"pressure-degraded ({shed}); priority {priority} is "
                f"below admission.shed.priorityFloor "
                f"{self.shed_priority_floor}", section=shed)
        with self._cv:
            if len(self._queued) >= self.max_queued \
                    and self.in_flight >= self.max_in_flight:
                self._reject_locked(
                    "queue_full", tenant,
                    f"{self.in_flight} in flight, {len(self._queued)} "
                    f"queued (admission.maxQueued={self.max_queued})",
                    retry_scale=len(self._queued) + 1)
            if deadline is not None and self._hold_ewma_s > 0:
                # up-front deadline check: with every slot busy, this
                # ticket waits roughly one EWMA hold per queue "wave"
                # ahead of it — admit-to-fail-later wastes a slot the
                # whole wait, so refuse now while it is still free
                waves = (len(self._queued) + self.in_flight
                         - (self.max_in_flight - 1)) / self.max_in_flight
                est_wait_s = max(0.0, waves) * self._hold_ewma_s
                if now + est_wait_s >= deadline:
                    self._reject_locked(
                        "deadline", tenant,
                        f"estimated queue wait {est_wait_s:.3f}s "
                        f"exceeds the remaining query.timeout budget "
                        f"{deadline - now:.3f}s")
            t = AdmissionTicket(tenant, priority, next(self._seq),
                                deadline)
            self._queued.append(t)
            try:
                while not (self.in_flight < self.max_in_flight
                           and self._head_locked() is t):
                    if t.deadline is not None \
                            and time.monotonic() >= t.deadline:
                        self._reject_locked(
                            "deadline", tenant,
                            "query deadline expired while queued "
                            f"({(time.monotonic() - t.enqueued_at):.3f}s "
                            "in queue)")
                    # bounded wait slices: re-evaluate aging promotion
                    # and the deadline even when no release wakes us
                    self._cv.wait(timeout=min(
                        0.05, self.aging_ms / 1000.0
                        if self.aging_ms > 0 else 0.05))
            except BaseException:
                # reject/timeout/interrupt: the ticket must leave the
                # queued table in the same critical section — a
                # stranded entry would block every later head check
                self._queued.remove(t)
                self._cv.notify_all()
                raise
            self._queued.remove(t)
            self.in_flight += 1
            t.admitted = True
            t.queued_ms = round(
                (time.monotonic() - t.enqueued_at) * 1000.0, 3)
            self.admitted_total += 1
        from ..metrics import registry as metrics_registry
        mr = metrics_registry.REGISTRY
        if mr is not None:
            wait_s = t.queued_ms / 1000.0
            mr.counter("srtpu_admission_admitted_total",
                       tenant=tenant or "default").inc()
            mr.histogram("srtpu_admission_wait_seconds",
                         tenant=tenant or "default").observe(wait_s)
            # tail view of the same wait: mergeable quantile sketch
            # (ISSUE 20) — the per-tenant p99 the /slo report and the
            # mixed-tenant battery read
            mr.summary("srtpu_admission_wait_latency_seconds",
                       tenant=tenant or "default").observe(wait_s)
        return t

    def _effective_priority(self, t: AdmissionTicket, now: float) -> int:
        if self.aging_ms <= 0:
            return t.priority
        waited_ms = (now - t.enqueued_at) * 1000.0
        return t.priority + int(waited_ms // self.aging_ms)

    def _head_locked(self) -> Optional[AdmissionTicket]:
        """The queued ticket next in line: highest effective (aged)
        priority, FIFO (lowest seq) within a class. Caller holds _cv."""
        if not self._queued:
            return None
        now = time.monotonic()
        return max(self._queued,
                   key=lambda t: (self._effective_priority(t, now),
                                  -t.seq))

    # ---------------------------------------------------------- release
    # tpulint: never-raise
    def release(self, ticket: AdmissionTicket) -> None:
        """Return an admitted ticket's slot (idempotent; a ticket that
        was never admitted is a no-op). Runs on every query exit path,
        so it must never raise into an already-unwinding query."""
        try:
            with self._cv:
                if not ticket.admitted or ticket.released:
                    return
                ticket.released = True
                self.in_flight = max(0, self.in_flight - 1)
                held_s = max(0.0, time.monotonic() - ticket.enqueued_at
                             - ticket.queued_ms / 1000.0)
                self._hold_ewma_s = (held_s if self._hold_ewma_s == 0.0
                                     else 0.8 * self._hold_ewma_s
                                     + 0.2 * held_s)
                self._cv.notify_all()
        except Exception:  # noqa: BLE001 - release must never raise
            log.exception("admission release failed")

    # ----------------------------------------------------------- reject
    def _reject(self, reason: str, tenant: Optional[str], detail: str,
                retry_scale: int = 1,
                section: Optional[str] = None) -> None:
        with self._cv:
            self._reject_locked(reason, tenant, detail,
                                retry_scale=retry_scale, section=section)

    def _reject_locked(self, reason: str, tenant: Optional[str],
                       detail: str, retry_scale: int = 1,
                       section: Optional[str] = None) -> None:
        """Count, burst-detect and raise one refusal. Caller holds _cv;
        the raise happens BEFORE any slot/queue state is taken for this
        request, so a rejection can never leak a permit. Side effects
        with their own locks (metrics, flight dump) are deferred to
        :meth:`_note_rejected` on the unlocked unwind path."""
        now = time.monotonic()
        self.rejected[reason] = self.rejected.get(reason, 0) + 1
        self._reject_times.append(now)
        burst = (len(self._reject_times) >= self.shed_burst
                 and (now - self._reject_times[0]) * 1000.0
                 <= self.shed_window_ms)
        retry_s = self.retry_after_ms * max(1, retry_scale) / 1000.0
        e = AdmissionRejected(reason, detail, retry_after_s=retry_s,
                              tenant=tenant)
        e.burst_section = (section or detail) if burst else None
        raise e

    def _note_rejected(self, e: AdmissionRejected) -> None:
        """Unlocked rejection side effects: the reason-labeled counter
        and, on a burst, ONE admission_shed flight bundle naming the
        pressured section (rate-limited further by the recorder)."""
        from ..metrics import registry as metrics_registry
        mr = metrics_registry.REGISTRY
        if mr is not None:
            mr.counter("srtpu_admission_rejected_total",
                       reason=e.reason).inc()
        section = getattr(e, "burst_section", None)
        if section is not None:
            from ..ops import flight as flight_mod
            fr = flight_mod.RECORDER
            if fr is not None:
                fr.trigger(
                    "admission_shed",
                    detail=f"{self.shed_burst} admission rejections "
                           f"within {self.shed_window_ms}ms; last "
                           f"reason={e.reason}; pressured section: "
                           f"{section}")

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        """One consistent scheduler snapshot (the /healthz admission
        section and the load tests read this)."""
        with self._cv:
            now = time.monotonic()
            queued = [{"tenant": t.tenant, "priority": t.priority,
                       "effectivePriority":
                           self._effective_priority(t, now),
                       "queuedMs": round(
                           (now - t.enqueued_at) * 1000.0, 1)}
                      for t in self._queued]
            return {"inFlight": self.in_flight,
                    "maxInFlight": self.max_in_flight,
                    "queued": queued,
                    "maxQueued": self.max_queued,
                    "admitted": self.admitted_total,
                    "rejected": dict(self.rejected),
                    "holdEwmaS": round(self._hold_ewma_s, 4)}

    def queue_depth(self) -> int:
        """Queued-ticket count for the metrics sampler — deliberately
        NOT under _cv so a sampler pass (which a flight bundle may run
        while an admission path holds the lock) can never deadlock."""
        # tpulint: disable=lock-discipline — lock-free by design: a
        # racy len() read for a telemetry gauge
        return len(self._queued)


# ---------------------------------------------------------------------------
# installation (the trace/metrics/ops pattern)
# ---------------------------------------------------------------------------

#: the process-global controller; ``None`` means admission control is
#: OFF and every query costs exactly one attribute load + branch
CONTROLLER: Optional[AdmissionController] = None

_INSTALL_LOCK = threading.Lock()


def active_admission() -> Optional[AdmissionController]:
    # tpulint: disable=lock-discipline — lock-free by design: the
    # disabled-path contract is one unlocked reference read per query
    return CONTROLLER


def install_admission(
        ctl: Optional[AdmissionController]) -> Optional[AdmissionController]:
    """Install (or with ``None`` remove) the process-global controller
    (tests / the per-test reset)."""
    global CONTROLLER
    with _INSTALL_LOCK:
        CONTROLLER = ctl
    return ctl


def ensure_admission_from_conf(conf) -> Optional[AdmissionController]:
    """Install the controller iff ``spark.rapids.tpu.admission.enabled``
    — one conf lookup per ExecContext construction. First enabled conf
    wins for the process lifetime (the install-once registry pattern:
    admission is a process property, like the ops port)."""
    global CONTROLLER
    if not bool(conf.get(ADMISSION_ENABLED)):
        # tpulint: disable=lock-discipline — lock-free by design:
        # admission-off fast path; installation itself locks below
        return CONTROLLER
    with _INSTALL_LOCK:
        if CONTROLLER is None:
            from ..config import CONCURRENT_TPU_TASKS
            max_if = int(conf.get(ADMISSION_MAX_IN_FLIGHT))
            if max_if <= 0:
                max_if = int(conf.get(CONCURRENT_TPU_TASKS))
            CONTROLLER = AdmissionController(
                max_in_flight=max_if,
                max_queued=int(conf.get(ADMISSION_MAX_QUEUED)),
                aging_ms=int(conf.get(ADMISSION_AGING_MS)),
                retry_after_ms=int(conf.get(ADMISSION_RETRY_AFTER_MS)),
                shed_priority_floor=int(
                    conf.get(ADMISSION_SHED_PRIORITY_FLOOR)),
                shed_burst=int(conf.get(ADMISSION_SHED_BURST)),
                shed_window_ms=int(conf.get(ADMISSION_SHED_WINDOW_MS)))
        return CONTROLLER
