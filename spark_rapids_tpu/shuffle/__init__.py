from .partitioning import PartitionedBatches, hash_partition_ids, partition_batch
from .exchange import CpuShuffleExchangeExec, ShuffleCatalog, ShuffleExchangeExec

__all__ = ["PartitionedBatches", "hash_partition_ids", "partition_batch",
           "CpuShuffleExchangeExec", "ShuffleCatalog", "ShuffleExchangeExec"]
