"""Broadcast exchange (ref GpuBroadcastExchangeExec.scala:74,354-477).

The reference builds the broadcast relation once on the driver
(relationFuture collects serialized host batches, lazily concatenated by
SerializeConcatHostBuffersDeserializeBatch) and ships it to every executor,
where GpuBroadcastHelper materializes it onto the device once.

TPU-native shape: one process hosts the query, so "broadcast" = build the
child's result exactly once per query, hold it as a single coalesced batch
in a per-context cache, and hand the same device-resident batch to every
consumer (all stream batches of a broadcast join, multiple joins reusing
the same exchange — the analog of Spark's reuseExchange). In the
multi-chip path the batch is replicated across the mesh by the sharding
layer (see parallel/collective.py), the moral equivalent of the driver
broadcast hop.
"""
from __future__ import annotations

from typing import Iterator

from ..columnar import ColumnarBatch, concat_batches
from ..exec.base import ESSENTIAL, ExecContext, TpuExec
from ..mem import SpillableBatch
from ..types import Schema

__all__ = ["BroadcastExchangeExec"]


class BroadcastExchangeExec(TpuExec):
    """Build-once, consume-many exchange. ``broadcast(ctx)`` returns the
    single coalesced batch, memoized per ExecContext (the per-query analog
    of the executor-wide broadcast cache)."""

    def __init__(self, child: TpuExec):
        super().__init__([child])
        self._schema = child.output_schema()

    def output_schema(self) -> Schema:
        return self._schema

    def broadcast(self, ctx: ExecContext) -> ColumnarBatch:
        """The cached relation is held as a SpillableBatch (lowest spill
        priority — broadcast data is cheap to rebuild from host) so its HBM
        footprint stays visible to the memory manager; `get()` migrates it
        back if it was spilled between consumers."""
        from ..mem.spillable import SpillPriorities
        cache = getattr(ctx, "_broadcast_cache", None)
        if cache is None:
            cache = ctx._broadcast_cache = {}
        sb = cache.get(self._exec_id)
        if sb is None:
            size_m = ctx.metric(self._exec_id, "dataSize", ESSENTIAL)
            from ..mem import wrap_spillables
            spill = wrap_spillables(self.children[0].execute(ctx),
                                    ctx.memory)
            try:
                with ctx.semaphore.held():
                    if spill:
                        out = concat_batches([s.get() for s in spill])
                    else:
                        from ..exec.joins import _empty_batch
                        out = _empty_batch(self._schema)
            finally:
                for s in spill:
                    s.close()
            size_m.add(out.device_size_bytes())
            sb = SpillableBatch(
                out, ctx.memory,
                spill_priority=SpillPriorities.OUTPUT_FOR_SHUFFLE)
            cache[self._exec_id] = sb
            ctx.add_cleanup(sb.close)
        return sb.get()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        yield self.broadcast(ctx)

    def describe(self):
        return "BroadcastExchange"
