"""CRC32C (Castagnoli) block checksums for the shuffle transport.

Every block the transport moves carries a CRC32C computed by the sender
and verified by the receiver (ref the shuffle-plugin's buffer integrity
checks and Spark's shuffle checksum support, SPARK-35275: a corrupt
block must surface as a FAILED fetch that the retry machinery can
recover, never as silently wrong query results).

CRC32C rather than zlib's CRC32 because it is the de-facto storage
checksum (iSCSI, ext4, Parquet pages) and has hardware support on every
server platform — when a native implementation is importable we use it;
otherwise the table-driven software fallback below keeps the wire format
identical (the polynomial is part of the protocol, so every cluster
member computes the same digest regardless of which path it has).
"""
from __future__ import annotations

__all__ = ["crc32c", "ChecksumError"]


class ChecksumError(ValueError):
    """A block's payload does not match its CRC32C header (corruption in
    transit or in the store) — callers treat this like a failed fetch."""


_CASTAGNOLI_POLY = 0x82F63B78  # reflected 0x1EDC6F41


def _make_table():
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ _CASTAGNOLI_POLY if crc & 1 else crc >> 1
        table.append(crc)
    return tuple(table)


_TABLE = _make_table()


def _crc32c_sw(data, crc: int = 0) -> int:
    """Software CRC32C. O(n) Python loop — fine for the host-staged
    transport's block sizes; the native path below takes over when a
    compiled implementation is present."""
    crc = ~crc & 0xFFFFFFFF
    table = _TABLE
    for b in memoryview(data).tobytes():
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return ~crc & 0xFFFFFFFF


try:  # hardware/native implementations, if the image has one
    from crc32c import crc32c as _crc32c_native  # type: ignore
except ImportError:
    try:
        import google_crc32c  # type: ignore

        def _crc32c_native(data, crc=0):
            return google_crc32c.extend(crc, bytes(data))
    except ImportError:
        _crc32c_native = None


def crc32c(data, crc: int = 0) -> int:
    """CRC32C of ``data`` (optionally extending a running ``crc``)."""
    if _crc32c_native is not None:
        return _crc32c_native(data, crc)
    return _crc32c_sw(data, crc)
