"""Multi-process execution: N worker processes + TCP shuffle.

The cross-process runtime the reference gets from Spark (driver/executor
split + shuffle service) rebuilt TPU-engine-style (ref
RapidsShuffleInternalManagerBase.scala:238 threaded writer, :614 threaded
reader, :1228 manager; heartbeat discovery Plugin.scala:428-439):

  driver                      worker processes (JAX_PLATFORMS=cpu)
  ------                      --------------------------------------
  ShuffleHeartbeatManager <--- ShuffleHeartbeatEndpoint heartbeats
  LocalCluster.execute(df)     each runs a BlockServer (transport.py)
    split plan at the agg      map task: run fragment, hash-partition
    (and, r3, at a shuffled    output, PUT blocks to partition owners
    JOIN below it)             join task: fetch co-partitions of both
    ship typed tasks --------> sides, local join + partial agg, PUT
    collect + finish plan <---- serialized Arrow results

Aggregates are decomposed into update/merge pairs exactly like the
distinct rewrite (plan/rewrites.py): Sum/Min/Max merge with themselves,
Count(+Star) merges by summing, Average splits into sum+count with a
driver-side divide — so distributing cannot change results.

Joins (r3): when BOTH sides of an equi-join are large, the driver
hash-shuffles both sides by their join keys (one map task per worker per
side), each worker joins its co-partitioned slice locally and runs the
partial aggregation, then the existing agg shuffle/merge finishes — the
host-staged analog of GpuShuffledHashJoinExec over
RapidsShuffleInternalManagerBase exchanges (:614). Small sides keep the
replicated (broadcast) path.

All control traffic is the typed-task protocol in transport.py, signed
with a per-cluster HMAC token — workers execute only registered task
entry points, never shipped code objects.

This is deliberately the MULTITHREADED-mode analog (host-staged blocks
over TCP). The single-process device-resident path (ShuffleCatalog) and
the SPMD collective path (parallel/planner.py) remain the fast paths; this
runtime is the scale-out seam for multi-host deployments.
"""
from __future__ import annotations

import copy
import os
import pickle
import secrets
import time
from typing import Dict, List, Optional, Tuple

from .heartbeat import ShuffleHeartbeatEndpoint, ShuffleHeartbeatManager
from .transport import BlockClient, BlockServer, ShuffleFetchFailed

__all__ = ["LocalCluster", "ShuffleFetchFailed"]


class _RemoteManager:
    """Worker-side proxy giving ShuffleHeartbeatEndpoint the manager
    interface over the driver's control socket."""

    def __init__(self, driver_addr, token: Optional[bytes]):
        self._client = BlockClient(driver_addr, token=token)

    def register(self, executor_id: str, address: dict):
        return self._client.task("register", executor_id=executor_id,
                                 address=address)


# ---------------------------------------------------------------------------
# worker process
# ---------------------------------------------------------------------------

_WORKER: Dict[str, object] = {}


def _worker_main(worker_id: int, driver_addr, ready_q, token: bytes,
                 bind_host: str = "127.0.0.1"):
    # CPU backend only: worker processes must never grab the TPU the
    # driver session owns (one chip, many processes — the reference's
    # one-GPU-per-executor assignment, Plugin.scala:536)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=1")
    # the TPU plugin (when installed) force-sets jax_platforms at register
    # time, ignoring the env var — override it back the way the test
    # conftest does, or every worker would fight the driver for the chip
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_device", "cpu")
    if os.environ.get("SRTPU_CLUSTER_DEBUG"):
        import faulthandler
        import sys
        faulthandler.dump_traceback_later(30, repeat=True, file=sys.stderr)
    server = BlockServer(host=bind_host, token=token, tasks=_WORKER_TASKS)
    _WORKER["server"] = server
    _WORKER["id"] = f"worker-{worker_id}"
    _WORKER["peers"] = {}
    _WORKER["token"] = token

    def on_new_peer(p):
        _WORKER["peers"][p["id"]] = BlockClient(
            (p["addr"]["host"], p["addr"]["port"]), token=token)

    ep = ShuffleHeartbeatEndpoint(
        _RemoteManager(tuple(driver_addr), token), _WORKER["id"],
        {"host": server.address[0], "port": server.address[1]},
        on_new_peer=on_new_peer)
    _WORKER["endpoint"] = ep
    ep.heartbeat()
    if ready_q is not None:           # standalone (multi-host) workers
        ready_q.put((worker_id, server.address))
    import threading
    stop = threading.Event()
    _WORKER["stop"] = stop
    while not stop.is_set():           # heartbeat loop; tasks arrive via
        time.sleep(1.0)                # the BlockServer "task" op
        try:
            ep.heartbeat()
        except Exception:
            return                     # driver gone: exit


def _worker_stop():
    _WORKER["stop"].set()              # type: ignore
    return True


def _worker_heartbeat():
    _WORKER["endpoint"].heartbeat()    # type: ignore
    return sorted(_WORKER["peers"])    # type: ignore


def _peer_client(owner_id: str) -> Optional[BlockClient]:
    if owner_id == _WORKER["id"]:
        return None                    # local put goes straight to store
    peers: Dict[str, BlockClient] = _WORKER["peers"]  # type: ignore
    if owner_id not in peers:
        _WORKER["endpoint"].heartbeat()  # type: ignore
    return peers[owner_id]


def _hash_partition(table, exprs, n_parts: int):
    """Deterministic host hash partitioning of an Arrow table by the
    grouping expressions (same mixing as CpuShuffleExchangeExec so every
    process routes identically)."""
    import numpy as np
    import pyarrow as pa
    from ..columnar import ColumnarBatch
    if not exprs or n_parts == 1:
        return {0: table}
    batch = ColumnarBatch.from_arrow_host(table)
    h = np.full(table.num_rows, 42, dtype=np.uint64)
    for e in exprs:
        from ..exprs.arithmetic import arrow_to_masked_numpy
        arr = e.eval_host(batch)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        v, ok = arrow_to_masked_numpy(arr)
        v = np.asarray(v)
        if v.dtype == object:
            # Python's str hash is per-process randomized; routing must be
            # identical in EVERY worker (crc32 is stable everywhere)
            import zlib
            hv = np.asarray([zlib.crc32(str(x).encode()) for x in v],
                            dtype=np.uint64)
        elif np.issubdtype(v.dtype, np.floating):
            # normalize before hashing (advisor r2): -0.0 == 0.0 must
            # route together, and all NaN payloads are one group — raw
            # bit patterns would split them across reduce partitions
            f = v.astype(np.float64) + 0.0          # -0.0 -> +0.0
            f = np.where(np.isnan(f), np.nan, f)    # canonical NaN
            hv = f.view(np.uint64)
        else:
            hv = v.astype(np.int64).view(np.uint64)
        h = h * np.uint64(31) + np.where(ok, hv, np.uint64(7))
    pid = (h % np.uint64(n_parts)).astype(np.int64)
    out = {}
    for p in range(n_parts):
        sub = table.filter(pa.array(pid == p))
        if sub.num_rows:
            out[p] = sub
    return out


def _range_partition(table, key_name: str, ascending: bool,
                     nulls_first: bool, boundaries, n_parts: int):
    """Range partitioning by the FIRST sort key (ref GpuRangePartitioner):
    boundaries arrive ASC-sorted; equal key values always route to one
    partition, so a local sort per partition + ordered concatenation is a
    global sort (ties broken by the remaining keys locally, which all
    live in the same partition). Nulls route to the first/last partition
    per the null ordering."""
    import numpy as np
    import pyarrow as pa
    from ..columnar import ColumnarBatch
    from ..exprs.arithmetic import arrow_to_masked_numpy
    from ..exprs.base import ColumnRef
    if n_parts == 1 or not len(boundaries):
        return {0: table}
    batch = ColumnarBatch.from_arrow_host(table)
    arr = ColumnRef(key_name).eval_host(batch)
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    v, ok = arrow_to_masked_numpy(arr)
    v = np.asarray(v)
    b = np.asarray(boundaries)
    if ascending:
        pid = np.searchsorted(b, v, side="right").astype(np.int64)
    else:
        pid = (len(b) - np.searchsorted(b, v, side="left")).astype(np.int64)
    pid = np.clip(pid, 0, n_parts - 1)
    pid = np.where(ok, pid, 0 if nulls_first else n_parts - 1)
    out = {}
    for p in range(n_parts):
        sub = table.filter(pa.array(pid == p))
        if sub.num_rows:
            out[p] = sub
    return out


def _run_range_map_task(shuffle_id: int, plan_bytes: bytes,
                        key_bytes: bytes, boundaries_bytes: bytes,
                        owners: List[str]):
    """Evaluate the map fragment and RANGE-partition its output by the
    first sort key (the exchange below a distributed global sort; ref
    GpuShuffleExchangeExecBase with GpuRangePartitioner)."""
    from ..api.dataframe import TpuSession
    from ..plan.overrides import plan_query
    plan = pickle.loads(plan_bytes)
    key_name, ascending, nulls_first = pickle.loads(key_bytes)
    boundaries = pickle.loads(boundaries_bytes)
    session = TpuSession()
    physical = plan_query(plan, session.conf)
    table = physical.collect(session.exec_context())
    parts = _range_partition(table, key_name, ascending, nulls_first,
                             boundaries, len(owners))
    return _put_partitions(shuffle_id, parts, owners)


def _put_partitions(shuffle_id: int, parts, owners: List[str]):
    from ..columnar.serializer import serialize_table
    server: BlockServer = _WORKER["server"]  # type: ignore
    for p, sub in parts.items():
        data = serialize_table(sub, "lz4")
        client = _peer_client(owners[p])
        if client is None:
            server._put(shuffle_id, p, data)
        else:
            client.put(shuffle_id, p, data)
    return {p: t.num_rows for p, t in parts.items()}


def _fetch_concat(shuffle_id: int, parts: List[int]):
    """Fetch owned partitions from the local store (writers already
    routed them here)."""
    import pyarrow as pa
    from ..columnar.serializer import deserialize_table
    server: BlockServer = _WORKER["server"]  # type: ignore
    tables = []
    for p in parts:
        for blk in server._fetch(shuffle_id, p):
            tables.append(deserialize_table(blk))
    return pa.concat_tables(tables) if tables else None


def _scan_of(table):
    from ..plan import logical as L
    from ..types import Schema, from_arrow, StructField
    schema = Schema([StructField(f.name, from_arrow(f.type), True)
                     for f in table.schema])
    return L.LogicalScan([table], schema)


def _run_map_task(shuffle_id: int, plan_bytes: bytes, group_bytes: bytes,
                  owners: List[str]):
    """Execute the map fragment, hash-partition its output, PUT blocks to
    partition owners (ref RapidsShuffleThreadedWriterBase:238)."""
    from ..api.dataframe import TpuSession
    plan = pickle.loads(plan_bytes)
    groupings = pickle.loads(group_bytes)
    session = TpuSession()
    from ..plan.overrides import plan_query
    physical = plan_query(plan, session.conf)
    table = physical.collect(session.exec_context())
    parts = _hash_partition(table, groupings, len(owners))
    return _put_partitions(shuffle_id, parts, owners)


def _run_reduce_task(shuffle_id: int, parts: List[int], plan_bytes: bytes):
    """Merge-aggregate the owned partitions
    (ref RapidsShuffleThreadedReaderBase:614)."""
    from ..api.dataframe import TpuSession
    from ..columnar.serializer import serialize_table
    from ..plan.overrides import plan_query
    reduce_plan = pickle.loads(plan_bytes)
    t = _fetch_concat(shuffle_id, parts)
    if t is None:
        return None
    reduce_plan = copy.copy(reduce_plan)
    reduce_plan.children = [_scan_of(t)]
    session = TpuSession()
    physical = plan_query(reduce_plan, session.conf)
    out = physical.collect(session.exec_context())
    return serialize_table(out, "lz4")


def _run_join_side_task(shuffle_id: int, plan_bytes: bytes,
                        key_bytes: bytes, owners: List[str]):
    """Evaluate one side of a shuffled join and hash-partition its rows
    by the JOIN keys (the exchange below GpuShuffledHashJoinExec)."""
    from ..api.dataframe import TpuSession
    from ..plan.overrides import plan_query
    plan = pickle.loads(plan_bytes)
    keys = pickle.loads(key_bytes)
    session = TpuSession()
    physical = plan_query(plan, session.conf)
    table = physical.collect(session.exec_context())
    parts = _hash_partition(table, keys, len(owners))
    return _put_partitions(shuffle_id, parts, owners)


def _run_join_local_task(shuffle_l: int, shuffle_r: int, parts: List[int],
                         template_bytes: bytes, group_bytes: bytes,
                         out_shuffle: int, owners: List[str],
                         schemas_bytes: bytes):
    """Fetch co-partitioned slices of both join sides, run the local
    join + upper fragment + PARTIAL aggregation, hash-partition the
    partials by grouping keys into the next shuffle."""
    from ..api.dataframe import TpuSession
    from ..plan import logical as L
    from ..plan.overrides import plan_query
    template = pickle.loads(template_bytes)
    groupings = pickle.loads(group_bytes)
    lschema, rschema = pickle.loads(schemas_bytes)
    lt = _fetch_concat(shuffle_l, parts)
    rt = _fetch_concat(shuffle_r, parts)
    if lt is None and rt is None:
        return {}
    lt = lt if lt is not None else _empty_like(lschema)
    rt = rt if rt is not None else _empty_like(rschema)
    join = _find_join(template)
    join.children = [L.LogicalScan([lt], lschema),
                     L.LogicalScan([rt], rschema)]
    session = TpuSession()
    physical = plan_query(template, session.conf)
    table = physical.collect(session.exec_context())
    parts_out = _hash_partition(table, groupings, len(owners))
    return _put_partitions(out_shuffle, parts_out, owners)


#: the closed task table workers expose over the transport — the typed
#: protocol's entire executable surface (ref RapidsShuffleTransport's
#: message enum: adding a capability means adding a NAME here, not
#: shipping code)
_WORKER_TASKS = {
    "map_agg": _run_map_task,
    "map_range": _run_range_map_task,
    # fetch owned partitions, apply an arbitrary unary plan over them,
    # return Arrow: the merge-agg reducer, the per-range local sorter,
    # and the per-hash-partition window runner are all this one task
    "reduce_agg": _run_reduce_task,
    "join_side": _run_join_side_task,
    "join_local": _run_join_local_task,
    "heartbeat": _worker_heartbeat,
    "stop": _worker_stop,
}


# ---------------------------------------------------------------------------
# plan decomposition (map partials / reduce merge / driver finish)
# ---------------------------------------------------------------------------

def _decompose_aggs(groupings, aggs, child_schema):
    """-> (map_aggs, reduce_aggs, final_projections) or None."""
    from ..exprs import aggregates as AG
    from ..exprs.arithmetic import Divide
    from ..exprs.base import Alias, ColumnRef, Literal
    from ..exprs.cast import Cast
    from ..exprs.conditional import Coalesce
    from ..types import FLOAT64, INT64
    map_aggs, reduce_aggs, projections = [], [], []
    for g in groupings:
        projections.append(ColumnRef(g.name_hint))
    for i, a in enumerate(aggs):
        if getattr(a, "distinct", False):
            return None
        out = a.name_hint
        t = f"__mp_t{i}"
        if isinstance(a, AG.Average):
            ps, pc = f"__mp_p{i}_s", f"__mp_p{i}_c"
            map_aggs.append(AG.Sum(Cast(a.child, FLOAT64)).with_name(ps))
            map_aggs.append(AG.Count(a.child).with_name(pc))
            ts, tc = f"__mp_t{i}_s", f"__mp_t{i}_c"
            reduce_aggs.append(AG.Sum(ColumnRef(ps)).with_name(ts))
            reduce_aggs.append(AG.Sum(ColumnRef(pc)).with_name(tc))
            projections.append(Alias(
                Divide(ColumnRef(ts), Cast(ColumnRef(tc), FLOAT64)), out))
        elif isinstance(a, (AG.CountStar, AG.Count)):
            p = f"__mp_p{i}"
            inner = (AG.CountStar() if isinstance(a, AG.CountStar)
                     else AG.Count(a.child))
            map_aggs.append(inner.with_name(p))
            reduce_aggs.append(AG.Sum(ColumnRef(p)).with_name(t))
            projections.append(Alias(
                Coalesce(ColumnRef(t), Literal(0, INT64)), out))
        elif isinstance(a, (AG.Sum, AG.Min, AG.Max)):
            p = f"__mp_p{i}"
            cls = type(a)
            map_aggs.append(cls(a.child).with_name(p))
            reduce_aggs.append(cls(ColumnRef(p)).with_name(t))
            projections.append(Alias(ColumnRef(t), out))
        else:
            return None
    return map_aggs, reduce_aggs, projections


def _find_root(plan, pred, through):
    """Topmost node matching ``pred`` reachable through unary
    driver-finishable nodes of the given types; returns (path, node)
    where path re-applies the upper fragment on the driver."""
    path = []
    node = plan
    while True:
        if pred(node):
            return path, node
        if isinstance(node, through) and len(node.children) == 1:
            path.append(node)
            node = node.children[0]
            continue
        return None, None


def _find_agg(plan):
    from ..plan import logical as L
    return _find_root(plan, lambda n: isinstance(n, L.Aggregate),
                      (L.Sort, L.Project, L.GlobalLimit, L.LocalLimit))


def _find_sort(plan):
    from ..plan import logical as L
    return _find_root(
        plan, lambda n: isinstance(n, L.Sort) and n.global_sort,
        (L.Project, L.GlobalLimit, L.LocalLimit))


def _find_window(plan):
    from ..plan import logical as L
    return _find_root(plan, lambda n: isinstance(n, L.Window),
                      (L.Sort, L.Project, L.GlobalLimit, L.LocalLimit))


def _largest_scan(child):
    scans: List = []
    _scan_sizes(child, scans)
    if not scans:
        return None
    return max(scans, key=lambda s: sum(t.num_rows for t in s.tables))


def _check_row_decomposable(child, stop_at=None, sliced=None) -> None:
    """The map fragment below a distributed agg/sort/window is executed
    on row SLICES of its largest scan, so it must be row-local: slicing
    the input and unioning the outputs has to equal running it whole.
    Project/Filter/Sample/inner-Join qualify; a nested Aggregate, Sort,
    Limit, Window, or an outer/semi/anti join (whose null-extended or
    filtered rows are per-slice artifacts — a dim row unmatched in one
    slice but matched in another would be emitted null-extended anyway)
    would silently compute per-slice results — refuse instead.
    ``stop_at`` marks a join the caller shuffles by key instead of
    slicing (its own subtrees are validated separately)."""
    from ..plan import logical as L
    ok = (L.Project, L.Filter, L.Join, L.LogicalScan, L.Sample,
          L.Union, L.Expand, L.Generate)

    def contains(n, target):
        return n is target or any(contains(c, target) for c in n.children)

    def walk(n):
        if n is stop_at:
            return
        if not isinstance(n, ok):
            raise ValueError(
                f"fragment below the distributed root is not "
                f"row-decomposable: {type(n).__name__} computes a "
                f"cross-row result and would be wrong on row slices")
        if isinstance(n, L.Join) and n.join_type != "inner":
            # a non-inner join slices safely ONLY when the sliced scan
            # feeds its row-preserving side (each output row then derives
            # from exactly one sliced row); a sliced null-producing or
            # filtering side emits per-slice artifacts
            preserving = {"left": 0, "leftsemi": 0, "leftanti": 0,
                          "existence": 0, "right": 1}.get(n.join_type)
            if preserving is None or sliced is None \
                    or not contains(n.children[preserving], sliced):
                raise ValueError(
                    f"{n.join_type} join is not row-decomposable with "
                    f"the sliced input on its non-preserving side")
        for c in n.children:
            walk(c)

    walk(child)


def _find_join(plan):
    """Topmost equi-Join in the subtree (depth-first)."""
    from ..plan import logical as L
    if isinstance(plan, L.Join):
        return plan
    for c in plan.children:
        j = _find_join(c)
        if j is not None:
            return j
    return None


def _scan_sizes(plan, out):
    from ..plan import logical as L
    if isinstance(plan, L.LogicalScan):
        out.append(plan)
    for c in plan.children:
        _scan_sizes(c, out)


def _subtree_rows(plan) -> int:
    scans: List = []
    _scan_sizes(plan, scans)
    return sum(sum(t.num_rows for t in s.tables) for s in scans)


def _replace_node(plan, old, new):
    if plan is old:
        return new
    clone = copy.copy(plan)
    clone.children = [_replace_node(c, old, new) for c in plan.children]
    return clone


# ---------------------------------------------------------------------------
# the cluster
# ---------------------------------------------------------------------------

class LocalCluster:
    """N worker processes shuffling over TCP with a shared HMAC token.

    Multi-host (VERDICT r3 #9): pass ``bind_host`` (a non-loopback
    address) and workers on OTHER hosts join via the standalone entry
    point — no code shipping, only the typed-task protocol:

        # on the driver host
        cl = LocalCluster(n_workers=0, bind_host="10.0.0.1")
        open("/shared/token", "wb").write(cl.token)
        print(cl.control.address)               # e.g. ('10.0.0.1', 41234)
        # on each worker host
        python -m spark_rapids_tpu.shuffle.worker \
            --driver 10.0.0.1:41234 --token-file /shared/token \
            --id 0 --bind 10.0.0.2
        # back on the driver
        cl.wait_for_workers(2)
        cl.execute(df)

    Local workers (``n_workers`` > 0) spawn as processes on this host and
    bind the same ``bind_host`` (ref Plugin.scala:428-439 heartbeat
    discovery; the transport is the RapidsShuffleTransport analog)."""

    def __init__(self, n_workers: int = 2, start_timeout_s: float = 60.0,
                 shuffle_join_min_rows: int = 100_000,
                 bind_host: str = "127.0.0.1"):
        import multiprocessing as mp
        self.token = secrets.token_bytes(32)
        self.bind_host = bind_host
        self.manager = ShuffleHeartbeatManager()
        # the control server binds ITS OWN manager: two live clusters in
        # one driver process must not cross-register workers
        self.control = BlockServer(host=bind_host, token=self.token,
                                   tasks={"register": self.manager.register})
        self.shuffle_join_min_rows = shuffle_join_min_rows
        ctx = mp.get_context("spawn")
        self._ready = ctx.Queue()
        self.procs = [ctx.Process(target=_worker_main,
                                  args=(i, self.control.address,
                                        self._ready, self.token,
                                        bind_host),
                                  daemon=True)
                      for i in range(n_workers)]
        for p in self.procs:
            p.start()
        self.workers: Dict[str, Tuple[str, int]] = {}
        deadline = time.monotonic() + start_timeout_s
        while len(self.workers) < n_workers:
            if time.monotonic() > deadline:
                raise TimeoutError("workers failed to start")
            wid, addr = self._ready.get(timeout=start_timeout_s)
            self.workers[f"worker-{wid}"] = tuple(addr)
        self.clients = {wid: BlockClient(addr, token=self.token)
                        for wid, addr in sorted(self.workers.items())}
        # let every worker discover every peer before tasks ship
        for c in self.clients.values():
            c.task("heartbeat")
        self._next_shuffle = [0]

    def wait_for_workers(self, n: int, timeout_s: float = 120.0) -> None:
        """Block until ``n`` workers (incl. externally-launched ones) have
        registered via heartbeat, then connect task clients to them."""
        deadline = time.monotonic() + timeout_s
        while True:
            peers = {p["id"]: (p["addr"]["host"], p["addr"]["port"])
                     for p in self.manager.peer_details()}
            if len(peers) >= n:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{len(peers)}/{n} workers registered")
            time.sleep(0.2)
        self.workers = dict(sorted(peers.items()))
        self.clients = {wid: BlockClient(addr, token=self.token)
                        for wid, addr in self.workers.items()}
        for c in self.clients.values():
            c.task("heartbeat")

    def _shuffle_id(self, owned: List[int]) -> int:
        sid = self._next_shuffle[0]
        self._next_shuffle[0] += 1
        owned.append(sid)
        return sid

    # -------------------------------------------------------------------
    def execute(self, df):
        """Distributed execution of a DataFrame whose plan is
        Sort/Project/Limit* over a decomposable Aggregate: map fragments
        run on workers, the shuffle moves partial-aggregate blocks, the
        reduce merges, the driver finishes the plan. When the aggregate
        sits over an equi-join whose sides are BOTH large, the join is
        itself shuffled (both sides hash-partitioned by join key) before
        the local join + partial agg. Returns Arrow."""
        from ..plan import logical as L
        from ..plan.rewrites import prune_columns
        from ..types import Schema, from_arrow, StructField
        import pyarrow as pa

        plan = prune_columns(df.plan)
        path, agg = _find_agg(plan)
        if agg is None:
            wpath, win = _find_window(plan)
            if win is not None:
                return self._execute_window(df, plan, wpath, win)
            spath, sort = _find_sort(plan)
            if sort is not None:
                return self._execute_sort(df, plan, spath, sort)
            raise ValueError(
                "plan has no distributable aggregate/sort/window root")
        dec = _decompose_aggs(agg.groupings, agg.aggs,
                              agg.children[0].schema())
        if dec is None:
            raise ValueError("aggregates are not merge-decomposable")
        map_aggs, reduce_aggs, projections = dec

        worker_ids = sorted(self.clients)
        n = len(worker_ids)
        import concurrent.futures as cf
        pool = cf.ThreadPoolExecutor(max_workers=2 * n)
        group_bytes = pickle.dumps([self._group_ref(g)
                                    for g in agg.groupings])

        join = _find_join(agg.children[0])
        shuffled_join = (
            join is not None and join.condition is None
            and join.join_type in ("inner", "left", "right", "full")
            and join.left_keys and join.right_keys
            and _subtree_rows(join.children[0]) >= self.shuffle_join_min_rows
            and _subtree_rows(join.children[1]) >= self.shuffle_join_min_rows)
        if shuffled_join:
            # the join itself is key-shuffled (exact for outer types);
            # each SIDE is row-sliced and must be row-local on its own
            _check_row_decomposable(agg.children[0], stop_at=join)
            for side in join.children:
                _check_row_decomposable(side,
                                        sliced=_largest_scan(side))
        else:
            _check_row_decomposable(agg.children[0],
                                    sliced=_largest_scan(agg.children[0]))

        owned_sids: List[int] = []     # THIS call's shuffles only
        try:
            if shuffled_join:
                agg_shuffle = self._exec_shuffled_join(
                    pool, worker_ids, agg, join, map_aggs, group_bytes,
                    owned_sids)
            else:
                agg_shuffle = self._exec_sliced_map(
                    pool, worker_ids, agg, map_aggs, group_bytes,
                    owned_sids)

            # reduce: worker w owns partition w; the child is patched
            # worker-side with a scan of the fetched blocks
            reduce_proto = L.Aggregate(
                [self._group_ref(g) for g in agg.groupings], reduce_aggs,
                L.RangeRel(0, 1),
                int_key_cards=getattr(agg, "int_key_cards", None))
            results = []
            futures = [pool.submit(self.clients[wid].task, "reduce_agg",
                                   shuffle_id=agg_shuffle, parts=[wi],
                                   plan_bytes=pickle.dumps(reduce_proto))
                       for wi, wid in enumerate(worker_ids)]
            from ..columnar.serializer import deserialize_table
            for f in futures:
                got = f.result()
                if got is not None:
                    results.append(deserialize_table(got))
        finally:
            # settle in-flight tasks BEFORE dropping, or a late map PUT
            # would recreate blocks for an already-dropped shuffle id
            pool.shutdown(wait=True)
            for c in self.clients.values():
                for sid in owned_sids:
                    try:
                        c.drop(sid)
                    except Exception:
                        continue

        merged = pa.concat_tables(results) if results else None
        # driver finish: restore names/avg divides, then the upper path
        from ..api.dataframe import TpuSession
        session = getattr(df, "session", None) or TpuSession()
        if merged is None:
            agg_out_schema = L.Aggregate(agg.groupings, agg.aggs,
                                         agg.children[0]).schema()
            merged = _empty_like(agg_out_schema)
            final = L.LogicalScan([merged], agg_out_schema)
        else:
            schema = Schema([StructField(f.name, from_arrow(f.type), True)
                             for f in merged.schema])
            final = L.Project(projections,
                              L.LogicalScan([merged], schema))
        for node in reversed(path):
            clone = copy.copy(node)
            clone.children = [final]
            final = clone
        from ..plan.overrides import plan_query
        physical = plan_query(final, session.conf)
        return physical.collect(session.exec_context())

    # -------------------------------------------------------------------
    def _shuffle_scope(self):
        """Task pool + shuffle-id ownership with guaranteed cleanup: the
        one lifecycle every distributed round (agg/sort/window) shares."""
        import concurrent.futures as cf
        from contextlib import contextmanager

        @contextmanager
        def scope():
            pool = cf.ThreadPoolExecutor(max_workers=2 * len(self.clients))
            owned: List[int] = []
            try:
                yield pool, owned
            finally:
                # settle in-flight tasks BEFORE dropping, or a late map
                # PUT would recreate blocks for a dropped shuffle id
                pool.shutdown(wait=True)
                for c in self.clients.values():
                    for sid in owned:
                        try:
                            c.drop(sid)
                        except Exception:
                            continue
        return scope()

    def _driver_finish(self, df, results, out_schema, path):
        """Concatenate worker results (in task order) and re-apply the
        driver-finishable upper path."""
        import pyarrow as pa
        from ..api.dataframe import TpuSession
        from ..plan import logical as L
        from ..plan.overrides import plan_query
        session = getattr(df, "session", None) or TpuSession()
        merged = (pa.concat_tables(results) if results
                  else _empty_like(out_schema))
        final = L.LogicalScan([merged], out_schema)
        for node in reversed(path):
            clone = copy.copy(node)
            clone.children = [final]
            final = clone
        physical = plan_query(final, session.conf)
        return physical.collect(session.exec_context())

    def _sliced_fragments(self, child):
        """Slice the largest in-memory scan of a fragment row-wise across
        workers; returns the per-worker fragment plans."""
        from ..plan import logical as L
        import pyarrow as pa
        scans: List = []
        _scan_sizes(child, scans)
        if not scans:
            raise ValueError("no in-memory scans to distribute")
        fact = max(scans, key=lambda s: sum(t.num_rows for t in s.tables))
        fact_table = pa.concat_tables(fact.tables) \
            if len(fact.tables) > 1 else fact.tables[0]
        n = len(self.clients)
        per = -(-fact_table.num_rows // n)
        plans = []
        for wi in range(n):
            slice_w = fact_table.slice(wi * per, per)
            scan_w = L.LogicalScan([slice_w], fact._schema,
                                   columns=fact.columns)
            plans.append(_replace_node(child, fact, scan_w))
        return plans, fact, fact_table

    def _collect_local(self, worker_ids, pool, shuffle_id, proto):
        """One reduce_agg-style task per worker over its owned partition;
        results come back in worker (partition) order."""
        from ..columnar.serializer import deserialize_table
        futures = [pool.submit(self.clients[wid].task, "reduce_agg",
                               shuffle_id=shuffle_id, parts=[wi],
                               plan_bytes=pickle.dumps(proto))
                   for wi, wid in enumerate(worker_ids)]
        results = []
        for f in futures:
            got = f.result()
            if got is not None:
                results.append(deserialize_table(got))
        return results

    def _execute_sort(self, df, plan, path, sort):
        """Distributed global sort (VERDICT r3 #6): sample the first sort
        key for range boundaries, range-shuffle the fragment output, sort
        each range locally, concatenate in range order (ref
        GpuRangePartitioner + GpuSortExec over the shuffle manager,
        RapidsShuffleInternalManagerBase.scala:238-614)."""
        import copy as _copy
        from ..plan import logical as L
        child = sort.children[0]
        _check_row_decomposable(child, sliced=_largest_scan(child))
        order0 = sort.orders[0]
        key_name = order0.expr.name_hint
        if key_name not in child.schema().names():
            raise ValueError("distributed sort keys must be child columns")
        worker_ids = sorted(self.clients)
        n = len(worker_ids)
        plans, fact, fact_table = self._sliced_fragments(child)
        boundaries = self._sample_boundaries(df, child, order0, n,
                                             fact=fact,
                                             fact_table=fact_table)
        with self._shuffle_scope() as (pool, owned_sids):
            sid = self._shuffle_id(owned_sids)
            key_bytes = pickle.dumps((key_name, order0.ascending,
                                      order0.nulls_first))
            boundaries_bytes = pickle.dumps(boundaries)
            futures = [pool.submit(
                self.clients[wid].task, "map_range", shuffle_id=sid,
                plan_bytes=pickle.dumps(p), key_bytes=key_bytes,
                boundaries_bytes=boundaries_bytes, owners=worker_ids)
                for wid, p in zip(worker_ids, plans)]
            for f in futures:
                f.result()
            proto = _copy.copy(sort)
            proto.children = [L.RangeRel(0, 1)]
            # partition w holds range w: descending orders put the
            # LARGEST range in partition 0, so worker order IS sort order
            results = self._collect_local(worker_ids, pool, sid, proto)
        return self._driver_finish(df, results, sort.schema(), path)

    def _sample_boundaries(self, df, child, order0, n_parts: int,
                           sample_rows: int = 20000, fact=None,
                           fact_table=None):
        """Range boundaries from a driver-local sample of the fragment
        output (the RangePartitioner sampling pass, run through the same
        fragment plan the workers will run). ``fact``/``fact_table`` come
        from the caller's _sliced_fragments pass — re-concatenating a
        multi-chunk fact table here would double the driver copy cost."""
        import numpy as np
        import pyarrow as pa
        from ..api.dataframe import TpuSession
        from ..columnar import ColumnarBatch
        from ..exprs.arithmetic import arrow_to_masked_numpy
        from ..exprs.base import ColumnRef
        from ..plan import logical as L
        from ..plan.overrides import plan_query
        if fact is None or fact_table is None:
            scans: List = []
            _scan_sizes(child, scans)
            fact = max(scans,
                       key=lambda s: sum(t.num_rows for t in s.tables))
            fact_table = pa.concat_tables(fact.tables) \
                if len(fact.tables) > 1 else fact.tables[0]
        total = fact_table.num_rows
        if total > sample_rows:
            rng = np.random.RandomState(77)
            idx = np.sort(rng.choice(total, sample_rows, replace=False))
            sample = fact_table.take(pa.array(idx))
        else:
            sample = fact_table
        scan_s = L.LogicalScan([sample], fact._schema, columns=fact.columns)
        plan_s = _replace_node(child, fact, scan_s)
        session = getattr(df, "session", None) or TpuSession()
        out = plan_query(plan_s, session.conf).collect(
            session.exec_context())
        batch = ColumnarBatch.from_arrow_host(out)
        arr = ColumnRef(order0.expr.name_hint).eval_host(batch)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        v, ok = arrow_to_masked_numpy(arr)
        v = np.asarray(v)[np.asarray(ok, bool)]
        if not len(v):
            return []
        v = np.sort(v)          # ASC always; routing handles direction
        cuts = [v[int(len(v) * i / n_parts)] for i in range(1, n_parts)]
        return list(cuts)

    def _execute_window(self, df, plan, path, win):
        """Distributed windows (VERDICT r3 #6): hash-shuffle the fragment
        output by the window partition keys (co-locating every window
        partition on one worker), run the full Window node per worker,
        concatenate (ref hash-partitioned GpuWindowExec over the shuffle
        manager)."""
        import copy as _copy
        from ..exprs.base import ColumnRef
        from ..plan import logical as L
        specs = [spec for _e, spec, _n in win.window_exprs]
        keysets = {tuple(e.name_hint for e in s.partition_by)
                   for s in specs}
        if len(keysets) != 1 or not next(iter(keysets)):
            raise ValueError("distributed windows need one shared, "
                             "non-empty partition_by")
        keys = [ColumnRef(k) for k in next(iter(keysets))]
        child = win.children[0]
        _check_row_decomposable(child, sliced=_largest_scan(child))
        cnames = child.schema().names()
        if any(k.name not in cnames for k in keys):
            raise ValueError("window partition keys must be child columns")
        worker_ids = sorted(self.clients)
        n = len(worker_ids)
        plans, _fact, _ft = self._sliced_fragments(child)
        with self._shuffle_scope() as (pool, owned_sids):
            sid = self._shuffle_id(owned_sids)
            group_bytes = pickle.dumps(keys)
            futures = [pool.submit(
                self.clients[wid].task, "map_agg", shuffle_id=sid,
                plan_bytes=pickle.dumps(p), group_bytes=group_bytes,
                owners=worker_ids)
                for wid, p in zip(worker_ids, plans)]
            for f in futures:
                f.result()
            proto = _copy.copy(win)
            proto.children = [L.RangeRel(0, 1)]
            results = self._collect_local(worker_ids, pool, sid, proto)
        return self._driver_finish(df, results, win.schema(), path)

    def _exec_sliced_map(self, pool, worker_ids, agg, map_aggs,
                         group_bytes, owned_sids: List[int]) -> int:
        """Original single-exchange path: the fact scan sliced row-wise,
        dims ride replicated (broadcast analog); partial agg on top."""
        from ..plan import logical as L
        import pyarrow as pa
        scans: List = []
        _scan_sizes(agg.children[0], scans)
        if not scans:
            raise ValueError("no in-memory scans to distribute")
        fact = max(scans, key=lambda s: sum(t.num_rows for t in s.tables))
        n = len(worker_ids)
        shuffle_id = self._shuffle_id(owned_sids)
        fact_table = pa.concat_tables(fact.tables) if len(fact.tables) > 1 \
            else fact.tables[0]
        per = -(-fact_table.num_rows // n)
        futures = []
        for wi, wid in enumerate(worker_ids):
            slice_w = fact_table.slice(wi * per, per)
            scan_w = L.LogicalScan([slice_w], fact._schema,
                                   columns=fact.columns)
            child_w = _replace_node(agg.children[0], fact, scan_w)
            map_plan = L.Aggregate(
                list(agg.groupings), map_aggs, child_w,
                int_key_cards=getattr(agg, "int_key_cards", None))
            futures.append(pool.submit(
                self.clients[wid].task, "map_agg", shuffle_id=shuffle_id,
                plan_bytes=pickle.dumps(map_plan), group_bytes=group_bytes,
                owners=worker_ids))
        for f in futures:
            f.result()
        return shuffle_id

    # -------------------------------------------------------------------
    def _exec_shuffled_join(self, pool, worker_ids, agg, join, map_aggs,
                            group_bytes, owned_sids: List[int]) -> int:
        """Two-exchange path: hash-shuffle both join sides by join keys,
        local join + partial agg per worker, then the agg exchange."""
        from ..plan import logical as L
        import pyarrow as pa
        n = len(worker_ids)
        side_shuffles = []
        futures = []
        for side, keys in ((0, join.left_keys), (1, join.right_keys)):
            subtree = join.children[side]
            scans: List = []
            _scan_sizes(subtree, scans)
            if not scans:
                raise ValueError("join side has no in-memory scans")
            fact = max(scans,
                       key=lambda s: sum(t.num_rows for t in s.tables))
            shuffle_id = self._shuffle_id(owned_sids)
            side_shuffles.append(shuffle_id)
            fact_table = pa.concat_tables(fact.tables) \
                if len(fact.tables) > 1 else fact.tables[0]
            per = -(-fact_table.num_rows // n)
            key_bytes = pickle.dumps(list(keys))
            for wi, wid in enumerate(worker_ids):
                slice_w = fact_table.slice(wi * per, per)
                scan_w = L.LogicalScan([slice_w], fact._schema,
                                       columns=fact.columns)
                plan_w = _replace_node(subtree, fact, scan_w)
                futures.append(pool.submit(
                    self.clients[wid].task, "join_side",
                    shuffle_id=shuffle_id,
                    plan_bytes=pickle.dumps(plan_w),
                    key_bytes=key_bytes, owners=worker_ids))
        for f in futures:
            f.result()

        # local join + partial agg per worker; output rides the agg
        # exchange. The template is the agg child with the join's inputs
        # to be patched worker-side (located by the same deterministic
        # walk both sides of the wire run).
        lschema = join.children[0].schema()
        rschema = join.children[1].schema()
        template_join = copy.copy(join)
        template_join.children = [L.RangeRel(0, 1), L.RangeRel(0, 1)]
        template_child = _replace_node(agg.children[0], join,
                                       template_join)
        template = L.Aggregate(
            list(agg.groupings), map_aggs, template_child,
            int_key_cards=getattr(agg, "int_key_cards", None))
        agg_shuffle = self._shuffle_id(owned_sids)
        schemas_bytes = pickle.dumps((lschema, rschema))
        template_bytes = pickle.dumps(template)
        futures = [pool.submit(
            self.clients[wid].task, "join_local",
            shuffle_l=side_shuffles[0], shuffle_r=side_shuffles[1],
            parts=[wi], template_bytes=template_bytes,
            group_bytes=group_bytes, out_shuffle=agg_shuffle,
            owners=worker_ids, schemas_bytes=schemas_bytes)
            for wi, wid in enumerate(worker_ids)]
        for f in futures:
            f.result()
        return agg_shuffle

    @staticmethod
    def _group_ref(g):
        from ..exprs.base import ColumnRef
        return ColumnRef(g.name_hint)

    def shutdown(self):
        for c in self.clients.values():
            try:
                c.task("stop")
            except Exception:
                pass
            c.close()
        for p in self.procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
        self.control.close()


def _empty_like(schema):
    import pyarrow as pa
    from ..types import to_arrow
    return pa.table({f.name: pa.array([], type=to_arrow(f.dtype))
                     for f in schema.fields})
